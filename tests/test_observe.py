# Observability tests: metrics registry (merge associativity, wire
# round-trip), frame tracing (Chrome-trace schema, span tree vs the
# pipeline graph, parked/resumed + fused-group frames), the
# telemetry-disabled mode (zero per-frame keys), periodic export into
# the Recorder's metrics plane, and the Recorder's stop() count flush.

import json
import queue
import time

import numpy as np
import pytest

from aiko_services_tpu.observe import (
    Histogram, MetricsRegistry, merge_snapshots, snapshot_from_wire,
    snapshot_quantile)
from aiko_services_tpu.dashboard import format_snapshot_lines
from aiko_services_tpu.pipeline import (
    AsyncHostElement, ComputeElement, PipelineElement, StreamEvent,
    create_pipeline)
from aiko_services_tpu.runtime import Process, Recorder
from aiko_services_tpu.transport import get_broker, reset_brokers
from aiko_services_tpu.utils import parse
from helpers import wait_for


@pytest.fixture(autouse=True)
def clean_brokers():
    reset_brokers()
    yield
    reset_brokers()


# -- elements under observation (loaded by module path) ----------------------

class FusedScale(ComputeElement):
    """Pure compute element: inherits the free group_kernel, so the
    micro-batch scheduler runs it through the FUSED whole-group path."""

    def compute(self, state, x):
        return {"y": x * 2.0}


class SlowAsync(AsyncHostElement):
    """Parks the frame on a worker thread (StreamEvent.PENDING), then
    resumes through process_frame_response -- the parked/resumed shape."""

    def process_async(self, stream, y):
        time.sleep(0.005)
        return {"z": np.asarray(y) + 1.0}


class PlainDouble(PipelineElement):
    def process_frame(self, stream, x):
        return StreamEvent.OKAY, {"y": np.asarray(x) * 2.0}


def _local(class_name):
    return {"local": {"module": "tests.test_observe",
                      "class_name": class_name}}


def _observed_definition(telemetry=True, micro_batch=4):
    return {
        "name": "observed",
        "parameters": {"telemetry": telemetry, "metrics_interval": 0},
        "graph": ["(fused (host))"],
        "elements": [
            {"name": "fused", "input": [{"name": "x"}],
             "output": [{"name": "y"}],
             "parameters": {"micro_batch": micro_batch},
             "deploy": _local("FusedScale")},
            {"name": "host", "input": [{"name": "y"}],
             "output": [{"name": "z"}],
             "deploy": _local("SlowAsync")},
        ],
    }


# -- metrics registry --------------------------------------------------------

class TestMetrics:
    def test_counter_gauge_histogram(self):
        registry = MetricsRegistry()
        registry.counter("events").inc()
        registry.counter("events").inc(4)
        registry.gauge("depth").set(7)
        histogram = registry.histogram("lat_s")
        for value in (0.0001, 0.004, 0.004, 2.5):
            histogram.record(value)
        snapshot = registry.snapshot()
        assert snapshot["counters"]["events"] == 5
        assert snapshot["gauges"]["depth"] == 7.0
        record = snapshot["histograms"]["lat_s"]
        assert record["count"] == 4
        assert record["min"] == 0.0001 and record["max"] == 2.5
        assert sum(record["buckets"]) == 4
        assert abs(record["sum"] - 2.5081) < 1e-9

    def test_histogram_merge_associative(self):
        registries = [MetricsRegistry() for _ in range(3)]
        # dyadic values: float addition is exact in ANY grouping, so
        # associativity is checked structurally, not up-to-rounding
        samples = ([0.5, 0.03125, 4.0], [2.0 ** -16, 0.25],
                   [1.0, 1.0, 0.00390625])
        for registry, values in zip(registries, samples):
            for value in values:
                registry.histogram("h").record(value)
            registry.counter("n").inc(len(values))
        one, two, three = (r.snapshot() for r in registries)
        left = merge_snapshots(merge_snapshots(one, two), three)
        right = merge_snapshots(one, merge_snapshots(two, three))
        assert left == right
        assert left["counters"]["n"] == 8
        assert left["histograms"]["h"]["count"] == 8
        assert left["histograms"]["h"]["min"] == 2.0 ** -16
        assert left["histograms"]["h"]["max"] == 4.0
        # empty-side merge keeps real min/max (placeholder must not win)
        empty = MetricsRegistry()
        empty.histogram("h")
        merged = merge_snapshots(empty.snapshot(), left)
        assert merged["histograms"]["h"]["min"] == 2.0 ** -16

    def test_histogram_quantile_log_bucket_edges(self):
        """The ONE quantile-extraction helper (dashboard, gateway
        summary, and tune all read it): empty, single-bucket, q=0/1,
        and interior interpolation."""
        empty = Histogram()
        assert empty.quantile(0.5) == 0.0
        assert empty.quantile(0.0) == 0.0 and empty.quantile(1.0) == 0.0
        # single bucket: every sample lands in one log bucket -- the
        # estimate must interpolate within [min, max], never report
        # the bucket's full geometric span
        single = Histogram()
        for value in (0.0010, 0.0011, 0.0012):
            single.record(value)
        assert single.quantile(0.0) == 0.0010
        assert single.quantile(1.0) == 0.0012
        assert 0.0010 <= single.quantile(0.5) <= 0.0012
        # q clamps outside [0, 1]
        assert single.quantile(-3) == 0.0010
        assert single.quantile(7) == 0.0012
        # interior: 90 fast + 10 slow samples -- p50 stays in the fast
        # bucket's range, p99 in the slow one's
        mixed = Histogram()
        for _ in range(90):
            mixed.record(0.001)
        for _ in range(10):
            mixed.record(1.0)
        assert mixed.quantile(0.5) < 0.01
        assert mixed.quantile(0.99) > 0.5
        assert mixed.quantile(0.999) <= mixed.quantile(1.0) == 1.0

    def test_snapshot_quantile_matches_and_handles_unknown_ladder(self):
        histogram = Histogram()
        for value in (0.0001, 0.004, 0.02, 2.5):
            histogram.record(value)
        snapshot = histogram.snapshot()
        for q in (0.0, 0.25, 0.5, 0.9, 1.0):
            assert snapshot_quantile(snapshot, q) == \
                histogram.quantile(q)
        # custom-ladder snapshot without bounds: falls back to the
        # observed range instead of mis-reading the buckets
        custom = Histogram(bounds=(1, 2, 4))
        for value in (1.5, 3.0):
            custom.record(value)
        estimate = snapshot_quantile(custom.snapshot(), 0.5)
        assert 1.5 <= estimate <= 3.0
        # with explicit bounds the ladder is used
        assert snapshot_quantile(custom.snapshot(), 0.5,
                                 bounds=(1, 2, 4)) == \
            custom.quantile(0.5)

    def test_dashboard_lines_show_shared_quantiles(self):
        registry = MetricsRegistry()
        for _ in range(50):
            registry.histogram("element_s:asr").record(0.002)
        lines = format_snapshot_lines(registry.snapshot())
        line = next(line for line in lines if "element_s:asr" in line)
        assert "p50=" in line and "p99=" in line

    def test_sexpr_wire_round_trip(self):
        registry = MetricsRegistry()
        registry.counter("pipeline.frames_total").inc(42)
        registry.gauge("cohorts:detector").set(2.0)
        registry.histogram("element_s:asr").record(0.0123)
        payload = registry.to_payload("ns/host/1/2")
        command, parameters = parse(payload)
        assert command == "metrics"
        assert parameters[0] == "ns/host/1/2"
        restored = snapshot_from_wire(parameters[1])
        assert restored == registry.snapshot()
        # snapshot lines render for dashboards without raising
        assert any("element_s:asr" in line
                   for line in format_snapshot_lines(restored))


# -- frame tracing through the engine ----------------------------------------

class TestTracing:
    def _run_observed(self, frames=4):
        process = Process(transport_kind="loopback")
        pipeline = create_pipeline(process, _observed_definition())
        responses = queue.Queue()
        stream = pipeline.create_stream("s1", queue_response=responses)
        for index in range(frames):  # queued before the loop: all park
            pipeline.create_frame(
                stream, {"x": np.full((2, 3), float(index), np.float32)})
        process.run(in_thread=True)
        outputs = [responses.get(timeout=30) for _ in range(frames)]
        return process, pipeline, outputs

    def test_trace_spans_cover_graph_with_fused_and_parked_frame(
            self, tmp_path):
        process, pipeline, outputs = self._run_observed()
        try:
            for _, frame, output in outputs:
                assert np.asarray(output["z"]).shape == (2, 3)
                # compat keys survive, queue-wait reported apart
                assert "time_fused" in frame.metrics
                assert "time_host" in frame.metrics
                assert "time_queue_fused" in frame.metrics
                assert frame.metrics["time_pipeline"] > 0
            traces = list(pipeline.telemetry.tracer.completed)
            assert len(traces) == 4
            assert len({trace.trace_id for trace in traces}) == 4
            for trace in traces:
                kinds = {(kind, name) for kind, name, *_ in trace.events}
                names = {name for _, name, *_ in trace.events}
                # element spans cover every node on the graph path
                assert {"fused", "host"} <= names
                assert ("X", f"queue:fused") in kinds
                assert ("i", "park:host") in kinds
                assert ("i", "resume:host") in kinds
                fused_span = next(
                    event for event in trace.events
                    if event[0] == "X" and event[1] == "fused")
                assert fused_span[5]["path"] == "fused"
                assert fused_span[5]["group"] >= 1
            # registry: fused dispatches counted, occupancy recorded
            snapshot = pipeline.telemetry.registry.snapshot()
            assert snapshot["counters"]["pipeline.frames_total"] == 4
            assert snapshot["counters"]["pipeline.fused_groups"] >= 1
            assert snapshot["counters"]["pipeline.compiles_fused"] >= 1
            assert snapshot["histograms"]["element_s:fused"]["count"] == 4
            assert snapshot["histograms"]["queue_s:fused"]["count"] == 4
            # export: schema-valid, Perfetto-loadable JSON
            path = tmp_path / "trace.json"
            count = pipeline.telemetry.export_trace(str(path))
            document = json.loads(path.read_text())
            assert isinstance(document["traceEvents"], list)
            assert len(document["traceEvents"]) == count
            for event in document["traceEvents"]:
                assert {"ph", "name", "pid", "tid"} <= set(event)
                if event["ph"] in ("X", "i"):
                    assert isinstance(event["ts"], (int, float))
                if event["ph"] == "X":
                    assert event["dur"] >= 0
            # span tree: every frame's element/queue spans nest inside
            # that frame's top-level span bounds
            frames = [event for event in document["traceEvents"]
                      if event.get("cat") == "frame"]
            assert len(frames) == 4
            for frame_event in frames:
                trace_id = frame_event["args"]["trace_id"]
                start = frame_event["ts"]
                end = start + frame_event["dur"]
                children = [
                    event for event in document["traceEvents"]
                    if event["ph"] == "X" and event.get("cat") != "frame"
                    and event.get("args", {}).get("trace_id") == trace_id]
                assert {"fused", "host", "queue:fused"} <= {
                    event["name"] for event in children}
                slack = 2000.0  # us: async resume timestamps are approx
                for child in children:
                    assert child["ts"] >= start - slack
                    assert child["ts"] + child["dur"] <= end + slack
        finally:
            process.terminate()

    def test_metrics_disabled_writes_zero_per_frame_keys(self):
        process = Process(transport_kind="loopback")
        pipeline = create_pipeline(
            process, _observed_definition(telemetry=False))
        process.run(in_thread=True)
        responses = queue.Queue()
        stream = pipeline.create_stream("s1", queue_response=responses)
        for index in range(2):
            pipeline.create_frame(
                stream, {"x": np.ones((2, 3), np.float32) * index})
        for _ in range(2):
            _, frame, output = responses.get(timeout=30)
            assert np.asarray(output["z"]).shape == (2, 3)
            assert frame.metrics == {}  # ZERO per-frame keys
            assert frame.trace is None
        assert not pipeline.telemetry.enabled
        assert list(pipeline.telemetry.tracer.completed) == []
        snapshot = pipeline.telemetry.registry.snapshot()
        # pre-registered hot-path counters exist but never ticked
        assert all(value == 0 for value in snapshot["counters"].values())
        assert snapshot["histograms"] == {}
        process.terminate()


# -- queue-wait vs compute split: one contract, three dispatch paths ---------

class TestQueueComputeSplit:
    """`time_queue_{node}` (scheduler/slot-induced wait) vs
    `time_{node}` (element compute) must mean the SAME thing on the
    fused, chained, and engine-managed (decode/) paths -- tune/'s
    attribution depends on it (ISSUE 10 satellite)."""

    def _run(self, definition, frames, make_frame):
        process = Process(transport_kind="loopback")
        pipeline = create_pipeline(process, definition)
        responses = queue.Queue()
        stream = pipeline.create_stream("s", queue_response=responses,
                                        grace_time=300)
        for index in range(frames):
            pipeline.create_frame(stream, make_frame(index))
        process.run(in_thread=True)
        results = [responses.get(timeout=120) for _ in range(frames)]
        traces = list(pipeline.telemetry.tracer.completed)
        process.terminate()
        return results, traces

    def _assert_split(self, results, traces, node, path):
        for _, frame, _ in results:
            assert f"time_{node}" in frame.metrics, frame.metrics
            assert f"time_queue_{node}" in frame.metrics, frame.metrics
            assert frame.metrics[f"time_{node}"] >= 0.0
            assert frame.metrics[f"time_queue_{node}"] >= 0.0
        for trace in traces:
            names = {name for _, name, *_ in trace.events}
            assert f"queue:{node}" in names \
                or any(name.startswith(f"queue:{node}[")
                       for name in names)
            spans = [event for event in trace.events
                     if event[0] == "X" and event[1] == node]
            if path is not None:
                assert spans and spans[0][5]["path"] == path

    def test_fused_path_split(self):
        results, traces = self._run(
            _observed_definition(micro_batch=4), 4,
            lambda index: {"x": np.full((2, 3), float(index),
                                        np.float32)})
        self._assert_split(results, traces, "fused", "fused")

    def test_chained_path_split(self):
        # PlainDouble has no group_kernel: micro_batch > 1 coalesces
        # on the CHAINED path -- same keys, same meaning
        definition = {
            "name": "chained_split",
            "parameters": {"metrics_interval": 0},
            "graph": ["(plain)"],
            "elements": [
                {"name": "plain", "input": [{"name": "x"}],
                 "output": [{"name": "y"}],
                 "parameters": {"micro_batch": 4},
                 "deploy": _local("PlainDouble")},
            ],
        }
        results, traces = self._run(
            definition, 4,
            lambda index: {"x": np.full((2, 3), float(index),
                                        np.float32)})
        self._assert_split(results, traces, "plain", "chained")

    def test_engine_managed_path_split(self):
        # LMGenerate `continuous: true`: the engine's slot wait lands
        # in time_queue_lm and the response-side time_lm is compute
        # EXCLUDING that wait (the engine subtracts it), matching the
        # micro-batch paths where the queue interval closes before
        # element_start
        definition = {
            "name": "engine_split",
            "parameters": {"metrics_interval": 0},
            "graph": ["(lm)"],
            "elements": [
                {"name": "lm", "input": [{"name": "tokens"}],
                 "output": [{"name": "generated"}],
                 "parameters": {
                     "vocab_size": 300, "d_model": 32, "n_layers": 1,
                     "n_heads": 2, "n_kv_heads": 1, "d_ff": 64,
                     "max_seq_len": 128, "dtype": "float32",
                     "max_new_tokens": 4, "continuous": True,
                     "decode_slots": 2, "kv_block_size": 8},
                 "deploy": {"local": {
                     "module": "aiko_services_tpu.elements",
                     "class_name": "LMGenerate"}}},
            ],
        }
        rng = np.random.default_rng(5)
        prompts = [rng.integers(1, 300, size=(1, 7)).astype(np.int32)
                   for _ in range(3)]
        results, traces = self._run(definition, 3,
                                    lambda index: {"tokens":
                                                   prompts[index]})
        self._assert_split(results, traces, "lm", None)
        # the engine path ALSO reconstructs per-slot prefill/decode
        # spans onto the frame trace
        for trace in traces:
            names = {name for _, name, *_ in trace.events}
            assert any(name.startswith("prefill:lm")
                       for name in names)
            assert any(name.startswith("decode_steps:lm")
                       for name in names)
        # compute excludes the slot wait: the split halves sum to at
        # most the frame's own wall time
        trace_by_frame = {trace.frame_id: trace for trace in traces}
        for _, frame, _ in results:
            trace = trace_by_frame[frame.frame_id]
            wall_s = (trace.end_us - trace.start_us) / 1e6
            assert (frame.metrics["time_lm"]
                    + frame.metrics["time_queue_lm"]) \
                <= wall_s + 0.05


# -- export over the control plane -------------------------------------------

class TestExport:
    def test_periodic_publish_reaches_recorder(self):
        process = Process(transport_kind="loopback")
        recorder = Recorder(process)
        definition = {
            "name": "exported",
            "parameters": {"metrics_interval": 0.05},
            "graph": ["(double)"],
            "elements": [
                {"name": "double", "input": [{"name": "x"}],
                 "output": [{"name": "y"}],
                 "deploy": _local("PlainDouble")},
            ],
        }
        pipeline = create_pipeline(process, definition)
        process.run(in_thread=True)
        responses = queue.Queue()
        stream = pipeline.create_stream("s1", queue_response=responses)
        pipeline.create_frame(stream, {"x": np.ones((2,), np.float32)})
        responses.get(timeout=10)
        wait_for(lambda: recorder.metrics_sources(), timeout=10)
        # two sources ride the one topic: the pipeline's registry and
        # the process-global one (deduplicated by source name, so N
        # pipelines cannot inflate the fleet merge)
        wait_for(lambda: pipeline.topic_path
                 in recorder.metrics_sources(), timeout=10)
        source = pipeline.topic_path
        # the global-registry source is keyed by OS pid (NOT the
        # Process object's possibly "-N"-suffixed process_id): every
        # Process object in one interpreter shares one global registry
        import os
        process_source = (f"{process.namespace}/{process.hostname}/"
                          f"{os.getpid()}/process")
        wait_for(lambda: process_source in recorder.metrics_sources(),
                 timeout=10)
        snapshot = wait_for(
            lambda: (recorder.metrics_for(source) or {}).get(
                "counters", {}).get("pipeline.frames_total")
            and recorder.metrics_for(source), timeout=10)
        assert snapshot["counters"]["pipeline.frames_total"] >= 1
        assert recorder.merged_metrics()["counters"][
            "pipeline.frames_total"] >= 1
        # pipeline EC share mirrors the compact summary for dashboards
        wait_for(lambda: isinstance(
            pipeline.share.get("metrics"), dict), timeout=10)
        assert pipeline.share["metrics"]["frames"] >= 1
        process.terminate()

    def test_recorder_flushes_record_count_on_stop(self):
        process = Process(transport_kind="loopback")
        recorder = Recorder(process)
        process.run(in_thread=True)
        log_topic = f"{process.namespace}/host/9/1/log"
        for index in range(5):
            process.publish(log_topic, f"line {index}")
        get_broker().drain()
        wait_for(lambda: len(recorder.records(log_topic)) == 5)
        # modulo-16 rate limit: the live share is still stale...
        assert recorder.share.get("record_count") == 0
        recorder.stop()
        # ...stop() flushes the final count
        assert recorder.share.get("record_count") == 5
        process.terminate()
