"""The bench's FINAL output line must stay inside the driver's capture
window and parse as JSON.  Round 4's record (`BENCH_r04.json`) was
`"parsed": null` because the single output line outgrew the ~2000-char
tail the driver keeps; `compact_headline` is the guard that can never
regress that way again."""

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import bench  # noqa: E402


def _fat_detail():
    """A detail dict sized like the real round-4 output (the one that
    broke the capture window): every config present, long prose fields."""
    configs = {
        "text": {"frames_per_sec": 1891.2, "p50_ms": 1.174,
                 "p50_arrival_ms": 1.062, "drain_per_frame_ms": 0.112,
                 "vs_reference_broker_ceiling": 37.8},
        "asr": {"frames_per_sec_chip": 43.07, "audio_sec_per_sec": 861.4,
                "p50_ms": 26.04, "p50_arrival_ms": 2.62,
                "drain_per_frame_ms": 23.42, "model": "whisper_small",
                "batch": 4, "mfu": 0.026},
        "detector": {"frames_per_sec_chip": 73.62,
                     "images_per_sec": 1177.9, "p50_ms": 10.88,
                     "p50_arrival_ms": 0.34, "drain_per_frame_ms": 10.54,
                     "model": "yolov8n 640x640", "batch": 16,
                     "mfu": 0.0134},
        "llm": {"model": "llama32_1b (1236M params)", "batch": 4,
                "prompt_len": 128, "time_to_first_token_ms": 116.6,
                "tokens_per_sec": 481.0,
                "tokens_per_sec_by_batch": {"batch_16": 1598.5,
                                            "batch_64": 3430.0},
                "decode_mfu": 0.0061},
        "llm_sharded": {"tokens_per_sec": 10.7,
                        "collectives_per_decode_step": 2,
                        "collective_kinds": ["all-reduce"],
                        "mesh": "virtual 8-device CPU (data=2, model=4)",
                        "model": ("llama32_1b architecture at reduced "
                                  "width (16 layers, 32/8 GQA heads, "
                                  "tied embeddings)")},
        "train": {"model": "llama32_1b architecture, 8 layers (749M)",
                  "batch": 4, "seq_len": 1024, "tokens_per_sec": 16914.0,
                  "step_ms": 242.2, "train_mfu": 0.386,
                  "loss_finite": True},
        "longcontext": {"model": "llama32_1b architecture, 8 layers",
                        "batch": 1,
                        "prefill": {"seq_4096": {"tokens_per_sec": 23518.0,
                                                 "prefill_ms": 174.2,
                                                 "mfu": 0.1322},
                                    "seq_16384": {"tokens_per_sec": 8445.4,
                                                  "prefill_ms": 1940.0,
                                                  "mfu": 0.0647}}},
        "serving": {"streams": 32, "frames_per_sec_total": 591.5,
                    "coalesced_trials": [591.5, 1030.2, 1895.8, 1766.4,
                                         1820.9],
                    "coalesced_spread": [591.5, 1895.8],
                    "frames_per_sec_uncoalesced": 1617.2,
                    "uncoalesced_trials": [1617.2, 1084.3, 1216.9,
                                           1153.0, 1201.4],
                    "uncoalesced_spread": [1084.3, 1617.2],
                    "coalescing_speedup": 0.37, "trials_per_arm": 5,
                    "micro_batch": 16,
                    "model": "yolov8n 640x640",
                    "vs_reference_broker_ceiling": 11.8, "mfu": 0.0067},
        "latency": {"frames_per_sec_chip": 11.2, "p50_ms": 96.4,
                    "p50_arrival_ms": 92.1, "drain_per_frame_ms": 4.3,
                    "audio_seconds_per_frame": 5.0, "rows_per_frame": 2,
                    "micro_batch": 1, "frame_window": 1,
                    "operating_point": "latency (one frame in flight)",
                    "stages": ("whisper_small -> (text, llama32_1b "
                               "decode -> reply text) + yolov8n-640 -> "
                               "detections"),
                    "mfu": 0.011},
        "tts": {"frames_per_sec_chip": 24.55, "p50_ms": 132.4,
                "p50_arrival_ms": 1.13, "drain_per_frame_ms": 131.27,
                "audio_seconds_per_frame": 25.8,
                "speech_sec_per_sec": 633.3, "batch": 8, "mfu": 0.0032},
        "pipeline_multimodal": {
            "frames_per_sec_chip": 6.94, "p50_ms": 447.15,
            "p50_arrival_ms": 443.46, "drain_per_frame_ms": 3.7,
            "audio_seconds_per_frame": 5.0, "rows_per_frame": 16,
            "audio_realtime_factor": 555.32,
            "tokens_generated_per_frame": 512,
            "stages": ("whisper_small -> (text, llama32_1b decode -> "
                       "reply text) + yolov8n-640 -> detections"),
            "micro_batch": 4, "mfu": 0.0964},
    }
    return {
        "metric": "multimodal_pipeline_frames_per_sec",
        "value": 6.94,
        "unit": ("frames/sec end-to-end (3-stage speech+LM+vision graph, "
                 "HBM-resident, 1 chip)"),
        "vs_baseline": 92.55,
        "baseline": ("reference whisper-small single-GPU speech stage at "
                     "6x realtime"),
        "p50_frame_latency_ms": 447.15,
        "device": "TPU v5 lite",
        "peak_tflops_assumed": 197.0,
        "smoke": False,
        "configs": configs,
    }


def test_headline_line_fits_capture_window_and_parses():
    line = bench.compact_headline(_fat_detail())
    assert len(line) <= bench.HEADLINE_LINE_CAP
    parsed = json.loads(line)
    assert parsed["metric"] == "multimodal_pipeline_frames_per_sec"
    assert parsed["value"] == 6.94
    assert parsed["vs_baseline"] == 92.55
    # the per-config summary survives at this size
    assert parsed["summary"]["headline_mfu"] == 0.0964
    assert parsed["summary"]["serving_speedup"] == 0.37


def test_headline_line_cap_is_inside_driver_tail_window():
    # the driver keeps ~2000 chars; the cap must leave room for the
    # newline plus part of the preceding detail line being present
    assert bench.HEADLINE_LINE_CAP <= 1500


def test_headline_drops_fields_rather_than_overflow():
    detail = _fat_detail()
    detail["unit"] = "x" * 2000  # pathological prose field
    line = bench.compact_headline(detail)
    assert len(line) <= bench.HEADLINE_LINE_CAP
    parsed = json.loads(line)
    # the essentials can never be dropped
    assert parsed["metric"] and parsed["vs_baseline"] == 92.55


def test_headline_survives_device_fallback_field():
    detail = _fat_detail()
    detail["device_fallback"] = ("device init probe timed out after "
                                 "120s; measured smoke-scale on CPU")
    detail["smoke"] = True
    line = bench.compact_headline(detail)
    assert len(line) <= bench.HEADLINE_LINE_CAP
    assert json.loads(line)["smoke"] is True


def test_subset_runs_do_not_clobber_detail_file(tmp_path, monkeypatch):
    """A subset bench run must never overwrite BENCH_DETAIL.json -- the
    repo's committed end-to-end evidence record (round-5 review
    finding: an llm-only run replaced the full record with a partial
    one whose headline masqueraded as the pipeline metric)."""
    import subprocess

    repo = Path(__file__).resolve().parent.parent
    detail = repo / "BENCH_DETAIL.json"
    before = detail.read_text() if detail.exists() else None
    import os
    env = dict(os.environ)
    env.update(AIKO_BENCH_SMOKE="1", AIKO_BENCH_PROBE="0",
               AIKO_BENCH_PLATFORM="cpu", AIKO_BENCH_CONFIGS="text",
               JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    result = subprocess.run(
        [sys.executable, str(repo / "bench.py")], env=env,
        capture_output=True, text=True, timeout=600)
    assert result.returncode == 0, result.stderr[-2000:]
    final = result.stdout.strip().splitlines()[-1]
    parsed = json.loads(final)
    # honest labeling: a subset headline names its config
    assert parsed["metric"] == "text_headline_subset_run"
    after = detail.read_text() if detail.exists() else None
    assert after == before, "subset run clobbered BENCH_DETAIL.json"
