# Test configuration: force JAX onto a virtual 8-device CPU mesh BEFORE jax
# is imported anywhere, so sharding/collective tests run without TPU hardware.

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("AIKO_NAMESPACE", "aiko_test")
