# Test configuration: force JAX onto a virtual 8-device CPU mesh so
# sharding/collective tests run without TPU hardware.
#
# The environment's sitecustomize imports jax at interpreter start (before
# conftest), so setting JAX_PLATFORMS via os.environ is too late -- we must
# update jax.config directly.  XLA_FLAGS still works because the CPU backend
# client is created lazily on first device access.

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("AIKO_NAMESPACE", "aiko_test")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert len(jax.devices()) == 8, (
    "tests need the virtual 8-device CPU mesh; got "
    f"{jax.devices()}")
