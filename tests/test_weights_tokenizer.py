# Weight ingestion + tokenizer: safetensors round-trip (incl. bf16), HF
# Llama naming -> framework pytree parity, BPE train/encode/decode
# round-trips, HF tokenizer.json loading, and the streamed decode path.

import json

import jax
import jax.numpy as jnp
import ml_dtypes
import numpy as np
import pytest

from aiko_services_tpu.models import (
    BPETokenizer, TransformerConfig, forward, generate, generate_stream,
    init_params, load_llama_params, load_pytree, read_safetensors,
    save_pytree, train_bpe, write_safetensors)
from aiko_services_tpu.models.configs import (
    LLAMA3_8B, WHISPER_SMALL, YOLOV8N_SHAPE, transformer_flops_per_token)


# -- safetensors container ---------------------------------------------------

def test_safetensors_roundtrip(tmp_path):
    tensors = {
        "a": np.arange(12, dtype=np.float32).reshape(3, 4),
        "b.c": np.ones((2, 2), dtype=ml_dtypes.bfloat16),
        "ints": np.array([1, 2, 3], dtype=np.int64),
    }
    path = tmp_path / "t.safetensors"
    write_safetensors(path, tensors, metadata={"format": "pt"})
    loaded = read_safetensors(path)
    assert set(loaded) == set(tensors)
    for name in tensors:
        assert loaded[name].dtype == tensors[name].dtype
        np.testing.assert_array_equal(
            np.asarray(loaded[name], np.float64),
            np.asarray(tensors[name], np.float64))


def test_pytree_roundtrip(tmp_path):
    tree = {"layer": {"w": np.ones((2, 3), np.float32),
                      "b": np.zeros((3,), np.float32)},
            "top": np.full((4,), 2.0, np.float32)}
    path = tmp_path / "p.safetensors"
    save_pytree(path, tree)
    back = load_pytree(path)
    assert back["layer"]["w"].shape == (2, 3)
    assert back["top"][0] == 2.0
    cast = load_pytree(path, dtype="bfloat16")
    assert cast["layer"]["w"].dtype == ml_dtypes.bfloat16


def _tiny_config():
    return TransformerConfig(
        vocab_size=64, d_model=16, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=32, max_seq_len=32, dtype="float32")


def _write_hf_llama(path, config, seed=0, lm_head=False):
    """Fake HF-named checkpoint with HF (out, in) weight layout."""
    rng = np.random.default_rng(seed)
    hd = config.head_dim
    tensors = {
        "model.embed_tokens.weight": rng.standard_normal(
            (config.vocab_size, config.d_model)).astype(np.float32),
        "model.norm.weight": np.ones((config.d_model,), np.float32),
    }
    if lm_head:
        tensors["lm_head.weight"] = rng.standard_normal(
            (config.vocab_size, config.d_model)).astype(np.float32)
    for layer in range(config.n_layers):
        prefix = f"model.layers.{layer}."
        tensors.update({
            prefix + "input_layernorm.weight":
                np.ones((config.d_model,), np.float32),
            prefix + "post_attention_layernorm.weight":
                np.ones((config.d_model,), np.float32),
            prefix + "self_attn.q_proj.weight": rng.standard_normal(
                (config.n_heads * hd, config.d_model)).astype(np.float32),
            prefix + "self_attn.k_proj.weight": rng.standard_normal(
                (config.n_kv_heads * hd,
                 config.d_model)).astype(np.float32),
            prefix + "self_attn.v_proj.weight": rng.standard_normal(
                (config.n_kv_heads * hd,
                 config.d_model)).astype(np.float32),
            prefix + "self_attn.o_proj.weight": rng.standard_normal(
                (config.d_model, config.n_heads * hd)).astype(np.float32),
            prefix + "mlp.gate_proj.weight": rng.standard_normal(
                (config.d_ff, config.d_model)).astype(np.float32),
            prefix + "mlp.up_proj.weight": rng.standard_normal(
                (config.d_ff, config.d_model)).astype(np.float32),
            prefix + "mlp.down_proj.weight": rng.standard_normal(
                (config.d_model, config.d_ff)).astype(np.float32),
        })
    write_safetensors(path, tensors)
    return tensors


def test_load_llama_params_shapes_and_orientation(tmp_path):
    config = _tiny_config()
    path = tmp_path / "model.safetensors"
    tensors = _write_hf_llama(path, config)
    params = load_llama_params(path, config)
    hd = config.head_dim
    assert params["embed"]["w"].shape == (config.vocab_size, config.d_model)
    assert params["layers"]["wq"]["w"].shape == (
        config.n_layers, config.d_model, config.n_heads * hd)
    # orientation: our wq.w must be the transpose of HF q_proj
    np.testing.assert_allclose(
        np.asarray(params["layers"]["wq"]["w"][0]),
        tensors["model.layers.0.self_attn.q_proj.weight"].T, rtol=1e-6)
    # loaded params run end-to-end
    logits = forward(params, config, jnp.ones((1, 4), jnp.int32))
    assert logits.shape == (1, 4, config.vocab_size)
    assert bool(jnp.isfinite(logits).all())


def test_load_llama_untied_head_changes_logits(tmp_path):
    config = _tiny_config()
    tied = tmp_path / "tied.safetensors"
    untied = tmp_path / "untied.safetensors"
    _write_hf_llama(tied, config, seed=1)
    _write_hf_llama(untied, config, seed=1, lm_head=True)
    params_tied = load_llama_params(tied, config)
    params_untied = load_llama_params(untied, config)
    assert "lm_head" in params_untied and "lm_head" not in params_tied
    tokens = jnp.ones((1, 4), jnp.int32)
    a = forward(params_tied, config, tokens)
    b = forward(params_untied, config, tokens)
    assert not np.allclose(np.asarray(a), np.asarray(b))


def test_load_llama_sharded_on_mesh(tmp_path):
    from aiko_services_tpu.models import param_specs
    from aiko_services_tpu.parallel.mesh import create_mesh
    config = _tiny_config()
    path = tmp_path / "model.safetensors"
    _write_hf_llama(path, config)
    mesh = create_mesh({"data": 2, "fsdp": 1, "seq": 2, "model": 2})
    params = load_llama_params(path, config, mesh=mesh,
                               specs=param_specs(config))
    wq = params["layers"]["wq"]["w"]
    assert len(wq.sharding.device_set) == 8
    with jax.set_mesh(mesh):
        logits = forward(params, config, jnp.ones((2, 4), jnp.int32))
    assert bool(jnp.isfinite(logits).all())


def test_missing_tensor_raises(tmp_path):
    config = _tiny_config()
    path = tmp_path / "broken.safetensors"
    tensors = _write_hf_llama(path, config)
    del tensors["model.layers.1.mlp.up_proj.weight"]
    write_safetensors(path, tensors)
    with pytest.raises(KeyError, match="mlp.up_proj"):
        load_llama_params(path, config)


# -- tokenizer ---------------------------------------------------------------

def test_bpe_train_roundtrip():
    corpus = ["the pipeline processes frames of tokens",
              "frames flow through the pipeline elements"] * 10
    tokenizer = train_bpe(corpus, vocab_size=300)
    for text in ["the pipeline", "unseen wørds 123!", "  spaced  out  "]:
        assert tokenizer.decode(tokenizer.encode(text)) == text
    ids = tokenizer.encode("the pipeline", bos=True, eos=True)
    assert ids[0] == tokenizer.bos_id and ids[-1] == tokenizer.eos_id


def test_default_asset_loads_and_compresses():
    tokenizer = BPETokenizer.default()
    text = "The pipeline processes frames through elements."
    ids = tokenizer.encode(text)
    assert tokenizer.decode(ids) == text
    assert len(ids) < len(text) / 2  # real merges, not bytes


def test_hf_tokenizer_json_format(tmp_path):
    base = train_bpe(["hello world hello there"], vocab_size=280)
    hf = {
        "model": {
            "vocab": base.vocab,
            "merges": [f"{a} {b}" for a, b in base.merges],
        },
        "added_tokens": [
            {"id": 0, "content": "<|begin_of_text|>"},
            {"id": 1, "content": "<|end_of_text|>"},
        ],
    }
    path = tmp_path / "tokenizer.json"
    path.write_text(json.dumps(hf))
    tokenizer = BPETokenizer.from_file(path)
    assert tokenizer.bos_id == 0 and tokenizer.eos_id == 1
    assert tokenizer.decode(tokenizer.encode("hello world")) == (
        "hello world")


# -- presets + analytics -----------------------------------------------------

def test_reference_scale_configs():
    # Llama-3-8B ~8.0B params; Whisper-small ~240M (analytic counts)
    def lm_params(c):
        hd = c.head_dim
        per_layer = (c.d_model * hd * (c.n_heads * 2 + c.n_kv_heads * 2)
                     + 3 * c.d_model * c.d_ff + 2 * c.d_model)
        return (c.vocab_size * c.d_model * 2   # embed + untied head
                + c.n_layers * per_layer + c.d_model)
    total = lm_params(LLAMA3_8B)
    assert 7.5e9 < total < 8.6e9
    assert WHISPER_SMALL.d_model == 768 and WHISPER_SMALL.enc_layers == 12
    assert YOLOV8N_SHAPE.image_size == 640
    assert YOLOV8N_SHAPE.n_classes == 80
    flops = transformer_flops_per_token(LLAMA3_8B)
    assert 1.3e10 < flops < 2.0e10  # ~2*7B matmul params


# -- streamed decode ---------------------------------------------------------

def test_generate_stream_matches_generate():
    config = _tiny_config()
    params = init_params(config, jax.random.PRNGKey(0))
    prompt = jnp.array([[5, 6, 7]], jnp.int32)
    full, _ = generate(params, config, prompt, max_new_tokens=9)
    chunks = list(generate_stream(params, config, prompt,
                                  max_new_tokens=9, chunk=4))
    # first token streams immediately after prefill (TTFT), then chunks
    assert [offset for offset, _ in chunks] == [0, 1, 5]
    assert [block.shape[1] for _, block in chunks] == [1, 4, 4]
    streamed = np.concatenate([block for _, block in chunks], axis=1)
    np.testing.assert_array_equal(np.asarray(full), streamed)
