# Weight ingestion + tokenizer: safetensors round-trip (incl. bf16), HF
# Llama naming -> framework pytree parity, BPE train/encode/decode
# round-trips, HF tokenizer.json loading, and the streamed decode path.

import json

import jax
import jax.numpy as jnp
import ml_dtypes
import numpy as np
import pytest

from aiko_services_tpu.models import (
    BPETokenizer, TransformerConfig, forward, generate, generate_stream,
    init_params, load_llama_params, load_pytree, read_safetensors,
    save_pytree, train_bpe, write_safetensors)
from aiko_services_tpu.models.configs import (
    LLAMA3_8B, WHISPER_SMALL, YOLOV8N_SHAPE, transformer_flops_per_token)


# -- safetensors container ---------------------------------------------------

def test_safetensors_roundtrip(tmp_path):
    tensors = {
        "a": np.arange(12, dtype=np.float32).reshape(3, 4),
        "b.c": np.ones((2, 2), dtype=ml_dtypes.bfloat16),
        "ints": np.array([1, 2, 3], dtype=np.int64),
    }
    path = tmp_path / "t.safetensors"
    write_safetensors(path, tensors, metadata={"format": "pt"})
    loaded = read_safetensors(path)
    assert set(loaded) == set(tensors)
    for name in tensors:
        assert loaded[name].dtype == tensors[name].dtype
        np.testing.assert_array_equal(
            np.asarray(loaded[name], np.float64),
            np.asarray(tensors[name], np.float64))


def test_pytree_roundtrip(tmp_path):
    tree = {"layer": {"w": np.ones((2, 3), np.float32),
                      "b": np.zeros((3,), np.float32)},
            "top": np.full((4,), 2.0, np.float32)}
    path = tmp_path / "p.safetensors"
    save_pytree(path, tree)
    back = load_pytree(path)
    assert back["layer"]["w"].shape == (2, 3)
    assert back["top"][0] == 2.0
    cast = load_pytree(path, dtype="bfloat16")
    assert cast["layer"]["w"].dtype == ml_dtypes.bfloat16


def _tiny_config():
    return TransformerConfig(
        vocab_size=64, d_model=16, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=32, max_seq_len=32, dtype="float32")


def _write_hf_llama(path, config, seed=0, lm_head=False):
    """Fake HF-named checkpoint with HF (out, in) weight layout."""
    rng = np.random.default_rng(seed)
    hd = config.head_dim
    tensors = {
        "model.embed_tokens.weight": rng.standard_normal(
            (config.vocab_size, config.d_model)).astype(np.float32),
        "model.norm.weight": np.ones((config.d_model,), np.float32),
    }
    if lm_head:
        tensors["lm_head.weight"] = rng.standard_normal(
            (config.vocab_size, config.d_model)).astype(np.float32)
    for layer in range(config.n_layers):
        prefix = f"model.layers.{layer}."
        tensors.update({
            prefix + "input_layernorm.weight":
                np.ones((config.d_model,), np.float32),
            prefix + "post_attention_layernorm.weight":
                np.ones((config.d_model,), np.float32),
            prefix + "self_attn.q_proj.weight": rng.standard_normal(
                (config.n_heads * hd, config.d_model)).astype(np.float32),
            prefix + "self_attn.k_proj.weight": rng.standard_normal(
                (config.n_kv_heads * hd,
                 config.d_model)).astype(np.float32),
            prefix + "self_attn.v_proj.weight": rng.standard_normal(
                (config.n_kv_heads * hd,
                 config.d_model)).astype(np.float32),
            prefix + "self_attn.o_proj.weight": rng.standard_normal(
                (config.d_model, config.n_heads * hd)).astype(np.float32),
            prefix + "mlp.gate_proj.weight": rng.standard_normal(
                (config.d_ff, config.d_model)).astype(np.float32),
            prefix + "mlp.up_proj.weight": rng.standard_normal(
                (config.d_ff, config.d_model)).astype(np.float32),
            prefix + "mlp.down_proj.weight": rng.standard_normal(
                (config.d_model, config.d_ff)).astype(np.float32),
        })
    write_safetensors(path, tensors)
    return tensors


def test_load_llama_params_shapes_and_orientation(tmp_path):
    config = _tiny_config()
    path = tmp_path / "model.safetensors"
    tensors = _write_hf_llama(path, config)
    params = load_llama_params(path, config)
    hd = config.head_dim
    assert params["embed"]["w"].shape == (config.vocab_size, config.d_model)
    assert params["layers"]["wq"]["w"].shape == (
        config.n_layers, config.d_model, config.n_heads * hd)
    # orientation: our wq.w must be the transpose of HF q_proj
    np.testing.assert_allclose(
        np.asarray(params["layers"]["wq"]["w"][0]),
        tensors["model.layers.0.self_attn.q_proj.weight"].T, rtol=1e-6)
    # loaded params run end-to-end
    logits = forward(params, config, jnp.ones((1, 4), jnp.int32))
    assert logits.shape == (1, 4, config.vocab_size)
    assert bool(jnp.isfinite(logits).all())


def test_load_llama_untied_head_changes_logits(tmp_path):
    config = _tiny_config()
    tied = tmp_path / "tied.safetensors"
    untied = tmp_path / "untied.safetensors"
    _write_hf_llama(tied, config, seed=1)
    _write_hf_llama(untied, config, seed=1, lm_head=True)
    params_tied = load_llama_params(tied, config)
    params_untied = load_llama_params(untied, config)
    assert "lm_head" in params_untied and "lm_head" not in params_tied
    tokens = jnp.ones((1, 4), jnp.int32)
    a = forward(params_tied, config, tokens)
    b = forward(params_untied, config, tokens)
    assert not np.allclose(np.asarray(a), np.asarray(b))


def test_load_llama_sharded_on_mesh(tmp_path):
    from aiko_services_tpu.models import param_specs
    from aiko_services_tpu.parallel.mesh import create_mesh
    config = _tiny_config()
    path = tmp_path / "model.safetensors"
    _write_hf_llama(path, config)
    mesh = create_mesh({"data": 2, "fsdp": 1, "seq": 2, "model": 2})
    params = load_llama_params(path, config, mesh=mesh,
                               specs=param_specs(config))
    wq = params["layers"]["wq"]["w"]
    assert len(wq.sharding.device_set) == 8
    with jax.set_mesh(mesh):
        logits = forward(params, config, jnp.ones((2, 4), jnp.int32))
    assert bool(jnp.isfinite(logits).all())


def test_missing_tensor_raises(tmp_path):
    config = _tiny_config()
    path = tmp_path / "broken.safetensors"
    tensors = _write_hf_llama(path, config)
    del tensors["model.layers.1.mlp.up_proj.weight"]
    write_safetensors(path, tensors)
    with pytest.raises(KeyError, match="mlp.up_proj"):
        load_llama_params(path, config)


# -- tokenizer ---------------------------------------------------------------

def test_bpe_train_roundtrip():
    corpus = ["the pipeline processes frames of tokens",
              "frames flow through the pipeline elements"] * 10
    tokenizer = train_bpe(corpus, vocab_size=300)
    for text in ["the pipeline", "unseen wørds 123!", "  spaced  out  "]:
        assert tokenizer.decode(tokenizer.encode(text)) == text
    ids = tokenizer.encode("the pipeline", bos=True, eos=True)
    assert ids[0] == tokenizer.bos_id and ids[-1] == tokenizer.eos_id


def test_default_asset_loads_and_compresses():
    tokenizer = BPETokenizer.default()
    text = "The pipeline processes frames through elements."
    ids = tokenizer.encode(text)
    assert tokenizer.decode(ids) == text
    assert len(ids) < len(text) / 2  # real merges, not bytes


def test_hf_tokenizer_json_format(tmp_path):
    base = train_bpe(["hello world hello there"], vocab_size=280)
    hf = {
        "model": {
            "vocab": base.vocab,
            "merges": [f"{a} {b}" for a, b in base.merges],
        },
        "added_tokens": [
            {"id": 0, "content": "<|begin_of_text|>"},
            {"id": 1, "content": "<|end_of_text|>"},
        ],
    }
    path = tmp_path / "tokenizer.json"
    path.write_text(json.dumps(hf))
    tokenizer = BPETokenizer.from_file(path)
    assert tokenizer.bos_id == 0 and tokenizer.eos_id == 1
    assert tokenizer.decode(tokenizer.encode("hello world")) == (
        "hello world")


# -- presets + analytics -----------------------------------------------------

def test_reference_scale_configs():
    # Llama-3-8B ~8.0B params; Whisper-small ~240M (analytic counts)
    def lm_params(c):
        hd = c.head_dim
        per_layer = (c.d_model * hd * (c.n_heads * 2 + c.n_kv_heads * 2)
                     + 3 * c.d_model * c.d_ff + 2 * c.d_model)
        return (c.vocab_size * c.d_model * 2   # embed + untied head
                + c.n_layers * per_layer + c.d_model)
    total = lm_params(LLAMA3_8B)
    assert 7.5e9 < total < 8.6e9
    assert WHISPER_SMALL.d_model == 768 and WHISPER_SMALL.enc_layers == 12
    assert YOLOV8N_SHAPE.image_size == 640
    assert YOLOV8N_SHAPE.n_classes == 80
    flops = transformer_flops_per_token(LLAMA3_8B)
    assert 1.3e10 < flops < 2.0e10  # ~2*7B matmul params


# -- streamed decode ---------------------------------------------------------

def test_generate_stream_matches_generate():
    config = _tiny_config()
    params = init_params(config, jax.random.PRNGKey(0))
    prompt = jnp.array([[5, 6, 7]], jnp.int32)
    full, _ = generate(params, config, prompt, max_new_tokens=9)
    chunks = list(generate_stream(params, config, prompt,
                                  max_new_tokens=9, chunk=4))
    # first token streams immediately after prefill (TTFT), then chunks
    assert [offset for offset, _ in chunks] == [0, 1, 5]
    assert [block.shape[1] for _, block in chunks] == [1, 4, 4]
    streamed = np.concatenate([block for _, block in chunks], axis=1)
    np.testing.assert_array_equal(np.asarray(full), streamed)


# -- whisper checkpoint ingestion --------------------------------------------

def _tiny_asr_config():
    from aiko_services_tpu.models import AsrConfig
    return AsrConfig(
        n_mels=8, d_model=16, enc_layers=2, dec_layers=2, n_heads=4,
        vocab_size=64, max_frames=16, max_text_len=12, dtype="float32")


def _write_hf_whisper(path, config, seed=0):
    """Fake HF openai/whisper-* checkpoint: HF (out, in) linear layout,
    biases on q/v/out + fc + norms, NO bias on k_proj, 30 s-sized
    positional tables (longer than the config windows)."""
    rng = np.random.default_rng(seed)
    d = config.d_model

    def t(*shape):
        return rng.standard_normal(shape).astype(np.float32) * 0.05

    tensors = {
        "model.encoder.conv1.weight": t(d, config.n_mels, 3),
        "model.encoder.conv1.bias": t(d),
        "model.encoder.conv2.weight": t(d, d, 3),
        "model.encoder.conv2.bias": t(d),
        "model.encoder.embed_positions.weight": t(config.max_frames + 8, d),
        "model.encoder.layer_norm.weight": t(d),
        "model.encoder.layer_norm.bias": t(d),
        "model.decoder.embed_tokens.weight": t(config.vocab_size, d),
        "model.decoder.embed_positions.weight": t(
            config.max_text_len + 8, d),
        "model.decoder.layer_norm.weight": t(d),
        "model.decoder.layer_norm.bias": t(d),
    }

    def attention(prefix):
        tensors[prefix + "q_proj.weight"] = t(d, d)
        tensors[prefix + "q_proj.bias"] = t(d)
        tensors[prefix + "k_proj.weight"] = t(d, d)  # no bias (HF whisper)
        tensors[prefix + "v_proj.weight"] = t(d, d)
        tensors[prefix + "v_proj.bias"] = t(d)
        tensors[prefix + "out_proj.weight"] = t(d, d)
        tensors[prefix + "out_proj.bias"] = t(d)

    for layer in range(config.enc_layers):
        prefix = f"model.encoder.layers.{layer}."
        attention(prefix + "self_attn.")
        tensors[prefix + "self_attn_layer_norm.weight"] = t(d)
        tensors[prefix + "self_attn_layer_norm.bias"] = t(d)
        tensors[prefix + "fc1.weight"] = t(4 * d, d)
        tensors[prefix + "fc1.bias"] = t(4 * d)
        tensors[prefix + "fc2.weight"] = t(d, 4 * d)
        tensors[prefix + "fc2.bias"] = t(d)
        tensors[prefix + "final_layer_norm.weight"] = t(d)
        tensors[prefix + "final_layer_norm.bias"] = t(d)
    for layer in range(config.dec_layers):
        prefix = f"model.decoder.layers.{layer}."
        attention(prefix + "self_attn.")
        attention(prefix + "encoder_attn.")
        tensors[prefix + "self_attn_layer_norm.weight"] = t(d)
        tensors[prefix + "self_attn_layer_norm.bias"] = t(d)
        tensors[prefix + "encoder_attn_layer_norm.weight"] = t(d)
        tensors[prefix + "encoder_attn_layer_norm.bias"] = t(d)
        tensors[prefix + "fc1.weight"] = t(4 * d, d)
        tensors[prefix + "fc1.bias"] = t(4 * d)
        tensors[prefix + "fc2.weight"] = t(d, 4 * d)
        tensors[prefix + "fc2.bias"] = t(d)
        tensors[prefix + "final_layer_norm.weight"] = t(d)
        tensors[prefix + "final_layer_norm.bias"] = t(d)
    write_safetensors(path, tensors)
    return tensors


def test_load_whisper_params_shapes_orientation_and_forward(tmp_path):
    from aiko_services_tpu.models import asr_forward, load_whisper_params
    config = _tiny_asr_config()
    path = tmp_path / "whisper.safetensors"
    tensors = _write_hf_whisper(path, config)
    params = load_whisper_params(path, config)
    # conv layout passes through untransposed (d, in, k)
    assert params["conv1"]["w"].shape == (config.d_model, config.n_mels, 3)
    # linear orientation: ours is HF transposed, bias carried
    np.testing.assert_allclose(
        np.asarray(params["enc_layers"]["attn"]["wq"]["w"][0]),
        tensors["model.encoder.layers.0.self_attn.q_proj.weight"].T,
        rtol=1e-6)
    assert "b" in params["enc_layers"]["attn"]["wq"]
    assert "b" not in params["enc_layers"]["attn"]["wk"]  # HF k_proj
    assert "bias" in params["dec_layers"]["cross_norm"]
    # positional tables sliced to the serving windows
    assert params["enc_positions"].shape == (config.max_frames,
                                             config.d_model)
    assert params["dec_positions"].shape == (config.max_text_len,
                                             config.d_model)
    # stacked layers run end-to-end through the jitted forward
    mel = jnp.ones((1, config.n_mels, 24), jnp.float32)
    tokens = jnp.ones((1, 4), jnp.int32)
    logits = asr_forward(params, config, mel, tokens)
    assert logits.shape == (1, 4, config.vocab_size)
    assert bool(jnp.isfinite(logits).all())


def test_whisper_biases_change_output(tmp_path):
    """The bias terms must actually flow through the forward: zeroing
    them changes logits (guards against a map that loads-but-drops)."""
    from aiko_services_tpu.models import asr_forward, load_whisper_params
    config = _tiny_asr_config()
    path = tmp_path / "whisper.safetensors"
    _write_hf_whisper(path, config, seed=3)
    params = load_whisper_params(path, config)
    mel = jnp.ones((1, config.n_mels, 24), jnp.float32)
    tokens = jnp.ones((1, 4), jnp.int32)
    base = np.asarray(asr_forward(params, config, mel, tokens))
    stripped = jax.tree_util.tree_map(lambda leaf: leaf, params)
    stripped["dec_norm"] = {
        "scale": params["dec_norm"]["scale"],
        "bias": jnp.zeros_like(params["dec_norm"]["bias"])}
    changed = np.asarray(asr_forward(stripped, config, mel, tokens))
    assert not np.allclose(base, changed)
    stripped_fc = jax.tree_util.tree_map(lambda leaf: leaf, params)
    stripped_fc["dec_layers"]["mlp"]["w1"] = {
        "w": params["dec_layers"]["mlp"]["w1"]["w"],
        "b": jnp.zeros_like(params["dec_layers"]["mlp"]["w1"]["b"])}
    changed_fc = np.asarray(asr_forward(stripped_fc, config, mel, tokens))
    assert not np.allclose(base, changed_fc)


def test_speech_to_text_element_ingests_hf_whisper(tmp_path):
    """The element probes the container and loads HF naming with no code
    changes (reference speech_elements.py:229 runs pretrained whisper)."""
    import queue
    from aiko_services_tpu.pipeline import create_pipeline
    from aiko_services_tpu.runtime import Process
    config = _tiny_asr_config()
    path = tmp_path / "whisper.safetensors"
    _write_hf_whisper(path, config)
    definition = {
        "name": "asr_hf",
        "graph": ["(tone (asr))"],
        "elements": [
            {"name": "tone", "output": [{"name": "audio"}],
             "parameters": {"data_sources": [[220, 0.2]]},
             "deploy": {"local": {"module": "aiko_services_tpu.elements",
                                  "class_name": "ToneSource"}}},
            {"name": "asr", "input": [{"name": "audio"}],
             "output": [{"name": "tokens"}],
             "parameters": {"d_model": config.d_model, "n_mels": 8,
                            "enc_layers": config.enc_layers,
                            "dec_layers": config.dec_layers,
                            "n_heads": config.n_heads,
                            "vocab_size": config.vocab_size,
                            "max_frames": config.max_frames,
                            "max_tokens": 4, "dtype": "float32",
                            "weights": str(path)},
             "deploy": {"local": {"module": "aiko_services_tpu.elements",
                                  "class_name": "SpeechToText"}}},
        ],
    }
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, definition)
    process.run(in_thread=True)
    responses = queue.Queue()
    pipeline.create_stream("s", queue_response=responses)
    _, _, outputs = responses.get(timeout=30)
    assert np.asarray(outputs["tokens"]).shape == (1, 4)
    process.terminate()


# -- yolov8 checkpoint ingestion ---------------------------------------------

def _tiny_yolo_config():
    from aiko_services_tpu.models import YoloV8Config
    return YoloV8Config(
        n_classes=4, width=(4, 8, 16, 32, 64), repeats=(1, 2, 2, 1),
        image_size=64, max_detections=8, score_threshold=0.01,
        dtype="float32")


def _write_ultralytics_yolo(path, config, seed=0):
    """Fake ultralytics YOLOv8 state_dict: torch (O, I, kh, kw) conv
    weights + separate BatchNorm tensors; head's final 1x1 convs are
    plain conv2d with bias."""
    rng = np.random.default_rng(seed)
    tensors = {}

    def conv_bn(stem, c_in, c_out, k):
        tensors[f"{stem}.conv.weight"] = (
            rng.standard_normal((c_out, c_in, k, k)).astype(np.float32)
            * 0.1)
        tensors[f"{stem}.bn.weight"] = rng.uniform(
            0.5, 1.5, c_out).astype(np.float32)
        tensors[f"{stem}.bn.bias"] = (
            rng.standard_normal(c_out).astype(np.float32) * 0.1)
        tensors[f"{stem}.bn.running_mean"] = (
            rng.standard_normal(c_out).astype(np.float32) * 0.1)
        tensors[f"{stem}.bn.running_var"] = rng.uniform(
            0.5, 2.0, c_out).astype(np.float32)

    def plain(stem, c_in, c_out):
        tensors[f"{stem}.weight"] = (
            rng.standard_normal((c_out, c_in, 1, 1)).astype(np.float32)
            * 0.1)
        tensors[f"{stem}.bias"] = (
            rng.standard_normal(c_out).astype(np.float32) * 0.1)

    def c2f(module, c_in, c_out, n):
        half = c_out // 2
        conv_bn(f"model.{module}.cv1", c_in, c_out, 1)
        conv_bn(f"model.{module}.cv2", (2 + n) * half, c_out, 1)
        for i in range(n):
            conv_bn(f"model.{module}.m.{i}.cv1", half, half, 3)
            conv_bn(f"model.{module}.m.{i}.cv2", half, half, 3)

    w, r = config.width, config.repeats
    conv_bn("model.0", 3, w[0], 3)
    conv_bn("model.1", w[0], w[1], 3)
    c2f(2, w[1], w[1], r[0])
    conv_bn("model.3", w[1], w[2], 3)
    c2f(4, w[2], w[2], r[1])
    conv_bn("model.5", w[2], w[3], 3)
    c2f(6, w[3], w[3], r[2])
    conv_bn("model.7", w[3], w[4], 3)
    c2f(8, w[4], w[4], r[3])
    conv_bn("model.9.cv1", w[4], w[4] // 2, 1)
    conv_bn("model.9.cv2", w[4] * 2, w[4], 1)
    c2f(12, w[4] + w[3], w[3], 1)
    c2f(15, w[3] + w[2], w[2], 1)
    conv_bn("model.16", w[2], w[2], 3)
    c2f(18, w[3] + w[2], w[3], 1)
    conv_bn("model.19", w[3], w[3], 3)
    c2f(21, w[4] + w[3], w[4], 1)
    box_c, cls_c = config.head_box_hidden, config.head_cls_hidden
    for scale, c_in in enumerate((w[2], w[3], w[4])):
        conv_bn(f"model.22.cv2.{scale}.0", c_in, box_c, 3)
        conv_bn(f"model.22.cv2.{scale}.1", box_c, box_c, 3)
        plain(f"model.22.cv2.{scale}.2", box_c, 4 * config.reg_max)
        conv_bn(f"model.22.cv3.{scale}.0", c_in, cls_c, 3)
        conv_bn(f"model.22.cv3.{scale}.1", cls_c, cls_c, 3)
        plain(f"model.22.cv3.{scale}.2", cls_c, config.n_classes)
    write_safetensors(path, tensors)
    return tensors


def test_load_yolov8_structure_matches_init(tmp_path):
    from aiko_services_tpu.models import init_yolo_params, load_yolov8_params
    config = _tiny_yolo_config()
    path = tmp_path / "yolo.safetensors"
    _write_ultralytics_yolo(path, config)
    loaded = load_yolov8_params(path, config)
    initialized = init_yolo_params(config, jax.random.PRNGKey(0))
    assert (jax.tree_util.tree_structure(loaded)
            == jax.tree_util.tree_structure(initialized))
    same_shapes = jax.tree_util.tree_map(
        lambda a, b: a.shape == b.shape, loaded, initialized)
    assert all(jax.tree_util.tree_leaves(same_shapes))


def test_yolov8_bn_folding_is_numerically_exact(tmp_path):
    """conv2d(folded_params) must equal BatchNorm(conv(x)) computed the
    torch way (eps=1e-3)."""
    from aiko_services_tpu.models import load_yolov8_params
    config = _tiny_yolo_config()
    path = tmp_path / "yolo.safetensors"
    tensors = _write_ultralytics_yolo(path, config)
    params = load_yolov8_params(path, config)
    rng = np.random.default_rng(7)
    x = rng.standard_normal((1, 6, 6, 3)).astype(np.float32)  # NHWC
    from aiko_services_tpu.models.layers import conv2d
    folded = np.asarray(conv2d(params["m0"], jnp.asarray(x), stride=2))
    # reference: plain conv then BN, torch semantics
    w = tensors["model.0.conv.weight"]  # (O, I, kh, kw)
    raw_out = np.asarray(conv2d(
        {"w": jnp.asarray(np.ascontiguousarray(w.transpose(2, 3, 1, 0)))},
        jnp.asarray(x), stride=2))
    gamma = tensors["model.0.bn.weight"]
    beta = tensors["model.0.bn.bias"]
    mean = tensors["model.0.bn.running_mean"]
    var = tensors["model.0.bn.running_var"]
    expected = (raw_out - mean) / np.sqrt(var + 1e-3) * gamma + beta
    np.testing.assert_allclose(folded, expected, rtol=2e-4, atol=2e-5)


def test_yolo_detect_end_to_end(tmp_path):
    from aiko_services_tpu.models import load_yolov8_params, yolo_detect
    config = _tiny_yolo_config()
    path = tmp_path / "yolo.safetensors"
    _write_ultralytics_yolo(path, config)
    params = load_yolov8_params(path, config)
    images = jnp.asarray(
        np.random.default_rng(1).random((2, 3, 64, 64), np.float32))
    out = yolo_detect(params, config, images)
    assert out["boxes"].shape == (2, config.max_detections, 4)
    assert out["scores"].shape == (2, config.max_detections)
    assert bool(jnp.isfinite(out["boxes"]).all())
    # DFL decode keeps boxes inside [0 - reg_max*stride, size + ...):
    # with finite inputs the xyxy ordering must hold where valid
    valid = np.asarray(out["valid"])
    boxes = np.asarray(out["boxes"])
    if valid.any():
        picked = boxes[valid]
        assert (picked[:, 2] >= picked[:, 0]).all()
        assert (picked[:, 3] >= picked[:, 1]).all()


def test_detector_element_ingests_ultralytics_yolo(tmp_path):
    import queue
    from aiko_services_tpu.pipeline import create_pipeline
    from aiko_services_tpu.runtime import Process
    config = _tiny_yolo_config()
    path = tmp_path / "yolo.safetensors"
    _write_ultralytics_yolo(path, config)
    definition = {
        "name": "det_hf",
        "graph": ["(camera (detector))"],
        "elements": [
            {"name": "camera", "output": [{"name": "image"}],
             "parameters": {"data_sources": [[3, 64, 64]]},
             "deploy": {"local": {"module": "aiko_services_tpu.elements",
                                  "class_name": "ImageSource"}}},
            {"name": "detector", "input": [{"name": "image"}],
             "output": [{"name": "detections"}],
             "parameters": {"weights": str(path), "n_classes": 4,
                            "image_size": 64, "max_detections": 8,
                            "score_threshold": 0.01, "dtype": "float32"},
             "deploy": {"local": {"module": "aiko_services_tpu.elements",
                                  "class_name": "Detector"}}},
        ],
    }
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, definition)
    process.run(in_thread=True)
    responses = queue.Queue()
    pipeline.create_stream("s", queue_response=responses)
    _, _, outputs = responses.get(timeout=60)
    detections = outputs["detections"]
    assert np.asarray(detections["boxes"]).shape == (1, 8, 4)
    process.terminate()


def test_infer_yolov8_config_reads_architecture_from_shapes(tmp_path):
    from aiko_services_tpu.models import infer_yolov8_config
    config = _tiny_yolo_config()
    path = tmp_path / "yolo.safetensors"
    _write_ultralytics_yolo(path, config)
    inferred = infer_yolov8_config(path, image_size=64, dtype="float32")
    assert inferred.width == config.width
    assert inferred.repeats == config.repeats
    assert inferred.neck_repeats == config.neck_repeats
    assert inferred.n_classes == config.n_classes
    assert inferred.reg_max == config.reg_max
    assert inferred.image_size == 64
