# Example pipeline definitions must parse and (the cheap ones) run.

import queue
from pathlib import Path

import pytest

from aiko_services_tpu.pipeline import (
    create_pipeline, parse_pipeline_definition)
from aiko_services_tpu.runtime import Process
from aiko_services_tpu.transport import reset_brokers

EXAMPLES = Path(__file__).resolve().parent.parent / "examples"


@pytest.fixture(autouse=True)
def clean_brokers():
    reset_brokers()
    yield
    reset_brokers()


@pytest.mark.parametrize("path", sorted(EXAMPLES.glob("*.json")),
                         ids=lambda p: p.name)
def test_example_definitions_parse(path):
    definition = parse_pipeline_definition(path)
    assert definition.elements


def test_pipeline_text_example_runs():
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process,
                               str(EXAMPLES / "pipeline_text.json"))
    process.run(in_thread=True)
    responses = queue.Queue()
    pipeline.create_stream("s", queue_response=responses)
    # 4 inputs, sample_rate 2 -> 2 surviving frames, uppercased
    texts = sorted(responses.get(timeout=15)[2]["text"] for _ in range(2))
    assert texts == ["FRAME THREE", "HELLO WORLD"]
    process.terminate()


def test_pipeline_compute_example_runs():
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process,
                               str(EXAMPLES / "pipeline_compute.json"))
    process.run(in_thread=True)
    responses = queue.Queue()
    pipeline.create_stream("s", queue_response=responses)
    import numpy as np
    for _ in range(3):
        _, _, outputs = responses.get(timeout=30)
        assert outputs["tensor"].shape == (8, 16)
        assert np.isfinite(outputs["tensor"]).all()
    process.terminate()
