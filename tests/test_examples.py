# Example pipeline definitions must parse and (the cheap ones) run.

import queue
from pathlib import Path

import pytest

from aiko_services_tpu.pipeline import (
    create_pipeline, parse_pipeline_definition)
from aiko_services_tpu.runtime import Process
from aiko_services_tpu.transport import reset_brokers

EXAMPLES = Path(__file__).resolve().parent.parent / "examples"


@pytest.fixture(autouse=True)
def clean_brokers():
    reset_brokers()
    yield
    reset_brokers()


@pytest.mark.parametrize("path", sorted(EXAMPLES.glob("*.json")),
                         ids=lambda p: p.name)
def test_example_definitions_parse(path):
    definition = parse_pipeline_definition(path)
    assert definition.elements


def test_pipeline_text_example_runs():
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process,
                               str(EXAMPLES / "pipeline_text.json"))
    process.run(in_thread=True)
    responses = queue.Queue()
    pipeline.create_stream("s", queue_response=responses)
    # 4 inputs, sample_rate 2 -> 2 surviving frames, uppercased
    texts = sorted(responses.get(timeout=15)[2]["text"] for _ in range(2))
    assert texts == ["FRAME THREE", "HELLO WORLD"]
    process.terminate()


def test_tutorial_minimal_actor_runs():
    """The README's entry-point tutorial must keep working verbatim."""
    import sys
    sys.path.insert(0, str(EXAMPLES))
    try:
        import tutorial_minimal_actor
        assert tutorial_minimal_actor.main() == ["HELLO, ACTOR!!"]
    finally:
        sys.path.remove(str(EXAMPLES))


def test_pipeline_compute_example_runs():
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process,
                               str(EXAMPLES / "pipeline_compute.json"))
    process.run(in_thread=True)
    responses = queue.Queue()
    pipeline.create_stream("s", queue_response=responses)
    import numpy as np
    for _ in range(3):
        _, _, outputs = responses.get(timeout=30)
        assert outputs["tensor"].shape == (8, 16)
        assert np.isfinite(outputs["tensor"]).all()
    process.terminate()


def test_pipeline_longcontext_example_runs_scaled_down():
    """The long-context example (sequence-parallel LM element) executes
    on the virtual 8-device mesh; scaled-down model, same sharding
    topology (data 1 x seq 4 x model 2)."""
    import json

    import numpy as np

    with open(EXAMPLES / "pipeline_longcontext.json") as f:
        definition = json.load(f)
    tokens = definition["elements"][0]
    tokens["parameters"]["data_sources"] = [[1, 64]]
    tokens["parameters"]["count"] = 1
    tokens["parameters"]["vocab_size"] = 128  # match the scaled lm
    lm = definition["elements"][1]
    lm["parameters"].update({"vocab_size": 128, "d_model": 32,
                             "n_layers": 2, "n_heads": 4, "n_kv_heads": 2,
                             "d_ff": 64, "max_seq_len": 128,
                             "dtype": "float32"})
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, definition)
    process.run(in_thread=True)
    responses = queue.Queue()
    pipeline.create_stream("s1", queue_response=responses)
    _, _, outputs = responses.get(timeout=120)
    logits = np.asarray(outputs["logits"])
    assert logits.shape == (1, 64, 128)
    assert np.isfinite(logits).all()
    process.terminate()


def test_pipeline_longcontext_ragged_length_buckets():
    """A context length NOT divisible by the seq axis still works: the
    engine's bucketing pads tokens to a seq-divisible bucket and un-pads
    the logits (causal attention makes end-padding exact)."""
    import json

    import numpy as np

    from aiko_services_tpu.pipeline import create_pipeline
    from aiko_services_tpu.runtime import Process

    with open(EXAMPLES / "pipeline_longcontext.json") as f:
        definition = json.load(f)
    tokens = definition["elements"][0]
    tokens["parameters"]["data_sources"] = [[1, 50]]  # 50 % 4 != 0
    tokens["parameters"]["count"] = 1
    tokens["parameters"]["vocab_size"] = 128
    lm = definition["elements"][1]
    lm["parameters"].update({"vocab_size": 128, "d_model": 32,
                             "n_layers": 2, "n_heads": 4, "n_kv_heads": 2,
                             "d_ff": 64, "max_seq_len": 128,
                             "dtype": "float32"})
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, definition)
    process.run(in_thread=True)
    responses = queue.Queue()
    pipeline.create_stream("s1", queue_response=responses)
    _, _, outputs = responses.get(timeout=120)
    logits = np.asarray(outputs["logits"])
    assert logits.shape == (1, 50, 128)  # un-padded back to 50
    assert np.isfinite(logits).all()
    process.terminate()


def test_pipeline_robot_loop_example_end_to_end():
    """The full reference xgo story, hermetic: robot camera (binary
    video topic, resolved by registrar discovery) -> detector ->
    detections side-channel -> chat LM (vision context injected into
    the system-prompted request) -> RobotControl driving the robot
    from (action ...) text."""
    import json
    import queue
    import time
    from pathlib import Path

    import numpy as np

    from aiko_services_tpu.elements import RobotActor
    from aiko_services_tpu.pipeline import create_pipeline
    from aiko_services_tpu.runtime import Process, Registrar
    from aiko_services_tpu.transport import get_broker

    definition = json.loads(
        (Path(__file__).parent.parent
         / "examples/pipeline_robot_loop.json").read_text())
    process = Process(transport_kind="loopback")
    Registrar(process, search_timeout=0.05)
    robot = RobotActor(process, name="dog")
    pipeline = create_pipeline(process, definition)
    process.run(in_thread=True)

    import threading
    published = threading.Event()
    process.add_message_handler(
        lambda _topic, _payload: published.set(),
        f"{process.namespace}/detections")

    responses = queue.Queue()
    # multi-root graph: each stream executes ONE root's sub-path
    # (Stream.graph_path, the reference pipeline_paths capability)
    # no discovery wait needed: the camera element watches the services
    # cache and subscribes the moment the robot appears
    pipeline.create_stream(
        "vision", queue_response=queue.Queue(), graph_path="camera",
        grace_time=300)
    robot.start_camera(period=0.1, height=64, width=64)
    # wait for the vision leg (camera -> detector -> publish) to emit on
    # the side-channel BEFORE asking -- detector compile dominates
    assert published.wait(timeout=240), (
        "vision leg never published detections")

    pipeline.create_stream(
        "chat", queue_response=responses, graph_path="ask",
        parameters={
            "control.robot_topic": robot.topic_path,
            "detections_window": 300.0,  # compile tolerance
        })
    saw_prompt_with_context = False
    saw_robot_action = False
    for _ in range(8):
        try:
            _, frame, outputs = responses.get(timeout=60)
        except queue.Empty:
            break
        if "prompt" in outputs:
            prompt = outputs["prompt"][0]
            assert "You control a robot dog" in prompt
            if "Visible objects:" in prompt:
                saw_prompt_with_context = True
        if saw_prompt_with_context:
            break
    robot.stop_camera()
    assert saw_prompt_with_context, (
        "LM prompt never received vision context")

    # the control leg: literal action text drives the discovered robot
    # (the LM is random-weight here; the reference constrains it to this
    # grammar via the same system prompt)
    before = float(robot.share["odometer"])
    # graph_path may name ANY node: a "drive" stream runs just the
    # control element, feeding it literal action text
    pipeline.create_stream(
        "drive", queue_response=queue.Queue(), graph_path="control",
        parameters={"control.robot_topic": robot.topic_path})
    pipeline.create_frame(
        pipeline.streams["drive"],
        {"text": ["(action move 0.5) (action speak hello)"]})
    # the injected frame enters at the graph heads; drain until the
    # robot's odometer moves
    get_broker().drain()
    import time
    for _ in range(100):
        if float(robot.share["odometer"]) > before:
            saw_robot_action = True
            break
        time.sleep(0.1)
    assert saw_robot_action, "robot never acted on (action move 0.5)"
    assert robot.share["utterances"] >= 1
    process.terminate()
