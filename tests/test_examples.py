# Example pipeline definitions must parse and (the cheap ones) run.

import queue
from pathlib import Path

import pytest

from aiko_services_tpu.pipeline import (
    create_pipeline, parse_pipeline_definition)
from aiko_services_tpu.runtime import Process
from aiko_services_tpu.transport import reset_brokers

EXAMPLES = Path(__file__).resolve().parent.parent / "examples"


@pytest.fixture(autouse=True)
def clean_brokers():
    reset_brokers()
    yield
    reset_brokers()


@pytest.mark.parametrize("path", sorted(EXAMPLES.glob("*.json")),
                         ids=lambda p: p.name)
def test_example_definitions_parse(path):
    definition = parse_pipeline_definition(path)
    assert definition.elements


def test_pipeline_text_example_runs():
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process,
                               str(EXAMPLES / "pipeline_text.json"))
    process.run(in_thread=True)
    responses = queue.Queue()
    pipeline.create_stream("s", queue_response=responses)
    # 4 inputs, sample_rate 2 -> 2 surviving frames, uppercased
    texts = sorted(responses.get(timeout=15)[2]["text"] for _ in range(2))
    assert texts == ["FRAME THREE", "HELLO WORLD"]
    process.terminate()


def test_pipeline_compute_example_runs():
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process,
                               str(EXAMPLES / "pipeline_compute.json"))
    process.run(in_thread=True)
    responses = queue.Queue()
    pipeline.create_stream("s", queue_response=responses)
    import numpy as np
    for _ in range(3):
        _, _, outputs = responses.get(timeout=30)
        assert outputs["tensor"].shape == (8, 16)
        assert np.isfinite(outputs["tensor"]).all()
    process.terminate()


def test_pipeline_longcontext_example_runs_scaled_down():
    """The long-context example (sequence-parallel LM element) executes
    on the virtual 8-device mesh; scaled-down model, same sharding
    topology (data 1 x seq 4 x model 2)."""
    import json

    import numpy as np

    with open(EXAMPLES / "pipeline_longcontext.json") as f:
        definition = json.load(f)
    tokens = definition["elements"][0]
    tokens["parameters"]["data_sources"] = [[1, 64]]
    tokens["parameters"]["count"] = 1
    tokens["parameters"]["vocab_size"] = 128  # match the scaled lm
    lm = definition["elements"][1]
    lm["parameters"].update({"vocab_size": 128, "d_model": 32,
                             "n_layers": 2, "n_heads": 4, "n_kv_heads": 2,
                             "d_ff": 64, "max_seq_len": 128,
                             "dtype": "float32"})
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, definition)
    process.run(in_thread=True)
    responses = queue.Queue()
    pipeline.create_stream("s1", queue_response=responses)
    _, _, outputs = responses.get(timeout=120)
    logits = np.asarray(outputs["logits"])
    assert logits.shape == (1, 64, 128)
    assert np.isfinite(logits).all()
    process.terminate()


def test_pipeline_longcontext_ragged_length_buckets():
    """A context length NOT divisible by the seq axis still works: the
    engine's bucketing pads tokens to a seq-divisible bucket and un-pads
    the logits (causal attention makes end-padding exact)."""
    import json

    import numpy as np

    from aiko_services_tpu.pipeline import create_pipeline
    from aiko_services_tpu.runtime import Process

    with open(EXAMPLES / "pipeline_longcontext.json") as f:
        definition = json.load(f)
    tokens = definition["elements"][0]
    tokens["parameters"]["data_sources"] = [[1, 50]]  # 50 % 4 != 0
    tokens["parameters"]["count"] = 1
    tokens["parameters"]["vocab_size"] = 128
    lm = definition["elements"][1]
    lm["parameters"].update({"vocab_size": 128, "d_model": 32,
                             "n_layers": 2, "n_heads": 4, "n_kv_heads": 2,
                             "d_ff": 64, "max_seq_len": 128,
                             "dtype": "float32"})
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, definition)
    process.run(in_thread=True)
    responses = queue.Queue()
    pipeline.create_stream("s1", queue_response=responses)
    _, _, outputs = responses.get(timeout=120)
    logits = np.asarray(outputs["logits"])
    assert logits.shape == (1, 50, 128)  # un-padded back to 50
    assert np.isfinite(logits).all()
    process.terminate()
