# ML + media element tests: each model family behind a pipeline element,
# then the flagship 3-stage multi-modal pipeline (speech -> LLM, vision ->
# detections in one graph) -- tiny configs on CPU.

import queue

import numpy as np
import pytest

from aiko_services_tpu.pipeline import create_pipeline
from aiko_services_tpu.runtime import Process
from aiko_services_tpu.transport import reset_brokers

ELEMENTS = "aiko_services_tpu.elements"

TINY_ASR = {"d_model": 32, "enc_layers": 1, "dec_layers": 1, "n_heads": 2,
            "vocab_size": 300, "max_frames": 64, "dtype": "float32",
            "max_tokens": 4}
TINY_LM = {"vocab_size": 300, "d_model": 32, "n_layers": 1, "n_heads": 2,
           "n_kv_heads": 1, "d_ff": 64, "dtype": "float32"}
TINY_DET = {"n_classes": 4, "base_channels": 4, "image_size": 32,
            "max_detections": 4, "dtype": "float32"}


@pytest.fixture(autouse=True)
def clean_brokers():
    reset_brokers()
    yield
    reset_brokers()


def local(class_name):
    return {"local": {"module": ELEMENTS, "class_name": class_name}}


def run_frames(definition, count=1, timeout=120):
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, definition)
    process.run(in_thread=True)
    responses = queue.Queue()
    pipeline.create_stream("s", queue_response=responses, grace_time=300)
    results = [responses.get(timeout=timeout) for _ in range(count)]
    process.terminate()
    return results


def test_speech_to_text_element():
    definition = {
        "name": "asr_pipe",
        "graph": ["(tone (framing (asr (text))))"],
        "elements": [
            {"name": "tone", "output": [{"name": "audio"}],
             "parameters": {"data_sources": [[440, 0.2], [880, 0.2]]},
             "deploy": local("ToneSource")},
            {"name": "framing", "input": [{"name": "audio"}],
             "output": [{"name": "audio"}],
             "parameters": {"window_count": 2},
             "deploy": local("AudioFraming")},
            {"name": "asr", "input": [{"name": "audio"}],
             "output": [{"name": "tokens"}],
             "parameters": TINY_ASR, "deploy": local("SpeechToText")},
            {"name": "text", "input": [{"name": "tokens"}],
             "output": [{"name": "text"}],
             "deploy": local("TokensToText")},
        ],
    }
    results = run_frames(definition, count=2)
    for _, _, outputs in results:
        assert isinstance(outputs["text"], list)
        assert np.asarray(outputs["tokens"]).shape == (1, 4)


def test_detector_element_and_overlay():
    definition = {
        "name": "detect_pipe",
        "graph": ["(camera (detector (overlay)))"],
        "elements": [
            {"name": "camera", "output": [{"name": "image"}],
             "parameters": {"data_sources": [[3, 32, 32]]},
             "deploy": local("ImageSource")},
            {"name": "detector", "input": [{"name": "image"}],
             "output": [{"name": "detections"}],
             "parameters": TINY_DET, "deploy": local("Detector")},
            {"name": "overlay",
             "input": [{"name": "image"}, {"name": "detections"}],
             "output": [{"name": "image"}, {"name": "overlay"}],
             "deploy": local("ImageOverlay")},
        ],
    }
    [(_, _, outputs)] = run_frames(definition)
    assert outputs["image"].dtype == np.uint8
    assert set(outputs["overlay"]) == {"objects", "rectangles"}
    for obj in outputs["overlay"]["objects"]:
        assert obj["confidence"] > 0


def test_three_stage_multimodal_pipeline():
    """The flagship shape (BASELINE.md config 5 analogue): speech -> ASR
    tokens -> LLM scoring while vision -> detector runs in the same graph,
    everything device-resident between elements."""
    definition = {
        "name": "flagship",
        "graph": ["(sources (asr (lm)) (detector))"],
        "elements": [
            {"name": "sources",
             "output": [{"name": "audio"}, {"name": "image"}],
             "parameters": {"data_sources": [[440, 0.2]]},
             "deploy": local("MultiModalSource")},
            {"name": "asr", "input": [{"name": "audio"}],
             "output": [{"name": "tokens"}],
             "parameters": TINY_ASR, "deploy": local("SpeechToText")},
            {"name": "lm", "input": [{"name": "tokens"}],
             "output": [{"name": "logits"}, {"name": "nll"}],
             "parameters": TINY_LM, "deploy": local("LMForward")},
            {"name": "detector", "input": [{"name": "image"}],
             "output": [{"name": "detections"}],
             "parameters": TINY_DET, "deploy": local("Detector")},
        ],
    }
    [(_, frame, outputs)] = run_frames(definition)
    assert np.isfinite(np.asarray(outputs["nll"])).all()
    assert "detections" in outputs
    assert {"time_asr", "time_lm", "time_detector"} <= set(frame.metrics)


def test_image_read_write_roundtrip(tmp_path):
    from PIL import Image
    source_path = tmp_path / "in.png"
    target_path = tmp_path / "out_{}.png"
    Image.fromarray(
        (np.random.default_rng(0).random((16, 16, 3)) * 255)
        .astype(np.uint8)).save(source_path)
    definition = {
        "name": "image_pipe",
        "graph": ["(read (resize (write)))"],
        "elements": [
            {"name": "read", "output": [{"name": "image"}],
             "parameters": {"data_sources": [str(source_path)]},
             "deploy": local("ImageReadFile")},
            {"name": "resize", "input": [{"name": "image"}],
             "output": [{"name": "image"}],
             "parameters": {"resize_height": 8, "resize_width": 8},
             "deploy": local("ImageResize")},
            {"name": "write", "input": [{"name": "image"}],
             "output": [{"name": "image"}],
             "parameters": {"data_targets": [str(target_path)]},
             "deploy": local("ImageWriteFile")},
        ],
    }
    run_frames(definition)
    with Image.open(tmp_path / "out_0.png") as result:
        assert result.size == (8, 8)


def test_audio_wav_roundtrip(tmp_path):
    target = tmp_path / "tone.wav"
    definition = {
        "name": "audio_pipe",
        "graph": ["(tone (write))"],
        "elements": [
            {"name": "tone", "output": [{"name": "audio"}],
             "parameters": {"data_sources": [[440, 0.1]]},
             "deploy": local("ToneSource")},
            {"name": "write", "input": [{"name": "audio"}],
             "output": [{"name": "audio"}],
             "parameters": {"data_targets": [str(target)]},
             "deploy": local("AudioWriteFile")},
        ],
    }
    run_frames(definition)
    definition2 = {
        "name": "audio_read",
        "graph": ["(read (sample))"],
        "elements": [
            {"name": "read", "output": [{"name": "audio"}],
             "parameters": {"data_sources": [str(target)]},
             "deploy": local("AudioReadFile")},
            {"name": "sample", "input": [{"name": "audio"}],
             "output": [{"name": "audio"}],
             "deploy": local("AudioSample")},
        ],
    }
    [(_, _, outputs)] = run_frames(definition2)
    audio = np.asarray(outputs["audio"])
    assert audio.shape == (1600,)
    assert 0.5 < np.abs(audio).max() <= 1.0


def test_tokens_to_text_out_of_range_ids():
    # ADVICE round 1: ids >= 259 must be skipped, not crash bytes()
    from aiko_services_tpu.elements.ml import TokensToText
    element = TokensToText.__new__(TokensToText)
    element.get_parameter = lambda name, default=None, stream=None: default
    tokens = np.array([[0, 1, 2, 3 + ord("h"), 3 + ord("i"), 300, 1023]])
    outputs = element.process_async(None, tokens=tokens)
    assert outputs["text"] == ["hi"]


def test_text_to_tokens_to_lm_with_tokenizer_streaming():
    # real-text path: TextToTokens (BPE asset) -> LMGenerate with streamed
    # token chunks published to /out, decoded text in the response
    definition = {
        "name": "chat_pipe",
        "graph": ["(prompt (lm))"],
        "elements": [
            {"name": "prompt", "input": [{"name": "text"}],
             "output": [{"name": "tokens"}],
             "deploy": local("TextToTokens")},
            {"name": "lm", "input": [{"name": "tokens"}],
             "output": [{"name": "generated"}, {"name": "text"}],
             "parameters": {**TINY_LM, "vocab_size": 4096,
                            "tokenizer": "default", "max_new_tokens": 6,
                            "stream_tokens": True, "stream_chunk": 2},
             "deploy": local("LMGenerate")},
        ],
    }
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, definition)
    streamed = []
    process.add_message_handler(
        lambda topic, payload: streamed.append(payload),
        f"{pipeline.elements['lm'].topic_path}/out")
    process.run(in_thread=True)
    responses = queue.Queue()
    pipeline.create_stream("s", queue_response=responses, grace_time=300)
    pipeline.process_frame({"stream_id": "s"}, {"text": "hello pipeline"})
    _, _, outputs = responses.get(timeout=120)
    assert np.asarray(outputs["generated"]).shape == (1, 6)
    assert isinstance(outputs["text"], list)
    # 6 tokens in chunks of 2 -> 3 streamed publishes
    from helpers import wait_for
    wait_for(lambda: len([s for s in streamed if "tokens" in s]) >= 3)
    process.terminate()


def test_lm_generate_weights_parameter(tmp_path):
    # seeded random params saved to safetensors load back identically
    import jax
    from aiko_services_tpu.models import (
        TransformerConfig, generate, init_params, save_pytree)
    config = TransformerConfig(
        vocab_size=300, d_model=32, n_layers=1, n_heads=2, n_kv_heads=1,
        d_ff=64, max_seq_len=2048, dtype="float32")
    params = init_params(config, jax.random.PRNGKey(0))
    path = tmp_path / "lm.safetensors"
    save_pytree(path, params)

    definition = {
        "name": "wpipe",
        "graph": ["(lm)"],
        "elements": [
            {"name": "lm", "input": [{"name": "tokens"}],
             "output": [{"name": "generated"}],
             "parameters": {**TINY_LM, "weights": str(path),
                            "max_new_tokens": 4},
             "deploy": local("LMGenerate")},
        ],
    }
    prompt = np.array([[7, 8, 9]], np.int32)
    [(_, _, outputs)] = run_frames_with_data(definition, {"tokens": prompt})
    expected, _ = generate(params, config, prompt, 4)
    np.testing.assert_array_equal(np.asarray(outputs["generated"]),
                                  np.asarray(expected))


def run_frames_with_data(definition, frame_data, timeout=120):
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, definition)
    process.run(in_thread=True)
    responses = queue.Queue()
    pipeline.create_stream("s", queue_response=responses, grace_time=300)
    pipeline.process_frame({"stream_id": "s"}, frame_data)
    results = [responses.get(timeout=timeout)]
    process.terminate()
    return results


def test_lm_forward_sequence_parallel_on_element_mesh():
    """Long-context is first-class at the ELEMENT layer: an LMForward
    with sequence_parallel=true and a seq axis in its sharding block runs
    ring attention over the element's mesh and matches the dense
    element's logits."""
    import queue as queue_module
    from aiko_services_tpu.runtime import Process
    from aiko_services_tpu.pipeline import create_pipeline

    def definition(name, extra_params, sharding=None):
        element = {
            "name": "lm", "input": [{"name": "tokens"}],
            "output": [{"name": "logits"}, {"name": "nll"}],
            "parameters": dict(
                {"vocab_size": 128, "d_model": 32, "n_layers": 2,
                 "n_heads": 4, "n_kv_heads": 2, "d_ff": 64,
                 "max_seq_len": 64, "dtype": "float32"}, **extra_params),
            "deploy": {"local": {"module": "aiko_services_tpu.elements",
                                 "class_name": "LMForward"}}}
        if sharding:
            element["sharding"] = sharding
        return {
            "name": name, "graph": ["(tokens (lm))"],
            "elements": [
                {"name": "tokens", "output": [{"name": "tokens"}],
                 "parameters": {"data_sources": [[2, 32]],
                                "vocab_size": 128},
                 "deploy": {"local": {
                     "module": "aiko_services_tpu.elements",
                     "class_name": "TokenSource"}}},
                element,
            ]}

    def run(pipeline_definition):
        process = Process(transport_kind="loopback")
        pipeline = create_pipeline(process, pipeline_definition)
        process.run(in_thread=True)
        responses = queue_module.Queue()
        pipeline.create_stream("s1", queue_response=responses)
        _, _, outputs = responses.get(timeout=60)
        logits = np.asarray(outputs["logits"])
        process.terminate()
        return logits

    dense = run(definition("lm_dense", {}))
    assert np.isfinite(dense).all()  # guard: NaN==NaN parity is vacuous
    ringed = run(definition(
        "lm_sp", {"sequence_parallel": True},
        sharding={"axes": {"data": 2, "seq": 2, "model": 2},
                  "inputs": {"tokens": ["data", None]}}))
    np.testing.assert_allclose(ringed, dense, atol=2e-3, rtol=2e-3)


def test_lm_generate_sequence_parallel_matches_dense():
    """LMGenerate with sequence_parallel: ring prefill + seq-sharded KV
    decode on the element's mesh must reproduce dense greedy output.
    (Prompt lengths must divide the seq axis -- power-of-two buckets
    do.)"""
    import queue as queue_module
    from aiko_services_tpu.runtime import Process
    from aiko_services_tpu.pipeline import create_pipeline

    def definition(name, extra_params, sharding=None):
        element = {
            "name": "lm", "input": [{"name": "tokens"}],
            "output": [{"name": "generated"}],
            "parameters": dict(
                {"vocab_size": 128, "d_model": 32, "n_layers": 2,
                 "n_heads": 4, "n_kv_heads": 2, "d_ff": 64,
                 "max_seq_len": 64, "dtype": "float32",
                 "max_new_tokens": 8}, **extra_params),
            "deploy": {"local": {"module": "aiko_services_tpu.elements",
                                 "class_name": "LMGenerate"}}}
        if sharding:
            element["sharding"] = sharding
        return {
            "name": name, "graph": ["(tokens (lm))"],
            "elements": [
                {"name": "tokens", "output": [{"name": "tokens"}],
                 "parameters": {"data_sources": [[2, 16]],
                                "vocab_size": 128},
                 "deploy": {"local": {
                     "module": "aiko_services_tpu.elements",
                     "class_name": "TokenSource"}}},
                element,
            ]}

    def run(pipeline_definition):
        process = Process(transport_kind="loopback")
        pipeline = create_pipeline(process, pipeline_definition)
        process.run(in_thread=True)
        responses = queue_module.Queue()
        pipeline.create_stream("s1", queue_response=responses)
        _, _, outputs = responses.get(timeout=60)
        generated = np.asarray(outputs["generated"])
        process.terminate()
        return generated

    dense = run(definition("gen_dense", {}))
    sp = run(definition(
        "gen_sp", {"sequence_parallel": True},
        sharding={"axes": {"data": 2, "seq": 2, "model": 2},
                  "inputs": {"tokens": ["data", None]}}))
    np.testing.assert_array_equal(sp, dense)


def test_lm_generate_sp_text_pad_parity():
    """Text prompts whose width does NOT divide the seq axis: the
    sequence-parallel path left-pads to a seq-multiple with the TOKENIZER
    pad id, so output must equal the dense model run on the identically
    padded prompt (round-2 advisor: id-0 seq padding diverged from the
    batch padding's pad id)."""
    import jax
    from aiko_services_tpu.models import (
        BPETokenizer, TransformerConfig, generate, init_params)
    from aiko_services_tpu.runtime import Process
    from aiko_services_tpu.pipeline import create_pipeline

    tokenizer = BPETokenizer.default()
    prompts = ["pad parity", "pp"]
    encoded = [tokenizer.encode(p, bos=True) for p in prompts]
    width = max(len(ids) for ids in encoded)
    seq_size = 2
    assert width % seq_size != 0, (
        f"pick prompts with max width not divisible by {seq_size} "
        f"(got {width})")

    params_def = {
        "vocab_size": tokenizer.vocab_size, "d_model": 32, "n_layers": 2,
        "n_heads": 4, "n_kv_heads": 2, "d_ff": 64, "max_seq_len": 64,
        "dtype": "float32", "max_new_tokens": 6, "tokenizer": "default",
        "sequence_parallel": True}
    definition = {
        "name": "sp_pad", "graph": ["(lm)"],
        "elements": [
            {"name": "lm", "input": [{"name": "text"}],
             "output": [{"name": "generated"}],
             "parameters": params_def,
             "sharding": {"axes": {"data": 2, "seq": 2, "model": 2}},
             "deploy": {"local": {"module": "aiko_services_tpu.elements",
                                  "class_name": "LMGenerate"}}},
        ]}
    [(_, _, outputs)] = run_frames_with_data(
        definition, {"text": prompts}, timeout=180)
    sp_out = np.asarray(outputs["generated"])

    config = TransformerConfig(
        vocab_size=tokenizer.vocab_size, d_model=32, n_layers=2,
        n_heads=4, n_kv_heads=2, d_ff=64, max_seq_len=64, dtype="float32")
    params = init_params(config, jax.random.PRNGKey(0))
    pad = tokenizer.pad_id or 0
    target = ((width + seq_size - 1) // seq_size) * seq_size
    padded = np.full((len(encoded), target), pad, np.int32)
    for row, ids in enumerate(encoded):
        padded[row, target - len(ids):] = ids
    expected, _ = generate(params, config, padded, 6)
    np.testing.assert_array_equal(sp_out, np.asarray(expected))

    # batch 1 (the common serving case) on a data-sharded mesh: the
    # element pads the batch to the data-axis multiple and slices it
    # back (round-3 verify drive caught this crashing in _sp_cache)
    [(_, _, single)] = run_frames_with_data(
        definition, {"text": prompts[0]}, timeout=180)
    np.testing.assert_array_equal(
        np.asarray(single["generated"]), np.asarray(expected)[:1])


# -- LLM chat semantics + detections side-channel ----------------------------
# (reference elements_llm.py:137-210: S-expression-constrained system
# prompt; {ns}/detections subscription with a 1 s freshness window)

def _chat_lm_pipeline(process, window=30.0):
    # default window is wide: first-frame setup (tokenizer + params +
    # compile) can exceed the reference's 1 s freshness rule, which the
    # dedicated staleness test covers with a warmed model
    definition = {
        "name": "chat_lm",
        "graph": ["(lm)"],
        "elements": [
            {"name": "lm", "input": [{"name": "text"}],
             "output": [{"name": "generated"}, {"name": "text"},
                        {"name": "prompt"}],
             "parameters": {
                 "vocab_size": 300, "d_model": 32, "n_layers": 1,
                 "n_heads": 2, "n_kv_heads": 2, "d_ff": 64,
                 "max_seq_len": 256, "dtype": "float32",
                 "tokenizer": "default", "max_new_tokens": 2,
                 "detections_subscribe": True,
                 "detections_window": window,
                 "system_prompt": "You control a robot. Reply with "
                                  "(action ...) commands only.",
             },
             "deploy": local("LMGenerate")},
        ],
    }
    return create_pipeline(process, definition)


def test_lm_prompt_includes_fresh_detections_and_system_prompt():
    process = Process(transport_kind="loopback")
    pipeline = _chat_lm_pipeline(process)
    process.run(in_thread=True)
    responses = queue.Queue()
    stream = pipeline.create_stream("s", queue_response=responses)

    # cold prompt: system prompt present, no vision context yet
    pipeline.create_frame(stream, {"text": "wave hello"})
    _, _, outputs = responses.get(timeout=60)
    prompt = outputs["prompt"][0]
    assert "You control a robot" in prompt
    assert "Visible objects" not in prompt
    assert "wave hello" in prompt

    # a detections publish lands on the side-channel -> injected
    from aiko_services_tpu.transport import get_broker
    process.publish(f"{process.namespace}/detections",
                    "(detections (person dog))")
    get_broker().drain()
    pipeline.create_frame(stream, {"text": "what do you see?"})
    _, _, outputs = responses.get(timeout=60)
    prompt = outputs["prompt"][0]
    assert "Visible objects: person, dog." in prompt
    assert "what do you see?" in prompt
    process.terminate()


def test_lm_stale_detections_excluded():
    import time
    process = Process(transport_kind="loopback")
    pipeline = _chat_lm_pipeline(process, window=0.2)
    process.run(in_thread=True)
    responses = queue.Queue()
    stream = pipeline.create_stream("s", queue_response=responses)
    # prime the model compile FIRST so the staleness clock isn't racing
    # the (slow) first-frame jit
    pipeline.create_frame(stream, {"text": "warmup"})
    responses.get(timeout=60)

    from aiko_services_tpu.transport import get_broker
    process.publish(f"{process.namespace}/detections",
                    "(detections (cat))")
    get_broker().drain()
    time.sleep(0.4)  # let the 0.2 s freshness window lapse
    pipeline.create_frame(stream, {"text": "now?"})
    _, _, outputs = responses.get(timeout=60)
    assert "Visible objects" not in outputs["prompt"][0]
    process.terminate()


def test_detections_publish_element_closes_the_loop():
    """DetectionsPublish -> side-channel -> LMGenerate context."""
    process = Process(transport_kind="loopback")
    lm_pipeline = _chat_lm_pipeline(process)
    publish_definition = {
        "name": "vision_pub",
        "graph": ["(publish)"],
        "elements": [
            {"name": "publish", "input": [{"name": "detections"}],
             "output": [{"name": "detections"}],
             "parameters": {"class_names": ["car", "bike", "person"]},
             "deploy": local("DetectionsPublish")},
        ],
    }
    vision_pipeline = create_pipeline(process, publish_definition)
    process.run(in_thread=True)

    detections = {
        "boxes": np.zeros((1, 4, 4), np.float32),
        "scores": np.array([[0.9, 0.8, 0.0, 0.0]], np.float32),
        "classes": np.array([[2, 0, 0, 0]], np.int32),
        "valid": np.array([[True, True, False, False]]),
    }
    vision_responses = queue.Queue()
    vision_stream = vision_pipeline.create_stream(
        "v", queue_response=vision_responses)
    vision_pipeline.create_frame(vision_stream, {"detections": detections})
    vision_responses.get(timeout=30)  # publish completed

    from aiko_services_tpu.transport import get_broker
    get_broker().drain()
    responses = queue.Queue()
    stream = lm_pipeline.create_stream("s", queue_response=responses)
    lm_pipeline.create_frame(stream, {"text": "report"})
    _, _, outputs = responses.get(timeout=60)
    assert "Visible objects: person, car." in outputs["prompt"][0]
    process.terminate()


def test_meshed_lm_defaults_to_megatron_param_sharding():
    """A meshed LM element without an explicit sharding.state must NOT
    replicate its params (an 8B replicated over a pod blows HBM): the
    megatron param_specs tree is the default."""
    from jax.sharding import PartitionSpec as P
    definition = {
        "name": "sharded_lm",
        "graph": ["(lm)"],
        "elements": [
            {"name": "lm", "input": [{"name": "tokens"}],
             "output": [{"name": "logits"}, {"name": "nll"}],
             "parameters": {"vocab_size": 128, "d_model": 32,
                            "n_layers": 2, "n_heads": 4, "n_kv_heads": 2,
                            "d_ff": 64, "max_seq_len": 64,
                            "dtype": "float32"},
             "sharding": {"axes": {"data": 2, "fsdp": 2, "seq": 1,
                                   "model": 2}},
             "deploy": local("LMForward")},
        ],
    }
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, definition)
    process.run(in_thread=True)
    responses = queue.Queue()
    stream = pipeline.create_stream("s", queue_response=responses)
    pipeline.create_frame(
        stream, {"tokens": np.ones((2, 8), np.int32)})
    _, _, outputs = responses.get(timeout=60)
    assert np.asarray(outputs["logits"]).shape == (2, 8, 128)
    element = pipeline.elements["lm"]
    wq = element.state["layers"]["wq"]["w"]
    assert not wq.sharding.is_fully_replicated
    assert wq.sharding.spec == P(None, "fsdp", "model"), wq.sharding.spec
    process.terminate()


def test_restored_meshed_lm_keeps_megatron_sharding(tmp_path):
    """Checkpoint restore installs state WITHOUT running setup(): the
    configure() hook must still default the megatron state spec, or a
    restored 8B would re-shard fully replicated and blow per-chip HBM."""
    from jax.sharding import PartitionSpec as P
    from aiko_services_tpu.utils.checkpoint import Checkpointer

    def definition(name):
        return {
            "name": name,
            "graph": ["(lm)"],
            "elements": [
                {"name": "lm", "input": [{"name": "tokens"}],
                 "output": [{"name": "logits"}, {"name": "nll"}],
                 "parameters": {"vocab_size": 128, "d_model": 32,
                                "n_layers": 2, "n_heads": 4,
                                "n_kv_heads": 2, "d_ff": 64,
                                "max_seq_len": 64, "dtype": "float32"},
                 "sharding": {"axes": {"data": 2, "fsdp": 2, "seq": 1,
                                       "model": 2}},
                 "deploy": local("LMForward")},
            ],
        }

    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, definition("ckpt_lm"))
    process.run(in_thread=True)
    responses = queue.Queue()
    stream = pipeline.create_stream("s", queue_response=responses)
    pipeline.create_frame(stream, {"tokens": np.ones((2, 8), np.int32)})
    responses.get(timeout=60)
    checkpointer = Checkpointer(tmp_path / "ckpt")
    pipeline.checkpoint(checkpointer, step=1)
    process.terminate()

    restore_process = Process(transport_kind="loopback")
    restored = create_pipeline(restore_process, definition("ckpt_lm"))
    restore_process.run(in_thread=True)
    restored.restore_checkpoint(checkpointer, step=1)
    element = restored.elements["lm"]
    wq = element.state["layers"]["wq"]["w"]
    assert wq.sharding.spec == P(None, "fsdp", "model"), wq.sharding.spec
    # and the restored element still serves frames
    rq = queue.Queue()
    restored_stream = (restored.streams.get("s")
                       or restored.create_stream("s2", queue_response=rq))
    if restored_stream.queue_response is None:
        restored_stream.queue_response = rq
    restored.create_frame(restored_stream,
                          {"tokens": np.ones((2, 8), np.int32)})
    _, _, outputs = rq.get(timeout=60)
    assert np.asarray(outputs["logits"]).shape == (2, 8, 128)
    restore_process.terminate()


def test_multimodal_batch_matches_per_item_synth():
    """read_batch's fused synthesis must match the per-item on-device
    synthesizers: images bit-exact (same fold_in), audio to f32
    rounding (XLA fuses the broadcast sin differently)."""
    import jax.numpy as jnp
    import numpy as np
    from aiko_services_tpu.elements.audio_io import (
        SAMPLE_RATE, synthesize_tone_on_device)
    from aiko_services_tpu.elements.compute import _multimodal_batch
    from aiko_services_tpu.elements.image_io import (
        synthesize_image_on_device)
    seconds, shape = 0.25, (3, 8, 8)
    audio, image = _multimodal_batch(
        jnp.asarray([440.0, 523.25], jnp.float32),
        jnp.asarray([7, 8], jnp.uint32),
        int(seconds * SAMPLE_RATE), SAMPLE_RATE, shape)
    for row, (freq, seed) in enumerate([(440.0, 7), (523.25, 8)]):
        one_audio = synthesize_tone_on_device(freq, seconds)
        one_image = synthesize_image_on_device(shape, seed)
        assert np.allclose(np.asarray(audio[row]), np.asarray(one_audio),
                           atol=1e-3)
        assert np.array_equal(np.asarray(image[row]),
                              np.asarray(one_image))


def test_lm_generate_kv_int8_parameter_matches_dense():
    """kv_dtype="int8" at the ELEMENT level: same greedy tokens as the
    full-precision cache (the serving memory knob, VERDICT r5 item 4)."""
    prompt = np.array([[7, 8, 9, 10]], np.int32)
    outs = {}
    for label, extra in (("fp", {}), ("q", {"kv_dtype": "int8"})):
        definition = {
            "name": f"kv_{label}",
            "graph": ["(lm)"],
            "elements": [
                {"name": "lm", "input": [{"name": "tokens"}],
                 "output": [{"name": "generated"}],
                 "parameters": {**TINY_LM, "max_new_tokens": 6, **extra},
                 "deploy": local("LMGenerate")},
            ],
        }
        [(_, _, outputs)] = run_frames_with_data(
            definition, {"tokens": prompt})
        outs[label] = np.asarray(outputs["generated"])
    np.testing.assert_array_equal(outs["fp"], outs["q"])


def test_lm_generate_weight_dtype_int8():
    """weight_dtype="int8" at the ELEMENT level: serving decode with
    8-bit weights produces a valid generation (numerics pinned at the
    model level in TestWeightOnlyInt8)."""
    prompt = np.array([[7, 8, 9, 10]], np.int32)
    definition = {
        "name": "w8", "graph": ["(lm)"],
        "elements": [
            {"name": "lm", "input": [{"name": "tokens"}],
             "output": [{"name": "generated"}],
             "parameters": {**TINY_LM, "max_new_tokens": 6,
                            "weight_dtype": "int8"},
             "deploy": local("LMGenerate")},
        ],
    }
    [(_, _, outputs)] = run_frames_with_data(definition, {"tokens": prompt})
    generated = np.asarray(outputs["generated"])
    assert generated.shape == (1, 6)
    assert ((generated >= 0) & (generated < TINY_LM["vocab_size"])).all()


# -- fused whole-group execution on the model stages -------------------------

def _inject_frames(definition, frames, timeout=120):
    """Queue `frames` before the event loop starts (all park in the
    micro-batch scheduler), return ({frame_id: outputs}, pipeline)."""
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, definition)
    responses = queue.Queue()
    stream = pipeline.create_stream("s", queue_response=responses,
                                    grace_time=300)
    for frame_data in frames:
        pipeline.create_frame(stream, frame_data)
    process.run(in_thread=True)
    got = {}
    for _ in range(len(frames)):
        _, frame, outputs = responses.get(timeout=timeout)
        got[frame.frame_id] = outputs
    process.terminate()
    return got, pipeline


def _tree_equal(left, right):
    if isinstance(left, dict):
        assert set(left) == set(right)
        for key in left:
            _tree_equal(left[key], right[key])
        return
    left = np.asarray(left)
    right = np.asarray(right)
    assert left.dtype == right.dtype and left.shape == right.shape
    np.testing.assert_array_equal(left, right)


def test_detector_fused_group_matches_chained():
    """Detector's group kernel (concat+detect+split as ONE program) must
    reproduce the chained micro-batch path's detections exactly."""

    def build(fused):
        return {
            "name": "fused_det",
            "graph": ["(detector)"],
            "elements": [
                {"name": "detector", "input": [{"name": "image"}],
                 "output": [{"name": "detections"}],
                 "parameters": {**TINY_DET, "micro_batch": 4,
                                "micro_batch_fused": fused},
                 "deploy": local("Detector")},
            ],
        }

    rng = np.random.default_rng(0)
    frames = [{"image": rng.uniform(
        0, 1, (1, 3, 32, 32)).astype(np.float32)} for _ in range(3)]
    fused_got, fused_pipe = _inject_frames(build(True), frames)
    chained_got, chained_pipe = _inject_frames(build(False), frames)
    assert fused_pipe._fused_programs and not chained_pipe._fused_programs
    assert set(fused_got) == set(chained_got)
    for frame_id in fused_got:
        _tree_equal(fused_got[frame_id]["detections"],
                    chained_got[frame_id]["detections"])


def test_speech_to_text_fused_group_matches_chained():
    def build(fused):
        return {
            "name": "fused_asr",
            "graph": ["(asr)"],
            "elements": [
                {"name": "asr", "input": [{"name": "audio"}],
                 "output": [{"name": "tokens"}],
                 "parameters": {**TINY_ASR, "micro_batch": 4,
                                "micro_batch_fused": fused},
                 "deploy": local("SpeechToText")},
            ],
        }

    rng = np.random.default_rng(1)
    frames = [{"audio": rng.standard_normal(
        (1, 1600)).astype(np.float32)} for _ in range(3)]
    fused_got, fused_pipe = _inject_frames(build(True), frames)
    chained_got, _ = _inject_frames(build(False), frames)
    assert fused_pipe._fused_programs
    for frame_id in fused_got:
        _tree_equal(fused_got[frame_id]["tokens"],
                    chained_got[frame_id]["tokens"])


def test_lm_generate_fused_group_matches_chained():
    def build(fused):
        return {
            "name": "fused_lm",
            "graph": ["(lm)"],
            "elements": [
                {"name": "lm", "input": [{"name": "tokens"}],
                 "output": [{"name": "generated"}],
                 "parameters": {**TINY_LM, "micro_batch": 4,
                                "micro_batch_fused": fused,
                                "max_new_tokens": 4},
                 "deploy": local("LMGenerate")},
            ],
        }

    rng = np.random.default_rng(2)
    frames = [{"tokens": rng.integers(
        1, 300, (1, 6), dtype=np.int32)} for _ in range(3)]
    fused_got, fused_pipe = _inject_frames(build(True), frames)
    chained_got, _ = _inject_frames(build(False), frames)
    assert fused_pipe._fused_programs
    for frame_id in fused_got:
        _tree_equal(fused_got[frame_id]["generated"],
                    chained_got[frame_id]["generated"])


def test_lm_generate_group_kernel_gated_on_host_work():
    """Configurations whose process_frame does per-frame host work
    (tokenizer decode, token streaming) must fall back to the chained
    path: group_kernel returns None."""
    definition = {
        "name": "gated_lm",
        "graph": ["(lm)"],
        "elements": [
            {"name": "lm", "input": [{"name": "text"}],
             "output": [{"name": "generated"}, {"name": "text"}],
             "parameters": {**TINY_LM, "tokenizer": "default",
                            "max_new_tokens": 2},
             "deploy": local("LMGenerate")},
        ],
    }
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, definition)
    process.run(in_thread=True)
    responses = queue.Queue()
    stream = pipeline.create_stream("s", queue_response=responses,
                                    grace_time=300)
    assert pipeline.elements["lm"].group_kernel(stream) is None
    process.terminate()
