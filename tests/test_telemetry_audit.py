# Telemetry-name audit (ISSUE 14 satellite): every counter / gauge /
# histogram name the serving, decode, and pipeline layers write must
# appear in the README's observability documentation -- undocumented
# telemetry is telemetry nobody alarms on.
#
# The scan is an AST walk over the package sources: any call of the
# form `<registry-ish>.counter("name")` / `.gauge("name")` /
# `.histogram("name")` with a LITERAL first argument is harvested.
# Dynamic families (f-strings like `gateway.routed:{replica}`) are
# audited by their literal prefix where one exists in the same call
# (JoinedStr leading literal), and skipped when fully dynamic.

import ast
import re
from pathlib import Path

PACKAGE = Path(__file__).resolve().parent.parent / "aiko_services_tpu"
README = Path(__file__).resolve().parent.parent / "README.md"

# the layers the audit covers (ISSUE 14: serve/, decode/, pipeline/ --
# observe/ itself included since it defines the shared instruments;
# ISSUE 15 added transport/ + runtime/ so the broker.* / share.* /
# registrar.* control-plane instruments are enforced too)
SCANNED_DIRS = ("serve", "decode", "pipeline", "observe", "transport",
                "runtime")

_METHODS = {"counter", "gauge", "histogram"}


def _instrument_names():
    """{metric name (or family prefix) -> [source files]} from the
    scanned sources."""
    names: dict = {}
    for directory in SCANNED_DIRS:
        for path in sorted((PACKAGE / directory).glob("*.py")):
            tree = ast.parse(path.read_text())
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call) or not isinstance(
                        node.func, ast.Attribute):
                    continue
                if node.func.attr not in _METHODS or not node.args:
                    continue
                argument = node.args[0]
                name = None
                if isinstance(argument, ast.Constant) and isinstance(
                        argument.value, str):
                    name = argument.value
                elif isinstance(argument, ast.JoinedStr) \
                        and argument.values \
                        and isinstance(argument.values[0],
                                       ast.Constant):
                    # f"gateway.queue_depth:p{n}" -> family prefix
                    name = str(argument.values[0].value)
                elif isinstance(argument, ast.BinOp) and isinstance(
                        argument.op, ast.Add) and isinstance(
                        argument.left, ast.Constant):
                    # "element_s:" + node -> family prefix
                    name = str(argument.left.value)
                if name:
                    names.setdefault(name, []).append(
                        str(path.relative_to(PACKAGE.parent)))
    return names


def _documented(name: str, readme_text: str) -> bool:
    """A name is documented when the README mentions it verbatim, or
    (for a family like "element_s:" / "gateway.queue_depth:p") mentions
    the family with any suffix."""
    base = name.rstrip(":")
    if base.endswith(":p"):           # per-priority gauge families
        base = base[:-2]
    return base in readme_text


def test_every_instrument_name_is_documented():
    names = _instrument_names()
    assert len(names) >= 40, (
        f"audit scan looks broken: only {len(names)} instrument "
        f"names found")
    readme_text = README.read_text()
    missing = {name: files for name, files in sorted(names.items())
               if not _documented(name, readme_text)}
    assert not missing, (
        "telemetry names missing from the README "
        "observability/telemetry tables (document them in the "
        "'Telemetry reference' table):\n" + "\n".join(
            f"  {name}  ({', '.join(sorted(set(files)))})"
            for name, files in missing.items()))


def test_readme_has_a_telemetry_reference_table():
    text = README.read_text()
    assert "### Telemetry reference" in text
    # the table is real markdown, not prose: a header rule row exists
    # within the section (before the next heading)
    section = text.split("### Telemetry reference", 1)[1]
    section = section.split("\n## ", 1)[0]
    assert re.search(r"^\|[-| ]+\|$", section, re.MULTILINE), \
        "telemetry reference section carries no markdown table"
