# Orchestration layer tests: state machine, process manager, storage +
# request idioms, recorder, lifecycle manager/client -- hermetic over the
# loopback broker.

import os
import sys
import time

import pytest

from aiko_services_tpu.runtime import (
    LifeCycleClient, LifeCycleManager, ProcessManager, Recorder, Registrar,
    Process, StateMachine, StateMachineError, Storage, do_request)
from aiko_services_tpu.runtime.service import ServiceFilter
from aiko_services_tpu.transport import get_broker, reset_brokers
from helpers import wait_for


@pytest.fixture(autouse=True)
def clean_brokers():
    reset_brokers()
    yield
    reset_brokers()


class TestStateMachine:
    class Model:
        entered = None

        def on_enter_primary(self, **kwargs):
            self.entered = ("primary", kwargs)

    def _machine(self):
        model = self.Model()
        return model, StateMachine(
            model,
            states=["start", "primary_search", "primary", "secondary"],
            transitions=[
                {"name": "initialize", "source": "start",
                 "dest": "primary_search"},
                {"name": "promote", "source": "primary_search",
                 "dest": "primary"},
                {"name": "demote", "source": "*", "dest": "secondary"},
            ],
            initial="start")

    def test_transitions_and_callbacks(self):
        model, machine = self._machine()
        machine.transition("initialize")
        assert machine.get_state() == "primary_search"
        machine.transition("promote", reason="timeout")
        assert model.entered == ("primary", {"reason": "timeout"})

    def test_wildcard_source(self):
        _, machine = self._machine()
        machine.transition("demote")
        assert machine.get_state() == "secondary"

    def test_invalid_transition_raises(self):
        _, machine = self._machine()
        with pytest.raises(StateMachineError, match="invalid from"):
            machine.transition("promote")  # not in primary_search


class TestProcessManager:
    def test_spawn_and_reap(self):
        exits = []
        manager = ProcessManager(
            lambda process_id, code: exits.append((process_id, code)))
        child = manager.spawn(
            "sleeper", sys.executable,
            arguments=["-c", "import time; time.sleep(0.1)"],
            use_interpreter=False)
        assert "sleeper" in manager
        wait_for(lambda: ("sleeper", 0) in exits, timeout=10)
        assert child.returncode == 0
        manager.terminate()

    def test_kill(self):
        manager = ProcessManager()
        manager.spawn("stuck", sys.executable,
                      arguments=["-c", "import time; time.sleep(60)"],
                      use_interpreter=False)
        start = time.time()
        manager.kill("stuck")
        assert time.time() - start < 10
        assert "stuck" not in manager
        manager.terminate()

    def test_resolve_command_module(self):
        path = ProcessManager.resolve_command("json")
        assert path.endswith("__init__.py")


class TestSystemBootstrap:
    """`aiko system start|stop`: the one-command local deployment
    (registrar + named pipeline as detached children, pids recorded in
    a state file the stop command consumes)."""

    def _definition(self, tmp_path):
        import json
        path = tmp_path / "tiny.json"
        path.write_text(json.dumps({
            "name": "tiny", "graph": ["(source)"],
            "elements": [
                {"name": "source",
                 "output": [{"name": "text", "type": "str"}],
                 "parameters": {"data_sources": ["x"]},
                 "deploy": {"local": {
                     "module": "aiko_services_tpu.elements",
                     "class_name": "TextSource"}}}]}))
        return path

    def test_start_then_stop(self, tmp_path):
        from click.testing import CliRunner
        from aiko_services_tpu.cli import main as cli_main
        from aiko_services_tpu.cli import _pid_alive, _system_state

        state_file = tmp_path / "system.json"
        runner = CliRunner()
        result = runner.invoke(cli_main, [
            "system", "start", str(self._definition(tmp_path)),
            "--name", "boot_pipe", "--transport", "loopback",
            "--no-dashboard", "--state-file", str(state_file)])
        assert result.exit_code == 0, result.output
        state = _system_state(str(state_file))
        pids = state["pids"]
        assert set(pids) == {"registrar", "pipeline:boot_pipe"}
        assert all(_pid_alive(pid) for pid in pids.values())

        # double-start refuses while the recorded pids are alive
        again = runner.invoke(cli_main, [
            "system", "start", str(self._definition(tmp_path)),
            "--no-dashboard", "--state-file", str(state_file)])
        assert again.exit_code == 1

        status = runner.invoke(cli_main, [
            "system", "status", "--state-file", str(state_file)])
        assert status.exit_code == 0 and "up" in status.output

        result = runner.invoke(cli_main, [
            "system", "stop", "--state-file", str(state_file)])
        assert result.exit_code == 0, result.output
        wait_for(lambda: not any(_pid_alive(pid)
                                 for pid in pids.values()), timeout=15)
        assert not state_file.exists()

    def test_stop_without_state_is_an_error(self, tmp_path):
        from click.testing import CliRunner
        from aiko_services_tpu.cli import main as cli_main
        runner = CliRunner()
        result = runner.invoke(cli_main, [
            "system", "stop", "--state-file",
            str(tmp_path / "missing.json")])
        assert result.exit_code == 1

    @pytest.mark.skipif(not os.path.exists("/proc"),
                        reason="pid identity check needs /proc; without "
                               "it the fallback would SIGTERM this very "
                               "test process")
    def test_stop_refuses_recycled_pid(self, tmp_path):
        """A stale state file whose pid now belongs to an UNRELATED
        process (reboot/pid reuse) must not be signalled: this very
        test process is alive but is not an `aiko_services_tpu`
        child, so stop leaves it alone."""
        import json
        import os
        from click.testing import CliRunner
        from aiko_services_tpu.cli import main as cli_main

        state_file = tmp_path / "system.json"
        state_file.write_text(json.dumps(
            {"pids": {"registrar": os.getpid()}}))
        runner = CliRunner()
        result = runner.invoke(cli_main, [
            "system", "stop", "--state-file", str(state_file)])
        assert result.exit_code == 0, result.output
        assert "leaving it alone" in result.output
        assert not state_file.exists()


class TestStorage:
    def test_save_load_keys_delete_via_wire(self):
        process = Process(transport_kind="loopback")
        registrar_process = Process(transport_kind="loopback")
        Registrar(registrar_process, search_timeout=0.05)
        registrar_process.run(in_thread=True)
        storage = Storage(process)
        process.run(in_thread=True)

        # local API
        storage.save("alpha", {"x": 1})
        storage.save("beta", [1, 2, 3])

        results = []
        do_request(
            process, ServiceFilter(protocol="storage*"),
            lambda proxy, response_topic: proxy.keys(response_topic),
            results.append)
        wait_for(lambda: results, timeout=10)
        assert results[0] == ["alpha", "beta"]

        loaded = []
        do_request(
            process, ServiceFilter(protocol="storage*"),
            lambda proxy, response_topic: proxy.load(
                "alpha", response_topic),
            loaded.append)
        wait_for(lambda: loaded, timeout=10)
        import json
        assert json.loads(loaded[0][0]) == {"x": 1}

        storage.delete("alpha")
        gone = []
        do_request(
            process, ServiceFilter(protocol="storage*"),
            lambda proxy, response_topic: proxy.load(
                "alpha", response_topic),
            gone.append)
        wait_for(lambda: gone == [[]], timeout=10)
        process.terminate()
        registrar_process.terminate()


class TestRecorder:
    def test_log_aggregation(self):
        process = Process(transport_kind="loopback")
        recorder = Recorder(process)
        process.run(in_thread=True)
        log_topic = f"{process.namespace}/host/123/1/log"
        for index in range(5):
            process.publish(log_topic, f"line {index}")
        get_broker().drain()
        wait_for(lambda: len(recorder.records(log_topic)) == 5)
        assert recorder.topics() == [log_topic]
        assert recorder.records(log_topic)[0] == "line 0"
        process.terminate()

    def test_ring_bounded(self):
        process = Process(transport_kind="loopback")
        recorder = Recorder(process, ring_size=4)
        process.run(in_thread=True)
        log_topic = f"{process.namespace}/host/1/1/log"
        for index in range(10):
            process.publish(log_topic, f"line {index}")
        get_broker().drain()
        wait_for(lambda: recorder.records(log_topic) and
                 recorder.records(log_topic)[-1] == "line 9")
        assert recorder.records(log_topic) == [
            "line 6", "line 7", "line 8", "line 9"]
        process.terminate()


class TestLifeCycle:
    def test_handshake_and_delete(self, tmp_path):
        registrar_process = Process(transport_kind="loopback")
        Registrar(registrar_process, search_timeout=0.05)
        registrar_process.run(in_thread=True)

        manager_process = Process(transport_kind="loopback")
        changes = []
        manager = LifeCycleManager(
            manager_process, "lcm",
            client_change_handler=lambda cmd, cid: changes.append(
                (cmd, cid)))
        manager_process.run(in_thread=True)

        # the OS child is a dummy sleeper; the handshake comes from a
        # client living in this test process on the shared loopback broker
        sleeper = tmp_path / "sleeper.py"
        sleeper.write_text("import time; time.sleep(30)\n")
        client_id = manager.create_client(str(sleeper))
        record = manager.clients[client_id]
        assert record["state"] == "spawning"

        client_process = Process(transport_kind="loopback")
        client = LifeCycleClient(
            client_process, "worker", manager.topic_path, client_id)
        client.share["task"] = "indexing"
        client_process.run(in_thread=True)

        wait_for(lambda: manager.clients[client_id]["state"] == "running",
                 timeout=10)
        assert ("add", client_id) in changes

        # manager mirrors the client's share via ECConsumer
        client.ec_producer.update("task", "training")
        wait_for(lambda: manager.clients[client_id]["share"].get(
            "task") == "training", timeout=10)

        manager.delete_client(client_id)
        wait_for(lambda: client_id not in manager.clients, timeout=15)
        assert ("remove", client_id) in changes

        for process in (registrar_process, manager_process,
                        client_process):
            process.terminate()

    def test_handshake_timeout_kills_client(self, tmp_path):
        # reap path 1: handshake-lease lapse -- the OS child came up
        # but never announced; the lease kills it and drops the record
        manager_process = Process(transport_kind="loopback")
        manager = LifeCycleManager(manager_process, "lcm2",
                                   handshake_lease_time=0.2)
        manager_process.run(in_thread=True)
        sleeper = tmp_path / "sleeper.py"
        sleeper.write_text("import time; time.sleep(30)\n")
        client_id = manager.create_client(str(sleeper))
        wait_for(lambda: client_id not in manager.clients, timeout=10)
        assert client_id not in manager.process_manager
        manager_process.terminate()

    def test_client_crash_with_lwt_reaps_record_and_zombie(self,
                                                           tmp_path):
        """Reap path 2: the client's broker connection dies (severed
        transport, the fault harness's crash primitive) -- LWT
        "(absent)" fires, the registrar removes the client's services,
        and the manager's registrar watch must reap the record AND the
        wedged OS child, even though the child process never exited on
        its own."""
        registrar_process = Process(transport_kind="loopback")
        Registrar(registrar_process, search_timeout=0.05)
        registrar_process.run(in_thread=True)

        manager_process = Process(transport_kind="loopback")
        changes = []
        manager = LifeCycleManager(
            manager_process, "lcm3",
            client_change_handler=lambda cmd, cid: changes.append(
                (cmd, cid)))
        manager_process.run(in_thread=True)

        sleeper = tmp_path / "sleeper.py"
        sleeper.write_text("import time; time.sleep(30)\n")
        client_id = manager.create_client(str(sleeper))

        client_process = Process(transport_kind="loopback")
        LifeCycleClient(client_process, "worker3",
                        manager.topic_path, client_id)
        client_process.run(in_thread=True)
        wait_for(lambda: manager.clients.get(
            client_id, {}).get("state") == "running", timeout=10)
        assert client_id in manager.process_manager  # sleeper alive

        client_process.transport.sever()  # crash WITH LWT
        wait_for(lambda: client_id not in manager.clients, timeout=15)
        assert ("remove", client_id) in changes
        # kill=True: the zombie OS child goes too
        wait_for(lambda: client_id not in manager.process_manager,
                 timeout=15)
        for process in (registrar_process, manager_process):
            process.terminate()

    def test_exit_handler_delivered_off_monitor_thread(self, tmp_path):
        """Reap path 3: an OS child exit is observed on the
        ProcessManager MONITOR thread, but every state mutation (record
        removal, change handler) must land on the manager's event loop
        -- the single-threaded scheduler the rest of the actor's state
        assumes."""
        import threading

        manager_process = Process(transport_kind="loopback")
        removals = []
        manager = LifeCycleManager(
            manager_process, "lcm4",
            client_change_handler=lambda cmd, cid: removals.append(
                (cmd, cid, threading.current_thread().name)))
        manager_process.run(in_thread=True)
        quick = tmp_path / "quick.py"
        quick.write_text("import sys; sys.exit(0)\n")
        client_id = manager.create_client(str(quick))
        wait_for(lambda: client_id not in manager.clients, timeout=15)
        wait_for(lambda: removals, timeout=10)
        command, removed_id, thread_name = removals[0]
        assert (command, removed_id) == ("remove", client_id)
        assert thread_name != "process-manager"   # not the monitor
        assert thread_name.endswith("-loop")      # the event loop
        manager_process.terminate()
