# Functional vision correctness: the pipeline must DETECT, not just
# produce detection-shaped output.  The committed checkpoint
# (tests/assets/detector_shapes.safetensors, trained by
# examples/train_detector_shapes.py to perfect held-out accuracy on
# colored-square images) flows through the REAL element path: image in
# -> Detector(weights=...) -> correct class + box out.
#
# Reference parity: the reference's vision seat detects because it
# loads pretrained ultralytics YOLOv8 (yolo.py:51-54); with no
# published checkpoints in this image, a trained-to-correctness tiny
# model proves the same capability end to end.

import pathlib
import queue

import numpy as np
import pytest

from aiko_services_tpu.pipeline import create_pipeline
from aiko_services_tpu.runtime import Process
from aiko_services_tpu.transport import reset_brokers

ASSET = (pathlib.Path(__file__).parent / "assets"
         / "detector_shapes.safetensors")


def _asset_metadata() -> dict:
    import ast

    from aiko_services_tpu.models import SafetensorsFile
    container = SafetensorsFile(ASSET)
    metadata = {key: ast.literal_eval(value)
                for key, value in container.metadata.items()}
    container.close()
    return metadata


_METADATA = _asset_metadata()
_CONFIG = _METADATA["config"]
COLORS = np.asarray(_METADATA["colors"], np.float32)
IMAGE_SIZE = int(_CONFIG["image_size"])


@pytest.fixture(autouse=True)
def clean_brokers():
    reset_brokers()
    yield
    reset_brokers()


def _square_image(class_id: int, x0: int, y0: int, side: int):
    rng = np.random.default_rng(class_id * 1000 + x0 + y0)
    image = rng.uniform(0.0, 0.25,
                        (3, IMAGE_SIZE, IMAGE_SIZE)).astype(np.float32)
    image[:, y0:y0 + side, x0:x0 + side] = (
        COLORS[class_id][:, None, None] * 0.9)
    return image, (x0, y0, x0 + side, y0 + side)


def _iou(a, b) -> float:
    lt = np.maximum(np.asarray(a[:2]), np.asarray(b[:2]))
    rb = np.minimum(np.asarray(a[2:]), np.asarray(b[2:]))
    wh = np.maximum(rb - lt, 0.0)
    inter = wh[0] * wh[1]
    union = ((a[2] - a[0]) * (a[3] - a[1])
             + (b[2] - b[0]) * (b[3] - b[1]) - inter)
    return float(inter / max(union, 1e-9))


def test_pipeline_detects_correct_class_and_box():
    """Image in -> the RIGHT object out: one valid detection, correct
    class, IoU >= 0.7 -- fails if the pipeline stops detecting."""
    definition = {
        "name": "det_correct",
        "graph": ["(detector)"],
        "elements": [
            {"name": "detector", "input": [{"name": "image"}],
             "output": [{"name": "detections"}],
             "parameters": {**_CONFIG, "weights": str(ASSET)},
             "deploy": {"local": {
                 "module": "aiko_services_tpu.elements",
                 "class_name": "Detector"}}},
        ],
    }
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, definition)
    process.run(in_thread=True)
    responses = queue.Queue()
    stream = pipeline.create_stream("s1", queue_response=responses)
    cases = [  # (class, x0, y0, side): distinct held-out placements
        (0, 6, 8, 20), (1, 30, 12, 16), (2, 14, 34, 22), (3, 36, 36, 18)]
    expected = []
    for class_id, x0, y0, side in cases:
        image, box = _square_image(class_id, x0, y0, side)
        expected.append((class_id, box))
        pipeline.create_frame(stream, {"image": image[None]})
    for index in range(len(cases)):
        _, frame, outputs = responses.get(timeout=120)
        class_id, box = expected[frame.frame_id]
        detections = {key: np.asarray(value)[0]
                      for key, value in outputs["detections"].items()}
        valid = detections["valid"]
        assert valid.sum() == 1, (
            f"case {frame.frame_id}: expected exactly one detection, "
            f"got {int(valid.sum())}")
        slot = int(np.argmax(valid))
        assert int(detections["classes"][slot]) == class_id
        assert _iou(detections["boxes"][slot], box) >= 0.7, (
            detections["boxes"][slot], box)
    process.terminate()
