import time

from aiko_services_tpu.runtime import EventEngine
from helpers import wait_for


def make_engine():
    engine = EventEngine("test")
    engine.loop_in_thread()
    return engine


def test_timer_fires_repeatedly():
    engine = make_engine()
    fired = []
    engine.add_timer_handler(lambda: fired.append(time.monotonic()), 0.01)
    wait_for(lambda: len(fired) >= 3)
    engine.terminate()
    assert len(fired) >= 3


def test_timer_removal():
    engine = make_engine()
    fired = []

    def handler():
        fired.append(1)
        engine.remove_timer_handler(handler)

    engine.add_timer_handler(handler, 0.005)
    time.sleep(0.1)
    engine.terminate()
    assert len(fired) == 1


def test_queue_dispatch():
    engine = make_engine()
    received = []
    engine.add_queue_handler(received.append, ["message"])
    for index in range(10):
        engine.queue_put(index, "message")
    wait_for(lambda: len(received) == 10)
    engine.terminate()
    assert received == list(range(10))


def test_mailbox_priority_order():
    """The first-registered mailbox (control) drains before later ones."""
    engine = EventEngine("test")
    received = []
    engine.add_mailbox_handler(
        lambda name, item: received.append(("control", item)), "control")
    engine.add_mailbox_handler(
        lambda name, item: received.append(("in", item)), "in")
    # enqueue before loop starts so priority is observable deterministically
    engine.mailbox_put("in", 1)
    engine.mailbox_put("in", 2)
    engine.mailbox_put("control", 99)
    engine.loop_in_thread()
    wait_for(lambda: len(received) == 3)
    engine.terminate()
    assert received[0] == ("control", 99)
    assert received[1:] == [("in", 1), ("in", 2)]


def test_mailbox_put_before_handler_registered():
    engine = make_engine()
    received = []
    engine.mailbox_put("late", "early-item")
    engine.add_mailbox_handler(
        lambda name, item: received.append(item), "late")
    wait_for(lambda: received)
    engine.terminate()
    assert received == ["early-item"]


def test_dispatch_latency_beats_reference_tick():
    """The reference loop polls at 10 ms; ours must dispatch 1000 queue items
    far faster than the 10 s the reference tick would imply."""
    engine = make_engine()
    received = []
    engine.add_queue_handler(received.append, ["message"])
    start = time.monotonic()
    for index in range(1000):
        engine.queue_put(index, "message")
    wait_for(lambda: len(received) == 1000)
    elapsed = time.monotonic() - start
    engine.terminate()
    assert elapsed < 2.0, f"dispatch too slow: {elapsed:.3f}s"


def test_flatout_handler_runs_when_idle():
    engine = make_engine()
    count = []
    engine.add_flatout_handler(lambda: count.append(1))
    wait_for(lambda: len(count) > 5)
    engine.remove_flatout_handler
    engine.terminate()


def test_handler_exception_does_not_kill_loop():
    engine = make_engine()
    received = []

    def bad_handler(item):
        raise RuntimeError("boom")

    engine.add_queue_handler(bad_handler, ["message"])
    engine.add_queue_handler(received.append, ["message"])
    engine.queue_put("x", "message")
    wait_for(lambda: received)
    engine.terminate()
    assert received == ["x"]
