# Cross-process data plane tests (VERDICT round-1 item 5): the tensor
# transfer plane (descriptor over control plane, bytes over a direct
# socket -- never base64 through the broker) and the jax.distributed
# multi-process runtime with a global mesh.

import json
import os
import queue
import socket
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from aiko_services_tpu.pipeline.transfer import (
    TensorTransferServer, fetch, reset_transfer_server)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestTransferServer:
    def test_offer_fetch_roundtrip(self):
        server = TensorTransferServer()
        try:
            array = np.arange(4096, dtype=np.float32).reshape(64, 64)
            descriptor = server.offer(array)
            assert descriptor["dtype"] == "float32"
            assert descriptor["shape"] == [64, 64]
            fetched = fetch(descriptor)
            np.testing.assert_array_equal(fetched, array)
        finally:
            server.close()

    def test_fetch_lingers_then_expires(self, monkeypatch):
        # keys survive their first fetch for AIKO_TRANSFER_LINGER seconds
        # (broker redelivery / second hop-topic subscriber), then expire
        monkeypatch.setenv("AIKO_TRANSFER_LINGER", "1.0")
        server = TensorTransferServer()
        try:
            descriptor = server.offer(np.ones(8))
            np.testing.assert_array_equal(fetch(descriptor), np.ones(8))
            np.testing.assert_array_equal(fetch(descriptor), np.ones(8))
            time.sleep(1.3)
            with pytest.raises(KeyError):
                fetch(descriptor)
        finally:
            server.close()

    def test_unknown_key_raises(self):
        server = TensorTransferServer()
        try:
            descriptor = server.offer(np.ones(4))
            bogus = dict(descriptor, key="0" * 32)
            with pytest.raises(KeyError):
                fetch(bogus)
        finally:
            server.close()

    def test_non_contiguous_and_bfloat16_like_dtypes(self):
        server = TensorTransferServer()
        try:
            array = np.arange(64, dtype=np.int16).reshape(8, 8)[::2, ::2]
            fetched = fetch(server.offer(array))
            np.testing.assert_array_equal(fetched, array)
        finally:
            server.close()


class TestCodecIntegration:
    def test_large_array_travels_as_descriptor(self, monkeypatch):
        """The encoded control message must contain a descriptor, not the
        array bytes; decode fetches over the socket."""
        monkeypatch.setenv("AIKO_TRANSFER_THRESHOLD", "0")
        reset_transfer_server()
        from aiko_services_tpu.pipeline.tensors import (
            decode_frame_data, encode_frame_data)
        array = np.random.default_rng(0).normal(size=(128, 128))
        text = encode_frame_data({"x": array})
        assert "__tensorref__" in text
        assert "__ndarray__" not in text
        # control message is tiny: descriptor only, no payload
        assert len(text) < 512
        decoded = decode_frame_data(text)
        np.testing.assert_array_equal(decoded["x"], array)
        reset_transfer_server()

    def test_small_values_stay_inline(self, monkeypatch):
        monkeypatch.setenv("AIKO_TRANSFER_THRESHOLD", str(1 << 16))
        from aiko_services_tpu.pipeline.tensors import (
            decode_frame_data, encode_frame_data)
        array = np.arange(16, dtype=np.int32)
        text = encode_frame_data({"x": array})
        assert "__tensorref__" not in text
        np.testing.assert_array_equal(decode_frame_data(text)["x"], array)

    def test_kill_switch(self, monkeypatch):
        monkeypatch.setenv("AIKO_TRANSFER", "0")
        monkeypatch.setenv("AIKO_TRANSFER_THRESHOLD", "0")
        from aiko_services_tpu.pipeline.tensors import encode_frame_data
        text = encode_frame_data({"x": np.zeros((64, 64))})
        assert "__tensorref__" not in text


class TestCrossOSProcess:
    def test_array_moves_between_processes_without_base64(self):
        """A second OS process offers a tensor; this process receives only
        the descriptor (via the child's stdout, standing in for the
        control plane) and pulls the bytes over the socket."""
        child = textwrap.dedent("""
            import json, sys
            import numpy as np
            from aiko_services_tpu.pipeline.transfer import (
                TensorTransferServer)
            server = TensorTransferServer()
            array = np.arange(65536, dtype=np.float32).reshape(256, 256)
            print(json.dumps(server.offer(array)), flush=True)
            sys.stdin.readline()  # hold the server open until fetched
        """)
        env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(
            [sys.executable, "-c", child], stdin=subprocess.PIPE,
            stdout=subprocess.PIPE, env=env, text=True)
        try:
            descriptor = json.loads(proc.stdout.readline())
            assert "data" not in descriptor  # no inline payload anywhere
            array = fetch(descriptor)
            assert array.shape == (256, 256)
            np.testing.assert_allclose(array[255, 255], 65535.0)
        finally:
            proc.stdin.write("done\n")
            proc.stdin.close()
            proc.wait(timeout=10)


JD_WORKER = textwrap.dedent("""
    import sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    from aiko_services_tpu.parallel import (
        global_mesh, initialize_distributed, process_count, process_index)
    coordinator, rank = sys.argv[1], int(sys.argv[2])
    assert initialize_distributed(coordinator_address=coordinator,
                                  num_processes=2, process_id=rank)
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = global_mesh({"data": -1})
    assert len(jax.devices()) == 2 and process_count() == 2
    sharded = jax.device_put(
        jnp.arange(16.0), NamedSharding(mesh, P("data")))
    total = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(
        sharded)
    print(f"rank {process_index()} total {float(total)}", flush=True)
""")


class TestJaxDistributed:
    def test_two_process_global_mesh_collective(self):
        """Two OS processes join via jax.distributed; a global 2-device
        mesh spans them and a jit-compiled cross-process reduction
        returns the full sum on both ranks."""
        with socket.socket() as probe:
            probe.bind(("127.0.0.1", 0))
            port = probe.getsockname()[1]
        coordinator = f"127.0.0.1:{port}"
        env = dict(os.environ, PYTHONPATH=REPO)
        env.pop("XLA_FLAGS", None)  # one CPU device per process
        workers = [
            subprocess.Popen(
                [sys.executable, "-c", JD_WORKER, coordinator, str(rank)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                env=env, text=True)
            for rank in range(2)]
        outputs = []
        for worker in workers:
            out, _ = worker.communicate(timeout=120)
            outputs.append(out)
            assert worker.returncode == 0, out
        combined = "\n".join(outputs)
        assert "total 120.0" in combined


class TestPipelineRemoteHop:
    def test_remote_hop_carries_descriptor_not_base64(self, monkeypatch):
        """A tensor crossing a remote-element hop rides the transfer
        plane: every broker message stays tiny (descriptors), and the
        remote pipeline still computes on the real array."""
        monkeypatch.setenv("AIKO_TRANSFER_THRESHOLD", "1024")
        reset_transfer_server()
        import jax
        from aiko_services_tpu.runtime import Process, Registrar
        from aiko_services_tpu.pipeline import create_pipeline
        from aiko_services_tpu.transport.loopback import get_broker

        def local(cls):
            return {"local": {"module": "aiko_services_tpu.elements",
                              "class_name": cls}}

        registrar_process = Process(transport_kind="loopback")
        Registrar(registrar_process, search_timeout=0.05)
        registrar_process.run(in_thread=True)

        remote_definition = {
            "name": "tensor_server",
            "graph": ["(total)"],
            "elements": [
                {"name": "total", "input": [{"name": "values"}],
                 "output": [{"name": "number"}],
                 "deploy": local("PE_Sum")},
            ],
        }
        process_b = Process(transport_kind="loopback")
        create_pipeline(process_b, remote_definition)
        process_b.run(in_thread=True)

        captured = []
        process_b.add_message_handler(
            lambda topic, payload: captured.append((topic, payload)),
            "#")

        local_definition = {
            "name": "tensor_client",
            "graph": ["(source (remote_total))"],
            "elements": [
                {"name": "source", "output": [{"name": "values"}],
                 "parameters": {"data_sources": [4096]},
                 "deploy": local("PE_RandomTensor")},
                {"name": "remote_total",
                 "input": [{"name": "values"}],
                 "output": [{"name": "number"}],
                 "deploy": {"remote": {"service_filter": {
                     "name": "tensor_server"}}}},
            ],
        }
        process_a = Process(transport_kind="loopback")
        pipeline_a = create_pipeline(process_a, local_definition)
        process_a.run(in_thread=True)
        import time
        deadline = time.monotonic() + 10
        while not pipeline_a.ready and time.monotonic() < deadline:
            time.sleep(0.01)
        assert pipeline_a.ready

        responses = queue.Queue()
        pipeline_a.create_stream("s1", queue_response=responses)
        _, _, outputs = responses.get(timeout=10)
        assert np.isfinite(float(np.asarray(outputs["number"])))

        def text_of(payload):
            return (payload.decode("utf-8", "replace")
                    if isinstance(payload, bytes) else str(payload))

        frame_messages = [text_of(payload) for topic, payload in captured
                          if "process_frame" in text_of(payload)]
        assert frame_messages, "no frame traffic captured"
        assert any("__tensorref__" in payload
                   for payload in frame_messages)
        assert all("__ndarray__" not in payload
                   for payload in frame_messages)
        for process in (process_a, process_b, registrar_process):
            process.terminate()
        reset_transfer_server()


class TestTransferHardening:
    def test_accept_loop_restarts_after_listener_death(self):
        """An UNEXPECTED listener-socket death (injected here by closing
        it out from under accept) must restart the accept loop on the
        SAME port: outstanding descriptors bake in (host, port), so a
        dead listener would otherwise turn every later fetch into a
        dropped frame."""
        from aiko_services_tpu.observe.metrics import get_registry
        server = TensorTransferServer()
        try:
            array = np.arange(256, dtype=np.float32)
            descriptor = server.offer(array)
            restarts0 = get_registry().counter(
                "transfer.listener_restarts").value
            server._listener.close()  # injected listener death
            deadline = time.monotonic() + 10
            fetched = None
            while time.monotonic() < deadline:
                try:
                    fetched = fetch(descriptor, timeout=2.0, retries=0)
                    break
                except ValueError:  # TransferError: not yet restarted
                    time.sleep(0.05)
            assert fetched is not None, "listener never came back"
            np.testing.assert_array_equal(fetched, array)
            assert get_registry().counter(
                "transfer.listener_restarts").value == restarts0 + 1
        finally:
            server.close()

    def test_reset_then_get_recreates_singleton_after_listener_death(self):
        """close -> get -> fetch: reset_transfer_server leaves a closed
        singleton behind; get_transfer_server must hand back a LIVE
        replacement whose fetches work, even after the previous
        instance's listener died abnormally."""
        from aiko_services_tpu.pipeline.transfer import (
            get_transfer_server)
        reset_transfer_server()
        first = get_transfer_server()
        first._listener.close()   # injected death, then deliberate close
        reset_transfer_server()
        second = get_transfer_server()
        try:
            assert second is not first and not second._closed
            array = np.arange(64, dtype=np.int32)
            descriptor = second.offer(array)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                try:
                    np.testing.assert_array_equal(
                        fetch(descriptor, timeout=2.0), array)
                    break
                except ValueError:
                    time.sleep(0.05)
            else:
                raise AssertionError("recreated server never served")
        finally:
            reset_transfer_server()

    def test_fetched_array_is_writable(self):
        server = TensorTransferServer()
        try:
            fetched = fetch(server.offer(np.zeros((32, 32))))
            fetched[0, 0] = 7.0  # must not raise read-only
            assert fetched[0, 0] == 7.0
        finally:
            server.close()

    def test_bfloat16_roundtrip(self):
        import ml_dtypes
        server = TensorTransferServer()
        try:
            array = np.arange(64).astype(ml_dtypes.bfloat16)
            fetched = fetch(server.offer(array))
            assert fetched.dtype == ml_dtypes.bfloat16
            np.testing.assert_array_equal(
                fetched.astype(np.float32), array.astype(np.float32))
        finally:
            server.close()

    def test_dead_producer_raises_transfer_error_a_value_error(self):
        from aiko_services_tpu.pipeline.transfer import TransferError
        with socket.socket() as probe:
            probe.bind(("127.0.0.1", 0))
            dead_port = probe.getsockname()[1]
        descriptor = {"host": "127.0.0.1", "port": dead_port,
                      "key": "0" * 32, "dtype": "float32", "shape": [4]}
        with pytest.raises(TransferError):
            fetch(descriptor, timeout=2.0)
        assert issubclass(TransferError, ValueError)  # pipeline drops it

    def test_is_distributed_does_not_initialize_backend(self):
        # calling is_distributed() must leave jax.distributed.initialize
        # runnable (regression: jax.process_count() booted the backend)
        child = textwrap.dedent("""
            import jax
            jax.config.update("jax_platforms", "cpu")
            from aiko_services_tpu.parallel import is_distributed
            assert is_distributed() is False
            from jax._src import distributed
            # the local runtime must still be uninitialized
            assert distributed.global_state.client is None
            import jax._src.xla_bridge as xb
            assert not xb._backends, "backend was initialized"
            print("clean", flush=True)
        """)
        env = dict(os.environ, PYTHONPATH=REPO)
        result = subprocess.run([sys.executable, "-c", child],
                                capture_output=True, text=True, env=env,
                                timeout=60)
        assert result.returncode == 0, result.stdout + result.stderr
        assert "clean" in result.stdout

    def test_lost_response_payload_releases_parked_frame(self):
        """If a remote response's tensor payload is unrecoverable (its
        producer died), the parked frame must be released, not leaked
        until the stream lease expires."""
        import jax
        from aiko_services_tpu.runtime import Process
        from aiko_services_tpu.pipeline import create_pipeline
        from aiko_services_tpu.pipeline.stream import Frame

        process = Process(transport_kind="loopback")
        definition = {
            "name": "leakcheck",
            "graph": ["(add)"],
            "elements": [
                {"name": "add", "input": [{"name": "number"}],
                 "output": [{"name": "number"}],
                 "deploy": {"local": {
                     "module": "aiko_services_tpu.elements",
                     "class_name": "PE_Add"}}},
            ],
        }
        pipeline = create_pipeline(process, definition)
        process.run(in_thread=True)
        pipeline.create_stream("s1")
        stream = pipeline.streams["s1"]
        frame = Frame(frame_id=0)
        # park state as the engine produces it: the node is BOTH the
        # fallback holder and a pending node (un-named responses route
        # by the pending-parks set)
        frame.paused_pe_name = "add"
        frame.pending_nodes = {"add"}
        stream.frames[0] = frame
        with socket.socket() as probe:
            probe.bind(("127.0.0.1", 0))
            dead_port = probe.getsockname()[1]
        bad_payload = json.dumps({"number": {"__tensorref__": {
            "host": "127.0.0.1", "port": dead_port, "key": "0" * 32,
            "dtype": "float32", "shape": [4]}}})
        pipeline.process_frame_response(
            json.dumps({"stream_id": "s1", "frame_id": 0}), bad_payload)
        assert 0 not in stream.frames, "parked frame leaked"
        process.terminate()
