# ComputeElement tests: jit-compiled element math in a live pipeline, with
# device-resident swag between elements, shape bucketing, and mesh-sharded
# state -- all on the virtual 8-device CPU mesh.

import queue

import jax
import numpy as np
import pytest

from aiko_services_tpu.pipeline import (
    bucket_length, create_pipeline, pad_axis_to)
from aiko_services_tpu.runtime import Process
from aiko_services_tpu.transport import reset_brokers

ELEMENTS = "aiko_services_tpu.elements"


@pytest.fixture(autouse=True)
def clean_brokers():
    reset_brokers()
    yield
    reset_brokers()


def local(class_name):
    return {"local": {"module": ELEMENTS, "class_name": class_name}}


def test_bucket_length():
    assert bucket_length(1) == 16
    assert bucket_length(17) == 32
    assert bucket_length(100, buckets=[128, 512]) == 128
    # beyond the last bucket: grow power-of-two, never truncate
    assert bucket_length(1000, buckets=[128, 512]) == 1024


def test_pad_axis_to():
    array = np.ones((2, 5), np.float32)
    padded = pad_axis_to(array, 1, 8)
    assert padded.shape == (2, 8)
    assert padded[0, 5] == 0
    with pytest.raises(ValueError, match="shrink"):
        pad_axis_to(array, 1, 4)


def test_bucketing_pads_compute_and_unpads_outputs():
    definition = {
        "name": "bucketed",
        "graph": ["(source (scale (sink)))"],
        "elements": [
            {"name": "source", "output": [{"name": "tensor"}],
             "parameters": {"data_sources": [[4, 50]]},  # ragged axis 1
             "deploy": local("ArraySource")},
            {"name": "scale", "input": [{"name": "tensor"}],
             "output": [{"name": "tensor"}],
             "parameters": {"scale": 2.0, "bucket_axes": {"tensor": 1},
                            "bucket_min": 16},
             "deploy": local("JaxScale")},
            {"name": "sink", "input": [{"name": "tensor"}],
             "output": [{"name": "tensor"}],
             "deploy": local("ToHost")},
        ],
    }
    _, _, outputs = _run_one_frame(definition)
    # padded to 64 inside compute, sliced back to 50 on the way out
    assert outputs["tensor"].shape == (4, 50)


def test_dynamic_parameters_apply_without_recompile():
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, {
        "name": "dynamic",
        "graph": ["(source (scale (sink)))"],
        "elements": [
            {"name": "source", "output": [{"name": "tensor"}],
             "parameters": {"data_sources": [[2, 4]], "seed": 3},
             "deploy": local("ArraySource")},
            {"name": "scale", "input": [{"name": "tensor"}],
             "output": [{"name": "tensor"}],
             "parameters": {"scale": 1.0, "offset": 0.0},
             "deploy": local("JaxScale")},
            {"name": "sink", "input": [{"name": "tensor"}],
             "output": [{"name": "tensor"}],
             "deploy": local("ToHost")},
        ],
    })
    process.run(in_thread=True)
    responses = queue.Queue()
    # frame 1 with scale=1, then live-update to scale=100 for frame 2
    pipeline.create_stream("s1", queue_response=responses)
    _, _, first = responses.get(timeout=15)
    pipeline.elements["scale"].set_parameter("scale", 100.0)
    pipeline.process_frame({"stream_id": "s1"},
                           {"tensor": np.ones((2, 4), np.float32)})
    _, _, second = responses.get(timeout=15)
    np.testing.assert_allclose(second["tensor"],
                               np.full((2, 4), 100.0), rtol=1e-6)
    process.terminate()


def _compute_pipeline(sharding=None):
    mlp = {"name": "mlp", "input": [{"name": "tensor"}],
           "output": [{"name": "tensor"}],
           "parameters": {"features": 16, "hidden": 32},
           "deploy": local("JaxMLP")}
    if sharding:
        mlp["sharding"] = sharding
    return {
        "name": "compute_pipeline",
        "graph": ["(source (scale (mlp (sink))))"],
        "elements": [
            {"name": "source", "output": [{"name": "tensor"}],
             "parameters": {"data_sources": [[4, 16]]},
             "deploy": local("ArraySource")},
            {"name": "scale", "input": [{"name": "tensor"}],
             "output": [{"name": "tensor"}],
             "parameters": {"scale": 3.0},
             "deploy": local("JaxScale")},
            mlp,
            {"name": "sink", "input": [{"name": "tensor"}],
             "output": [{"name": "tensor"}],
             "deploy": local("ToHost")},
        ],
    }


def _run_one_frame(definition):
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, definition)
    process.run(in_thread=True)
    responses = queue.Queue()
    pipeline.create_stream("s1", queue_response=responses)
    _, frame, outputs = responses.get(timeout=15)
    process.terminate()
    return pipeline, frame, outputs


def test_compute_pipeline_end_to_end():
    pipeline, frame, outputs = _run_one_frame(_compute_pipeline())
    assert isinstance(outputs["tensor"], np.ndarray)
    assert outputs["tensor"].shape == (4, 16)
    assert "time_mlp" in frame.metrics


def test_intermediate_swag_stays_on_device():
    """Between ComputeElements the tensor must be a jax.Array, never numpy:
    verified by a probe element inserted mid-graph."""
    definition = _compute_pipeline()
    definition["graph"] = ["(source (scale (probe (mlp (sink)))))"]
    definition["elements"].insert(2, {
        "name": "probe", "input": [{"name": "tensor"}],
        "output": [{"name": "tensor"}],
        "deploy": local("PE_Inspect")})
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, definition)
    process.run(in_thread=True)
    responses = queue.Queue()
    stream = pipeline.create_stream("s1", queue_response=responses)
    responses.get(timeout=15)
    inspected = stream.variables["inspected"]
    assert isinstance(inspected[0]["tensor"], jax.Array)
    process.terminate()


def test_sharded_state_on_mesh():
    sharding = {"axes": {"data": -1},
                "state": None,                      # params replicated
                "inputs": {"tensor": ["data", None]}}  # batch sharded
    definition = _compute_pipeline(sharding)
    # batch 8: divisible across the 8-device data axis
    definition["elements"][0]["parameters"]["data_sources"] = [[8, 16]]
    pipeline, _, outputs = _run_one_frame(definition)
    element = pipeline.elements["mlp"]
    assert element.mesh is not None
    assert element.mesh.devices.size == 8
    assert element.state["w1"].sharding.is_fully_replicated
    assert outputs["tensor"].shape == (8, 16)


def test_mesh_subslice_stage_placement():
    """sharding.devices pins an element to a device sub-range: two stages
    split the 8-device host into disjoint 4-device meshes (stage-level
    pipeline parallelism)."""
    definition = _compute_pipeline(
        {"axes": {"data": -1}, "devices": [0, 4],
         "inputs": {"tensor": ["data", None]}})
    definition["elements"][0]["parameters"]["data_sources"] = [[8, 16]]
    pipeline, _, outputs = _run_one_frame(definition)
    element = pipeline.elements["mlp"]
    assert element.mesh.devices.size == 4
    import jax
    assert set(element.mesh.devices.flat) == set(jax.devices()[:4])
    assert outputs["tensor"].shape == (8, 16)


def test_gstreamer_elements_gated():
    """Without GStreamer the stream elements fail the stream with a clear
    diagnostic instead of crashing the pipeline."""
    from aiko_services_tpu.elements import gst_available
    if gst_available():  # pragma: no cover
        pytest.skip("GStreamer present; gating not exercised")
    definition = {
        "name": "gst_pipe",
        "graph": ["(reader)"],
        "elements": [
            {"name": "reader", "output": [{"name": "image"}],
             "parameters": {"data_sources": ["rtsp://nowhere/stream"]},
             "deploy": local("VideoStreamReader")},
        ],
    }
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, definition)
    process.run(in_thread=True)
    stream = pipeline.create_stream("s1")
    assert stream is None  # start_stream errored, stream destroyed
    process.terminate()


def test_scale_element_math():
    _, _, outputs = _run_one_frame({
        "name": "just_scale",
        "graph": ["(source (scale (sink)))"],
        "elements": [
            {"name": "source", "output": [{"name": "tensor"}],
             "parameters": {"data_sources": [[2, 4]], "seed": 7},
             "deploy": local("ArraySource")},
            {"name": "scale", "input": [{"name": "tensor"}],
             "output": [{"name": "tensor"}],
             "parameters": {"scale": 10.0, "offset": 1.0},
             "deploy": local("JaxScale")},
            {"name": "sink", "input": [{"name": "tensor"}],
             "output": [{"name": "tensor"}],
             "deploy": local("ToHost")},
        ],
    })
    rng = np.random.default_rng(7)
    expected = rng.standard_normal((2, 4), dtype=np.float32) * 10.0 + 1.0
    np.testing.assert_allclose(outputs["tensor"], expected, rtol=1e-5)


def test_compute_element_group_kernel_fused_micro_batch():
    """ComputeElements get fused whole-group execution for free:
    compute() traces into the scheduler's concat+kernel+split program,
    outputs match the chained path bit-for-bit, and dynamic parameters
    still apply live (they ride the traced context, never baked-in
    constants)."""

    def build(fused):
        return {
            "name": "fused_scale",
            "graph": ["(scale)"],
            "elements": [
                {"name": "scale", "input": [{"name": "tensor"}],
                 "output": [{"name": "tensor"}],
                 "parameters": {"scale": 3.0, "micro_batch": 4,
                                "micro_batch_fused": fused},
                 "deploy": local("JaxScale")},
            ],
        }

    def run(fused):
        process = Process(transport_kind="loopback")
        pipeline = create_pipeline(process, build(fused))
        responses = queue.Queue()
        stream = pipeline.create_stream("s1", queue_response=responses)
        for index in range(6):  # queued before the loop: all park
            pipeline.create_frame(
                stream,
                {"tensor": np.full((2, 3), float(index), np.float32)})
        process.run(in_thread=True)
        got = {}
        for _ in range(6):
            _, frame, outputs = responses.get(timeout=30)
            got[frame.frame_id] = np.asarray(outputs["tensor"])
        # live dynamic-parameter update flows through the cached program
        pipeline.elements["scale"].set_parameter("scale", 5.0)
        pipeline.create_frame(
            stream, {"tensor": np.full((2, 3), 7.0, np.float32)})
        _, _, outputs = responses.get(timeout=30)
        got["updated"] = np.asarray(outputs["tensor"])
        fused_used = bool(pipeline._fused_programs)
        process.terminate()
        return got, fused_used

    fused_got, fused_used = run(True)
    chained_got, chained_used = run(False)
    assert fused_used and not chained_used
    assert set(fused_got) == set(chained_got)
    for key in fused_got:
        assert fused_got[key].tobytes() == chained_got[key].tobytes()
    assert float(fused_got["updated"][0, 0]) == 35.0  # 7 * updated 5


def test_blocking_metrics_element_stays_on_chained_path():
    """blocking_metrics promises an in-window block_until_ready and the
    compute_seconds stream variable -- both live in process_frame, so a
    blocking_metrics element must not be fused-eligible."""
    definition = {
        "name": "blocking_scale",
        "graph": ["(scale)"],
        "elements": [
            {"name": "scale", "input": [{"name": "tensor"}],
             "output": [{"name": "tensor"}],
             "parameters": {"scale": 3.0, "micro_batch": 4,
                            "blocking_metrics": True},
             "deploy": local("JaxScale")},
        ],
    }
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, definition)
    responses = queue.Queue()
    stream = pipeline.create_stream("s1", queue_response=responses)
    for index in range(4):
        pipeline.create_frame(
            stream, {"tensor": np.full((1, 3), float(index), np.float32)})
    process.run(in_thread=True)
    for _ in range(4):
        responses.get(timeout=30)
    assert not pipeline._fused_programs  # chained path
    assert "scale" in stream.variables.get("compute_seconds", {})
    process.terminate()
