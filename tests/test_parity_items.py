# Small parity items (VERDICT round-1 missing #7/#8 + media gaps):
# config bootstrap (TCP probe + UDP MCU responder), AOP tracing proxy,
# contention-diagnosing lock, audio FFT/resampler elements, and the
# video<->images converter pipelines.

import json
import os
import socket
import threading
import time

import numpy as np
import pytest

from aiko_services_tpu.utils import (
    BootstrapResponder, DiagnosticLock, get_mqtt_host, probe_tcp)


class TestConfigBootstrap:
    def test_probe_tcp_detects_listener(self):
        listener = socket.socket()
        listener.bind(("127.0.0.1", 0))
        listener.listen(1)
        port = listener.getsockname()[1]
        try:
            assert probe_tcp("127.0.0.1", port, timeout=1.0)
        finally:
            listener.close()
        assert not probe_tcp("127.0.0.1", port, timeout=0.2)

    def test_get_mqtt_host_picks_first_reachable(self):
        listener = socket.socket()
        listener.bind(("127.0.0.1", 0))
        listener.listen(1)
        port = listener.getsockname()[1]
        # a dead candidate: a localhost port nothing listens on, reached
        # via a hostname alias so the candidate strings differ
        with socket.socket() as probe:
            probe.bind(("127.0.0.1", 0))
            dead_port = probe.getsockname()[1]
        try:
            host = get_mqtt_host(candidates=["127.0.0.1"], port=port,
                                 timeout=0.2)
            assert host == "127.0.0.1"
        finally:
            listener.close()
        assert get_mqtt_host(candidates=["127.0.0.1"], port=dead_port,
                             timeout=0.2) is None

    def test_bootstrap_responder_replies_with_endpoint(self, monkeypatch):
        monkeypatch.setenv("AIKO_NAMESPACE", "aiko_test")
        responder = BootstrapResponder(port=0, mqtt_host="broker.local",
                                       mqtt_port=1884)
        try:
            client = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            client.settimeout(5.0)
            client.sendto(b"boot?", ("127.0.0.1", responder.port))
            reply, _ = client.recvfrom(512)
            assert reply == b"(boot aiko_test broker.local 1884)"
            client.close()
        finally:
            responder.close()


class TestTracingProxy:
    def test_traces_enter_exit_with_result(self):
        from aiko_services_tpu.runtime import trace_all_methods

        class Thing:
            value = 41

            def bump(self, by):
                return self.value + by

        events = []

        def tracer(name, phase, elapsed, args, result):
            events.append((name, phase, result))

        proxy = trace_all_methods(Thing(), tracer)
        assert proxy.bump(1) == 42
        assert proxy.value == 41          # non-callables pass through
        assert events == [("bump", "enter", None), ("bump", "exit", 42)]

    def test_traces_exceptions(self):
        from aiko_services_tpu.runtime import trace_all_methods

        class Boom:
            def go(self):
                raise RuntimeError("nope")

        events = []
        proxy = trace_all_methods(
            Boom(), lambda name, phase, elapsed, args, result:
            events.append(phase))
        with pytest.raises(RuntimeError):
            proxy.go()
        assert events == ["enter", "error"]

    def test_default_tracer_logs(self):
        import logging
        from aiko_services_tpu.runtime import trace_all_methods
        from aiko_services_tpu.runtime import proxy as proxy_module

        class Thing:
            def ping(self):
                return "pong"

        records = []
        handler = logging.Handler()
        handler.emit = lambda record: records.append(record.getMessage())
        proxy_module._LOGGER.addHandler(handler)
        try:
            trace_all_methods(Thing()).ping()
        finally:
            proxy_module._LOGGER.removeHandler(handler)
        joined = " ".join(records)
        assert "TRACE" in joined and "ping" in joined


class TestDiagnosticLock:
    def test_uncontended_fast_path(self):
        lock = DiagnosticLock("fast")
        with lock:
            assert lock.locked()
        assert not lock.locked()
        assert lock.contentions == 0

    def test_contention_is_counted_and_logged(self):
        import logging
        from aiko_services_tpu.utils import lock as lock_module
        records = []
        handler = logging.Handler()
        handler.emit = lambda record: records.append(record.getMessage())
        lock_module._LOGGER.addHandler(handler)
        lock = DiagnosticLock("busy", warn_seconds=0.05)
        lock.acquire()
        done = threading.Event()

        def waiter():
            lock.acquire()
            lock.release()
            done.set()

        thread = threading.Thread(target=waiter)
        thread.start()
        time.sleep(0.2)  # several warn_seconds slices elapse
        lock.release()
        assert done.wait(5)
        thread.join(5)
        lock_module._LOGGER.removeHandler(handler)
        assert lock.contentions == 1
        contended = [r for r in records if "busy" in r and "contended" in r]
        assert len(contended) >= 2  # re-warns each warn_seconds slice
        assert "held by MainThread" in contended[0]

    def test_acquire_timeout_expires(self):
        lock = DiagnosticLock("timed", warn_seconds=0.05)
        lock.acquire()
        assert lock.acquire(timeout=0.15) is False
        lock.release()


    def test_nonblocking_contention(self):
        lock = DiagnosticLock("nb")
        lock.acquire()
        assert lock.acquire(blocking=False) is False
        lock.release()


class TestAudioElements:
    @staticmethod
    def _element(cls, params=None):
        params = params or {}
        element = cls.__new__(cls)
        element.get_parameter = (
            lambda name, default=None, stream=None:
            params.get(name, default))
        return element

    def test_fft_finds_tone_frequency(self):
        from aiko_services_tpu.elements import AudioFFT
        from aiko_services_tpu.elements.audio_io import synthesize_tone
        element = self._element(AudioFFT)
        audio = synthesize_tone(440.0, 0.5)
        _, outputs = AudioFFT.process_frame(element, None, audio)
        spectrum = np.asarray(outputs["spectrum"])
        frequencies = np.asarray(outputs["frequencies"])
        peak_hz = frequencies[int(np.argmax(spectrum))]
        assert abs(peak_hz - 440.0) < 4.0

    def test_resample_halves_and_preserves_tone(self):
        from aiko_services_tpu.elements import AudioResample
        from aiko_services_tpu.elements.audio_io import synthesize_tone
        element = self._element(AudioResample, {"rate_in": 16000,
                                                "rate_out": 8000})
        audio = synthesize_tone(440.0, 0.25)
        _, outputs = AudioResample.process_frame(element, None, audio)
        resampled = np.asarray(outputs["audio"])
        assert outputs["sample_rate"] == 8000
        assert abs(len(resampled) - len(audio) // 2) <= 1
        spectrum = np.abs(np.fft.rfft(resampled))
        peak_hz = np.fft.rfftfreq(len(resampled), 1 / 8000)[
            int(np.argmax(spectrum))]
        assert abs(peak_hz - 440.0) < 8.0

    def test_resample_preserves_batch_shape(self):
        from aiko_services_tpu.elements import AudioResample
        element = self._element(
            AudioResample, {"rate_in": 16000, "rate_out": 8000})
        audio = np.random.default_rng(0).standard_normal(
            (2, 1000)).astype(np.float32)
        _, outputs = AudioResample.process_frame(element, None, audio)
        assert np.asarray(outputs["audio"]).shape == (2, 500)

    def test_resample_identity(self):
        from aiko_services_tpu.elements import AudioResample
        element = self._element(AudioResample, {"rate_in": 16000,
                                                "rate_out": 16000})
        audio = np.arange(100, dtype=np.float32)
        _, outputs = AudioResample.process_frame(element, None, audio)
        np.testing.assert_array_equal(np.asarray(outputs["audio"]), audio)


class TestConverterPipelines:
    @pytest.mark.parametrize("path", [
        "examples/pipeline_video_to_images.json",
        "examples/pipeline_images_to_video.json",
    ])
    def test_definitions_parse(self, path):
        from aiko_services_tpu.pipeline import parse_pipeline_definition
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        with open(os.path.join(repo, path)) as handle:
            definition = parse_pipeline_definition(json.load(handle))
        assert definition.name in ("video_to_images", "images_to_video")

    def test_images_to_video_roundtrip(self, tmp_path):
        """Write PNGs, run the converter pipeline, read the video back:
        the reference's standalone converters as a framework graph."""
        cv2 = pytest.importorskip("cv2")
        import queue
        from PIL import Image
        from aiko_services_tpu.runtime import Process
        from aiko_services_tpu.pipeline import create_pipeline

        frames_dir = tmp_path / "frames"
        frames_dir.mkdir()
        for index in range(3):
            array = np.full((32, 32, 3), index * 60, np.uint8)
            Image.fromarray(array).save(
                frames_dir / f"frame_{index:02d}.png")
        out_path = tmp_path / "out.avi"
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        with open(os.path.join(
                repo, "examples/pipeline_images_to_video.json")) as handle:
            definition = json.load(handle)
        definition["elements"][0]["parameters"]["data_sources"] = [
            str(frames_dir / "*.png")]
        definition["elements"][1]["parameters"].update(
            {"data_targets": [str(out_path)], "frame_rate": 5,
             "fourcc": "MJPG"})
        process = Process(transport_kind="loopback")
        pipeline = create_pipeline(process, definition)
        process.run(in_thread=True)
        responses = queue.Queue()
        pipeline.create_stream("s1", queue_response=responses)
        for _ in range(3):
            responses.get(timeout=20)
        deadline = time.monotonic() + 10
        while "s1" in pipeline.streams and time.monotonic() < deadline:
            time.sleep(0.05)  # generator exhaustion closes the writer
        process.terminate()
        capture = cv2.VideoCapture(str(out_path))
        count = 0
        while capture.read()[0]:
            count += 1
        capture.release()
        assert count == 3


class TestMicrophoneSpeaker:
    """The reference's mic/speaker seats (audio_io.py:440-640) with the
    mute protocol, exercised over a fake sounddevice module."""

    @staticmethod
    def _fake_sounddevice(recorded, played):
        import types
        fake = types.ModuleType("sounddevice")

        def rec(samples, samplerate, channels, dtype):
            recorded.append(samples)
            return np.full((samples, 1), 0.25, np.float32)

        fake.rec = rec
        fake.play = lambda array, samplerate: played.append(
            (np.asarray(array), samplerate))
        fake.wait = lambda: None
        return fake

    def test_gated_without_sounddevice(self, monkeypatch):
        import sys
        from aiko_services_tpu.elements import MicrophoneSource
        # force ImportError even on hosts that have sounddevice
        monkeypatch.setitem(sys.modules, "sounddevice", None)
        element = MicrophoneSource.__new__(MicrophoneSource)
        element.share = {}
        element.get_parameter = (
            lambda name, default=None, stream=None: default)
        event, outputs = MicrophoneSource.start_stream(element, None, "s")
        from aiko_services_tpu.pipeline import StreamEvent
        assert event == StreamEvent.ERROR
        assert "sounddevice" in outputs["diagnostic"]

    def test_speaker_mutes_discovered_microphone(self, monkeypatch):
        import sys
        import queue as queue_module
        from aiko_services_tpu.runtime import Process, Registrar
        from aiko_services_tpu.pipeline import create_pipeline
        from aiko_services_tpu.transport.loopback import get_broker
        from aiko_services_tpu.elements.robot import RobotActor  # any svc

        recorded, played = [], []
        monkeypatch.setitem(
            sys.modules, "sounddevice",
            self._fake_sounddevice(recorded, played))

        process = Process(transport_kind="loopback")
        Registrar(process, search_timeout=0.05)
        # stand-in microphone service: capture (update mute ...) on its
        # control topic (the ECProducer normally consumes these)
        mic = RobotActor(process, name="mic_service")
        mutes = []
        process.add_message_handler(
            lambda topic, payload: mutes.append(str(payload)),
            f"{mic.topic_path}/control")
        definition = {
            "name": "playback",
            "graph": ["(tone (speaker))"],
            "elements": [
                {"name": "tone", "output": [{"name": "audio"}],
                 "parameters": {"data_sources": [[440, 0.01]]},
                 "deploy": {"local": {
                     "module": "aiko_services_tpu.elements",
                     "class_name": "ToneSource"}}},
                {"name": "speaker", "input": [{"name": "audio"}],
                 "output": [{"name": "audio"}],
                 "parameters": {"microphone_service": "mic_service"},
                 "deploy": {"local": {
                     "module": "aiko_services_tpu.elements",
                     "class_name": "SpeakerSink"}}},
            ],
        }
        pipeline = create_pipeline(process, definition)
        process.run(in_thread=True)
        # warm registrar discovery so the speaker finds the microphone
        from aiko_services_tpu.runtime import ServiceFilter
        from aiko_services_tpu.runtime.share import (
            services_cache_create_singleton)
        cache = services_cache_create_singleton(process)
        deadline = time.monotonic() + 5
        while (not list(cache.services.filter_services(
                ServiceFilter(name="mic_service")))
               and time.monotonic() < deadline):
            get_broker().drain()
            time.sleep(0.01)
        responses = queue_module.Queue()
        pipeline.create_stream("s1", queue_response=responses)
        responses.get(timeout=10)
        assert played and played[0][1] == 16000
        deadline = time.monotonic() + 5
        while len(mutes) < 2 and time.monotonic() < deadline:
            get_broker().drain()
            time.sleep(0.01)
        assert any("mute" in m and "true" in m for m in mutes), mutes
        assert any("mute" in m and "false" in m for m in mutes), mutes
        process.terminate()

    def test_microphone_chunks_and_mute_zeroing(self, monkeypatch):
        import sys
        recorded, played = [], []
        monkeypatch.setitem(
            sys.modules, "sounddevice",
            self._fake_sounddevice(recorded, played))
        from aiko_services_tpu.runtime import Process
        from aiko_services_tpu.pipeline import create_pipeline
        import queue as queue_module

        definition = {
            "name": "mic_pipe",
            "graph": ["(mic)"],
            "elements": [
                {"name": "mic", "output": [{"name": "audio"}],
                 "parameters": {"chunk_seconds": 0.01, "frame_window": 1},
                 "deploy": {"local": {
                     "module": "aiko_services_tpu.elements",
                     "class_name": "MicrophoneSource"}}},
            ],
        }
        process = Process(transport_kind="loopback")
        pipeline = create_pipeline(process, definition)
        process.run(in_thread=True)
        responses = queue_module.Queue()
        pipeline.create_stream("s1", queue_response=responses)
        _, _, outputs = responses.get(timeout=10)
        audio = np.asarray(outputs["audio"])
        assert audio.shape == (160,)           # 0.01 s at 16 kHz
        assert np.allclose(audio, 0.25)        # live chunk
        # live mute: flip the share flag, next chunks are zeroed
        element = pipeline.elements["mic"]
        element.share["mute"] = "true"  # wire form: EC stores strings
        for _ in range(3):
            _, _, outputs = responses.get(timeout=10)
            if np.allclose(np.asarray(outputs["audio"]), 0.0):
                break
        assert np.allclose(np.asarray(outputs["audio"]), 0.0)
        pipeline.destroy_stream("s1")
        process.terminate()
