# Cross-request prefix KV reuse (ISSUE 16): the hash-chain identity,
# refcounted COW sharing on the paged pool (warm admissions borrow
# cached prompt blocks and prefill only the tail), eviction/accounting
# reconciliation under storms and preemption, bit-identity with cold
# prefill for f32 AND int8 KV, and the gateway's prefix-affinity
# power-of-two routing (warm replica wins, saturated holder loses).

import numpy as np
import pytest

import jax

from aiko_services_tpu.decode import (
    BlockManager, DecodeEngine, PrefixCache, PrefixPolicy, chain_hashes,
    prefix_head)
from aiko_services_tpu.models import TransformerConfig, generate, init_params
from aiko_services_tpu.runtime import Process
from aiko_services_tpu.serve import Gateway
from aiko_services_tpu.serve.gateway import _Replica
from aiko_services_tpu.transport import reset_brokers

TINY = dict(vocab_size=64, n_layers=2, n_heads=2, n_kv_heads=2,
            d_model=32, d_ff=64, max_seq_len=64, dtype="float32")

ARMED = "prefix_cache=on"


@pytest.fixture(autouse=True)
def clean_brokers():
    reset_brokers()
    yield
    reset_brokers()


@pytest.fixture(scope="module")
def tiny_model():
    config = TransformerConfig(**TINY)
    return init_params(config, jax.random.PRNGKey(0)), config


def reference(params, config, prompt, max_new):
    """Closed-batch greedy completion -- the bit-identity oracle."""
    out, _ = generate(params, config, np.asarray(prompt)[None],
                      max_new_tokens=max_new)
    return np.asarray(out)[0]


def drain(engine, limit=2000):
    done = {}
    steps = 0
    while engine.has_work():
        report = engine.step()
        for completion in report.completions:
            done[completion.request_id] = completion
        steps += 1
        assert steps < limit, "engine failed to drain (deadlock?)"
    return done


# -- hash chain --------------------------------------------------------------

class TestChainHashes:
    def test_deterministic_and_prefix_stable(self):
        tokens = np.arange(1, 25, dtype=np.int32)
        first = chain_hashes(tokens, 8)
        assert first == chain_hashes(tokens, 8)
        assert len(first) == 3                    # full blocks only
        assert len(chain_hashes(tokens[:23], 8)) == 2
        # a chain digest commits to the WHOLE prefix, so a chain over a
        # token prefix is a list prefix of the full chain
        assert chain_hashes(tokens[:16], 8) == first[:2]
        assert prefix_head(tokens, 8) == first[0]
        assert prefix_head(tokens[:7], 8) is None

    def test_block_size_seeds_distinct_namespaces(self):
        tokens = np.arange(1, 9, dtype=np.int32)
        assert chain_hashes(tokens, 8)[0] != chain_hashes(tokens, 4)[0]
        assert len(set(chain_hashes(tokens, 4))) == 2

    def test_divergence_changes_suffix_digests(self):
        base = np.arange(1, 25, dtype=np.int32)
        fork = base.copy()
        fork[8] += 1                              # mutate block 1
        left, right = chain_hashes(base, 8), chain_hashes(fork, 8)
        assert left[0] == right[0]
        assert left[1] != right[1]
        assert left[2] != right[2]                # chained: all later differ


# -- policy grammar ----------------------------------------------------------

class TestPrefixPolicy:
    def test_parse_defaults_and_off(self):
        policy = PrefixPolicy.parse(ARMED)
        assert policy.enabled and policy.min_prefix_blocks == 1
        assert not PrefixPolicy.parse("prefix_cache=off").enabled

    def test_scope_validation(self):
        gateway_only = PrefixPolicy.parse(
            "prefix_cache=on;affinity_weight=2")
        gateway_only.validate_gateway()
        with pytest.raises(ValueError, match="affinity_weight"):
            gateway_only.validate_engine()
        engine_only = PrefixPolicy.parse(
            "prefix_cache=on;min_prefix_blocks=2;cache_blocks=8")
        engine_only.validate_engine()
        with pytest.raises(ValueError, match="min_prefix_blocks"):
            engine_only.validate_gateway()

    def test_bad_values_rejected(self):
        with pytest.raises(ValueError):
            PrefixPolicy.parse("prefix_cache=maybe")
        with pytest.raises(ValueError):
            PrefixPolicy.parse("prefix_cache=on;min_prefix_blocks=0")
        with pytest.raises(ValueError):
            PrefixPolicy.parse("prefix_cache=on;warmth=high")


# -- BlockManager free-set (O(1) double-free guard) --------------------------

class TestBlockManagerFreeSet:
    def test_free_set_mirrors_list_through_storm(self):
        """The membership set behind free() stays exactly in sync with
        the LIFO list through an interleaved allocate/free storm -- the
        O(n) scan this replaced would have made release waves O(n^2)."""
        manager = BlockManager(64, 4)
        rng = np.random.default_rng(11)
        held = []
        for _ in range(200):
            if held and rng.integers(0, 2):
                batch = held.pop()
                manager.free(batch)
            else:
                granted = manager.allocate(int(rng.integers(1, 6)))
                if granted is not None:
                    held.append(granted)
            assert manager._free_set == set(manager._free)
            assert manager.free_count == len(manager._free)
        for batch in held:
            manager.free(batch)
        assert manager.free_count == manager.capacity

    def test_double_free_still_rejected(self):
        manager = BlockManager(8, 4)
        granted = manager.allocate(2)
        manager.free(granted)
        with pytest.raises(ValueError, match="double free"):
            manager.free([granted[0]])


# -- PrefixCache unit --------------------------------------------------------

class TestPrefixCacheUnit:
    def test_register_acquire_release_refcounts(self):
        manager = BlockManager(10, 4)
        cache = PrefixCache(manager)
        tokens = np.arange(1, 13, dtype=np.int32)
        hashes = chain_hashes(tokens, 4)
        blocks = manager.allocate(3)
        assert len(cache.register(hashes, blocks)) == 3
        assert cache.shared_count == 3 and cache.cached_count == 0
        cache.release(blocks)
        assert cache.shared_count == 0 and cache.cached_count == 3
        matched = cache.acquire(hashes[:2])
        assert matched == blocks[:2]              # chain order
        assert cache.shared_count == 2 and cache.cached_count == 1
        assert cache.hits == 1
        cache.release(matched)
        with pytest.raises(ValueError, match="released more times"):
            cache.release([blocks[0]])            # below zero

    def test_resident_blocks_peeks_without_acquiring(self):
        manager = BlockManager(10, 4)
        cache = PrefixCache(manager)
        hashes = chain_hashes(np.arange(1, 9, dtype=np.int32), 4)
        blocks = manager.allocate(2)
        cache.register(hashes, blocks)
        assert cache.resident_blocks(hashes) == blocks
        assert cache.resident_blocks(hashes + ["missing"]) == blocks
        assert cache.shared_count == 2            # unchanged: no acquire
        assert cache.lookup(hashes) == 2

    def test_allocate_evicts_lru_before_failing(self):
        manager = BlockManager(8, 4)              # capacity 7
        cache = PrefixCache(manager)
        hashes = chain_hashes(np.arange(1, 13, dtype=np.int32), 4)
        blocks = manager.allocate(3)
        cache.register(hashes, blocks)
        cache.release(blocks)                     # all 3 now rc0/LRU
        private = cache.allocate(4)               # uses the plain free 4
        assert len(private) == 4
        assert manager.free_count == 0
        granted = cache.allocate(2)               # must reclaim cached
        assert len(granted) == 2
        assert cache.evictions == 2
        assert cache.cached_count == 1
        # LRU order: the chain HEAD was evicted first, so the longest
        # resident prefix is now empty (the chain broke at its root)
        assert cache.lookup(hashes) == 0
        cache.allocate(2)                         # cannot be satisfied
        assert cache.evictions == 3 and cache.cached_count == 0
        manager.free(private + granted)

    def test_cache_blocks_cap_trims_idle_tier(self):
        manager = BlockManager(10, 4)
        cache = PrefixCache(manager, cache_blocks=2)
        hashes = chain_hashes(np.arange(1, 17, dtype=np.int32), 4)
        blocks = manager.allocate(4)
        cache.register(hashes, blocks)
        assert cache.shared_count == 4            # referenced: cap ignores
        cache.release(blocks)
        assert cache.cached_count == 2            # trimmed to the cap
        assert cache.evictions == 2
        assert manager.free_count == manager.capacity - 2

    def test_unregistered_release_goes_back_to_manager(self):
        manager = BlockManager(8, 4)
        cache = PrefixCache(manager)
        blocks = cache.allocate(3)
        cache.release(blocks)                     # never registered
        assert manager.free_count == manager.capacity
        assert cache.cached_count == 0


# -- engine: warm bit-identity ----------------------------------------------

@pytest.mark.parametrize("chunk", [None, 8],
                         ids=["monolithic", "chunked"])
def test_warm_prefill_bit_identical_f32(tiny_model, chunk):
    """A repeat prompt borrows its cached prompt blocks and prefills
    only the tail; the completion is bit-identical to the cold run."""
    params, config = tiny_model
    prompt = np.arange(1, 21, dtype=np.int32)     # 2 full blocks of 8
    expected = reference(params, config, prompt, 6)
    engine = DecodeEngine(params, config, decode_slots=2, kv_block_size=8,
                          prefill_chunk_size=chunk, prefix_policy=ARMED)
    engine.submit(0, prompt, 6)
    done = drain(engine)
    np.testing.assert_array_equal(done[0].tokens, expected)
    assert engine.counters["prefix_hits"] == 0    # cold: nothing cached
    engine.submit(1, prompt, 6)
    done = drain(engine)
    np.testing.assert_array_equal(done[1].tokens, expected)
    assert engine.counters["prefix_hits"] == 1
    assert engine.counters["prefix_blocks_shared"] == 2
    assert done[1].stats["prefix_blocks"] == 2
    assert engine.prefix.shared_count == 0        # all refs released
    assert (engine.blocks.free_count + engine.prefix.cached_count
            == engine.blocks.capacity)


def test_warm_prefill_bit_identical_int8():
    """Shared int8 KV blocks carry their per-block scales: a warm
    admission is bit-identical to the cold int8 path too."""
    config = TransformerConfig(**{**TINY, "kv_dtype": "int8"})
    params = init_params(config, jax.random.PRNGKey(0))
    prompt = np.arange(1, 21, dtype=np.int32)
    cold = DecodeEngine(params, config, decode_slots=1, kv_block_size=8)
    cold.submit(0, prompt, 6)
    expected = drain(cold)[0].tokens
    engine = DecodeEngine(params, config, decode_slots=1, kv_block_size=8,
                          prefix_policy=ARMED)
    engine.submit(0, prompt, 6)
    np.testing.assert_array_equal(drain(engine)[0].tokens, expected)
    engine.submit(1, prompt, 6)
    done = drain(engine)
    np.testing.assert_array_equal(done[1].tokens, expected)
    assert engine.counters["prefix_hits"] == 1
    assert done[1].stats["prefix_blocks"] == 2


def test_partial_hit_prefills_only_the_uncached_tail(tiny_model):
    """A prompt sharing one leading block with the cache gets that
    block for free, counts a partial hit, and computes the rest."""
    params, config = tiny_model
    base = np.arange(1, 25, dtype=np.int32)       # 3 full blocks of 8
    engine = DecodeEngine(params, config, decode_slots=2, kv_block_size=8,
                          prefix_policy=ARMED)
    engine.submit(0, base, 4)
    drain(engine)
    fork = base.copy()
    fork[8:] = (fork[8:] + 7) % 63 + 1            # diverge from block 1 on
    engine.submit(1, fork, 4)
    done = drain(engine)
    np.testing.assert_array_equal(
        done[1].tokens, reference(params, config, fork, 4))
    assert engine.counters["prefix_hits"] == 1
    assert engine.counters["prefix_partial_hits"] == 1
    assert done[1].stats["prefix_blocks"] == 1    # only the common head


def test_cow_fork_on_divergence_decodes_concurrently(tiny_model):
    """Two live requests share the same cached prefix blocks and fork
    into private tails: neither corrupts the other (COW by block-table
    indirection -- decode writes always land in slot-owned blocks)."""
    params, config = tiny_model
    base = np.arange(1, 17, dtype=np.int32)       # the shared 2 blocks
    left = np.concatenate([base, np.arange(20, 26, dtype=np.int32)])
    right = np.concatenate([base, np.arange(40, 48, dtype=np.int32)])
    engine = DecodeEngine(params, config, decode_slots=2, kv_block_size=8,
                          prefix_policy=ARMED)
    engine.submit(0, base, 2)                     # seed the cache
    drain(engine)
    engine.submit(1, left, 6)
    engine.submit(2, right, 6)
    done = drain(engine)
    np.testing.assert_array_equal(
        done[1].tokens, reference(params, config, left, 6))
    np.testing.assert_array_equal(
        done[2].tokens, reference(params, config, right, 6))
    assert engine.counters["prefix_hits"] >= 2
    assert engine.prefix.shared_count == 0


def test_min_prefix_blocks_skips_tiny_matches(tiny_model):
    params, config = tiny_model
    prompt = np.arange(1, 13, dtype=np.int32)     # 1 usable block only
    engine = DecodeEngine(
        params, config, decode_slots=1, kv_block_size=8,
        prefix_policy="prefix_cache=on;min_prefix_blocks=2")
    engine.submit(0, prompt, 4)
    drain(engine)
    engine.submit(1, prompt, 4)
    done = drain(engine)
    np.testing.assert_array_equal(
        done[1].tokens, reference(params, config, prompt, 4))
    assert engine.counters["prefix_hits"] == 0    # below the floor
    assert engine.prefix.shared_count == 0


# -- eviction / accounting under pressure ------------------------------------

def test_accounting_reconciles_through_storm(tiny_model):
    """Seeded admission waves over shared prefixes with an
    oversubscribed pool: after every wave the pool partitions exactly
    into free + cached (no leak, no double count), and dropping the
    idle tier returns the pool to its cold state."""
    params, config = tiny_model
    rng = np.random.default_rng(3)
    bases = [rng.integers(1, 64, size=16).astype(np.int32)
             for _ in range(3)]
    engine = DecodeEngine(params, config, decode_slots=3, kv_block_size=8,
                          kv_blocks=12, prefix_policy=ARMED)
    capacity = engine.blocks.capacity
    request = 0
    for _ in range(4):
        for base in bases:
            tail = rng.integers(
                1, 64, size=int(rng.integers(0, 9))).astype(np.int32)
            engine.submit(request, np.concatenate([base, tail]), 4)
            request += 1
        done = drain(engine)
        assert len(done) == 3
        assert engine.prefix.shared_count == 0
        assert (engine.blocks.free_count + engine.prefix.cached_count
                == capacity)
        done.clear()
    assert engine.counters["prefix_hits"] > 0
    assert engine.counters["prefix_evictions"] == engine.prefix.evictions
    engine.prefix.drop()
    assert engine.prefix.cached_count == 0
    assert engine.blocks.free_count == capacity


def test_preempting_shared_holder_never_frees_siblings_blocks(tiny_model):
    """Pool exhaustion preempts the youngest slot while it BORROWS a
    cached block another slot also references: the release only
    decrefs -- the survivor keeps decoding over intact KV and both
    complete bit-identical."""
    params, config = tiny_model
    engine = DecodeEngine(params, config, decode_slots=2, kv_block_size=4,
                          kv_blocks=8, prefix_policy=ARMED)
    prompt = np.arange(1, 9, dtype=np.int32)      # 2 full blocks of 4
    expected = reference(params, config, prompt, 12)
    engine.submit(0, prompt, 12)
    engine.step()                                 # prefill registers blocks
    assert engine.prefix.shared_count == 2
    engine.submit(1, prompt, 12)
    done = drain(engine)
    assert engine.counters["preempted"] >= 1
    assert engine.counters["prefix_hits"] >= 1
    np.testing.assert_array_equal(done[0].tokens, expected)
    np.testing.assert_array_equal(done[1].tokens, expected)
    assert engine.prefix.shared_count == 0
    assert (engine.blocks.free_count + engine.prefix.cached_count
            == engine.blocks.capacity)


def test_feature_off_is_the_cold_path(tiny_model):
    """No policy (or prefix_cache=off) means no cache object, no new
    counters moving, and byte-for-byte the pre-prefix release path."""
    params, config = tiny_model
    for spec in (None, "prefix_cache=off"):
        engine = DecodeEngine(params, config, decode_slots=2,
                              kv_block_size=8, prefix_policy=spec)
        assert engine.prefix is None
        prompt = np.arange(1, 21, dtype=np.int32)
        engine.submit(0, prompt, 4)
        engine.submit(1, prompt, 4)
        done = drain(engine)
        assert engine.counters["prefix_hits"] == 0
        assert "prefix_blocks" not in done[1].stats
        assert engine.blocks.free_count == engine.blocks.capacity


# -- gateway affinity routing ------------------------------------------------

HEAD = "a" * 32


def _affinity_gateway(weight=2.0, seed=0, prefix=True):
    process = Process(transport_kind="loopback")
    spec = (f"prefix_cache=on;affinity_weight={weight}"
            if prefix else None)
    return Gateway(process, policy="max_inflight=8;queue=32",
                   router_seed=seed, prefix=spec)


def _fake_replica(name, inflight=0, heads=""):
    return _Replica(f"pool/{name}", name,
                    cache={"inflight": inflight, "prefix_heads": heads})


class TestAffinityRouting:
    def test_warm_replica_wins_modest_load_gap(self):
        gateway = _affinity_gateway(weight=2.0)
        warm = _fake_replica("warm", inflight=1, heads=HEAD)
        for replica in (warm, _fake_replica("cold0"),
                        _fake_replica("cold1")):
            gateway.replicas[replica.topic_path] = replica
        for _ in range(4):                        # every draw, not one lucky
            assert gateway._place(0.0, prefix_hint=HEAD) is warm
        assert gateway.telemetry.affinity_hits.value == 4
        assert gateway.telemetry.affinity_misses.value == 0

    def test_overloaded_holder_loses_to_balance(self):
        gateway = _affinity_gateway(weight=2.0)
        hot = _fake_replica("hot", inflight=6, heads=HEAD)
        for replica in (hot, _fake_replica("cold0"),
                        _fake_replica("cold1")):
            gateway.replicas[replica.topic_path] = replica
        chosen = gateway._place(0.0, prefix_hint=HEAD)
        assert chosen is not hot                  # discount < load gap
        assert gateway.telemetry.affinity_misses.value == 1

    def test_saturated_holder_falls_back_cleanly(self):
        gateway = _affinity_gateway(weight=10.0)
        full = _fake_replica("full", heads=HEAD)
        full.outstanding = gateway.policy.max_inflight   # latches saturated
        cold = _fake_replica("cold0")
        for replica in (full, cold, _fake_replica("cold1")):
            gateway.replicas[replica.topic_path] = replica
        chosen = gateway._place(0.0, prefix_hint=HEAD)
        assert chosen is not full                 # filtered before scoring
        assert gateway.telemetry.affinity_misses.value == 1

    def test_no_hint_or_no_policy_keeps_counters_still(self):
        for prefix in (True, False):
            gateway = _affinity_gateway(prefix=prefix)
            for index in range(3):
                replica = _fake_replica(f"r{index}")
                gateway.replicas[replica.topic_path] = replica
            assert gateway._place(0.0) is not None
            assert gateway._place(0.0, prefix_hint=HEAD if not prefix
                                  else None) is not None
            assert gateway.telemetry.affinity_hits.value == 0
            assert gateway.telemetry.affinity_misses.value == 0

    def test_gateway_scope_grammar_rejected_at_construction(self):
        process = Process(transport_kind="loopback")
        with pytest.raises(ValueError, match="AIKO411"):
            Gateway(process, policy="max_inflight=8;queue=32",
                    prefix="prefix_cache=on;min_prefix_blocks=2")
        with pytest.raises(ValueError, match="AIKO404"):
            Gateway(process, policy="max_inflight=8;queue=32",
                    prefix="prefix_cache=on;warmth=high")
