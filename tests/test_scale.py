# Control-plane scale: the reference's unrealized aspiration was
# "1,000 - 10,000 Services per Process; 1,000+ Processes" (reference:
# src/aiko_services/main/process.py:45-48, an open to-do).  This suite
# REALIZES the first target hermetically: 1,000 services in one process,
# all registrar-registered, filterable, and reaped on death.

import queue
import time

import pytest

from aiko_services_tpu.runtime import (
    ConnectionState, Process, Registrar, ServiceFilter)
from aiko_services_tpu.runtime.actor import Actor
from aiko_services_tpu.transport.loopback import get_broker, reset_brokers
from helpers import wait_for

SERVICES = 1000


@pytest.fixture(autouse=True)
def clean():
    reset_brokers()
    yield
    reset_brokers()


def test_thousand_services_register_filter_and_reap():
    registrar_process = Process(transport_kind="loopback")
    registrar = Registrar(registrar_process, search_timeout=0.05)
    registrar_process.run(in_thread=True)

    # ISSUE 15 satellite: the registrar COALESCES its service_count
    # share update (ECProducer.stage), so a registration storm emits
    # O(ticks) share publishes -- not one per service.  An ECConsumer
    # lease makes the publishes real (no lease, no wire traffic), and
    # counts how many delta payloads actually carried the key.
    observer_process = Process(transport_kind="loopback")
    observer_process.run(in_thread=True)
    mirror: dict = {}
    from aiko_services_tpu.runtime.share import ECConsumer
    consumer = ECConsumer(observer_process, mirror, registrar.topic_path,
                          lease_time=300)
    wait_for(lambda: consumer.synced, timeout=30)
    count_publishes = [0]
    consumer.add_change_handler(
        lambda _c, command, name, value:
        count_publishes.__setitem__(
            0, count_publishes[0] + (name == "service_count")))

    worker = Process(transport_kind="loopback")
    start = time.perf_counter()
    actors = [Actor(worker, name=f"svc_{index:04d}")
              for index in range(SERVICES)]
    worker.run(in_thread=True)
    wait_for(lambda: worker.connection.is_connected(
        ConnectionState.REGISTRAR), timeout=30)
    def worker_count():
        return len(list(registrar.services_table.filter_services(
            ServiceFilter(name="svc_*"))))

    wait_for(lambda: worker_count() >= SERVICES, timeout=60)
    elapsed = time.perf_counter() - start
    assert worker_count() == SERVICES  # exactly: no lost registrations
    # registration throughput is a capability claim: keep it honest
    assert elapsed < 60, f"registering {SERVICES} services took {elapsed:.0f}s"

    # wildcard filter over the full table
    matches = list(registrar.services_table.filter_services(
        ServiceFilter(name="svc_07*")))
    assert len(matches) == 100

    exact = list(registrar.services_table.filter_services(
        ServiceFilter(name="svc_0500")))
    assert len(exact) == 1 and exact[0].name == "svc_0500"

    # coalescing proof: the storm's share publish count is O(mailbox
    # drain cycles), not O(services) -- the EVENTUAL value is exact
    # (the table also holds the registrar/observer services, so compare
    # against the live table size) while the wire carried a small
    # fraction of 1,000 updates
    wait_for(lambda: str(mirror.get("service_count"))
             == str(len(registrar.services_table)), timeout=30)
    assert int(mirror["service_count"]) >= SERVICES
    storm_publishes = count_publishes[0]
    assert storm_publishes <= SERVICES // 10, (
        f"registration storm published service_count {storm_publishes} "
        f"times for {SERVICES} registrations -- coalescing regressed")

    # process death reaps EVERY worker service (LWT -> registrar purge)
    worker.terminate()
    get_broker().drain()
    wait_for(lambda: worker_count() == 0, timeout=30)
    consumer.terminate()
    observer_process.terminate()
    registrar_process.terminate()
    print(f"\n{SERVICES} services registered in {elapsed:.1f}s "
          f"({SERVICES / elapsed:.0f}/s); service_count publishes: "
          f"{storm_publishes}")


def test_hundred_process_instances_one_host():
    """The reference's second scale axis ("1,000+ Processes") relied on
    OS processes against a shared broker; here Process is instantiable
    (a deliberate redesign), so one host can carry many logical
    processes hermetically.  100 processes x 3 services register and
    resolve through one registrar."""
    registrar_process = Process(transport_kind="loopback")
    registrar = Registrar(registrar_process, search_timeout=0.05)
    registrar_process.run(in_thread=True)

    processes = []
    for p_index in range(100):
        process = Process(transport_kind="loopback")
        for s_index in range(3):
            Actor(process, name=f"p{p_index:03d}_s{s_index}")
        process.run(in_thread=True)
        processes.append(process)
    def worker_count():
        return len(list(registrar.services_table.filter_services(
            ServiceFilter(name="p*_s*"))))

    wait_for(lambda: worker_count() >= 300, timeout=60)
    assert worker_count() == 300

    matches = list(registrar.services_table.filter_services(
        ServiceFilter(name="p042_*")))
    assert len(matches) == 3

    # one process dies; exactly its services are reaped
    processes[42].terminate()
    get_broker().drain()
    wait_for(lambda: not list(registrar.services_table.filter_services(
        ServiceFilter(name="p042_*"))), timeout=30)
    assert list(registrar.services_table.filter_services(
        ServiceFilter(name="p041_*")))
    for process in processes:
        process.terminate()
    registrar_process.terminate()
