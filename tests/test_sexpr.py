import pytest

from aiko_services_tpu.utils import (
    generate, parse, parse_list_to_dict, parse_int, parse_float, parse_number,
    ParseError)


def test_simple_command():
    assert parse("(add a b)") == ("add", ["a", "b"])


def test_bare_atom():
    assert parse("topic") == ("topic", [])


def test_empty_payload():
    assert parse("") == ("", [])
    assert parse("()") == ("", [])


def test_nested_lists():
    command, parameters = parse("(process (a b) (c (d e)))")
    assert command == "process"
    assert parameters == [["a", "b"], ["c", ["d", "e"]]]


def test_keyword_dict():
    command, parameters = parse("(update (a: 1 b: 2))")
    assert command == "update"
    assert parameters == [{"a": "1", "b": "2"}]


def test_keyword_dict_nested_value():
    command, parameters = parse("(f (x: (1 2) y: ok))")
    assert parameters == [{"x": ["1", "2"], "y": "ok"}]


def test_quoted_strings():
    command, parameters = parse('(say "hello world" "a (b)")')
    assert parameters == ["hello world", "a (b)"]


def test_quoted_escape():
    command, parameters = parse(r'(say "a \"b\" \\c")')
    assert parameters == ['a "b" \\c']


def test_canonical_symbol():
    command, parameters = parse("(data 11:hello world x)")
    assert parameters == ["hello world", "x"]


def test_canonical_symbol_binary_safe():
    payload = generate("blob", [b"\x00\x01() \xff"])
    command, parameters = parse(payload)
    assert command == "blob"
    assert parameters[0] == "\x00\x01() \xff"


def test_generate_parse_roundtrip():
    cases = [
        ("add", ["a", "1", "2.5"]),
        ("share", [{"topic": "ns/h/1/1", "lease": "300"}]),
        ("graph", [["PE_0", ["PE_1", "PE_3"], ["PE_2", "PE_3"]]]),
        ("msg", ["with space", 'quote"inside'],),
        ("nested", [{"a": ["1", "2"], "b": {"c": "d"}}]),
    ]
    for command, parameters in cases:
        payload = generate(command, parameters)
        out_command, out_parameters = parse(payload)
        assert out_command == command
        # ints/floats stringify on the wire
        assert out_parameters == [
            _stringify(parameter) for parameter in parameters]


def _stringify(value):
    if isinstance(value, dict):
        return {key: _stringify(item) for key, item in value.items()}
    if isinstance(value, list):
        return [_stringify(item) for item in value]
    return str(value)


def test_digit_colon_atom_roundtrips():
    # "12:34" must NOT be emitted as a bare atom (it would re-parse as a
    # canonical len:data symbol)
    payload = generate("update", ["time", "12:34"])
    command, parameters = parse(payload)
    assert command == "update"
    assert parameters == ["time", "12:34"]


def test_generate_types():
    assert generate("f", [1, 2.5, True, None]) == "(f 1 2.5 true ())"


def test_unterminated_list_raises():
    with pytest.raises(ParseError):
        parse("(a (b c)")


def test_trailing_data_raises():
    with pytest.raises(ParseError):
        parse("(a) (b)")


def test_parse_list_to_dict():
    assert parse_list_to_dict(["a:", "1", "b:", "2"]) == {"a": "1", "b": "2"}
    assert parse_list_to_dict(["a", "1"]) == {"a": "1"}


def test_number_helpers():
    assert parse_int("42") == 42
    assert parse_int("x", 7) == 7
    assert parse_float("2.5") == 2.5
    assert parse_number("3") == 3
    assert parse_number("3.5") == 3.5
    assert parse_number("zzz", -1) == -1
