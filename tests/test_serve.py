# Serving-gateway suite (ISSUE 4): admission control (per-priority
# token buckets, typed `(overloaded ...)` sheds, SLO-aware rejection),
# least-loaded replica routing with stream pinning, bounded
# backpressure with `(throttle ...)` signals, and mid-stream failover
# on replica death (the seeded `replica_kill` fault point) -- plus the
# satellite hooks: the pipeline's queue_depth/inflight load export,
# stream-id collision accounting, deterministic lease jitter, and
# discovery-driven convergence through ServicesCache/ECConsumer.

import json
import os
import queue
import time

import numpy as np
import pytest

from aiko_services_tpu import faults as faults_module
from aiko_services_tpu.pipeline import (
    PipelineElement, StreamEvent, create_pipeline)
from aiko_services_tpu.pipeline.element import FrameGeneratorHandle
from aiko_services_tpu.runtime import Lease, Process, Registrar
from aiko_services_tpu.serve import AdmissionPolicy, Gateway, TokenBucket
from aiko_services_tpu.transport import reset_brokers
from helpers import wait_for


@pytest.fixture(autouse=True)
def clean():
    faults_module.reset_injector()
    reset_brokers()
    yield
    faults_module.reset_injector()
    reset_brokers()


class Scale(PipelineElement):
    """x -> x*10 (deterministic: failover replay must be bit-identical)."""

    def process_frame(self, stream, x):
        return StreamEvent.OKAY, {"y": x * 10.0}


class SlowScale(Scale):
    """Fixed host cost per frame: the element parameter `work_ms`
    models a replica's service time, so capacity and saturation are
    controlled by the test, not the machine."""

    def process_frame(self, stream, x):
        time.sleep(float(self.get_parameter("work_ms", 5, stream)) / 1000.0)
        return super().process_frame(stream, x)


class TickSource(PipelineElement):
    """DataSource driven by create_frames (throttle target)."""

    def start_stream(self, stream, stream_id):
        def generator(stream, frame_id):
            return StreamEvent.OKAY, {
                "x": np.ones((1, 2), np.float32) * frame_id}

        self.create_frames(stream, generator, rate=float(
            self.get_parameter("rate", 100, stream)))
        return StreamEvent.OKAY, None

    def process_frame(self, stream, x):
        return StreamEvent.OKAY, {"x": x}


def _replica_definition(name, class_name="Scale", work_ms=None,
                        parameters=None):
    element_parameters = {}
    if work_ms is not None:
        element_parameters["work_ms"] = work_ms
    return {
        "name": name,
        "parameters": dict(parameters or {}),
        "graph": ["(scale)"],
        "elements": [
            {"name": "scale", "input": [{"name": "x"}],
             "output": [{"name": "y"}],
             "parameters": element_parameters,
             "deploy": {"local": {"module": "tests.test_serve",
                                  "class_name": class_name}}},
        ],
    }


def _pool(replicas_n, policy, router_seed=0, faults=None,
          class_name="Scale", work_ms=None, replica_parameters=None):
    """N in-process replicas (each on its own virtual Process) behind
    one gateway; everything runs threaded on the shared loopback
    broker.  Returns (gateway, replicas, processes)."""
    processes, replicas = [], []
    for index in range(replicas_n):
        process = Process(transport_kind="loopback")
        processes.append(process)
        replicas.append(create_pipeline(process, _replica_definition(
            f"replica{index}", class_name=class_name, work_ms=work_ms,
            parameters=replica_parameters)))
    gateway_process = Process(transport_kind="loopback")
    processes.append(gateway_process)
    gateway = Gateway(gateway_process, policy=policy,
                      router_seed=router_seed, faults=faults)
    for replica in replicas:
        gateway.attach_replica(replica)
    for process in processes:
        process.run(in_thread=True)
    return gateway, replicas, processes


def _frame(value):
    return {"x": np.ones((1, 2), np.float32) * value}


def _drain(responses, expect, timeout=30):
    """Collect `expect` gateway replies: {frame_id: (status, scalar)}
    per stream, plus the raw items."""
    items = []
    for _ in range(expect):
        items.append(responses.get(timeout=timeout))
    return items


# -- policy grammar ----------------------------------------------------------


class TestAdmissionPolicy:
    def test_grammar_and_defaults(self):
        policy = AdmissionPolicy.parse(
            "max_inflight=4;queue=16;hysteresis=0.25;stale_after=3;"
            "throttle_high=0.75;throttle_low=0.25;throttle_rate=7;"
            "frame_deadline=2.5;bucket:1=20/5")
        assert policy.max_inflight == 4
        assert policy.queue_capacity == 16
        assert policy.hysteresis_s == 0.25
        assert policy.stale_after_s == 3.0
        assert policy.throttle_high == 0.75
        assert policy.throttle_rate == 7.0
        assert policy.frame_deadline_s == 2.5
        assert policy.bucket_for(1).rate == 20.0
        assert policy.bucket_for(0) is None  # unconfigured: admit freely
        defaults = AdmissionPolicy.parse(None)
        assert defaults.max_inflight == 8 and defaults.queue_capacity == 64

    def test_construction_error_codes_match_offline_lint(self):
        """Gateway construction must reject a spec with the SAME rule
        code `aiko lint` reports offline: AIKO404 for an unknown
        directive, AIKO403 for a bad value or cross-field violation."""
        from aiko_services_tpu.analyze.policies import check_gateway_policy
        process = Process(transport_kind="loopback")
        for spec, code in (("max_inflght=4", "AIKO404"),
                           ("max_inflight=many", "AIKO403"),
                           ("throttle_low=0.9;throttle_high=0.1",
                            "AIKO403")):
            problems = check_gateway_policy(spec)
            assert problems and problems[0][0] == code, (spec, problems)
            with pytest.raises(ValueError, match=code):
                Gateway(process, name=f"gw_{code}_{spec[:12]}",
                        policy=spec)

    def test_unknown_directive_rejected(self):
        with pytest.raises(ValueError):
            AdmissionPolicy.parse("max_inflght=4")

    def test_inverted_throttle_watermarks_rejected(self):
        with pytest.raises(ValueError):
            AdmissionPolicy.parse("throttle_high=0.2;throttle_low=0.5")

    def test_token_bucket_is_deterministic_in_injected_time(self):
        bucket = TokenBucket(rate=10.0, burst=2)
        takes = [bucket.try_take(0.0), bucket.try_take(0.0),
                 bucket.try_take(0.0),   # burst exhausted
                 bucket.try_take(0.05),  # +0.5 tokens: still short
                 bucket.try_take(0.1)]   # +0.5 more: one whole token
        assert takes == [True, True, False, False, True]


# -- routing & pinning -------------------------------------------------------


class TestRouting:
    def test_stream_pins_to_one_replica_for_its_lifetime(self):
        gateway, replicas, processes = _pool(2, "max_inflight=8;queue=32")
        try:
            responses = queue.Queue()
            gateway.submit_stream("s1", {}, queue_response=responses)
            for frame_id in range(12):
                gateway.submit_frame("s1", _frame(frame_id))
            _drain(responses, 12)
            # exactly one replica saw the stream: pinning, not spraying
            owners = [replica for replica in replicas
                      if replica.telemetry.registry.counter(
                          "pipeline.frames_total").value > 0]
            assert len(owners) == 1
            assert owners[0].telemetry.registry.counter(
                "pipeline.frames_total").value == 12
        finally:
            for process in processes:
                process.terminate()

    def test_streams_spread_over_replicas(self):
        gateway, replicas, processes = _pool(
            3, "max_inflight=8;queue=32", router_seed=11)
        try:
            responses = queue.Queue()
            for index in range(9):
                gateway.submit_stream(f"s{index}", {},
                                      queue_response=responses)
            wait_for(lambda: len(gateway.streams) == 9, timeout=10)
            loaded = [replica for replica in replicas
                      if any(stream.replica.name == replica.name
                             for stream in gateway.streams.values())]
            # power-of-two-choices with 9 idle-load streams must not
            # pile everything on one replica
            assert len(loaded) >= 2
        finally:
            for process in processes:
                process.terminate()


# -- admission & shedding ----------------------------------------------------


class TestAdmission:
    def test_duplicate_stream_id_sheds_typed(self):
        gateway, _, processes = _pool(1, None)
        try:
            responses = queue.Queue()
            gateway.submit_stream("dup", {}, queue_response=responses)
            wait_for(lambda: "dup" in gateway.streams, timeout=10)
            gateway.submit_stream("dup", {}, queue_response=responses)
            stream_id, frame_id, info, status = responses.get(timeout=10)
            assert (status, info["reason"]) == (
                "overloaded", "duplicate_stream_id")
        finally:
            for process in processes:
                process.terminate()

    def test_priority_token_bucket_rate_limits_streams(self):
        # priority 2 allows one stream (burst 1); priority 0 unlimited
        gateway, _, processes = _pool(
            1, "bucket:2=0.001/1")
        try:
            responses = queue.Queue()
            gateway.submit_stream("a", {"priority": 2},
                                  queue_response=responses)
            gateway.submit_stream("b", {"priority": 2},
                                  queue_response=responses)
            gateway.submit_stream("c", {"priority": 0},
                                  queue_response=responses)
            stream_id, _, info, status = responses.get(timeout=10)
            assert (stream_id, status, info["reason"]) == (
                "b", "overloaded", "rate_limited")
            wait_for(lambda: {"a", "c"} <= set(gateway.streams),
                     timeout=10)
            assert gateway.telemetry.shed_streams.value == 1
            assert gateway.telemetry.admitted.value == 2
        finally:
            for process in processes:
                process.terminate()

    def test_no_replica_sheds_stream(self):
        process = Process(transport_kind="loopback")
        gateway = Gateway(process, policy=None)
        process.run(in_thread=True)
        try:
            responses = queue.Queue()
            gateway.submit_stream("s", {}, queue_response=responses)
            _, _, info, status = responses.get(timeout=10)
            assert (status, info["reason"]) == ("overloaded", "no_replica")
        finally:
            process.terminate()

    def test_overload_sheds_lowest_priority_first(self):
        # one slow replica (50 ms/frame), 2 slots + 8 queue slots: a
        # burst of 18 frames across three priorities MUST shed, and
        # every shed must land on the lowest-priority streams while
        # priority 0 completes untouched (acceptance criterion 1,
        # ordering half).  The queue is sized to hold ALL of priority
        # 0's frames (6 < 8), so any p0 shed would be a real ordering
        # bug, never self-inflicted overflow
        gateway, _, processes = _pool(
            1, "max_inflight=2;queue=8", class_name="SlowScale",
            work_ms=50)
        try:
            by_priority = {0: queue.Queue(), 1: queue.Queue(),
                           2: queue.Queue()}
            for priority, responses in by_priority.items():
                gateway.submit_stream(
                    f"p{priority}", {"priority": priority},
                    queue_response=responses)
            wait_for(lambda: len(gateway.streams) == 3, timeout=10)
            per_stream = 6
            for frame_id in range(per_stream):
                for priority in (0, 1, 2):
                    gateway.submit_frame(f"p{priority}",
                                         _frame(frame_id))
            outcomes = {priority: {"ok": 0, "shed": 0}
                        for priority in by_priority}
            for priority, responses in by_priority.items():
                for _ in range(per_stream):
                    _, _, _, status = responses.get(timeout=60)
                    outcomes[priority][
                        "ok" if status == "ok" else "shed"] += 1
            assert outcomes[0] == {"ok": per_stream, "shed": 0}
            assert outcomes[2]["shed"] > 0
            assert outcomes[2]["shed"] >= outcomes[1]["shed"]
            assert gateway.telemetry.shed_frames.value == (
                outcomes[1]["shed"] + outcomes[2]["shed"])
        finally:
            for process in processes:
                process.terminate()

    def test_goodput_under_2x_overload_tracks_saturated_throughput(self):
        # acceptance criterion 1, goodput half.  Baseline: ONE replica
        # driven exactly at capacity (closed loop).  Overload: the same
        # replica behind the gateway under a 2x offered burst -- the
        # gateway sheds the excess fast and keeps the replica busy, so
        # admitted goodput stays within 10% of saturated throughput
        # (both rates are dominated by the element's deterministic
        # 10 ms service time, not wall-clock noise)
        work_ms = 10
        frames_n = 50
        process = Process(transport_kind="loopback")
        pipeline = create_pipeline(process, _replica_definition(
            "solo", class_name="SlowScale", work_ms=work_ms))
        responses = queue.Queue()
        stream = pipeline.create_stream("s", queue_response=responses)
        process.run(in_thread=True)
        start = time.perf_counter()
        for frame_id in range(frames_n):
            pipeline.create_frame(stream, _frame(frame_id))
        for _ in range(frames_n):
            responses.get(timeout=60)
        saturated = frames_n / (time.perf_counter() - start)
        process.terminate()

        reset_brokers()
        gateway, _, processes = _pool(
            1, "max_inflight=8;queue=32", class_name="SlowScale",
            work_ms=work_ms)
        try:
            gateway_responses = queue.Queue()
            gateway.submit_stream("s", {},
                                  queue_response=gateway_responses)
            wait_for(lambda: "s" in gateway.streams, timeout=10)
            offered = 2 * frames_n
            start = time.perf_counter()
            for frame_id in range(offered):
                gateway.submit_frame("s", _frame(frame_id))
            completed = 0
            for _ in range(offered):
                _, _, _, status = gateway_responses.get(timeout=60)
                if status == "ok":
                    completed += 1
            goodput = completed / (time.perf_counter() - start)
            shed = gateway.telemetry.shed_frames.value
            assert completed + shed == offered
            assert shed > 0  # 2x offered load MUST shed
            assert goodput >= 0.9 * saturated, (
                f"goodput {goodput:.1f}/s fell more than 10% below "
                f"saturated {saturated:.1f}/s")
        finally:
            for process in processes:
                process.terminate()

    def test_slo_aware_shed_rejects_when_queue_wait_blows_slo(self):
        gateway, _, processes = _pool(
            1, "max_inflight=1;queue=8", class_name="SlowScale",
            work_ms=30)
        try:
            responses = queue.Queue()
            gateway.submit_stream("tight", {"slo_ms": 1.0},
                                  queue_response=responses)
            wait_for(lambda: "tight" in gateway.streams, timeout=10)
            offered = 24
            for frame_id in range(offered):
                gateway.submit_frame("tight", _frame(frame_id))
            statuses = [item[3] for item in _drain(responses, offered,
                                                   timeout=60)]
            # once the completion-rate estimate warms up, a 1 ms SLO
            # against a ~30 ms/frame backlog must shed
            assert statuses.count("shed") > 0
        finally:
            for process in processes:
                process.terminate()


# -- failover ----------------------------------------------------------------


class TestFailover:
    def _run(self, faults):
        gateway, _, processes = _pool(
            2, "max_inflight=4;queue=64", router_seed=7, faults=faults)
        try:
            responses = queue.Queue()
            gateway.submit_stream("s1", {}, queue_response=responses)
            wait_for(lambda: "s1" in gateway.streams, timeout=10)
            for frame_id in range(20):
                gateway.submit_frame("s1", _frame(frame_id))
            got = {}
            for _ in range(20):
                _, frame_id, outputs, status = responses.get(timeout=60)
                assert status == "ok"
                got[frame_id] = np.asarray(outputs["y"]).tolist()
            summary = gateway.telemetry.summary()
            return got, summary
        finally:
            for process in processes:
                process.terminate()

    def test_replica_kill_fails_over_with_zero_lost_frames(self):
        # acceptance criterion 2: a seeded replica_kill mid-stream
        # (the replica's 6th routed frame) migrates the stream and
        # replays every un-acknowledged frame -- all 20 frames arrive
        # and the outputs are bit-identical to the unfaulted run
        baseline, base_summary = self._run(None)
        reset_brokers()
        faulted, fault_summary = self._run(
            "seed=3;replica_kill:frame=5")
        assert set(faulted) == set(baseline)          # zero lost frames
        assert faulted == baseline                    # bit-identical
        assert base_summary["failovers"] == 0
        assert fault_summary["failovers"] == 1
        assert fault_summary["replica_deaths"] == 1
        assert fault_summary["completed"] == 20

    def test_kill_with_no_spare_fails_stream_typed(self):
        gateway, _, processes = _pool(
            1, None, faults="seed=1;replica_kill:frame=2")
        try:
            responses = queue.Queue()
            gateway.submit_stream("s1", {}, queue_response=responses)
            wait_for(lambda: "s1" in gateway.streams, timeout=10)
            for frame_id in range(6):
                gateway.submit_frame("s1", _frame(frame_id))
            # the kill lands mid-burst: frames in flight release as
            # typed errors, frames submitted after the stream died are
            # dropped (pipeline-protocol parity) -- nothing leaks
            wait_for(lambda: "s1" not in gateway.streams, timeout=10)
            statuses = []
            try:
                while True:
                    statuses.append(responses.get(timeout=2)[3])
            except queue.Empty:
                pass
            assert "error" in statuses  # released, never leaked
            assert gateway.telemetry.released.value > 0
        finally:
            for process in processes:
                process.terminate()


# -- backpressure & throttle -------------------------------------------------


class TestBackpressure:
    def test_saturated_replica_parks_then_completes_all(self):
        gateway, _, processes = _pool(
            1, "max_inflight=1;queue=32", class_name="SlowScale",
            work_ms=10)
        try:
            responses = queue.Queue()
            gateway.submit_stream("s", {}, queue_response=responses)
            wait_for(lambda: "s" in gateway.streams, timeout=10)
            for frame_id in range(8):
                gateway.submit_frame("s", _frame(frame_id))
            items = _drain(responses, 8, timeout=60)
            assert [item[3] for item in items] == ["ok"] * 8
            # order preserved through park/drain
            assert [item[1] for item in items] == list(range(8))
            assert gateway.telemetry.routed.value == 8
        finally:
            for process in processes:
                process.terminate()

    def test_throttle_signal_caps_source_and_lifts(self):
        gateway, _, processes = _pool(
            1, "max_inflight=1;queue=8;throttle_high=0.5;"
            "throttle_low=0.125;throttle_rate=5",
            class_name="SlowScale", work_ms=20)
        try:
            throttle_calls = []
            responses = queue.Queue()
            gateway.submit_stream(
                "s", {}, queue_response=responses,
                throttle=lambda stream_id, rate: throttle_calls.append(
                    (stream_id, rate)))
            wait_for(lambda: "s" in gateway.streams, timeout=10)
            for frame_id in range(10):
                gateway.submit_frame("s", _frame(frame_id))
            _drain(responses, 10, timeout=60)
            # queue crossed the high-water mark under the burst, then
            # drained: exactly one throttle-on and one lift
            assert throttle_calls[0] == ("s", 5.0)
            assert throttle_calls[-1] == ("s", 0.0)
            assert gateway.telemetry.throttled.value == 1
            assert gateway.telemetry.unthrottled.value == 1
        finally:
            for process in processes:
                process.terminate()

    def test_frame_generator_rate_cap_and_pipeline_throttle(self):
        # the sibling hook itself, deterministically: set_rate caps the
        # effective interval, rate<=0 lifts, and Pipeline.throttle
        # reaches the generator through the element
        handle = FrameGeneratorHandle.__new__(FrameGeneratorHandle)
        handle.rate = 100.0
        handle._rate_cap = None
        assert handle._interval() == pytest.approx(0.01)
        handle.set_rate(10)
        assert handle._interval() == pytest.approx(0.1)
        handle.set_rate(500)  # a cap ABOVE the configured rate is inert
        assert handle._interval() == pytest.approx(0.01)
        handle.set_rate(0)
        assert handle._interval() == pytest.approx(0.01)

        definition = {
            "name": "gen_pipe",
            "graph": ["(source)"],
            "elements": [
                {"name": "source", "input": [{"name": "x"}],
                 "output": [{"name": "x"}],
                 "parameters": {"rate": 50},
                 "deploy": {"local": {"module": "tests.test_serve",
                                      "class_name": "TickSource"}}},
            ],
        }
        process = Process(transport_kind="loopback")
        pipeline = create_pipeline(process, definition)
        responses = queue.Queue()
        stream = pipeline.create_stream("g", queue_response=responses)
        process.run(in_thread=True)
        try:
            source = pipeline.elements["source"]
            handle = source._generators["g"]
            pipeline.throttle("g", 4)
            assert handle._interval() == pytest.approx(0.25)
            pipeline.throttle("g", 0)
            assert handle._interval() == pytest.approx(0.02)
        finally:
            process.terminate()


# -- discovery convergence (satellite: ServicesCache/ECConsumer) -------------


def _wire_pool(replica_names, policy, gateway_kwargs=None):
    """Registrar + wire-discovered replicas (no direct attach): the
    production topology, shrunk onto the loopback broker."""
    registrar_process = Process(transport_kind="loopback")
    Registrar(registrar_process, search_timeout=0.05)
    registrar_process.run(in_thread=True)
    processes = [registrar_process]
    replicas = []
    for name in replica_names:
        process = Process(transport_kind="loopback")
        processes.append(process)
        replicas.append((process, create_pipeline(
            process, _replica_definition(
                name, parameters={"metrics_interval": 0.2}))))
        process.run(in_thread=True)
    gateway_process = Process(transport_kind="loopback")
    processes.append(gateway_process)
    gateway = Gateway(gateway_process, policy=policy,
                      **(gateway_kwargs or {}))
    gateway.discover(name="replica*")
    gateway_process.run(in_thread=True)
    return gateway, replicas, processes


class TestDiscovery:
    def test_replica_appears_and_serves(self):
        gateway, replicas, processes = _wire_pool(
            ["replica0"], "max_inflight=4;queue=16")
        try:
            wait_for(lambda: len(gateway.replicas) == 1, timeout=10)
            replica = next(iter(gateway.replicas.values()))
            wait_for(lambda: replica.consumer.last_update is not None,
                     timeout=10)
            responses = queue.Queue()
            gateway.submit_stream("w", {}, queue_response=responses)
            for frame_id in range(4):
                gateway.submit_frame("w", _frame(frame_id))
            got = {}
            for _ in range(4):
                _, frame_id, outputs, status = responses.get(timeout=30)
                assert status == "ok"
                got[frame_id] = float(np.asarray(outputs["y"])[0, 0])
            assert got == {0: 0.0, 1: 10.0, 2: 20.0, 3: 30.0}
        finally:
            for process in processes:
                process.terminate()

    def test_replica_crash_mid_stream_fails_over(self):
        gateway, replicas, processes = _wire_pool(
            ["replica0", "replica1"], "max_inflight=4;queue=64",
            gateway_kwargs={"router_seed": 7})
        try:
            wait_for(lambda: len(gateway.replicas) == 2, timeout=10)
            wait_for(lambda: all(
                replica.consumer.last_update is not None
                for replica in gateway.replicas.values()), timeout=10)
            responses = queue.Queue()
            gateway.submit_stream("w", {}, queue_response=responses)
            wait_for(lambda: "w" in gateway.streams, timeout=10)
            owner_name = gateway.streams["w"].replica.name
            owner_process = next(
                process for process, pipeline in replicas
                if pipeline.name == owner_name)
            got = {}
            for frame_id in range(4):
                gateway.submit_frame("w", _frame(frame_id))
            for _ in range(4):
                _, frame_id, outputs, status = responses.get(timeout=30)
                got[frame_id] = float(np.asarray(outputs["y"])[0, 0])
            # CRASH the owner (severed transport: LWT "(absent)" fires,
            # the registrar reaps it, ServicesCache notifies the
            # gateway) with frames in flight
            for frame_id in range(4, 8):
                gateway.submit_frame("w", _frame(frame_id))
            owner_process.transport.sever()
            for _ in range(4):
                _, frame_id, outputs, status = responses.get(timeout=30)
                assert status == "ok"
                got[frame_id] = float(np.asarray(outputs["y"])[0, 0])
            assert got == {frame_id: frame_id * 10.0
                           for frame_id in range(8)}
            wait_for(lambda: len(gateway.replicas) == 1, timeout=10)
            assert gateway.streams["w"].replica.name != owner_name
            assert gateway.telemetry.failovers.value == 1
        finally:
            for process in processes:
                process.terminate()

    def test_stale_share_entries_exclude_replica_until_refresh(self):
        gateway, replicas, processes = _wire_pool(
            ["replica0"], "max_inflight=4;queue=16;stale_after=5")
        try:
            wait_for(lambda: len(gateway.replicas) == 1, timeout=10)
            replica = next(iter(gateway.replicas.values()))
            wait_for(lambda: replica.consumer.last_update is not None,
                     timeout=10)
            # age the mirror beyond stale_after: the gateway must stop
            # trusting the load view and refuse placement...
            replica.consumer.last_update -= 60.0
            responses = queue.Queue()
            gateway.submit_stream("stale", {}, queue_response=responses)
            _, _, info, status = responses.get(timeout=10)
            assert (status, info["reason"]) == ("overloaded",
                                                "no_replica")
            # ...and converge back WITHOUT a restart once the producer
            # speaks again (metrics_interval republish refreshes it)
            wait_for(lambda: (time.monotonic()
                              - (replica.consumer.last_update or 0)) < 5,
                     timeout=10)
            gateway.submit_stream("fresh", {}, queue_response=responses)
            wait_for(lambda: "fresh" in gateway.streams, timeout=10)
        finally:
            for process in processes:
                process.terminate()


# -- satellites --------------------------------------------------------------


class TestSatellites:
    def test_stream_id_collision_warns_and_counts(self):
        import logging

        class _Capture(logging.Handler):
            def __init__(self):
                super().__init__(level=logging.WARNING)
                self.messages = []

            def emit(self, record):
                self.messages.append(record.getMessage())

        capture = _Capture()
        from aiko_services_tpu.pipeline import pipeline as pipeline_module
        pipeline_module._LOGGER.addHandler(capture)
        process = Process(transport_kind="loopback")
        pipeline = create_pipeline(process, _replica_definition("solo"))
        process.run(in_thread=True)
        try:
            first = pipeline.create_stream("dup", parameters={"a": 1})
            again = pipeline.create_stream("dup", parameters={"a": 2})
            assert again is first
            assert first.parameters == {"a": 1}
            collisions = [message for message in capture.messages
                          if "collided" in message]
            assert len(collisions) == 1
            # the warning names BOTH parameter sets
            assert "'a': 1" in collisions[0] and "'a': 2" in collisions[0]
            assert pipeline.telemetry.registry.counter(
                "pipeline.stream_id_collision").value == 1
            # same parameters: benign re-create, no collision noise
            pipeline.create_stream("dup", parameters={"a": 1})
            assert pipeline.telemetry.registry.counter(
                "pipeline.stream_id_collision").value == 1
            assert sum("collided" in message
                       for message in capture.messages) == 1
        finally:
            pipeline_module._LOGGER.removeHandler(capture)
            process.terminate()

    def test_lease_jitter_is_deterministic_and_seeded(self):
        process = Process(transport_kind="loopback")
        pipeline = create_pipeline(process, _replica_definition(
            "jit", parameters={"faults": "seed=9"}))
        other = create_pipeline(process, _replica_definition(
            "jit2", parameters={"faults": "seed=10"}))
        process.run(in_thread=True)
        try:
            draws = {stream_id: pipeline._lease_jitter(stream_id)
                     for stream_id in ("s0", "s1", "s2")}
            # bounded, spread, and reproducible
            assert all(0.0 <= value < 0.1 for value in draws.values())
            assert len(set(draws.values())) == 3
            assert draws == {stream_id: pipeline._lease_jitter(stream_id)
                             for stream_id in ("s0", "s1", "s2")}
            # the fault-harness seed controls the draw
            assert (pipeline._lease_jitter("s0")
                    != other._lease_jitter("s0"))
            # the jitter lands on the lease's TIMER PERIOD only
            stream = pipeline.create_stream("s0", grace_time=10.0)
            lease = pipeline._stream_leases["s0"]
            expected = 10.0 * (1.0 + pipeline._lease_jitter("s0"))
            assert lease._timer_period == pytest.approx(expected)
            assert lease.lease_time == 10.0
            plain = Lease(process.event, 5.0, "plain")
            assert plain._timer_period == 5.0
            plain.terminate()
            del stream
        finally:
            process.terminate()

    def test_pipeline_load_export(self):
        process = Process(transport_kind="loopback")
        pipeline = create_pipeline(process, _replica_definition("ld"))
        process.run(in_thread=True)
        try:
            assert pipeline.load() == {
                "inflight": 0, "queue_depth": 0, "streams": 0}
            pipeline.create_stream("a")
            assert pipeline.load()["streams"] == 1
            assert pipeline.share.get("inflight") == 0
            assert pipeline.share.get("queue_depth") == 0
            summary = pipeline.telemetry.summary()
            assert summary["load"]["streams"] == 1
        finally:
            process.terminate()

    def test_direct_pipeline_contract_unchanged_without_gateway(self):
        # acceptance criterion 3: with no gateway in the path, the
        # pipeline's response contract, share keys, frame metrics keys,
        # and telemetry summary keys are exactly the legacy set (plus
        # the documented additive load/collision exports)
        process = Process(transport_kind="loopback")
        pipeline = create_pipeline(process, _replica_definition("leg"))
        responses = queue.Queue()
        stream = pipeline.create_stream("s", queue_response=responses)
        process.run(in_thread=True)
        try:
            pipeline.create_frame(stream, _frame(3))
            got_stream, got_frame, outputs = responses.get(timeout=30)
            # legacy 3-tuple with live objects, not gateway 4-tuples
            assert got_stream is stream and got_frame.frame_id == 0
            assert float(np.asarray(outputs["y"])[0, 0]) == 30.0
            assert set(got_frame.metrics) == {"time_scale",
                                              "time_pipeline"}
            for key in ("lifecycle", "stream_count", "frame_count",
                        "definition_name", "element_count"):
                assert key in pipeline.share
            summary = pipeline.telemetry.summary()
            legacy_keys = {"frames", "dropped", "errors", "fused_groups",
                           "chained_groups", "compiles_fused",
                           "cohort_splits", "retries", "dead_letters"}
            assert legacy_keys <= set(summary)
            assert set(summary) - legacy_keys == {"load"}
            assert summary["frames"] == 1
        finally:
            process.terminate()

    def test_gateway_metrics_snapshot_artifact(self):
        # CI uploads this snapshot: a seeded replica_kill scenario's
        # gateway metrics, written to AIKO_SERVE_METRICS_PATH when set
        gateway, _, processes = _pool(
            2, "max_inflight=4;queue=32", router_seed=7,
            faults="seed=3;replica_kill:frame=5")
        try:
            responses = queue.Queue()
            gateway.submit_stream("s1", {}, queue_response=responses)
            wait_for(lambda: "s1" in gateway.streams, timeout=10)
            for frame_id in range(20):
                gateway.submit_frame("s1", _frame(frame_id))
            for _ in range(20):
                assert responses.get(timeout=60)[3] == "ok"
            summary = gateway.telemetry.summary()
            assert summary["completed"] == 20
            assert summary["replica_deaths"] == 1
            path = os.environ.get("AIKO_SERVE_METRICS_PATH")
            if path:
                with open(path, "w") as handle:
                    json.dump({"summary": summary,
                               "snapshot": gateway.telemetry.snapshot()},
                              handle, indent=2, default=str)
        finally:
            for process in processes:
                process.terminate()
