# Checkpointer round-trip (params + stream cursors), pipeline-level
# checkpoint/restore, dashboard model over the loopback broker, CLI smoke.

import queue

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aiko_services_tpu.pipeline import create_pipeline
from aiko_services_tpu.runtime import Process, Registrar
from aiko_services_tpu.transport import get_broker, reset_brokers
from aiko_services_tpu.utils.checkpoint import Checkpointer
from helpers import wait_for

ELEMENTS = "aiko_services_tpu.elements"


@pytest.fixture(autouse=True)
def clean_brokers():
    reset_brokers()
    yield
    reset_brokers()


def local(class_name):
    return {"local": {"module": ELEMENTS, "class_name": class_name}}


class TestCheckpointer:
    def test_pytree_roundtrip_and_prune(self, tmp_path):
        checkpointer = Checkpointer(tmp_path / "ckpt", max_to_keep=2)
        tree = {"w": jnp.arange(6.0).reshape(2, 3),
                "nested": {"b": jnp.ones((4,))}}
        for step in (1, 2, 3):
            checkpointer.save(step, tree, metadata={"step": step})
        assert checkpointer.steps() == [2, 3]  # pruned to max_to_keep
        restored, metadata = checkpointer.restore()
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      np.arange(6.0).reshape(2, 3))
        assert metadata == {"step": 3}

    def test_restore_empty(self, tmp_path):
        checkpointer = Checkpointer(tmp_path / "none")
        tree, metadata = checkpointer.restore()
        assert tree is None and metadata == {}


class TestPipelineCheckpoint:
    def _definition(self):
        return {
            "name": "ckpt_pipe",
            "graph": ["(source (mlp (sink)))"],
            "elements": [
                {"name": "source", "output": [{"name": "tensor"}],
                 "parameters": {"data_sources": [[4, 16]]},
                 "deploy": local("ArraySource")},
                {"name": "mlp", "input": [{"name": "tensor"}],
                 "output": [{"name": "tensor"}],
                 "parameters": {"features": 16, "hidden": 8},
                 "deploy": local("JaxMLP")},
                {"name": "sink", "input": [{"name": "tensor"}],
                 "output": [{"name": "tensor"}],
                 "deploy": local("ToHost")},
            ],
        }

    def test_element_state_and_cursor_roundtrip(self, tmp_path):
        checkpointer = Checkpointer(tmp_path / "ckpt")
        process = Process(transport_kind="loopback")
        pipeline = create_pipeline(process, self._definition())
        process.run(in_thread=True)
        responses = queue.Queue()
        pipeline.create_stream("s1", queue_response=responses)
        responses.get(timeout=30)
        original_w1 = np.asarray(pipeline.elements["mlp"].state["w1"])
        pipeline.checkpoint(checkpointer, step=7)
        process.terminate()

        # fresh pipeline restores the same weights + stream cursor
        reset_brokers()
        process2 = Process(transport_kind="loopback")
        pipeline2 = create_pipeline(process2, self._definition())
        process2.run(in_thread=True)
        metadata = pipeline2.restore_checkpoint(checkpointer)
        assert metadata["pipeline"] == "ckpt_pipe"
        np.testing.assert_array_equal(
            np.asarray(pipeline2.elements["mlp"].state["w1"]), original_w1)
        assert "s1" in pipeline2.streams
        assert pipeline2.streams["s1"].frame_id >= 1
        process2.terminate()


class TestDashboard:
    def test_model_tracks_services_and_share(self):
        from aiko_services_tpu.dashboard import (
            DashboardModel, render_snapshot)
        registrar_process = Process(transport_kind="loopback")
        Registrar(registrar_process, search_timeout=0.05)
        registrar_process.run(in_thread=True)

        worker_process = Process(transport_kind="loopback")
        from aiko_services_tpu.runtime import Actor, ECProducer
        worker = Actor(worker_process, "worker")
        ECProducer(worker)
        worker_process.run(in_thread=True)

        viewer_process = Process(transport_kind="loopback")
        model = DashboardModel(viewer_process)
        viewer_process.run(in_thread=True)

        wait_for(lambda: any("worker" == str(fields.name)
                             for fields in model.rows.values()),
                 timeout=10)
        snapshot = render_snapshot(model)
        assert "worker" in snapshot and "service(s)" in snapshot

        worker_topic = next(topic for topic, fields in model.rows.items()
                            if str(fields.name) == "worker")
        model.select(worker_topic)
        worker.ec_producer.update("temperature", 42)
        # EC values cross the S-expression wire as text
        wait_for(lambda: model.selected_share.get("temperature") == "42",
                 timeout=10)

        # variable edit flows back to the worker's share
        model.update_variable("temperature", 7)
        get_broker().drain()
        wait_for(lambda: worker.share.get("temperature") == "7",
                 timeout=10)

        for process in (registrar_process, worker_process, viewer_process):
            process.terminate()


class TestCli:
    def test_cli_help_lists_commands(self):
        from click.testing import CliRunner
        from aiko_services_tpu.cli import main
        result = CliRunner().invoke(main, ["--help"])
        assert result.exit_code == 0
        for command in ("registrar", "pipeline", "storage", "recorder",
                        "dashboard", "bench"):
            assert command in result.output


class TestCursesUI:
    def test_curses_loop_renders_selects_and_quits(self, monkeypatch):
        """Exercise the real curses draw loop (previously '# pragma: no
        cover') against a fake curses module: renders the service table,
        selects a row (EC share mirror kicks in), k publishes terminate,
        q exits."""
        import sys
        import types
        import time as time_module
        from aiko_services_tpu.dashboard import DashboardModel, _run_curses
        from aiko_services_tpu.runtime import Process, Registrar
        from aiko_services_tpu.runtime.actor import Actor
        from aiko_services_tpu.transport.loopback import get_broker

        process = Process(transport_kind="loopback")
        Registrar(process, search_timeout=0.05)
        actor = Actor(process, name="victim")
        process.run(in_thread=True)
        model = DashboardModel(process)
        deadline = time_module.monotonic() + 5
        # wait for BOTH rows (registrar + victim): starting the UI on a
        # partial table makes every later assertion timing-dependent
        while len(model.rows) < 2 and time_module.monotonic() < deadline:
            get_broker().drain()
            time_module.sleep(0.01)
        assert len(model.rows) >= 2, model.rows

        drawn = []
        messages = []
        process.add_message_handler(
            lambda topic, payload: messages.append((topic, str(payload))),
            "#")

        def terminate_seen():
            return any(topic.endswith("/in") and "terminate" in payload
                       for topic, payload in list(messages))

        class FakeScreen:
            """Event-driven key feed: navigate once, press 'k' only
            while a selection is live (a transient cache re-sync can
            clear model.selected between render and keypress -- a
            fixed key script raced that and flaked ~1/10), quit once
            the terminate hit the wire."""

            def __init__(self):
                self.deadline = time_module.monotonic() + 20
                self.navigated = False

            def erase(self):
                pass

            def nodelay(self, flag):
                pass

            def addstr(self, y, x, text, *attrs):
                drawn.append(text)

            def refresh(self):
                pass

            def getch(self):
                if (terminate_seen()
                        or time_module.monotonic() > self.deadline):
                    return ord("q")
                if not self.navigated:  # exercise the arrow keys once
                    self.navigated = True
                    return fake_curses.KEY_DOWN
                if model.selected is not None:
                    return ord("k")
                get_broker().drain()
                return -1

        fake_curses = types.ModuleType("curses")
        fake_curses.A_BOLD = 1
        fake_curses.A_DIM = 2
        fake_curses.KEY_DOWN = 258
        fake_curses.KEY_UP = 259
        fake_curses.KEY_BACKSPACE = 263
        fake_curses.curs_set = lambda n: None
        fake_curses.wrapper = lambda ui: ui(FakeScreen())
        monkeypatch.setitem(sys.modules, "curses", fake_curses)

        _run_curses(model)
        joined = " ".join(drawn)
        assert "dashboard" in joined and "victim" in joined
        get_broker().drain()
        # "k" published (terminate) to the selected service's /in
        assert terminate_seen(), messages[-5:]
        process.terminate()

    def test_curses_edit_flow_updates_live_share_variable(self):
        """VERDICT r4 item 6: the UI's edit keybinding round-trips --
        'e' opens the input line, typed "name value" + Enter publishes
        (update ...) to the selected service's /control, and the
        worker's OWN share dict changes."""
        import time as time_module
        import types
        from aiko_services_tpu.dashboard import DashboardModel, _dashboard_ui
        from aiko_services_tpu.runtime import Actor, ECProducer, Process, Registrar
        from aiko_services_tpu.transport.loopback import get_broker

        process = Process(transport_kind="loopback")
        Registrar(process, search_timeout=0.05)
        worker = Actor(process, name="editable")
        ECProducer(worker)
        worker.ec_producer.update("rate", 1)
        process.run(in_thread=True)
        model = DashboardModel(process)
        deadline = time_module.monotonic() + 5
        while not any(str(f.name) == "editable"
                      for f in model.rows.values()):
            assert time_module.monotonic() < deadline
            get_broker().drain()
            time_module.sleep(0.01)

        # select the worker row deterministically
        rows = sorted(model.rows.items())
        worker_index = next(i for i, (_, f) in enumerate(rows)
                            if str(f.name) == "editable")

        keys = [curses_key for _ in range(worker_index)
                for curses_key in (258,)]        # KEY_DOWN to the row
        keys += [-1]                             # render pass: selects
        keys += [ord("e")]
        keys += [ord(c) for c in "rate 7"]
        keys += [10]                             # Enter commits
        keys += [ord("q")]

        class FakeScreen:
            def __init__(self, queued):
                self.queued = list(queued)

            def erase(self):
                pass

            def nodelay(self, flag):
                pass

            def addstr(self, y, x, text, *attrs):
                pass

            def refresh(self):
                pass

            def getch(self):
                return self.queued.pop(0) if self.queued else ord("q")

        fake_curses = types.SimpleNamespace(
            A_BOLD=1, A_DIM=2, KEY_DOWN=258, KEY_UP=259,
            KEY_BACKSPACE=263, curs_set=lambda n: None)
        _dashboard_ui(model, FakeScreen(keys), fake_curses)
        get_broker().drain()
        wait_for(lambda: worker.share.get("rate") == "7", timeout=10)
        process.terminate()

    def test_curses_history_page_shows_registrar_ring(self):
        """'h' on the selected registrar requests its (history ...) ring
        and the page renders add events for registered services."""
        import time as time_module
        import types
        from aiko_services_tpu.dashboard import DashboardModel, _dashboard_ui
        from aiko_services_tpu.runtime import Actor, Process, Registrar
        from aiko_services_tpu.transport.loopback import get_broker

        process = Process(transport_kind="loopback")
        Registrar(process, search_timeout=0.05)
        Actor(process, name="historic")
        process.run(in_thread=True)
        model = DashboardModel(process)
        deadline = time_module.monotonic() + 5
        while not any("registrar" in str(f.protocol)
                      for f in model.rows.values()):
            assert time_module.monotonic() < deadline
            get_broker().drain()
            time_module.sleep(0.01)

        rows = sorted(model.rows.items())
        registrar_index = next(i for i, (_, f) in enumerate(rows)
                               if "registrar" in str(f.protocol))
        keys = [258] * registrar_index + [-1, ord("h")]

        drawn = []

        class FakeScreen:
            def __init__(self, queued):
                self.queued = list(queued)

            def erase(self):
                pass

            def nodelay(self, flag):
                pass

            def addstr(self, y, x, text, *attrs):
                drawn.append(str(text))

            def refresh(self):
                pass

            def getch(self, _deadline=[None]):
                if _deadline[0] is None:
                    _deadline[0] = time_module.monotonic() + 30
                if self.queued:
                    return self.queued.pop(0)
                get_broker().drain()
                # keep rendering (-1) until history arrived, then quit;
                # the deadline keeps a lost reply from hanging the suite
                if (model.history_lines
                        or time_module.monotonic() > _deadline[0]):
                    return ord("q")
                return -1

        fake_curses = types.SimpleNamespace(
            A_BOLD=1, A_DIM=2, KEY_DOWN=258, KEY_UP=259,
            KEY_BACKSPACE=263, curs_set=lambda n: None)
        _dashboard_ui(model, FakeScreen(keys), fake_curses)
        assert model.history_lines, "history never arrived"
        joined = " ".join(drawn)
        assert "history:" in joined
        assert any("historic" in line for line in model.history_lines)
        process.terminate()
