# MQTT transport contract tests (VERDICT round-1 item 9: transport/
# mqtt.py had never executed -- paho is absent from the TPU image).
#
# A fake paho module (tests/fake_paho.py) is injected as
# transport.mqtt._paho, and the SAME behavioral contract is asserted
# against both LoopbackTransport and MqttTransport: pub/sub roundtrip,
# wildcard collapse, retained delivery, LWT on abnormal loss vs clean
# disconnect, and the LWT-change reconnect cycle (reference mqtt.py:
# 192-228 semantics).  A final test boots a full Process + Registrar
# stack over the MQTT transport.

import time

import pytest

import fake_paho
from aiko_services_tpu.transport import loopback as loopback_module
from aiko_services_tpu.transport import minimqtt
from aiko_services_tpu.transport import mqtt as mqtt_module
from aiko_services_tpu.transport.loopback import LoopbackTransport

# "socket" kind: the SAME MqttTransport code over a REAL TCP socket --
# the in-repo MQTT 3.1.1 client (transport/minimqtt.py) against the
# embedded broker (VERDICT r3 item 4: MQTT had only ever run against
# the in-repo fake paho)
_socket_state = {"broker": None, "transports": []}


def _socket_broker():
    if _socket_state["broker"] is None:
        _socket_state["broker"] = minimqtt.MiniMqttBroker()
    return _socket_state["broker"]


@pytest.fixture(autouse=True)
def fake_broker(monkeypatch):
    fake_paho.FakeMqttBroker.reset_all()
    monkeypatch.setattr(mqtt_module, "_paho", fake_paho)
    monkeypatch.setattr(mqtt_module, "_PAHO_ERROR", None)
    loopback_module.reset_brokers()
    yield
    fake_paho.FakeMqttBroker.reset_all()
    loopback_module.reset_brokers()
    broker = _socket_state["broker"]
    _socket_state["broker"] = None
    _socket_state["transports"] = []
    if broker is not None:
        broker.stop()


def make_transport(kind, on_message):
    if kind == "loopback":
        transport = LoopbackTransport(on_message)
    elif kind == "socket":
        mqtt_module._paho = minimqtt
        broker = _socket_broker()
        transport = mqtt_module.MqttTransport(
            on_message,
            configuration={"host": broker.host, "port": broker.port,
                           "username": None, "password": None,
                           "tls": False})
        _socket_state["transports"].append(transport)
    else:
        transport = mqtt_module.MqttTransport(
            on_message,
            configuration={"host": "fakehost", "port": 1883,
                           "username": None, "password": None,
                           "tls": False})
    return transport


def drain(kind):
    if kind == "loopback":
        loopback_module.get_broker().drain()
    elif kind == "socket":
        # a PINGREQ round-trip per live client: everything written
        # before it has been routed, and every delivery to that client
        # dispatched (same-TCP-stream ordering)
        for transport in _socket_state["transports"]:
            client = transport._client
            if client is not None and transport.connected:
                client.flush()
    # fake paho delivers synchronously


KINDS = ["loopback", "mqtt", "socket"]


@pytest.mark.parametrize("kind", KINDS)
class TestTransportContract:
    def test_pubsub_roundtrip(self, kind):
        received = []
        transport = make_transport(
            kind, lambda topic, payload: received.append((topic, payload)))
        transport.connect()
        transport.subscribe("ns/host/1/in")
        transport.publish("ns/host/1/in", "(hello world)")
        drain(kind)
        assert received == [("ns/host/1/in", "(hello world)")]
        transport.disconnect()

    def test_wildcard_collapse(self, kind):
        """A # subscription must receive everything a concrete
        subscription would -- and only matching topics."""
        received = []
        transport = make_transport(
            kind, lambda topic, payload: received.append(topic))
        transport.connect()
        transport.subscribe("ns/+/state")
        transport.subscribe("ns/deep/#")
        transport.publish("ns/alpha/state", "x")     # matches +
        transport.publish("ns/alpha/other", "x")     # matches neither
        transport.publish("ns/deep/a/b/c", "x")      # matches #
        drain(kind)
        assert sorted(received) == ["ns/alpha/state", "ns/deep/a/b/c"]
        transport.disconnect()

    def test_retained_delivered_on_late_subscribe(self, kind):
        received = []
        publisher = make_transport(kind, None)
        publisher.connect()
        publisher.publish("ns/service/registrar", "(primary found x)",
                          retain=True)
        drain(kind)
        subscriber = make_transport(
            kind, lambda topic, payload: received.append(payload))
        subscriber.connect()
        subscriber.subscribe("ns/service/registrar")
        drain(kind)
        assert received == ["(primary found x)"]
        publisher.disconnect()
        subscriber.disconnect()

    def test_retained_cleared_by_empty_payload(self, kind):
        received = []
        publisher = make_transport(kind, None)
        publisher.connect()
        publisher.publish("ns/boot", "stale", retain=True)
        publisher.publish("ns/boot", "", retain=True)
        drain(kind)
        subscriber = make_transport(
            kind, lambda topic, payload: received.append(payload))
        subscriber.connect()
        subscriber.subscribe("ns/boot")
        drain(kind)
        assert received == []
        publisher.disconnect()
        subscriber.disconnect()

    def test_no_lwt_on_clean_disconnect(self, kind):
        received = []
        watcher = make_transport(
            kind, lambda topic, payload: received.append(payload))
        watcher.connect()
        watcher.subscribe("ns/x/state")
        client = make_transport(kind, None)
        client.set_last_will_and_testament("ns/x/state", "(absent)")
        client.connect()
        client.disconnect()           # clean: no will
        drain(kind)
        assert received == []
        watcher.disconnect()


class _SocketBrokerAdapter:
    """drop()/retained surface over the embedded real-socket broker,
    mirroring fake_paho.FakeMqttBroker for the shared assertions."""

    def __init__(self, broker):
        self._broker = broker

    @property
    def retained(self):
        return self._broker.retained

    def drop(self, client):
        self._broker.drop_client(client._client_id)


def broker_for(kind):
    if kind == "socket":
        return _SocketBrokerAdapter(_socket_broker())
    return fake_paho.FakeMqttBroker.get("fakehost", 1883)


BROKER_KINDS = ["mqtt", "socket"]


@pytest.mark.parametrize("kind", BROKER_KINDS)
class TestMqttSpecifics:
    """Behaviors only observable against a broker (fake paho AND the
    real-socket embedded broker)."""

    def _pair(self, kind):
        received = []
        watcher = make_transport(
            kind, lambda topic, payload: received.append(
                (topic, payload)))
        watcher.connect()
        return watcher, received

    def test_lwt_fires_on_abnormal_drop(self, kind):
        watcher, received = self._pair(kind)
        watcher.subscribe("ns/+/+/+/state")
        drain(kind)
        client = make_transport(kind, None)
        client.set_last_will_and_testament(
            "ns/host/9/0/state", "(absent)", retain=True)
        client.connect()
        broker = broker_for(kind)
        broker.drop(client._client)   # socket loss, not disconnect()
        drain(kind)
        assert ("ns/host/9/0/state", "(absent)") in received
        # retained for late registrars
        assert broker.retained["ns/host/9/0/state"] == b"(absent)"
        watcher.disconnect()

    def test_lwt_change_cycles_connection(self, kind):
        """Changing the LWT must disconnect/reconnect (MQTT protocol:
        one will per connection, set at CONNECT -- reference
        mqtt.py:192-201) and resubscribe existing patterns."""
        watcher, received = self._pair(kind)
        watcher.subscribe("ns/#")
        drain(kind)
        client = make_transport(kind, None)
        client.set_last_will_and_testament("ns/a/state", "(absent a)")
        client.connect()
        client.subscribe("ns/control")
        client.set_last_will_and_testament("ns/b/state", "(absent b)")
        # reconnect cycle happened; subscriptions survived
        assert client.connected
        client.publish("ns/ping", "x")
        drain(kind)
        broker = broker_for(kind)
        broker.drop(client._client)
        drain(kind)
        assert ("ns/b/state", "(absent b)") in received
        assert ("ns/a/state", "(absent a)") not in received
        watcher.disconnect()

    def test_clear_lwt_cycles_and_disarms(self, kind):
        watcher, received = self._pair(kind)
        watcher.subscribe("ns/#")
        drain(kind)
        client = make_transport(kind, None)
        client.set_last_will_and_testament("ns/c/state", "(absent)")
        client.connect()
        client.clear_last_will_and_testament("ns/c/state")
        broker = broker_for(kind)
        broker.drop(client._client)
        drain(kind)
        assert received == []
        watcher.disconnect()

    def test_reconnect_resubscribes(self, kind):
        received = []
        client = make_transport(
            kind, lambda topic, payload: received.append(payload))
        client.subscribe("ns/data")   # subscribed before connect
        client.connect()
        client.disconnect()
        client.connect()              # patterns replayed on_connect
        client.publish("ns/data", "after-reconnect")
        drain(kind)
        assert received == ["after-reconnect"]
        client.disconnect()


class TestProcessOverMqtt:
    @pytest.mark.parametrize("kind", BROKER_KINDS)
    def test_registrar_handshake_over_mqtt_transport(self, monkeypatch,
                                                     kind):
        """The full runtime stack (Process + Registrar + actor
        registration) over MqttTransport -- against fake paho AND the
        embedded real-socket broker (the reference deployment
        topology over genuine TCP)."""
        if kind == "socket":
            mqtt_module._paho = minimqtt
            broker = _socket_broker()
            monkeypatch.setenv("AIKO_MQTT_HOST", broker.host)
            monkeypatch.setenv("AIKO_MQTT_PORT", str(broker.port))
        else:
            monkeypatch.setenv("AIKO_MQTT_HOST", "fakehost")
            monkeypatch.setenv("AIKO_MQTT_PORT", "1883")
        from aiko_services_tpu.runtime import (
            ConnectionState, Process, Registrar)

        registrar_process = Process(transport_kind="mqtt")
        registrar = Registrar(registrar_process, search_timeout=0.05)
        registrar_process.run(in_thread=True)

        worker = Process(transport_kind="mqtt")
        from aiko_services_tpu.runtime.actor import Actor
        actor = Actor(worker, name="mqtt_actor")
        worker.run(in_thread=True)

        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if (worker.connection.is_connected(ConnectionState.REGISTRAR)
                    and registrar.services_table.get_service(
                        actor.topic_path)):
                break
            time.sleep(0.02)
        fields = registrar.services_table.get_service(actor.topic_path)
        assert fields is not None and fields.name == "mqtt_actor"
        worker.terminate()
        registrar_process.terminate()

class TestMiniMqttReconnect:
    """The reconnect path over REAL sockets, driven by the fault
    harness: an injected abnormal connection drop must advance the
    mqtt.reconnects counter and replay both subscriptions and the
    last-will on the NEW session (the will is re-armed at CONNECT, so a
    second drop fires it again)."""

    def test_injected_drop_replays_subscriptions_and_lwt(self):
        from aiko_services_tpu import faults as faults_module
        from aiko_services_tpu.observe.metrics import get_registry
        injector = faults_module.create_injector(
            "connection_drop:times=2")
        registry = get_registry()
        reconnects0 = registry.counter("mqtt.reconnects").value

        received = []
        watcher = make_transport(
            "socket",
            lambda topic, payload: received.append((topic, payload)))
        watcher.connect()
        watcher.subscribe("ns/#")
        client = make_transport(
            "socket",
            lambda topic, payload: received.append((topic, payload)))
        client.set_last_will_and_testament("ns/x/state", "(absent)")
        client.connect()
        client.subscribe("ns/data")
        drain("socket")
        broker = _socket_broker()

        # injected drop 1: abnormal socket loss; the network loop must
        # reconnect (0.5 s backoff) and count it
        assert injector.connection_drop()
        broker.drop_client(client._client._client_id)
        deadline = time.monotonic() + 10
        while (time.monotonic() < deadline
               and registry.counter(
                   "mqtt.reconnects").value <= reconnects0):
            time.sleep(0.05)
        assert registry.counter(
            "mqtt.reconnects").value > reconnects0, "drop not counted"
        while time.monotonic() < deadline and not client.connected:
            time.sleep(0.05)
        assert client.connected, "client never reconnected"

        # subscriptions replayed on the new session
        received.clear()
        client.publish("ns/data", "after-reconnect")
        drain("socket")
        assert ("ns/data", "after-reconnect") in received

        # the last-will was re-armed at reconnect: injected drop 2
        # fires it again on the NEW session
        received.clear()
        assert injector.connection_drop()
        broker.drop_client(client._client._client_id)
        deadline = time.monotonic() + 10
        while (time.monotonic() < deadline
               and ("ns/x/state", "(absent)") not in received):
            time.sleep(0.05)
        assert ("ns/x/state", "(absent)") in received
        assert not injector.connection_drop()  # plan fully consumed
        assert injector.stats() == {"connection_drop": 2}
        watcher.disconnect()
        client.disconnect()


class TestMiniMqttClientUnit:
    """ADVICE r4 (low x2): CONNECT advertises the real keepalive, and
    flush() waits for its OWN ping's response."""

    def test_connect_body_encodes_real_keepalive(self):
        import struct
        client = minimqtt.Client()
        client.connect_async("localhost", 1883, keepalive=300)
        body = client._connect_body()
        # body = len-prefixed "MQTT" (6) + level (1) + flags (1) + keepalive
        assert struct.unpack(">H", body[8:10])[0] == 300

    def test_flush_fails_fast_when_disconnected(self):
        import time
        client = minimqtt.Client()  # no socket at all
        start = time.monotonic()
        assert client.flush(timeout=5.0) is False
        assert time.monotonic() - start < 1.0  # no blind timeout wait

    def test_flush_not_released_by_earlier_keepalive_pingresp(self):
        import threading
        import time

        class _FakeSock:
            def sendall(self, data):
                pass

        client = minimqtt.Client()
        client._sock = _FakeSock()
        # a keepalive PINGREQ is already outstanding when flush starts
        with client._ping_cond:
            client._ping_sent += 1
        result = {}

        def run_flush():
            result["ok"] = client.flush(timeout=5.0)

        thread = threading.Thread(target=run_flush)
        thread.start()
        time.sleep(0.1)
        # the keepalive's PINGRESP arrives: must NOT satisfy the barrier
        with client._ping_cond:
            client._ping_acked += 1
            client._ping_cond.notify_all()
        time.sleep(0.2)
        assert thread.is_alive()  # still waiting for ITS OWN response
        with client._ping_cond:
            client._ping_acked += 1
            client._ping_cond.notify_all()
        thread.join(timeout=5.0)
        assert result["ok"] is True

    def test_keepalive_send_failure_rolls_back_ping_count(self):
        """A keepalive PINGREQ that never hits the wire must not leave a
        phantom sent-count deficit: later flush() waiters would block on
        a PINGRESP that was never requested (mirrors flush()'s own
        rollback)."""
        class _DeadSock:
            def sendall(self, data):
                raise OSError("gone")

        client = minimqtt.Client()
        client._sock = _DeadSock()
        client._send_keepalive_ping()
        assert client._ping_sent == client._ping_acked == 0

    def test_flush_aborts_on_connection_loss(self):
        import threading
        import time

        class _FakeSock:
            def sendall(self, data):
                pass

        client = minimqtt.Client()
        client._sock = _FakeSock()
        result = {}

        def run_flush():
            result["ok"] = client.flush(timeout=5.0)

        thread = threading.Thread(target=run_flush)
        thread.start()
        time.sleep(0.1)
        with client._ping_cond:  # what _network_loop does on socket loss
            client._ping_gen += 1
            client._ping_acked = client._ping_sent
            client._ping_cond.notify_all()
        thread.join(timeout=5.0)
        assert result["ok"] is False
