# Distributed logging end-to-end: service loggers publish to
# "{topic_path}/log" once the transport connects (backlog flushed), the
# Recorder aggregates them, the dashboard shows them, and log_level is
# live-updatable through the EC share (reference logger.py:127-172,
# actor.py:259-265).

import queue

from aiko_services_tpu.dashboard import DashboardModel, render_snapshot
from aiko_services_tpu.pipeline import create_pipeline
from aiko_services_tpu.pipeline.stream import StreamEvent
from aiko_services_tpu.runtime import Process, Recorder, Registrar
from aiko_services_tpu.transport import get_broker, reset_brokers
from aiko_services_tpu.utils import generate

from helpers import wait_for


def setup_function(function):
    reset_brokers()


def _start_process():
    process = Process(transport_kind="loopback")
    Registrar(process, search_timeout=0.05)
    process.run(in_thread=True)
    return process


def test_service_logs_publish_to_log_topic():
    process = _start_process()
    received = []
    from aiko_services_tpu.runtime import Actor
    actor = Actor(process, "talker")
    process.add_message_handler(
        lambda topic, payload: received.append(payload), actor.topic_log)
    actor.logger.info("hello distributed world")
    wait_for(lambda: any("hello distributed world" in line
                         for line in received))
    process.terminate()


def test_backlog_flushes_on_connect():
    # log BEFORE the transport connects: records ride the ring buffer and
    # flush to /log at TRANSPORT (reference logger.py:140-145)
    process = Process(transport_kind="loopback")
    from aiko_services_tpu.runtime import Actor
    actor = Actor(process, "early")
    actor.logger.warning("logged before connect")
    received = []
    watcher = Process(transport_kind="loopback")
    watcher.add_message_handler(
        lambda topic, payload: received.append(payload), actor.topic_log)
    watcher.run(in_thread=True)
    process.run(in_thread=True)   # connects; ring must flush
    wait_for(lambda: any("logged before connect" in line
                         for line in received))
    process.terminate()
    watcher.terminate()


def test_log_level_live_update_via_control_topic():
    process = _start_process()
    from aiko_services_tpu.runtime import Actor
    actor = Actor(process, "leveled")   # Actor auto-creates its ECProducer
    assert actor.share["log_level"] == "INFO"
    received = []
    process.add_message_handler(
        lambda topic, payload: received.append(payload), actor.topic_log)
    actor.logger.debug("invisible")
    process.publish(actor.topic_control,
                    generate("update", ["log_level", "DEBUG"]))
    wait_for(lambda: actor.logger.level == 10)  # DEBUG
    actor.logger.debug("now visible")
    wait_for(lambda: any("now visible" in line for line in received))
    assert not any("invisible" in line for line in received)
    assert actor.ec_producer.get("log_level") == "DEBUG"
    process.terminate()


from aiko_services_tpu.pipeline import PipelineElement


class Chatty(PipelineElement):
    def process_frame(self, stream):
        self.logger.info("frame says chirp")
        return StreamEvent.OKAY, {"value": 1}


def test_element_log_to_recorder_to_dashboard():
    # the VERDICT round-1 done-criterion: element logs -> recorder ring ->
    # dashboard snapshot shows the line
    process = _start_process()
    recorder = Recorder(process)

    definition = {
        "name": "logpipe", "graph": ["(chatty)"],
        "elements": [
            {"name": "chatty", "output": [{"name": "value"}],
             "deploy": {"local": {"class_name": "Chatty",
                                  "module": __name__}}},
        ],
    }
    pipeline = create_pipeline(process, definition)
    responses = queue.Queue()
    pipeline.create_stream("s", queue_response=responses)
    pipeline.process_frame({"stream_id": "s"}, {})
    responses.get(timeout=10)

    element = pipeline.elements["chatty"]
    wait_for(lambda: any("frame says chirp" in record
                         for record in recorder.records(element.topic_log)))

    # dashboard: select the element, its log lines appear in the snapshot
    model = DashboardModel(process)
    wait_for(lambda: element.topic_path in model.rows)
    model.select(element.topic_path)
    element.logger.info("second chirp for the dashboard")
    get_broker().drain()
    wait_for(lambda: any("second chirp" in line
                         for line in model.log_lines))
    snapshot = render_snapshot(model)
    assert "second chirp for the dashboard" in snapshot
    process.terminate()


def test_distributed_logging_disabled(monkeypatch):
    monkeypatch.setenv("AIKO_LOG_DISTRIBUTED", "false")
    process = _start_process()
    from aiko_services_tpu.runtime import Actor
    actor = Actor(process, "muted")
    assert actor._log_ring is None
    received = []
    process.add_message_handler(
        lambda topic, payload: received.append(payload), actor.topic_log)
    actor.logger.info("should stay local")
    get_broker().drain()
    assert not received
    process.terminate()
