# Continuous batching with paged KV (aiko_services_tpu/decode/): the
# block manager's pool invariants, the engine's bit-compatibility with
# the closed-batch generate() path, the zero-recompile shape-stability
# guarantee across admission/eviction storms, exhaustion behavior
# (deferral + preemption, no deadlock), and the LMGenerate
# `continuous: true` pipeline integration.

import queue

import numpy as np
import pytest

import jax

from aiko_services_tpu.decode import BlockManager, DecodeEngine, TRASH_BLOCK
from aiko_services_tpu.models import TransformerConfig, generate, init_params
from aiko_services_tpu.pipeline import create_pipeline
from aiko_services_tpu.runtime import Process
from aiko_services_tpu.transport import reset_brokers

from helpers import wait_for

ELEMENTS = "aiko_services_tpu.elements"

TINY = dict(vocab_size=64, n_layers=2, n_heads=2, n_kv_heads=2,
            d_model=32, d_ff=64, max_seq_len=64, dtype="float32")


@pytest.fixture(autouse=True)
def clean_brokers():
    reset_brokers()
    yield
    reset_brokers()


@pytest.fixture(scope="module")
def tiny_model():
    config = TransformerConfig(**TINY)
    return init_params(config, jax.random.PRNGKey(0)), config


def reference(params, config, prompt, max_new):
    """Closed-batch greedy completion for ONE exact-length prompt --
    the bit-compatibility oracle for every engine test."""
    out, _ = generate(params, config, np.asarray(prompt)[None],
                      max_new_tokens=max_new)
    return np.asarray(out)[0]


def drain(engine, limit=2000):
    """Step the engine until idle; returns {request_id: Completion}."""
    done = {}
    steps = 0
    while engine.has_work():
        report = engine.step()
        for completion in report.completions:
            done[completion.request_id] = completion
        steps += 1
        assert steps < limit, "engine failed to drain (deadlock?)"
    return done


# -- BlockManager ------------------------------------------------------------

class TestBlockManager:
    def test_capacity_excludes_trash_block(self):
        manager = BlockManager(8, 4)
        assert manager.capacity == 7
        assert manager.free_count == 7

    def test_allocate_is_all_or_nothing(self):
        manager = BlockManager(4, 4)  # capacity 3
        assert manager.allocate(4) is None
        assert manager.free_count == 3  # nothing partially taken
        granted = manager.allocate(3)
        assert len(granted) == 3
        assert TRASH_BLOCK not in granted
        assert manager.allocate(1) is None

    def test_free_returns_blocks_and_rejects_double_free(self):
        manager = BlockManager(4, 4)
        granted = manager.allocate(2)
        manager.free(granted)
        assert manager.free_count == 3
        with pytest.raises(ValueError, match="double free"):
            manager.free([granted[0], granted[0]])
        with pytest.raises(ValueError, match="trash"):
            manager.free([TRASH_BLOCK])

    def test_blocks_for_rounds_up(self):
        manager = BlockManager(8, 4)
        assert manager.blocks_for(1) == 1
        assert manager.blocks_for(4) == 1
        assert manager.blocks_for(5) == 2

    def test_rejects_degenerate_pools(self):
        with pytest.raises(ValueError):
            BlockManager(1, 4)  # no room for trash + one real block
        with pytest.raises(ValueError):
            BlockManager(4, 0)


# -- engine vs closed batch: bit-identical ----------------------------------

def test_engine_matches_generate_bitwise(tiny_model):
    """The acceptance invariant: continuous-mode completions are
    bit-identical to the closed-batch generate() for the same prompts,
    across ragged lengths decoded interleaved in shared slots."""
    params, config = tiny_model
    rng = np.random.default_rng(7)
    prompts = [rng.integers(1, 64, size=n).astype(np.int32)
               for n in (5, 9, 3, 12, 7, 4)]
    max_new = 8
    engine = DecodeEngine(params, config, decode_slots=3, kv_block_size=8)
    for index, prompt in enumerate(prompts):
        engine.submit(index, prompt, max_new)
    done = drain(engine)
    assert len(done) == len(prompts)
    for index, prompt in enumerate(prompts):
        expected = reference(params, config, prompt, max_new)
        np.testing.assert_array_equal(done[index].tokens, expected)
    stats = engine.stats()
    assert stats["completed"] == len(prompts)
    assert stats["active_slots"] == 0
    assert stats["free_blocks"] == engine.blocks.capacity  # all returned


def test_engine_eos_frees_slot_early(tiny_model):
    """A sequence hitting eos_id completes before max_new; its tokens
    are EOS-padded to the fixed width and its slot frees immediately."""
    params, config = tiny_model
    prompt = np.arange(1, 6, dtype=np.int32)
    probe = DecodeEngine(params, config, decode_slots=1, kv_block_size=8)
    probe.submit(0, prompt, 12)
    tokens = drain(probe)[0].tokens
    # pretend some mid-sequence token is EOS: pick one whose FIRST
    # occurrence is past position 0, so the cut point is unambiguous
    cut = next(k for k in range(1, 12) if tokens[k] not in tokens[:k])
    eos = int(tokens[cut])
    engine = DecodeEngine(params, config, decode_slots=1, kv_block_size=8,
                          eos_id=eos)
    engine.submit(0, prompt, 12)
    completion = drain(engine)[0]
    assert completion.stats["tokens"] == cut + 1
    np.testing.assert_array_equal(completion.tokens[:cut + 1],
                                  tokens[:cut + 1])
    assert (completion.tokens[cut + 1:] == eos).all()


def test_engine_rejects_oversized_request(tiny_model):
    params, config = tiny_model
    engine = DecodeEngine(params, config, decode_slots=1, kv_block_size=8,
                          max_context=32)
    with pytest.raises(ValueError, match="max_context"):
        engine.submit(0, np.arange(1, 30, dtype=np.int32), 16)
    with pytest.raises(ValueError, match="empty"):
        engine.submit(1, np.zeros((0,), np.int32), 4)


def test_engine_admits_prompt_whose_pow2_bucket_overshoots(tiny_model):
    """A non-power-of-two (block-multiple) max_context must admit any
    request with prompt + max_new <= max_context, even when the
    power-of-two prefill bucket rounds past max_context — the bucket is
    clamped, prefill runs at the block-multiple length, and the output
    still matches the closed-batch reference."""
    params, config = tiny_model
    engine = DecodeEngine(params, config, decode_slots=1, kv_block_size=8,
                          max_context=24)
    prompt = np.arange(1, 21, dtype=np.int32)    # bucket(20) pow2 = 32 > 24
    engine.submit(0, prompt, 4)                  # 20 + 4 == 24: fits
    done = drain(engine)
    np.testing.assert_array_equal(done[0].tokens,
                                  reference(params, config, prompt, 4))
    with pytest.raises(ValueError, match="max_context"):
        engine.submit(1, prompt, 5)              # 20 + 5 > 24: real reject


# -- shape stability: the zero-recompile acceptance assertion ---------------

def test_zero_recompiles_across_admission_eviction_storm(tiny_model):
    """After warmup, a seeded sequence of >= 20 admissions/evictions at
    varying prompt lengths triggers ZERO new compiles (ISSUE 6
    acceptance criterion) -- the trash-block masking keeps every
    paged_decode_step / per-bucket paged_prefill shape identical."""
    params, config = tiny_model
    engine = DecodeEngine(params, config, decode_slots=3, kv_block_size=8)
    # warmup: one prompt per prefill bucket reachable under max_context,
    # plus the decode step itself
    for index, length in enumerate((3, 9, 17)):  # buckets 8, 16, 24
        engine.submit(("warmup", index),
                      np.arange(1, length + 1, dtype=np.int32), 3)
    drain(engine)
    warm = engine.compile_count
    assert warm > 0
    rng = np.random.default_rng(42)
    submitted = 0
    completed = 0
    while submitted < 24:
        # ragged arrival: keep the slot array churning (partial
        # occupancy, admissions mid-decode, evictions at EOS)
        for _ in range(int(rng.integers(1, 4))):
            length = int(rng.integers(1, 21))
            engine.submit(("storm", submitted),
                          rng.integers(1, 64, size=length).astype(np.int32),
                          int(rng.integers(1, 8)))
            submitted += 1
        for _ in range(int(rng.integers(1, 5))):
            completed += len(engine.step().completions)
    completed += len(drain(engine))
    assert completed == submitted >= 20
    assert engine.compile_count == warm, (
        f"admission/eviction storm recompiled "
        f"{engine.compile_count - warm} signatures")


# -- pool exhaustion: deferral and preemption -------------------------------

def test_exhausted_pool_defers_admission_without_deadlock(tiny_model):
    """With free slots but no free blocks, admission DEFERS (counter
    incremented, FIFO order kept) and resumes as completions free
    blocks -- the queue always drains."""
    params, config = tiny_model
    # capacity 3 blocks of 8; each request needs 2 prompt blocks, so the
    # second admission must wait for the first completion
    engine = DecodeEngine(params, config, decode_slots=2, kv_block_size=8,
                          kv_blocks=4)
    prompts = {index: np.arange(1, 10, dtype=np.int32) + index
               for index in range(3)}
    for index, prompt in prompts.items():
        engine.submit(index, prompt, 3)
    done = drain(engine)
    assert len(done) == 3
    # counted per deferred REQUEST (not per blocked engine tick): many
    # ticks pass while request 1 waits, but at most requests 1 and 2
    # can defer
    assert 1 <= engine.counters["deferred_admissions"] <= 2
    assert engine.counters["preempted"] == 0
    for index, prompt in prompts.items():
        np.testing.assert_array_equal(
            done[index].tokens, reference(params, config, prompt, 3))


def test_preemption_evicts_youngest_and_stays_deterministic(tiny_model):
    """Mid-decode block growth on an exhausted pool preempts the
    YOUNGEST slot (the oldest always progresses -- no livelock); greedy
    decode makes the re-prefilled victim's output bit-identical."""
    params, config = tiny_model
    # two slots, capacity 5: both admit with 1 block (prompt 4 -> bucket
    # 4), then growth toward 4 blocks each (4 + 12 = 16 positions)
    # exhausts the pool mid-decode
    engine = DecodeEngine(params, config, decode_slots=2, kv_block_size=4,
                          kv_blocks=6)
    prompts = {0: np.arange(1, 5, dtype=np.int32),
               1: np.arange(11, 15, dtype=np.int32)}
    for index, prompt in prompts.items():
        engine.submit(index, prompt, 12)
    done = drain(engine)
    assert engine.counters["preempted"] >= 1
    assert done[1].stats["preemptions"] >= 1  # youngest was the victim
    for index, prompt in prompts.items():
        np.testing.assert_array_equal(
            done[index].tokens, reference(params, config, prompt, 12))


def test_preempted_request_does_not_reemit_streamed_tokens(tiny_model):
    """emitted_upto survives preemption: the regenerated prefix is NOT
    re-surfaced, so a token-streaming consumer sees gapless offsets."""
    params, config = tiny_model
    engine = DecodeEngine(params, config, decode_slots=2, kv_block_size=4,
                          kv_blocks=6)
    for index in range(2):
        engine.submit(index, np.arange(1, 5, dtype=np.int32) + index, 12)
    emitted = {}
    steps = 0
    while engine.has_work():
        report = engine.step()
        for request_id, offset, token in report.emitted:
            emitted.setdefault(request_id, []).append((offset, token))
        steps += 1
        assert steps < 2000
    assert engine.counters["preempted"] >= 1
    for request_id, pairs in emitted.items():
        offsets = [offset for offset, _ in pairs]
        assert offsets == list(range(len(offsets))), (
            f"{request_id}: duplicated/gapped stream offsets {offsets}")
        assert len(pairs) == 12


def test_preemption_mid_chunked_prefill_frees_partial_blocks(tiny_model):
    """A slot preempted BETWEEN prefill chunks discards its partially
    written KV blocks back to the free list (no leak), and the
    re-admitted request still completes bit-identical -- the
    youngest-first policy extended to mid-prefill victims."""
    params, config = tiny_model
    # 2-position blocks: slot 0 (2-token prompt, 18 new) grows a block
    # every other step while slot 1 chunks a 16-token prompt 2 tokens
    # per tick (8 chunks, 8 blocks granted up front).  Capacity 11
    # exhausts on slot 0's growth around tick 6 -- mid-way through
    # slot 1's chunk sequence -- so the youngest (mid-prefill) slot is
    # preempted with blocks partially written.
    engine = DecodeEngine(params, config, decode_slots=2, kv_block_size=2,
                          kv_blocks=12, prefill_chunk_size=2)
    prompts = {0: np.arange(1, 3, dtype=np.int32),
               1: np.arange(11, 27, dtype=np.int32)}
    engine.submit(0, prompts[0], 18)
    engine.step()  # admit + prefill slot 0 (monolithic: bucket == chunk)
    engine.submit(1, prompts[1], 4)
    mid_prefill_preempted = False
    done = {}
    steps = 0
    while engine.has_work():
        slot1 = next((slot for slot in engine.slots
                      if slot is not None
                      and slot.request.request_id == 1), None)
        before = engine.counters["preempted"]
        report = engine.step()
        if (slot1 is not None and slot1.prefilling
                and engine.counters["preempted"] > before):
            mid_prefill_preempted = True
        for completion in report.completions:
            done[completion.request_id] = completion
        steps += 1
        assert steps < 4000
    assert engine.counters["preempted"] >= 1
    assert mid_prefill_preempted, (
        "scenario no longer preempts a mid-prefill slot; retune pool")
    # every block returned: a leaked partial grant would show here
    assert engine.stats()["free_blocks"] == engine.blocks.capacity
    for index, prompt in prompts.items():
        np.testing.assert_array_equal(
            done[index].tokens,
            reference(params, config, prompt, done[index].tokens.size))


def test_cancel_frees_slots_and_waiting(tiny_model):
    params, config = tiny_model
    engine = DecodeEngine(params, config, decode_slots=1, kv_block_size=8)
    for index in range(3):
        engine.submit(("s", index), np.arange(1, 6, dtype=np.int32), 8)
    engine.step()  # admit request 0 into the single slot
    assert engine.cancel(lambda rid: rid[1] != 1) == 2
    assert engine.counters["cancelled"] == 2
    done = drain(engine)
    assert list(done) == [("s", 1)]
    assert engine.stats()["free_blocks"] == engine.blocks.capacity


def test_engine_int8_kv_matches_quantized_generate():
    """The paged pool carries the int8 KV layout (codes + scales);
    pool-backed decode must match the contiguous int8 cache bitwise."""
    config = TransformerConfig(**{**TINY, "kv_dtype": "int8"})
    params = init_params(config, jax.random.PRNGKey(0))
    rng = np.random.default_rng(3)
    prompts = [rng.integers(1, 64, size=n).astype(np.int32)
               for n in (6, 11)]
    engine = DecodeEngine(params, config, decode_slots=2, kv_block_size=8)
    for index, prompt in enumerate(prompts):
        engine.submit(index, prompt, 6)
    done = drain(engine)
    for index, prompt in enumerate(prompts):
        np.testing.assert_array_equal(
            done[index].tokens, reference(params, config, prompt, 6))


# -- chunked prefill (paged_prefill_chunk) ----------------------------------


class TestChunkedPrefill:
    """ISSUE 11 tentpole (a): chunked prefill must be bit-identical to
    the monolithic paged_prefill path at every chunk size, and must
    actually interleave prefill progress with decode steps."""

    PROMPT_LENGTHS = (5, 21, 3, 33, 7, 12)

    def _run(self, params, config, chunk, max_new=8, **kwargs):
        rng = np.random.default_rng(13)
        prompts = [rng.integers(1, 64, size=n).astype(np.int32)
                   for n in self.PROMPT_LENGTHS]
        engine = DecodeEngine(params, config, decode_slots=3,
                              kv_block_size=8,
                              prefill_chunk_size=chunk, **kwargs)
        for index, prompt in enumerate(prompts):
            engine.submit(index, prompt, max_new)
        return prompts, engine, drain(engine)

    @pytest.mark.parametrize("chunk", (8, 16, 64))
    def test_chunked_matches_monolithic_bitwise(self, tiny_model, chunk):
        """Chunk sizes {1 block, 1 bucket, full prompt}: completions
        equal the closed-batch reference (and therefore the monolithic
        engine, which the other tests pin to the same oracle)."""
        params, config = tiny_model
        prompts, engine, done = self._run(params, config, chunk)
        for index, prompt in enumerate(prompts):
            np.testing.assert_array_equal(
                done[index].tokens, reference(params, config, prompt, 8))
        if chunk < 64:
            assert engine.counters["prefill_chunks"] > 0

    def test_chunked_int8_kv_matches_monolithic(self):
        config = TransformerConfig(**{**TINY, "kv_dtype": "int8"})
        params = init_params(config, jax.random.PRNGKey(0))
        prompts, engine, done = self._run(params, config, 8)
        for index, prompt in enumerate(prompts):
            np.testing.assert_array_equal(
                done[index].tokens, reference(params, config, prompt, 8))

    def test_prefill_interleaves_with_decode(self, tiny_model):
        """The convoy-breaking property itself: while a long prompt is
        mid-prefill, co-scheduled decode slots keep emitting tokens --
        counted by decode.chunk_interleaves."""
        params, config = tiny_model
        engine = DecodeEngine(params, config, decode_slots=2,
                              kv_block_size=8, prefill_chunk_size=8)
        engine.submit("short", np.arange(1, 4, dtype=np.int32), 24)
        engine.step()  # short prompt admitted and decoding
        engine.submit("long", np.arange(1, 34, dtype=np.int32), 4)
        interleaved_tokens = 0
        steps = 0
        while engine.has_work():
            long_slot = next(
                (slot for slot in engine.slots if slot is not None
                 and slot.request.request_id == "long"), None)
            mid_prefill = long_slot is not None and long_slot.prefilling
            report = engine.step()
            if mid_prefill:
                interleaved_tokens += sum(
                    1 for rid, _, _ in report.emitted if rid == "short")
            steps += 1
            assert steps < 2000
        assert interleaved_tokens > 0, (
            "no short-request tokens decoded during the long prefill")
        assert engine.counters["chunk_interleaves"] > 0

    def test_chunk_size_coerced_to_block_multiple(self, tiny_model):
        params, config = tiny_model
        engine = DecodeEngine(params, config, decode_slots=1,
                              kv_block_size=8, prefill_chunk_size=3)
        assert engine.prefill_chunk == 8  # pow2 floored at block size


# -- speculative decoding (paged_verify_step) -------------------------------


class TestSpeculativeDecoding:
    """ISSUE 11 tentpole (b): greedy-exact speculative decoding --
    draft proposes k, target verifies k+1 positions in one window,
    emitted tokens bit-identical to plain greedy decode."""

    def _models(self):
        config = TransformerConfig(**TINY)
        params = init_params(config, jax.random.PRNGKey(0))
        draft_config = TransformerConfig(**{**TINY, "n_layers": 1})
        draft_params = init_params(draft_config, jax.random.PRNGKey(3))
        return params, config, draft_params, draft_config

    def test_spec_decode_storm_bit_identical(self):
        """The satellite suite: a seeded 20-request engine storm with
        ragged prompt/completion lengths under speculation matches
        plain greedy bit-for-bit, with zero recompiles after warmup."""
        params, config, draft_params, draft_config = self._models()
        engine = DecodeEngine(params, config, decode_slots=3,
                              kv_block_size=8, draft_params=draft_params,
                              draft_config=draft_config, spec_k=3)
        rng = np.random.default_rng(42)
        # warmup: every prefill bucket + the spec-round executables
        for index, length in enumerate((3, 9, 17)):
            engine.submit(("warm", index),
                          np.arange(1, length + 1, dtype=np.int32), 5)
        drain(engine)
        warm = engine.compile_count
        workload = {}
        done = {}
        submitted = 0
        while submitted < 20:
            for _ in range(int(rng.integers(1, 4))):
                length = int(rng.integers(1, 21))
                prompt = rng.integers(1, 64, size=length).astype(np.int32)
                max_new = int(rng.integers(1, 10))
                workload[submitted] = (prompt, max_new)
                engine.submit(submitted, prompt, max_new)
                submitted += 1
            for _ in range(int(rng.integers(1, 5))):
                for completion in engine.step().completions:
                    done[completion.request_id] = completion
        done.update(drain(engine))
        assert len(done) >= 20
        for index, (prompt, max_new) in workload.items():
            np.testing.assert_array_equal(
                done[index].tokens,
                reference(params, config, prompt, max_new))
        assert engine.compile_count == warm, (
            f"speculative storm recompiled "
            f"{engine.compile_count - warm} signatures")
        assert engine.counters["spec_windows"] > 0

    def test_self_draft_accepts_full_window(self):
        """draft == target: every proposal matches, so each verify
        window emits k+1 tokens (modulo the final clipped window) --
        the acceptance accounting sanity check."""
        params, config, _, _ = self._models()
        engine = DecodeEngine(params, config, decode_slots=1,
                              kv_block_size=8, draft_params=params,
                              draft_config=config, spec_k=3)
        engine.submit(0, np.arange(1, 6, dtype=np.int32), 16)
        done = drain(engine)
        np.testing.assert_array_equal(
            done[0].tokens, reference(params, config,
                                      np.arange(1, 6), 16))
        stats = engine.stats()
        assert stats["accepted_len_mean"] > 3.0  # ceiling k+1 = 4
        assert 0.0 < stats["draft_overhead_frac"] < 1.0

    def test_spec_int8_kv_matches_plain(self):
        config = TransformerConfig(**{**TINY, "kv_dtype": "int8"})
        params = init_params(config, jax.random.PRNGKey(0))
        draft_config = TransformerConfig(
            **{**TINY, "kv_dtype": "int8", "n_layers": 1})
        draft_params = init_params(draft_config, jax.random.PRNGKey(3))
        rng = np.random.default_rng(3)
        prompts = [rng.integers(1, 64, size=n).astype(np.int32)
                   for n in (6, 11)]
        engine = DecodeEngine(params, config, decode_slots=2,
                              kv_block_size=8, draft_params=draft_params,
                              draft_config=draft_config, spec_k=2)
        for index, prompt in enumerate(prompts):
            engine.submit(index, prompt, 6)
        done = drain(engine)
        for index, prompt in enumerate(prompts):
            np.testing.assert_array_equal(
                done[index].tokens, reference(params, config, prompt, 6))

    def test_spec_eos_truncates_accepted_run(self, tiny_model):
        """An EOS inside an accepted window stops the run exactly where
        plain greedy decode would."""
        params, config = tiny_model
        prompt = np.arange(1, 6, dtype=np.int32)
        plain = reference(params, config, prompt, 12)
        cut = next(k for k in range(1, 12)
                   if plain[k] not in plain[:k])
        eos = int(plain[cut])
        engine = DecodeEngine(params, config, decode_slots=1,
                              kv_block_size=8, eos_id=eos,
                              draft_params=params, draft_config=config,
                              spec_k=4)
        engine.submit(0, prompt, 12)
        completion = drain(engine)[0]
        assert completion.stats["tokens"] == cut + 1
        np.testing.assert_array_equal(completion.tokens[:cut + 1],
                                      plain[:cut + 1])
        assert (completion.tokens[cut + 1:] == eos).all()

    def test_spec_with_chunked_prefill_storm(self):
        """Acceptance criterion: BOTH features on, a seeded admission
        storm decodes bit-identically with zero engine recompiles
        after warmup."""
        params, config, draft_params, draft_config = self._models()
        engine = DecodeEngine(params, config, decode_slots=3,
                              kv_block_size=8, prefill_chunk_size=8,
                              draft_params=draft_params,
                              draft_config=draft_config, spec_k=3)
        rng = np.random.default_rng(7)
        for index, length in enumerate((3, 9, 17, 33)):
            engine.submit(("warm", index),
                          np.arange(1, length + 1, dtype=np.int32), 3)
        drain(engine)
        warm = engine.compile_count
        workload = {}
        done = {}
        submitted = 0
        while submitted < 20:
            for _ in range(int(rng.integers(1, 4))):
                length = int(rng.integers(1, 40))
                prompt = rng.integers(1, 64, size=length).astype(np.int32)
                max_new = int(rng.integers(1, 8))
                workload[submitted] = (prompt, max_new)
                engine.submit(submitted, prompt, max_new)
                submitted += 1
            for _ in range(int(rng.integers(1, 5))):
                for completion in engine.step().completions:
                    done[completion.request_id] = completion
        done.update(drain(engine))
        for index, (prompt, max_new) in workload.items():
            np.testing.assert_array_equal(
                done[index].tokens,
                reference(params, config, prompt, max_new))
        assert engine.compile_count == warm
        assert engine.counters["prefill_chunks"] > 0
        assert engine.counters["spec_windows"] > 0

    def test_spec_rejects_mismatched_vocab_and_partial_config(self):
        params, config, draft_params, draft_config = self._models()
        from dataclasses import replace
        bad = replace(draft_config, vocab_size=32)
        with pytest.raises(ValueError, match="vocab"):
            DecodeEngine(params, config, draft_params=draft_params,
                         draft_config=bad)
        with pytest.raises(ValueError, match="BOTH"):
            DecodeEngine(params, config, draft_params=draft_params)
        with pytest.raises(ValueError, match="draft model"):
            DecodeEngine(params, config, spec_k=3)


# -- LMGenerate `continuous: true` pipeline integration ---------------------

LM_PARAMS = {"vocab_size": 300, "d_model": 32, "n_layers": 1,
             "n_heads": 2, "n_kv_heads": 1, "d_ff": 64,
             "max_seq_len": 128, "dtype": "float32", "max_new_tokens": 6}


def lm_definition(extra_parameters):
    return {
        "name": "lm_pipe",
        "graph": ["(lm)"],
        "elements": [
            {"name": "lm", "input": [{"name": "tokens"}],
             "output": [{"name": "generated"}],
             "parameters": {**LM_PARAMS, **extra_parameters},
             "deploy": {"local": {"module": ELEMENTS,
                                  "class_name": "LMGenerate"}}},
        ],
    }


def run_lm_frames(extra_parameters, frames, wait_out=0):
    """Run frames through a one-element LMGenerate pipeline; with
    `wait_out`, also wait for that many `/out` publishes BEFORE
    terminating (the response queue bypasses the broker, so the
    response can land while /out messages are still in flight)."""
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, lm_definition(extra_parameters))
    streamed = []
    if wait_out:
        process.add_message_handler(
            lambda topic, payload: streamed.append(payload),
            f"{pipeline.elements['lm'].topic_path}/out")
    process.run(in_thread=True)
    responses = queue.Queue()
    stream = pipeline.create_stream("s", queue_response=responses,
                                    grace_time=300)
    for frame in frames:
        pipeline.create_frame(stream, {"tokens": frame})
    results = [responses.get(timeout=120) for _ in range(len(frames))]
    if wait_out:
        wait_for(lambda: len(streamed) >= wait_out, timeout=30)
    lm_element = pipeline.elements["lm"]
    process.terminate()
    return results, streamed, lm_element


def test_continuous_pipeline_bit_identical_to_closed_batch():
    """ISSUE 6 acceptance: the SAME frames through `continuous: true`
    and the closed-batch path produce bit-identical completions -- and
    responses arrive per-frame, in frame order, from interleaved
    decoding."""
    rng = np.random.default_rng(0)
    frames = [rng.integers(1, 300, size=(2, 7)).astype(np.int32)
              for _ in range(3)]
    closed, _, _ = run_lm_frames({}, frames)
    continuous, _, lm_element = run_lm_frames(
        {"continuous": True, "decode_slots": 3, "kv_block_size": 8},
        frames)
    for (_, closed_frame, closed_out), (_, cont_frame, cont_out) in zip(
            closed, continuous):
        assert closed_frame.frame_id == cont_frame.frame_id
        np.testing.assert_array_equal(
            np.asarray(closed_out["generated"]),
            np.asarray(cont_out["generated"]))
    stats = lm_element.engine_stats()
    assert stats["completed"] == sum(frame.shape[0] for frame in frames)
    assert stats["active_slots"] == 0 and stats["waiting"] == 0


def test_continuous_pipeline_with_kernel_floor_features_bit_identical():
    """The AIKO405 surface end-to-end: `prefill_chunk_size` +
    `speculative: draft=self;k=3;layers=...` through LMGenerate produce
    completions bit-identical to the plain closed-batch path, and the
    engine telemetry (accepted-length mean, chunk counters) reaches
    engine_stats()."""
    rng = np.random.default_rng(21)
    frames = [rng.integers(1, 300, size=(2, 17)).astype(np.int32)
              for _ in range(2)]
    closed, _, _ = run_lm_frames({}, frames)
    continuous, _, lm_element = run_lm_frames(
        {"continuous": True, "decode_slots": 3, "kv_block_size": 8,
         "prefill_chunk_size": 8,
         "speculative": "draft=self;k=3;layers=1;seed=9"},
        frames)
    for (_, closed_frame, closed_out), (_, _, cont_out) in zip(
            closed, continuous):
        np.testing.assert_array_equal(
            np.asarray(closed_out["generated"]),
            np.asarray(cont_out["generated"]))
    stats = lm_element.engine_stats()
    assert stats["prefill_chunks"] > 0
    assert stats["spec_windows"] > 0
    assert stats["accepted_len_mean"] >= 1.0
    assert 0.0 <= stats["draft_overhead_frac"] <= 1.0
    assert stats["prefill_chunk_size"] == 8 and stats["spec_k"] == 3


def test_speculative_parameter_rejects_bad_spec():
    """A malformed `speculative` spec fails the first continuous frame
    with the same GrammarError message offline lint reports (AIKO405),
    not a cryptic engine crash."""
    from aiko_services_tpu.analyze.policies import (
        check_decode_parameters, parse_speculative_spec)

    with pytest.raises(ValueError, match="speculative"):
        parse_speculative_spec("draft=self")          # missing k
    with pytest.raises(ValueError, match="unknown"):
        parse_speculative_spec("draft=self;k=2;warp=9")
    with pytest.raises(ValueError, match="draft=self"):
        parse_speculative_spec("draft=toy;k=2;layers=1")
    problems = check_decode_parameters(
        {"continuous": True, "speculative": "draft=self;k=0"})
    assert any(code == "AIKO405" for code, _ in problems)
    # both features demand the continuous engine
    problems = check_decode_parameters(
        {"speculative": "draft=self;k=2", "prefill_chunk_size": 16})
    codes = [code for code, _ in problems]
    assert codes.count("AIKO405") == 2


def test_continuous_pipeline_zero_recompiles_after_warmup():
    """Same-shape traffic after the first frame re-uses the warmed
    executables: the engine's compile counter is flat across frames
    2..N even though every frame is a fresh admission/eviction cycle."""
    rng = np.random.default_rng(1)
    frames = [rng.integers(1, 300, size=(1, 9)).astype(np.int32)
              for _ in range(4)]
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, lm_definition(
        {"continuous": True, "decode_slots": 2, "kv_block_size": 8}))
    process.run(in_thread=True)
    responses = queue.Queue()
    stream = pipeline.create_stream("s", queue_response=responses,
                                    grace_time=300)
    pipeline.create_frame(stream, {"tokens": frames[0]})
    responses.get(timeout=120)
    warm = pipeline.elements["lm"].engine_stats()["compiles"]
    for frame in frames[1:]:
        pipeline.create_frame(stream, {"tokens": frame})
    for _ in frames[1:]:
        responses.get(timeout=120)
    assert pipeline.elements["lm"].engine_stats()["compiles"] == warm
    process.terminate()


def test_continuous_token_streaming_chunks():
    """`stream_tokens` under the engine publishes per-ROW chunks
    `(token_chunk stream_id frame_id row offset payload)` with gapless
    offsets as slots decode -- a DISTINCT command from the closed-batch
    `(tokens stream_id offset payload)` schema."""
    rng = np.random.default_rng(2)
    frames = [rng.integers(1, 300, size=(2, 5)).astype(np.int32)]
    # 2 rows x 6 tokens in chunks of 2 -> 6 publishes
    results, streamed, _ = run_lm_frames(
        {"continuous": True, "decode_slots": 2, "kv_block_size": 8,
         "stream_tokens": True, "stream_chunk": 2},
        frames, wait_out=6)
    assert len([s for s in streamed
                if s.startswith("(token_chunk")]) >= 6
    [(_, _, outputs)] = results
    assert np.asarray(outputs["generated"]).shape == (2, 6)


def test_continuous_stop_stream_cancels_inflight():
    """Destroying a stream mid-decode cancels its engine requests:
    slots and blocks free, no completion is delivered for the dead
    stream, and a following stream decodes normally."""
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, lm_definition(
        {"continuous": True, "decode_slots": 2, "kv_block_size": 8,
         "max_new_tokens": 64}))
    process.run(in_thread=True)
    responses = queue.Queue()
    stream = pipeline.create_stream("s1", queue_response=responses,
                                    grace_time=300)
    tokens = np.arange(1, 8, dtype=np.int32)[None]
    pipeline.create_frame(stream, {"tokens": tokens})
    lm_element = pipeline.elements["lm"]
    wait_for(lambda: lm_element.engine_stats() is not None
             and lm_element.engine_stats()["admitted"] >= 1, timeout=60)
    pipeline.destroy_stream("s1")
    wait_for(lambda: lm_element.engine_stats()["cancelled"] >= 1
             or lm_element.engine_stats()["completed"] >= 1, timeout=60)
    # a second stream is unaffected by the cancellation
    responses2 = queue.Queue()
    stream2 = pipeline.create_stream("s2", queue_response=responses2,
                                     grace_time=300)
    pipeline.create_frame(stream2, {"tokens": tokens})
    _, _, outputs = responses2.get(timeout=120)
    assert np.asarray(outputs["generated"]).shape == (1, 64)
    wait_for(lambda: lm_element.engine_stats()["active_slots"] == 0,
             timeout=60)
    process.terminate()


def test_engine_metrics_reach_summary_and_dashboard():
    """The decode.* gauges ride the pipeline telemetry: the EC-share
    summary grows a `decode` sub-dict (per-replica slot occupancy for
    the gateway / services page) and the dashboard pipeline plugin
    renders it."""
    rng = np.random.default_rng(5)
    frames = [rng.integers(1, 300, size=(2, 6)).astype(np.int32)]
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, lm_definition(
        {"continuous": True, "decode_slots": 2, "kv_block_size": 8}))
    process.run(in_thread=True)
    responses = queue.Queue()
    stream = pipeline.create_stream("s", queue_response=responses,
                                    grace_time=300)
    pipeline.create_frame(stream, {"tokens": frames[0]})
    responses.get(timeout=120)
    summary = pipeline.telemetry.summary()
    decode = summary["decode"]
    assert decode["completed"] == 2
    assert decode["active_slots"] == 0 and decode["waiting"] == 0
    assert decode["free_blocks"] > 0

    from aiko_services_tpu.dashboard import _pipeline_plugin

    class Model:
        selected_share = {"stream_count": 1, "frame_count": 1,
                          "element_count": 1, "metrics": summary}

    lines = _pipeline_plugin(Model())
    decode_lines = [line for line in lines if line.startswith("decode:")]
    assert decode_lines and "completed 2" in decode_lines[0]

    # over the real EC wire every value arrives as a STRING -- the
    # plugin must render those too, not only in-process numbers
    class WireModel:
        selected_share = {"metrics": dict(
            summary, decode={key: str(value)
                             for key, value in decode.items()})}

    wire_lines = _pipeline_plugin(WireModel())
    assert any(line.startswith("decode:") for line in wire_lines)
    process.terminate()
    # a pipeline without an engine keeps the old summary shape
    reset_brokers()
    plain_process = Process(transport_kind="loopback")
    plain = create_pipeline(plain_process, lm_definition({}))
    plain_process.run(in_thread=True)
    assert "decode" not in plain.telemetry.summary()
    plain_process.terminate()


def test_engine_failure_releases_pending_frames():
    """A crash inside the mailbox pump (device error mid-step) must not
    strand parked PENDING frames: in-flight frames get an error
    response, the broken engine is dropped, and the next continuous
    frame rebuilds a working one."""
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, lm_definition(
        {"continuous": True, "decode_slots": 2, "kv_block_size": 8}))
    process.run(in_thread=True)
    lm_element = pipeline.elements["lm"]
    responses = queue.Queue()
    stream = pipeline.create_stream("ok1", queue_response=responses,
                                    grace_time=300,
                                    parameters={"max_new_tokens": 4})
    tokens = np.arange(1, 9, dtype=np.int32)[None]
    pipeline.create_frame(stream, {"tokens": tokens})
    expected = np.asarray(responses.get(timeout=120)[2]["generated"])

    def explode():
        raise RuntimeError("injected device failure")

    lm_element._engine.step = explode
    doomed = pipeline.create_stream("doomed", grace_time=300)
    pipeline.create_frame(doomed, {"tokens": tokens})
    wait_for(lambda: lm_element._engine is None
             and not lm_element._engine_frames, timeout=60)

    responses2 = queue.Queue()
    stream2 = pipeline.create_stream("ok2", queue_response=responses2,
                                     grace_time=300,
                                     parameters={"max_new_tokens": 4})
    pipeline.create_frame(stream2, {"tokens": tokens})
    out = np.asarray(responses2.get(timeout=120)[2]["generated"])
    np.testing.assert_array_equal(out, expected)

    # crash AFTER a completion (telemetry hook) but BEFORE the response
    # is posted: the frame entry must still be registered so the
    # release path can error it out -- then the engine rebuilds again
    telemetry = pipeline.telemetry
    original = telemetry.record_engine_frame

    def boom(*args, **kwargs):
        raise RuntimeError("injected telemetry crash")

    telemetry.record_engine_frame = boom
    doomed2 = pipeline.create_stream("doomed2", grace_time=300)
    pipeline.create_frame(doomed2, {"tokens": tokens})
    wait_for(lambda: lm_element._engine is None
             and not lm_element._engine_frames, timeout=60)
    telemetry.record_engine_frame = original
    responses3 = queue.Queue()
    stream3 = pipeline.create_stream("ok3", queue_response=responses3,
                                     grace_time=300,
                                     parameters={"max_new_tokens": 4})
    pipeline.create_frame(stream3, {"tokens": tokens})
    out = np.asarray(responses3.get(timeout=120)[2]["generated"])
    np.testing.assert_array_equal(out, expected)
    process.terminate()


def test_rejected_submit_does_not_leak_frame_entry():
    """A frame whose rows the engine rejects (prompt + max_new over
    max_context) must not strand an _engine_frames entry or queued
    sibling rows; a following stream decodes normally."""
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, lm_definition(
        {"continuous": True, "decode_slots": 2, "kv_block_size": 8,
         "max_context": 32, "max_new_tokens": 20}))
    process.run(in_thread=True)
    lm_element = pipeline.elements["lm"]
    stream = pipeline.create_stream("bad", grace_time=300)
    # ragged rows left-padded to width 16: EVERY row's true width is 16
    # after padding, so 16 + 20 > max_context=32 -> submit raises after
    # row 0 queued... use an explicit 2-row (8, 16) unpadded pair
    # instead: row 0 (8 + 20 = 28) queues, row 1 (16 + 20 = 36) raises,
    # and the cleanup must also cancel the queued row 0
    bad = np.zeros((2, 16), np.int32)
    bad[0, :8] = np.arange(1, 9)
    bad[1, :] = np.arange(1, 17)
    pipeline.create_frame(stream, {"tokens": bad})
    wait_for(lambda: lm_element._engine is not None
             and not lm_element._engine_frames
             and not lm_element._engine.has_work(), timeout=60)
    # a fresh stream with admissible sizes is unaffected
    responses = queue.Queue()
    stream2 = pipeline.create_stream("ok", queue_response=responses,
                                     grace_time=300,
                                     parameters={"max_new_tokens": 4})
    pipeline.create_frame(
        stream2, {"tokens": np.arange(1, 9, dtype=np.int32)[None]})
    _, _, outputs = responses.get(timeout=120)
    assert np.asarray(outputs["generated"]).shape == (1, 4)
    process.terminate()


def test_gateway_routes_to_continuous_replicas_bit_identical():
    """The serving-tier composition the ISSUE names: a Gateway fronting
    LMGenerate replicas running `continuous: true` serves the same
    completions as a direct closed-batch pipeline -- frames route, the
    engine decodes them interleaved, and responses ride the gateway's
    exactly-once delivery."""
    from aiko_services_tpu.serve import Gateway

    rng = np.random.default_rng(9)
    frames = [rng.integers(1, 300, size=(1, 6)).astype(np.int32)
              for _ in range(4)]
    closed, _, _ = run_lm_frames({}, frames)
    expected = [np.asarray(outputs["generated"])
                for _, _, outputs in closed]
    reset_brokers()

    processes = []
    replicas = []
    for index in range(2):
        process = Process(transport_kind="loopback")
        processes.append(process)
        definition = lm_definition(
            {"continuous": True, "decode_slots": 2, "kv_block_size": 8})
        definition["name"] = f"replica{index}"
        replicas.append(create_pipeline(process, definition))
    gateway_process = Process(transport_kind="loopback")
    processes.append(gateway_process)
    gateway = Gateway(gateway_process, policy="max_inflight=8;queue=32")
    for replica in replicas:
        gateway.attach_replica(replica)
    for process in processes:
        process.run(in_thread=True)
    try:
        responses = queue.Queue()
        gateway.submit_stream("s1", {}, queue_response=responses)
        for frame_id, frame in enumerate(frames):
            gateway.submit_frame("s1", {"tokens": frame},
                                 frame_id=frame_id)
        got = {}
        for _ in frames:
            stream_id, frame_id, outputs, status = responses.get(
                timeout=120)
            assert status == "ok", (frame_id, outputs)
            got[frame_id] = np.asarray(outputs["generated"])
        for frame_id, reference_out in enumerate(expected):
            np.testing.assert_array_equal(got[frame_id], reference_out)
        # the stream pinned to ONE replica and its engine did the work
        engines = [replica.elements["lm"].engine_stats()
                   for replica in replicas]
        completed = [stats["completed"] if stats else 0
                     for stats in engines]
        assert sorted(completed) == [0, len(frames)]
    finally:
        for process in processes:
            process.terminate()


def test_continuous_interleaves_new_frames_mid_decode():
    """The open-batch property itself: a frame submitted while another
    is mid-decode is admitted into the RUNNING loop (admissions overlap
    decode progress) rather than convoying behind a closed batch."""
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, lm_definition(
        {"continuous": True, "decode_slots": 4, "kv_block_size": 8,
         "max_new_tokens": 48}))
    process.run(in_thread=True)
    responses = queue.Queue()
    stream = pipeline.create_stream("s", queue_response=responses,
                                    grace_time=300)
    lm_element = pipeline.elements["lm"]
    pipeline.create_frame(
        stream, {"tokens": np.arange(1, 8, dtype=np.int32)[None]})
    wait_for(lambda: lm_element.engine_stats() is not None
             and lm_element.engine_stats()["admitted"] >= 1, timeout=60)
    pipeline.create_frame(
        stream, {"tokens": np.arange(11, 18, dtype=np.int32)[None]})
    # both frames decode concurrently at some point
    wait_for(lambda: lm_element.engine_stats()["active_slots"] == 2,
             timeout=60)
    first = responses.get(timeout=120)
    second = responses.get(timeout=120)
    assert first[1].frame_id != second[1].frame_id
    process.terminate()
