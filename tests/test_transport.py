import pytest

from aiko_services_tpu.transport import (
    LoopbackTransport, get_broker, reset_brokers, topic_matches)
from helpers import wait_for


@pytest.fixture(autouse=True)
def clean_brokers():
    reset_brokers()
    yield
    reset_brokers()


def test_topic_matches():
    assert topic_matches("a/b/c", "a/b/c")
    assert topic_matches("a/+/c", "a/b/c")
    assert not topic_matches("a/+/c", "a/b/d")
    assert topic_matches("a/#", "a/b/c/d")
    assert topic_matches("#", "anything/at/all")
    assert not topic_matches("a/b", "a/b/c")
    assert not topic_matches("a/b/c", "a/b")
    assert topic_matches("+/+/+/+/state", "ns/host/1/0/state")


def test_publish_subscribe():
    received = []
    alpha = LoopbackTransport(lambda t, p: received.append((t, p)))
    beta = LoopbackTransport()
    alpha.subscribe("ns/test/in")
    alpha.connect()
    beta.connect()
    beta.publish("ns/test/in", "(hello world)")
    beta.publish("ns/other", "(ignored)")
    wait_for(lambda: received)
    assert received == [("ns/test/in", "(hello world)")]


def test_wildcard_subscription():
    received = []
    alpha = LoopbackTransport(lambda t, p: received.append(t))
    alpha.subscribe("ns/+/state")
    alpha.connect()
    beta = LoopbackTransport()
    beta.connect()
    beta.publish("ns/a/state", "x")
    beta.publish("ns/b/state", "y")
    beta.publish("ns/a/other", "z")
    get_broker().drain()
    assert sorted(received) == ["ns/a/state", "ns/b/state"]


def test_retained_message_delivered_on_subscribe():
    beta = LoopbackTransport()
    beta.connect()
    beta.publish("ns/boot", "(primary found x)", retain=True)
    get_broker().drain()
    received = []
    alpha = LoopbackTransport(lambda t, p: received.append((t, p)))
    alpha.connect()
    alpha.subscribe("ns/boot")
    wait_for(lambda: received)
    assert received == [("ns/boot", "(primary found x)")]


def test_retained_cleared_by_empty_payload():
    beta = LoopbackTransport()
    beta.connect()
    beta.publish("ns/boot", "(x)", retain=True)
    beta.publish("ns/boot", "", retain=True)
    get_broker().drain()
    assert get_broker().retained("ns/boot") is None


def test_lwt_fires_on_unclean_disconnect():
    received = []
    watcher = LoopbackTransport(lambda t, p: received.append((t, p)))
    watcher.subscribe("ns/victim/state")
    watcher.connect()
    victim = LoopbackTransport()
    victim.set_last_will_and_testament("ns/victim/state", "(absent)",
                                       retain=True)
    victim.connect()
    victim.disconnect(send_lwt=True)
    wait_for(lambda: received)
    assert received == [("ns/victim/state", "(absent)")]
    assert get_broker().retained("ns/victim/state") == "(absent)"


def test_no_lwt_on_clean_disconnect():
    received = []
    watcher = LoopbackTransport(lambda t, p: received.append((t, p)))
    watcher.subscribe("ns/victim/state")
    watcher.connect()
    victim = LoopbackTransport()
    victim.set_last_will_and_testament("ns/victim/state", "(absent)")
    victim.connect()
    victim.disconnect(send_lwt=False)
    get_broker().drain()
    assert received == []


def test_disconnected_client_receives_nothing():
    received = []
    alpha = LoopbackTransport(lambda t, p: received.append(t))
    alpha.subscribe("ns/x")
    alpha.connect()
    alpha.disconnect()
    beta = LoopbackTransport()
    beta.connect()
    beta.publish("ns/x", "1")
    get_broker().drain()
    assert received == []
