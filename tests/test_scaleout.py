# Ten-thousand-stream scale-out suite (ISSUE 15): the topic-trie broker
# fast path (trie match set == the linear `topic_matches` scan, bit for
# bit, over a generated corpus), sharded dispatch per-topic FIFO,
# avoided-wakeup accounting, coalesced control-plane publishes
# (ECProducer.stage delta folding), and the federated gateway tier
# (consistent-hash stream -> group assignment, wrong_group fast-fail,
# per-group journal namespacing, and a federated storm with zero lost
# frames).

import queue
import random

import pytest

from aiko_services_tpu.observe.metrics import get_registry
from aiko_services_tpu.pipeline import (
    PipelineElement, StreamEvent, create_pipeline)
from aiko_services_tpu.runtime import Process
from aiko_services_tpu.runtime.actor import Actor
from aiko_services_tpu.runtime.share import ECConsumer
from aiko_services_tpu.serve import (
    FederationPolicy, FederationRouter, Gateway, assign_group)
from aiko_services_tpu.transport import (
    TopicTrie, get_broker, reset_brokers, topic_matches)
from aiko_services_tpu.transport.loopback import (
    LoopbackBroker, LoopbackTransport)
from helpers import wait_for


@pytest.fixture(autouse=True)
def clean():
    reset_brokers()
    yield
    reset_brokers()


# -- topic trie ==== linear topic_matches, property-style --------------------


_SEGMENTS = ["a", "b", "c", "sensor", "x1", "", "state", "+"]
_PATTERN_SEGMENTS = _SEGMENTS + ["#"]


def _corpus(seed, topics_n=120, patterns_n=160):
    rng = random.Random(seed)

    def levels(source, count):
        return "/".join(rng.choice(source) for _ in range(count))

    topics = {levels(_SEGMENTS, rng.randint(1, 5))
              for _ in range(topics_n)}
    # edge cases the MQTT grammar defines precisely
    topics |= {"a", "a/b", "a/b/c", "/a", "a/", "a//c", "+", "a/+"}
    patterns = {levels(_PATTERN_SEGMENTS, rng.randint(1, 5))
                for _ in range(patterns_n)}
    patterns |= {"#", "+", "+/+", "a/#", "a/+/c", "/#", "/+", "a/#/b",
                 "a/b", "a//c", "+/b/#"}
    return sorted(topics), sorted(patterns)


class TestTopicTrie:
    def test_match_set_equals_linear_scan_bit_for_bit(self):
        topics, patterns = _corpus(seed=7)
        trie = TopicTrie()
        for pattern in patterns:
            trie.add(pattern, pattern)
        assert len(trie) == len(patterns)
        for topic in topics:
            linear = {pattern for pattern in patterns
                      if topic_matches(pattern, topic)}
            assert set(trie.match(topic)) == linear, topic
            assert trie.matches(topic) == bool(linear), topic

    def test_discard_keeps_equivalence_under_churn(self):
        topics, patterns = _corpus(seed=11)
        rng = random.Random(3)
        trie = TopicTrie()
        live = set()
        for pattern in patterns:
            trie.add(pattern, pattern)
            live.add(pattern)
        for pattern in rng.sample(sorted(live), len(live) // 2):
            trie.discard(pattern, pattern)
            live.discard(pattern)
        trie.discard("never/registered", "never/registered")  # no-op
        assert len(trie) == len(live)
        for topic in topics:
            linear = {pattern for pattern in live
                      if topic_matches(pattern, topic)}
            assert set(trie.match(topic)) == linear, topic

    def test_one_value_under_many_patterns_appears_once(self):
        trie = TopicTrie()
        trie.add("a/#", "client")
        trie.add("a/+", "client")
        trie.add("a/b", "client")
        assert trie.match("a/b") == ["client"]

    def test_remove_value_strips_every_registration(self):
        trie = TopicTrie()
        for pattern in ("a/#", "b/+", "c"):
            trie.add(pattern, "dead")
            trie.add(pattern, "alive")
        trie.remove_value("dead")
        assert len(trie) == 3
        for topic in ("a/x", "b/y", "c"):
            assert trie.match(topic) == ["alive"]


# -- broker fast path --------------------------------------------------------


class _Collector:
    """Loopback client collecting (topic, payload) in arrival order."""

    def __init__(self, broker_name, subscriptions):
        self.received = []
        self.transport = LoopbackTransport(
            on_message=lambda topic, payload: self.received.append(
                (topic, payload)),
            broker=broker_name)
        for pattern in subscriptions:
            self.transport.subscribe(pattern)
        self.transport.connect()


class TestBrokerFastPath:
    def test_trie_and_linear_arms_deliver_identically(self):
        """The A/B contract the bench asserts: same messages, same
        per-client order, whichever matcher routes them."""
        rng = random.Random(5)
        topics, patterns = _corpus(seed=19, topics_n=40, patterns_n=60)
        subscriptions = [rng.sample(patterns, 4) for _ in range(12)]
        messages = [(rng.choice(topics), f"m{index}")
                    for index in range(300)]
        deliveries = {}
        for mode in ("trie", "linear"):
            broker = get_broker(f"ab_{mode}")
            broker.match_mode = mode
            clients = [_Collector(f"ab_{mode}", subs)
                       for subs in subscriptions]
            for topic, payload in messages:
                broker.publish(topic, payload)
            broker.drain()
            deliveries[mode] = [client.received for client in clients]
        assert deliveries["trie"] == deliveries["linear"]
        # and the fast path actually filtered: every delivery matched
        for client_subs, received in zip(subscriptions,
                                         deliveries["trie"]):
            for topic, _ in received:
                assert any(topic_matches(pattern, topic)
                           for pattern in client_subs)

    def test_fanout_avoided_counts_skipped_wakeups(self):
        broker = get_broker("fanout")
        listener = _Collector("fanout", ["wanted/topic"])
        _bystanders = [_Collector("fanout", [f"other/{index}"])
                       for index in range(3)]
        avoided = get_registry().counter("broker.fanout_avoided")
        delivered = get_registry().counter("broker.fanout_delivered")
        avoided_before, delivered_before = avoided.value, delivered.value
        broker.publish("wanted/topic", "hello")
        broker.drain()
        assert listener.received == [("wanted/topic", "hello")]
        assert delivered.value - delivered_before == 1
        # 3 bystanders with zero matching subscriptions never woke
        assert avoided.value - avoided_before == 3

    def test_sharded_dispatch_preserves_per_topic_fifo(self):
        broker = LoopbackBroker("sharded", shards=4)
        try:
            received = []
            client = LoopbackTransport(
                on_message=lambda topic, payload: received.append(
                    (topic, payload)))
            client._broker_name = "unused"
            client.subscribe("#")
            # attach directly: this broker is not in the registry
            client._broker = broker
            client._connected = True
            broker.attach(client)
            topics = [f"stream/{index}" for index in range(8)]
            for sequence in range(50):
                for topic in topics:
                    broker.publish(topic, str(sequence))
            broker.drain()
            assert len(received) == 8 * 50
            per_topic = {}
            for topic, payload in received:
                per_topic.setdefault(topic, []).append(int(payload))
            # same topic -> same shard -> FIFO preserved per topic
            for topic in topics:
                assert per_topic[topic] == list(range(50)), topic
        finally:
            broker.shutdown()

    def test_partitioned_client_is_unrouted_until_heal(self):
        broker = get_broker("part")
        client = _Collector("part", ["t/#"])
        broker.drain()
        client.transport.partition()
        broker.publish("t/1", "lost")
        broker.drain()
        assert client.received == []
        client.transport.heal()
        broker.publish("t/2", "seen")
        broker.drain()
        assert ("t/2", "seen") in client.received


# -- process handler dispatch ------------------------------------------------


class TestProcessHandlerTrie:
    def test_wildcard_handlers_fire_in_registration_order(self):
        process = Process(transport_kind="loopback")
        calls = []
        process.add_message_handler(
            lambda topic, payload: calls.append("plus"), "ns/+/x")
        process.add_message_handler(
            lambda topic, payload: calls.append("hash"), "ns/a/#")
        process.add_message_handler(
            lambda topic, payload: calls.append("exact"), "ns/a/x")
        process.run(in_thread=True)
        process.publish("ns/a/x", "(ping)")
        wait_for(lambda: len(calls) == 3)
        assert calls == ["plus", "hash", "exact"]
        process.publish("ns/b/x", "(ping)")
        wait_for(lambda: len(calls) == 4)
        assert calls[3] == "plus"
        process.terminate()

    def test_removed_handler_stops_matching(self):
        process = Process(transport_kind="loopback")
        calls = []

        def handler(topic, payload):
            calls.append(topic)

        process.add_message_handler(handler, "gone/+")
        process.remove_message_handler(handler, "gone/+")
        process.add_message_handler(
            lambda topic, payload: calls.append("kept"), "kept/topic")
        process.run(in_thread=True)
        process.publish("gone/x", "(ping)")
        process.publish("kept/topic", "(ping)")
        wait_for(lambda: calls)
        assert calls == ["kept"]
        process.terminate()


# -- coalesced EC publishes --------------------------------------------------


class _Bursty(Actor):
    """Actor staging a burst of share updates in ONE mailbox turn."""

    def burst(self, count):
        for index in range(int(count)):
            self.ec_producer.stage("x", index)

    def stage_same(self, value):
        self.ec_producer.stage("x", value)

    def stage_then_update(self, staged, updated):
        # an immediate update() must SUPERSEDE the pending staged
        # value: the deferred flush must not later overwrite it
        self.ec_producer.stage("x", staged)
        self.ec_producer.update("x", updated)

    def remove_then_restage(self, value):
        # remove() drops the key on every consumer; re-staging the SAME
        # scalar must still publish (the consumer mirror is empty)
        self.ec_producer.remove("x")
        self.ec_producer.stage("x", value)


class TestCoalescedShare:
    def _wire(self):
        producer_process = Process(transport_kind="loopback")
        actor = _Bursty(producer_process, name="bursty")
        producer_process.run(in_thread=True)
        consumer_process = Process(transport_kind="loopback")
        consumer_process.run(in_thread=True)
        cache = {}
        consumer = ECConsumer(consumer_process, cache, actor.topic_path,
                              lease_time=60)
        wait_for(lambda: consumer.synced)
        return producer_process, consumer_process, actor, cache, consumer

    def test_burst_folds_into_one_delta(self):
        producer_process, consumer_process, actor, cache, consumer = (
            self._wire())
        updates = []
        consumer.add_change_handler(
            lambda _c, command, name, value: updates.append(
                (command, name, value)))
        delta_publishes = get_registry().counter("share.delta_publishes")
        before = delta_publishes.value
        actor.post_message("burst", [100])
        wait_for(lambda: cache.get("x") == "99")
        # 100 staged updates -> ONE delta payload, final value only
        assert delta_publishes.value - before == 1
        assert [u for u in updates if u[1] == "x"] == [
            ("update", "x", "99")]
        producer_process.terminate()
        consumer_process.terminate()

    def test_update_supersedes_pending_staged_value(self):
        producer_process, consumer_process, actor, cache, _ = self._wire()
        actor.post_message("stage_then_update", [1, 2])
        wait_for(lambda: cache.get("x") == "2")
        # the deferred flush must NOT roll the mirror back to the
        # staged 1; poke another key through a flush cycle and re-check
        actor.post_message("burst", [0])
        import time
        time.sleep(0.2)
        get_broker().drain()
        assert cache.get("x") == "2"
        producer_process.terminate()
        consumer_process.terminate()

    def test_remove_then_restage_same_value_republishes(self):
        producer_process, consumer_process, actor, cache, _ = self._wire()
        actor.post_message("stage_same", [9])
        wait_for(lambda: cache.get("x") == "9")
        actor.post_message("remove_then_restage", [9])
        # consumers dropped the key on remove; the re-stage of the SAME
        # scalar must republish it (the flushed-shadow was cleared)
        wait_for(lambda: cache.get("x") == "9")
        producer_process.terminate()
        consumer_process.terminate()

    def test_unchanged_scalar_restage_publishes_nothing(self):
        producer_process, consumer_process, actor, cache, _ = self._wire()
        delta_publishes = get_registry().counter("share.delta_publishes")
        actor.post_message("stage_same", [7])
        wait_for(lambda: cache.get("x") == "7")
        flushed = delta_publishes.value
        actor.post_message("stage_same", [7])     # identical value
        actor.post_message("burst", [0])          # force a flush cycle
        import time
        time.sleep(0.2)
        get_broker().drain()
        assert delta_publishes.value == flushed
        producer_process.terminate()
        consumer_process.terminate()


# -- federated gateway tier --------------------------------------------------


class Echo(PipelineElement):
    """Device-light element: the scale storm measures the CONTROL
    plane, so the data plane is one integer add."""

    def process_frame(self, stream, number):
        return StreamEvent.OKAY, {"number": int(number) + 1}


def _echo_definition(name):
    return {
        "name": name,
        "parameters": {"telemetry": False},
        "graph": ["(echo)"],
        "elements": [
            {"name": "echo", "input": [{"name": "number"}],
             "output": [{"name": "number"}],
             "deploy": {"local": {"module": "tests.test_scaleout",
                                  "class_name": "Echo"}}},
        ],
    }


def _federated_tier(groups, replicas_n=2, policy="max_inflight=64;"
                    "queue=4096", ha=False):
    """One shared replica fleet fronted by one gateway per group.
    Returns (router, gateways, replicas, processes)."""
    processes, replicas = [], []
    for index in range(replicas_n):
        process = Process(transport_kind="loopback")
        processes.append(process)
        replicas.append(create_pipeline(
            process, _echo_definition(f"replica{index}")))
    spec = f"groups={','.join(groups)}"
    gateways = {}
    for group in groups:
        process = Process(transport_kind="loopback")
        processes.append(process)
        gateways[group] = Gateway(
            process, name=f"gw_{group}", policy=policy,
            federation=f"{spec};group={group}",
            ha=(group if ha else None),
            telemetry=False)
        for replica in replicas:
            gateways[group].attach_replica(replica)
    for process in processes:
        process.run(in_thread=True)
    return FederationRouter(gateways), gateways, replicas, processes


class TestFederation:
    def test_assign_group_is_deterministic_and_balanced(self):
        groups = ("g0", "g1", "g2", "g3")
        first = [assign_group(f"s{index}", groups) for index in range(2000)]
        second = [assign_group(f"s{index}", groups)
                  for index in range(2000)]
        assert first == second
        from collections import Counter
        counts = Counter(first)
        assert set(counts) == set(groups)
        for group in groups:
            assert 0.15 < counts[group] / 2000 < 0.35, counts

    def test_consistent_hash_minimal_remap_on_group_loss(self):
        """Removing one group only remaps ITS streams: every stream
        owned by a surviving group keeps its assignment."""
        groups = ("g0", "g1", "g2", "g3")
        survivors = ("g0", "g1", "g2")
        for index in range(500):
            stream_id = f"s{index}"
            before = assign_group(stream_id, groups)
            after = assign_group(stream_id, survivors)
            if before != "g3":
                assert after == before, stream_id

    def test_policy_parse_and_rejections(self):
        policy = FederationPolicy.parse("groups=a,b,c;group=b")
        assert policy.groups == ("a", "b", "c")
        assert policy.group == "b"
        assert policy.owner_of("s1") in policy.groups
        with pytest.raises(ValueError):
            FederationPolicy.parse("groups=")
        with pytest.raises(ValueError):
            FederationPolicy.parse("groups=a,a")
        with pytest.raises(ValueError):
            FederationPolicy.parse("groups=a;group=z")
        with pytest.raises(ValueError, match="AIKO410"):
            Gateway(Process(transport_kind="loopback"),
                    federation="groups=a;group=z")

    def test_wrong_group_stream_is_shed_typed(self):
        router, gateways, _replicas, processes = _federated_tier(
            ("g0", "g1"))
        responses = queue.Queue()
        # find a stream id owned by g1, submit it to g0 directly
        stream_id = next(f"s{index}" for index in range(100)
                         if router.group_for(f"s{index}") == "g1")
        gateways["g0"].submit_stream(stream_id,
                                     queue_response=responses)
        reply = responses.get(timeout=10)
        assert reply[3] == "overloaded"
        assert reply[2]["reason"] == "wrong_group"
        # routed through the router it lands on its OWN group and serves
        router.submit_stream(stream_id, queue_response=responses)
        router.submit_frame(stream_id, {"number": 41}, frame_id=0)
        reply = responses.get(timeout=10)
        assert reply[3] == "ok" and reply[2]["number"] == 42
        for process in processes:
            process.terminate()

    def test_journals_namespace_per_group(self):
        """HA + federation compose: each group's journal lives under
        its own retained root, so a group's standby adopts exactly its
        own streams."""
        processes = []
        roots = {}
        for group in ("g0", "g1"):
            process = Process(transport_kind="loopback")
            processes.append(process)
            process.run(in_thread=True)
            gateway = Gateway(process, name=f"gw_{group}",
                              federation=f"groups=g0,g1;group={group}",
                              ha=group, telemetry=False)
            assert gateway.federation_group == group
            roots[group] = gateway.journal.backend.root_topic
        assert roots["g0"] != roots["g1"]
        assert "/gateway/g0/" in roots["g0"]
        assert "/gateway/g1/" in roots["g1"]
        for process in processes:
            process.terminate()

    def test_federated_storm_zero_lost_frames(self):
        """The tier-1-sized scale storm: hundreds of open-loop streams
        through a 2-group federated tier over a shared 2-replica
        fleet -- every frame answers exactly once (ok or typed shed;
        nothing lost), and ownership matches the consistent hash."""
        streams_n, frames_per_stream = 300, 2
        router, gateways, _replicas, processes = _federated_tier(
            ("g0", "g1"))
        responses = queue.Queue()
        for index in range(streams_n):
            router.submit_stream(f"s{index}", queue_response=responses,
                                 grace_time=300)
        for frame_id in range(frames_per_stream):
            for index in range(streams_n):
                router.submit_frame(f"s{index}",
                                    {"number": index}, frame_id=frame_id)
        outcomes = {"ok": 0, "shed": 0, "overloaded": 0, "error": 0}
        for _ in range(streams_n * frames_per_stream):
            reply = responses.get(timeout=60)
            outcomes[reply[3]] += 1
            if reply[3] == "ok":
                assert reply[2]["number"] == int(
                    reply[0][1:]) + 1
        assert outcomes["ok"] == streams_n * frames_per_stream
        assert outcomes["error"] == 0
        # ownership: every stream landed on its consistent-hash group
        for group, gateway in gateways.items():
            for stream_id in gateway.streams:
                assert router.group_for(stream_id) == group
        assert sum(len(g.streams) for g in gateways.values()) == streams_n
        for process in processes:
            process.terminate()
