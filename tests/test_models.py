# ASR + detector model tests and the log-mel frontend, on CPU.

import jax
import jax.numpy as jnp
import numpy as np

from aiko_services_tpu.models import (
    AsrConfig, DetectorConfig, asr_forward, decode_boxes, detect,
    init_asr_params, init_detector_params, non_max_suppression, transcribe)
from aiko_services_tpu.ops import log_mel_spectrogram, mel_filterbank

ASR = AsrConfig(n_mels=80, d_model=64, enc_layers=2, dec_layers=2,
                n_heads=4, vocab_size=64, max_frames=100, max_text_len=16,
                dtype="float32")
DET = DetectorConfig(n_classes=4, base_channels=8, image_size=64,
                     max_detections=8, dtype="float32")


class TestAudioOps:
    def test_mel_filterbank_shape_and_coverage(self):
        bank = mel_filterbank(16000, 400, 80)
        assert bank.shape == (80, 201)
        # every mel bin has some support; no all-zero rows
        assert (bank.sum(axis=1) > 0).all()

    def test_log_mel_spectrogram(self):
        wave = np.sin(2 * np.pi * 440 *
                      np.arange(16000) / 16000).astype(np.float32)
        mel = log_mel_spectrogram(wave[None])
        assert mel.shape == (1, 80, 101)  # 1 s @ 10 ms hop (+1 frame)
        assert bool(jnp.isfinite(mel).all())
        # 440 Hz tone concentrates energy in the low mel bins
        assert float(mel[0, :20].mean()) > float(mel[0, 60:].mean())

    def test_jit_compatible(self):
        wave = jnp.zeros((2, 8000), jnp.float32)
        mel = jax.jit(log_mel_spectrogram)(wave)
        assert mel.shape == (2, 80, 51)


class TestAsr:
    def test_teacher_forced_forward(self):
        params = init_asr_params(ASR, jax.random.PRNGKey(0))
        mel = jnp.zeros((2, 80, 100), jnp.float32)
        tokens = jnp.ones((2, 8), jnp.int32)
        logits = asr_forward(params, ASR, mel, tokens)
        assert logits.shape == (2, 8, 64)
        assert bool(jnp.isfinite(logits).all())

    def test_transcribe_matches_rescore_oracle(self):
        """The incremental KV-cached decode must produce the SAME tokens
        as the full-rescore loop (the numerics oracle)."""
        import jax
        from aiko_services_tpu.models.asr import transcribe_rescore
        params = init_asr_params(ASR, jax.random.PRNGKey(3))
        mel = jax.random.normal(
            jax.random.PRNGKey(4), (2, ASR.n_mels, 64), jnp.float32)
        fast = transcribe(params, ASR, mel, max_tokens=8)
        oracle = transcribe_rescore(params, ASR, mel, max_tokens=8)
        assert jnp.array_equal(fast, oracle), (fast, oracle)

    def test_transcribe_greedy(self):
        params = init_asr_params(ASR, jax.random.PRNGKey(0))
        mel = (jax.random.normal(jax.random.PRNGKey(1), (1, 80, 100))
               * 0.1)
        out = transcribe(params, ASR, mel, max_tokens=8)
        assert out.shape == (1, 8)
        assert int(out.min()) >= 0 and int(out.max()) < 64
        # deterministic
        out2 = transcribe(params, ASR, mel, max_tokens=8)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))

    def test_asr_differentiable(self):
        params = init_asr_params(ASR, jax.random.PRNGKey(0))
        mel = jnp.zeros((1, 80, 100), jnp.float32)
        tokens = jnp.ones((1, 4), jnp.int32)

        def loss(params):
            logits = asr_forward(params, ASR, mel, tokens)
            return jnp.mean(logits ** 2)

        grads = jax.grad(loss)(params)
        gnorm = sum(float(jnp.abs(g).sum())
                    for g in jax.tree_util.tree_leaves(grads))
        assert np.isfinite(gnorm) and gnorm > 0


class TestDetector:
    def test_detect_shapes(self):
        params = init_detector_params(DET, jax.random.PRNGKey(0))
        images = jnp.zeros((2, 3, 64, 64), jnp.float32)
        out = detect(params, DET, images)
        assert out["boxes"].shape == (2, 8, 4)
        assert out["scores"].shape == (2, 8)
        assert out["valid"].dtype == bool

    def test_decode_boxes_geometry(self):
        raw = jnp.zeros((1, 5 + 4, 4, 4), jnp.float32)
        boxes, scores, classes = decode_boxes(raw, DET)
        assert boxes.shape == (1, 16, 4)
        # zero logits: center at cell+0.5, size = stride
        first = np.asarray(boxes[0, 0])
        np.testing.assert_allclose(first, [0.5 * 16 - 8, 0.5 * 16 - 8,
                                           0.5 * 16 + 8, 0.5 * 16 + 8],
                                   rtol=1e-5)

    def test_nms_suppresses_overlaps(self):
        boxes = jnp.asarray([[0, 0, 10, 10], [1, 1, 11, 11],
                             [50, 50, 60, 60]], jnp.float32)
        scores = jnp.asarray([0.9, 0.8, 0.7], jnp.float32)
        classes = jnp.asarray([0, 0, 1], jnp.int32)
        _, final_scores, _, valid = non_max_suppression(
            boxes, scores, classes, DET)
        kept = np.asarray(final_scores)[np.asarray(valid)]
        # overlapping 0.8 box suppressed; 0.9 and 0.7 survive
        np.testing.assert_allclose(sorted(kept, reverse=True), [0.9, 0.7],
                                   rtol=1e-6)

    def test_nms_keeps_overlap_across_classes(self):
        boxes = jnp.asarray([[0, 0, 10, 10], [1, 1, 11, 11]], jnp.float32)
        scores = jnp.asarray([0.9, 0.8], jnp.float32)
        classes = jnp.asarray([0, 1], jnp.int32)  # different classes
        _, final_scores, _, valid = non_max_suppression(
            boxes, scores, classes, DET)
        assert int(np.asarray(valid).sum()) == 2


def test_nms_jacobi_matches_sequential_greedy_oracle():
    """The Jacobi fixed-point NMS must reproduce EXACT sequential greedy
    suppression (including revival chains: A kills B, so B cannot kill
    C) on randomized candidate sets."""
    import numpy as np
    from aiko_services_tpu.models.detector import DetectorConfig

    rng = np.random.default_rng(11)
    config = DetectorConfig(n_classes=3, max_detections=16,
                            score_threshold=0.0, iou_threshold=0.5)
    for trial in range(5):
        count = 40
        centers = rng.uniform(20, 200, (count, 2))
        sizes = rng.uniform(10, 60, (count, 2))
        boxes = np.concatenate([centers - sizes / 2,
                                centers + sizes / 2], axis=1)
        scores = rng.uniform(0.1, 1.0, count).astype(np.float32)
        classes = rng.integers(0, 3, count)

        def greedy(boxes, scores, classes):
            order = np.argsort(-scores, kind="stable")
            alive = []
            for index in order:
                box, cls = boxes[index], classes[index]
                ok = True
                for kept in alive:
                    if classes[kept] != cls:
                        continue
                    lt = np.maximum(box[:2], boxes[kept][:2])
                    rb = np.minimum(box[2:], boxes[kept][2:])
                    wh = np.maximum(rb - lt, 0)
                    inter = wh[0] * wh[1]
                    a1 = (box[2] - box[0]) * (box[3] - box[1])
                    a2 = ((boxes[kept][2] - boxes[kept][0])
                          * (boxes[kept][3] - boxes[kept][1]))
                    if inter / max(a1 + a2 - inter, 1e-9) > 0.5:
                        ok = False
                        break
                if ok:
                    alive.append(index)
            return sorted(scores[alive], reverse=True)[:16]

        want = np.asarray(greedy(boxes, scores, classes), np.float32)
        _, got_scores, _, valid = non_max_suppression(
            jnp.asarray(boxes, jnp.float32), jnp.asarray(scores),
            jnp.asarray(classes, jnp.int32), config)
        got = np.asarray(got_scores)[np.asarray(valid)]
        np.testing.assert_allclose(got, want[:len(got)], atol=1e-5,
                                   err_msg=f"trial {trial}")
        assert len(got) == len(want), f"trial {trial}"
