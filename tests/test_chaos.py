# Crash-consistent serving under process-level chaos (ISSUE 9): the
# gateway journal (serve/journal.py) -- sqlite and retained backends,
# AIKO407 grammar, compaction, stale cold-start -- hot-standby takeover
# through the shared RetainedElection with bit-identical exactly-once
# resumption, the process-scoped fault points (process_kill /
# broker_partition / registrar_kill) through ProcessManager and
# LoopbackTransport, the registrar-kill composition regression, the
# minimqtt bounded offline publish queue, and the `aiko deadletter`
# drain surface.

import json
import queue
import threading
import time

import numpy as np
import pytest

from aiko_services_tpu import faults as faults_module
from aiko_services_tpu.faults import create_injector
from aiko_services_tpu.observe import get_registry
from aiko_services_tpu.pipeline import (
    PipelineElement, StreamEvent, create_pipeline)
from aiko_services_tpu.pipeline.tensors import (
    decode_frame_data, encode_frame_data)
from aiko_services_tpu.runtime import (
    Process, ProcessManager, Recorder, Registrar)
from aiko_services_tpu.serve import Gateway, GatewayJournal, JournalPolicy
from aiko_services_tpu.transport import reset_brokers
from aiko_services_tpu.transport.loopback import LoopbackTransport, get_broker
from aiko_services_tpu.utils import epoch_now, generate, parse
from helpers import wait_for


@pytest.fixture(autouse=True)
def clean():
    faults_module.reset_injector()
    reset_brokers()
    yield
    faults_module.reset_injector()
    reset_brokers()


class Scale(PipelineElement):
    """x -> x*10 (deterministic: takeover replay must be bit-identical)."""

    def process_frame(self, stream, x):
        return StreamEvent.OKAY, {"y": x * 10.0}


def _replica_definition(name, parameters=None, element_parameters=None):
    return {
        "name": name,
        "parameters": dict(parameters or {}),
        "graph": ["(scale)"],
        "elements": [
            {"name": "scale", "input": [{"name": "x"}],
             "output": [{"name": "y"}],
             "parameters": dict(element_parameters or {}),
             "deploy": {"local": {"module": "tests.test_chaos",
                                  "class_name": "Scale"}}},
        ],
    }


def _frame_data(value):
    return {"x": np.ones((1, 2), np.float32) * value}


class WireClient:
    """Pipeline-protocol client over the broker: it outlives any
    gateway death, re-targets the surviving primary, and resubmits
    un-acked frames -- the client half of the exactly-once story."""

    def __init__(self, name="client"):
        self.process = Process(transport_kind="loopback")
        self.topic = f"{self.process.topic_path_process}/0/{name}"
        self.lock = threading.Lock()
        self.responses: dict = {}   # (sid, fid) -> [(status, outputs)]
        self.sheds: list = []
        self.process.add_message_handler(self._on_reply, self.topic)
        self.process.run(in_thread=True)

    def _on_reply(self, topic, payload):
        command, parameters = parse(payload)
        if command == "process_frame_response" and parameters:
            reply = parameters[0]
            key = (str(reply.get("stream_id")),
                   int(reply.get("frame_id", -1)))
            if reply.get("event"):
                entry = (str(reply["event"]), None)
            else:
                outputs = (decode_frame_data(parameters[1])
                           if len(parameters) > 1 else {})
                entry = ("ok", outputs)
            with self.lock:
                self.responses.setdefault(key, []).append(entry)
        elif command == "overloaded" and parameters:
            with self.lock:
                self.sheds.append(tuple(parameters))

    def create(self, gateway_topic, stream_id, parameters=None,
               grace_time=60.0):
        self.process.publish(
            f"{gateway_topic}/in",
            generate("create_stream", [
                stream_id,
                json.dumps(parameters or {}).encode("ascii"),
                grace_time, self.topic]))

    def submit(self, gateway_topic, stream_id, frame_id, value):
        self.process.publish(
            f"{gateway_topic}/in",
            generate("process_frame", [
                {"stream_id": stream_id, "frame_id": frame_id},
                encode_frame_data(_frame_data(value)).encode("ascii")]))

    def destroy(self, gateway_topic, stream_id):
        self.process.publish(f"{gateway_topic}/in",
                             generate("destroy_stream", [stream_id]))

    def acked(self, keys):
        with self.lock:
            return all(key in self.responses for key in keys)

    def outputs_map(self):
        """{(sid, fid): bytes-of-y} for every ok response (asserting
        single delivery)."""
        result = {}
        with self.lock:
            for key, entries in self.responses.items():
                assert len(entries) == 1, (
                    f"{key} answered {len(entries)} times: exactly-once "
                    f"violated")
                status, outputs = entries[0]
                if status == "ok":
                    value = np.asarray(outputs["y"])
                    result[key] = (value.dtype.str, value.tobytes())
        return result

    def stop(self):
        self.process.terminate()


def _ha_fleet(db_path, replicas_n=2, policy="max_inflight=8;queue=64",
              journal_extra="", group="grp"):
    """2 replicas + HA gateway pair (A primary, B standby) over one
    loopback broker; synchronous journaling (interval=0) pins the
    crash window shut so the scenario is deterministic."""
    spec = f"interval=0;search_timeout=0.3{journal_extra}"
    if db_path is not None:
        spec += f";path={db_path}"
    processes, replicas = [], []
    for index in range(replicas_n):
        process = Process(transport_kind="loopback")
        processes.append(process)
        replicas.append(create_pipeline(
            process, _replica_definition(f"replica{index}")))
        process.run(in_thread=True)
    process_a = Process(transport_kind="loopback")
    gateway_a = Gateway(process_a, policy=policy, router_seed=7,
                        journal=spec, ha=group)
    process_a.run(in_thread=True)
    processes.append(process_a)
    wait_for(lambda: gateway_a.role == "primary", timeout=10)
    process_b = Process(transport_kind="loopback")
    gateway_b = Gateway(process_b, policy=policy, router_seed=7,
                        journal=spec, ha=group)
    process_b.run(in_thread=True)
    processes.append(process_b)
    wait_for(lambda: gateway_b.election.state == "secondary", timeout=10)
    for replica in replicas:
        gateway_a.attach_replica(replica)
        gateway_b.attach_replica(replica)
    return gateway_a, gateway_b, replicas, processes


# -- journal policy grammar (AIKO407) ----------------------------------------


class TestJournalPolicy:
    def test_grammar_and_defaults(self):
        policy = JournalPolicy.parse(None)
        assert policy.backend == ""
        policy = JournalPolicy.parse("interval=0.2;backend=retained")
        assert policy.interval_s == 0.2
        policy = JournalPolicy.parse("path=/tmp/x.db")
        assert policy.backend == "sqlite"

    def test_construction_error_codes_match_offline_lint(self):
        from aiko_services_tpu.analyze.policies import check_journal_policy
        process = Process(transport_kind="loopback")
        with pytest.raises(ValueError) as error:
            Gateway(process, journal="backend=sqlite")
        assert "AIKO407" in str(error.value)
        problems = check_journal_policy("backend=sqlite")
        assert problems and problems[0][0] == "AIKO407"
        with pytest.raises(ValueError) as error:
            Gateway(process, journal="backnd=retained")
        assert "AIKO404" in str(error.value)
        assert check_journal_policy("backnd=retained")[0][0] == "AIKO404"

    def test_sqlite_requires_path_offline_and_online(self):
        with pytest.raises(ValueError, match="requires path"):
            JournalPolicy.parse("backend=sqlite")


# -- journal store semantics -------------------------------------------------


class TestJournalStore:
    def _record(self, stream_id, expires_in, replica="ns/h/p/1"):
        return {"stream_id": stream_id, "priority": 0, "slo_ms": 0.0,
                "parameters": {}, "grace_time": 60.0,
                "topic_response": "", "replica": replica, "cursor": 5,
                "delivered_upto": 4,
                "expires_at": epoch_now() + expires_in}

    def test_sqlite_roundtrip_forget_and_stale_drop(self, tmp_path):
        policy = JournalPolicy.parse(f"path={tmp_path / 'j.db'}")
        journal = GatewayJournal(policy)
        journal.write({"s1": self._record("s1", 60),
                       "s2": self._record("s2", -1)},
                      buckets={"0": 0.5})
        assert journal.entry_count() == 2
        live, buckets, dropped = journal.replay()
        assert [record["stream_id"] for record in live] == ["s1"]
        assert dropped == 1
        assert buckets == {"0": 0.5}
        # the stale entry was purged by replay
        assert journal.entry_count() == 1
        journal.write({}, forgotten=["s1"])
        assert journal.entry_count() == 0
        journal.stop()

    def test_compaction_sweeps_expired_entries(self, tmp_path):
        policy = JournalPolicy.parse(
            f"path={tmp_path / 'j.db'};compact_every=2")
        journal = GatewayJournal(policy)
        journal.write({"live": self._record("live", 60),
                       "stale": self._record("stale", -1)})
        assert journal.entry_count() == 2
        # second tick crosses compact_every: the sweep drops the
        # expired entry without an explicit forget
        journal.write({"live": self._record("live", 60)})
        assert journal.compactions == 1
        assert journal.compacted_entries == 1
        assert journal.entry_count() == 1
        journal.stop()


# -- gateway restart / takeover ----------------------------------------------


class TestGatewayRecovery:
    def test_restart_recovers_streams_from_sqlite(self, tmp_path):
        db_path = tmp_path / "gw.db"
        replica_process = Process(transport_kind="loopback")
        replica = create_pipeline(replica_process,
                                  _replica_definition("replica0"))
        replica_process.run(in_thread=True)
        process_a = Process(transport_kind="loopback")
        gateway_a = Gateway(process_a, journal=f"path={db_path};interval=0")
        gateway_a.attach_replica(replica)
        process_a.run(in_thread=True)
        client = WireClient()
        try:
            client.create(gateway_a.topic_path, "s1")
            for frame_id in range(3):
                client.submit(gateway_a.topic_path, "s1", frame_id,
                              frame_id)
            wait_for(lambda: client.acked(
                [("s1", fid) for fid in range(3)]), timeout=30)
            process_a.crash()   # no clean stop: the journal survives

            process_b = Process(transport_kind="loopback")
            gateway_b = Gateway(process_b,
                                journal=f"path={db_path};interval=0")
            gateway_b.attach_replica(replica)
            process_b.run(in_thread=True)
            wait_for(lambda: gateway_b.telemetry.journal_replayed.value
                     == 1, timeout=10)
            assert "s1" in gateway_b.streams
            recovered = gateway_b.streams["s1"]
            assert recovered.cursor == 3
            assert recovered.delivered_floor == 2
            # duplicate of an acked frame: deduped; new frames serve
            client.submit(gateway_b.topic_path, "s1", 2, 2)
            for frame_id in range(3, 6):
                client.submit(gateway_b.topic_path, "s1", frame_id,
                              frame_id)
            wait_for(lambda: client.acked(
                [("s1", fid) for fid in range(3, 6)]), timeout=30)
            assert gateway_b.telemetry.duplicates.value >= 1
            outputs = client.outputs_map()   # asserts exactly-once
            assert set(outputs) == {("s1", fid) for fid in range(6)}
            process_b.terminate()
        finally:
            client.stop()
            replica_process.terminate()

    def test_clean_stop_clears_journal(self, tmp_path):
        db_path = tmp_path / "gw.db"
        replica_process = Process(transport_kind="loopback")
        replica = create_pipeline(replica_process,
                                  _replica_definition("replica0"))
        replica_process.run(in_thread=True)
        process_a = Process(transport_kind="loopback")
        gateway_a = Gateway(process_a, journal=f"path={db_path};interval=0")
        gateway_a.attach_replica(replica)
        process_a.run(in_thread=True)
        client = WireClient()
        try:
            client.create(gateway_a.topic_path, "s1")
            client.submit(gateway_a.topic_path, "s1", 0, 1)
            wait_for(lambda: client.acked([("s1", 0)]), timeout=30)
            process_a.terminate()   # CLEAN stop destroys + forgets
            journal = GatewayJournal(
                JournalPolicy.parse(f"path={db_path}"))
            assert journal.entry_count() == 0
            journal.stop()
        finally:
            client.stop()
            replica_process.terminate()

    def test_full_outage_cold_start_defers_until_replicas_return(
            self, tmp_path):
        """A restart with journaled streams but an EMPTY pool (full
        outage: rediscovery still in flight) must DEFER adoption, not
        hard-fail and forget every stream."""
        db_path = tmp_path / "gw.db"
        journal = GatewayJournal(JournalPolicy.parse(f"path={db_path}"))
        journal.write({"s1": {
            "stream_id": "s1", "priority": 0, "slo_ms": 0.0,
            "parameters": {}, "grace_time": 60.0, "topic_response": "",
            "replica": "ns/old/1/1", "cursor": 2, "delivered_upto": 1,
            "expires_at": epoch_now() + 60.0}})
        journal.stop()
        process = Process(transport_kind="loopback")
        gateway = Gateway(
            process,
            journal=f"path={db_path};interval=0;replay_timeout=0.1")
        process.run(in_thread=True)
        replica_process = Process(transport_kind="loopback")
        try:
            # first recovery attempt fires with no replicas: deferred,
            # record intact
            time.sleep(0.3)
            assert gateway.streams == {}
            assert gateway.journal.entry_count() == 1
            # the fleet comes back: the retry adopts and re-pins
            replica = create_pipeline(replica_process,
                                      _replica_definition("replica0"))
            replica_process.run(in_thread=True)
            gateway.attach_replica(replica)
            wait_for(lambda: gateway.telemetry.journal_replayed.value
                     == 1, timeout=10)
            assert gateway.streams["s1"].delivered_floor == 1
        finally:
            process.terminate()
            replica_process.terminate()

    def test_stale_journal_cold_start_drops_expired(self, tmp_path):
        db_path = tmp_path / "gw.db"
        journal = GatewayJournal(JournalPolicy.parse(f"path={db_path}"))
        journal.write({"dead": {
            "stream_id": "dead", "priority": 0, "slo_ms": 0.0,
            "parameters": {}, "grace_time": 0.1, "topic_response": "",
            "replica": "ns/gone/1/1", "cursor": 9, "delivered_upto": 8,
            "expires_at": epoch_now() - 5.0}})
        journal.stop()
        process = Process(transport_kind="loopback")
        gateway = Gateway(process, journal=f"path={db_path};interval=0")
        process.run(in_thread=True)
        try:
            wait_for(lambda:
                     gateway.telemetry.journal_dropped_stale.value == 1,
                     timeout=10)
            assert gateway.streams == {}
            assert gateway.telemetry.journal_replayed.value == 0
            # the stale entry is purged, not re-pinned to a dead replica
            assert gateway.journal.entry_count() == 0
        finally:
            process.terminate()

    def test_hot_standby_takeover_bit_identical(self, tmp_path):
        """Acceptance: seeded gateway-kill -- the standby takes over
        from the journal, every pre-crash stream finishes, outputs are
        bit-identical to an uncrashed run, zero frames lost, and the
        duplicate resubmissions are absorbed exactly-once."""
        streams = [f"s{index}" for index in range(3)]

        def run(crash):
            gateway_a, gateway_b, _, processes = _ha_fleet(
                tmp_path / ("crash.db" if crash else "clean.db"),
                group="grp-crash" if crash else "grp-clean")
            client = WireClient()
            try:
                for stream_id in streams:
                    client.create(gateway_a.topic_path, stream_id)
                for stream_id in streams:
                    for frame_id in range(5):
                        client.submit(gateway_a.topic_path, stream_id,
                                      frame_id, frame_id)
                first = [(sid, fid) for sid in streams
                         for fid in range(5)]
                wait_for(lambda: client.acked(first), timeout=60)
                takeover_ms = None
                if crash:
                    gateway_a.process.crash()
                    # the takeover counter is recorded AFTER adoption
                    # completes -- the externally visible "B is
                    # primary" moment (the retained announce follows)
                    wait_for(lambda:
                             gateway_b.telemetry.takeovers.value == 1,
                             timeout=10)
                    assert gateway_b.role == "primary"
                    assert (gateway_b.telemetry.journal_replayed.value
                            == len(streams))
                    takeover_ms = gateway_b.telemetry.last_takeover_ms
                    assert takeover_ms is not None
                    target = gateway_b
                else:
                    target = gateway_a
                # the client replays its tail conservatively: frames
                # 3..4 are already acked (the new primary must dedupe
                # them), 5..9 are new
                for stream_id in streams:
                    for frame_id in range(3, 10):
                        client.submit(target.topic_path, stream_id,
                                      frame_id, frame_id)
                rest = [(sid, fid) for sid in streams
                        for fid in range(5, 10)]
                wait_for(lambda: client.acked(rest), timeout=60)
                if crash:
                    # 2 duplicate resubmissions per stream, deduped
                    assert (gateway_b.telemetry.duplicates.value
                            == 2 * len(streams))
                outputs = client.outputs_map()  # asserts exactly-once
                return outputs, takeover_ms
            finally:
                client.stop()
                for process in processes:
                    process.terminate()

        baseline, _ = run(crash=False)
        reset_brokers()
        recovered, takeover_ms = run(crash=True)
        expected = {(sid, fid) for sid in streams for fid in range(10)}
        assert set(baseline) == expected
        assert set(recovered) == expected      # frames_lost == 0
        assert recovered == baseline           # bit-identical
        assert takeover_ms >= 0.0

    def test_retained_backend_hot_mirror_takeover(self, tmp_path):
        gateway_a, gateway_b, _, processes = _ha_fleet(
            None, replicas_n=1, journal_extra=";backend=retained",
            group="grp-ret")
        client = WireClient()
        try:
            client.create(gateway_a.topic_path, "s1")
            for frame_id in range(4):
                client.submit(gateway_a.topic_path, "s1", frame_id,
                              frame_id)
            wait_for(lambda: client.acked(
                [("s1", fid) for fid in range(4)]), timeout=30)
            # the standby mirrors the retained journal continuously
            wait_for(lambda: gateway_b.journal.entry_count() == 1,
                     timeout=10)
            gateway_a.process.crash()
            wait_for(lambda: gateway_b.telemetry.takeovers.value == 1,
                     timeout=10)
            assert gateway_b.telemetry.journal_replayed.value == 1
            for frame_id in range(4, 8):
                client.submit(gateway_b.topic_path, "s1", frame_id,
                              frame_id)
            wait_for(lambda: client.acked(
                [("s1", fid) for fid in range(4, 8)]), timeout=30)
            outputs = client.outputs_map()
            assert set(outputs) == {("s1", fid) for fid in range(8)}
        finally:
            client.stop()
            for process in processes:
                process.terminate()

    def test_bucket_levels_survive_takeover(self, tmp_path):
        """A rate-limited client must not refill its admission budget
        by crashing the gateway: bucket token levels ride the journal."""
        gateway_a, gateway_b, _, processes = _ha_fleet(
            tmp_path / "bucket.db", replicas_n=1,
            policy="max_inflight=8;queue=64;bucket:0=0.0001/1",
            group="grp-bucket")
        client = WireClient()
        try:
            client.create(gateway_a.topic_path, "s1")
            client.submit(gateway_a.topic_path, "s1", 0, 0)
            wait_for(lambda: client.acked([("s1", 0)]), timeout=30)
            gateway_a.process.crash()
            wait_for(lambda: gateway_b.telemetry.takeovers.value == 1,
                     timeout=10)
            tokens = gateway_b.policy.buckets[0].tokens
            assert tokens < 1.0    # the spent token came back drained
            client.create(gateway_b.topic_path, "fresh")
            wait_for(lambda: any(shed[0] == "fresh"
                                 and shed[-1] == "rate_limited"
                                 for shed in client.sheds), timeout=10)
        finally:
            client.stop()
            for process in processes:
                process.terminate()


# -- registrar chaos regression (satellite) ----------------------------------


class TestRegistrarChaos:
    def test_registrar_kill_composes_with_lwt_reap(self):
        """Seeded registrar_kill mid-serving: the secondary promotes,
        services re-register, the in-flight stream completes -- and a
        replica crash AFTER the promotion is still reaped through the
        round-8 LWT path by the NEW primary, failing the stream over
        with zero loss."""
        injector = create_injector("seed=11;registrar_kill:node=reg1:frame=0")
        registrar_process_1 = Process(transport_kind="loopback")
        registrar_1 = Registrar(registrar_process_1, name="reg1",
                                search_timeout=0.1)
        registrar_process_1.run(in_thread=True)
        wait_for(lambda: registrar_1.state == "primary", timeout=10)
        registrar_process_2 = Process(transport_kind="loopback")
        registrar_2 = Registrar(registrar_process_2, name="reg2",
                                search_timeout=0.1)
        registrar_process_2.run(in_thread=True)
        wait_for(lambda: registrar_2.state == "secondary", timeout=10)
        processes = [registrar_process_1, registrar_process_2]
        replicas = []
        for index in range(2):
            process = Process(transport_kind="loopback")
            processes.append(process)
            replicas.append((process, create_pipeline(
                process, _replica_definition(
                    f"replica{index}",
                    parameters={"metrics_interval": 0.2}))))
            process.run(in_thread=True)
        gateway_process = Process(transport_kind="loopback")
        processes.append(gateway_process)
        gateway = Gateway(gateway_process,
                          policy="max_inflight=4;queue=64",
                          router_seed=7)
        gateway.discover(name="replica*")
        gateway_process.run(in_thread=True)
        try:
            wait_for(lambda: len(gateway.replicas) == 2, timeout=10)
            wait_for(lambda: all(
                replica.consumer.last_update is not None
                for replica in gateway.replicas.values()), timeout=10)
            responses = queue.Queue()
            gateway.submit_stream("w", {}, queue_response=responses)
            wait_for(lambda: "w" in gateway.streams, timeout=10)
            got = {}

            def drain(count):
                for _ in range(count):
                    _, frame_id, outputs, status = responses.get(
                        timeout=30)
                    assert status == "ok"
                    got[frame_id] = float(np.asarray(outputs["y"])[0, 0])

            for frame_id in range(3):
                gateway.submit_frame("w", _frame_data(frame_id))
            drain(3)
            # the seeded point fires on its first consult for reg1
            assert injector.registrar_kill("reg1")
            registrar_process_1.crash()
            wait_for(lambda: registrar_2.state == "primary", timeout=10)
            # services re-register with the promoted primary
            wait_for(lambda: len(registrar_2.services_table) >= 3,
                     timeout=10)
            # the in-flight stream keeps serving through the handover
            for frame_id in range(3, 6):
                gateway.submit_frame("w", _frame_data(frame_id))
            drain(3)
            # now crash the pinned replica: the PROMOTED registrar
            # reaps it from the LWT "(absent)" and the gateway fails
            # the stream over (round-8 reap + chaos compose)
            owner_name = gateway.streams["w"].replica.name
            owner_process = next(process for process, pipeline in replicas
                                 if pipeline.name == owner_name)
            for frame_id in range(6, 9):
                gateway.submit_frame("w", _frame_data(frame_id))
            owner_process.crash()
            drain(3)
            assert got == {frame_id: frame_id * 10.0
                           for frame_id in range(9)}
            wait_for(lambda: len(gateway.replicas) == 1, timeout=10)
            assert gateway.telemetry.failovers.value == 1
            assert injector.stats().get("registrar_kill") == 1
        finally:
            for process in processes:
                process.terminate()


# -- process-scoped fault points ---------------------------------------------


class TestProcessFaultPoints:
    def test_process_kill_consulted_by_process_manager(self, monkeypatch):
        monkeypatch.setenv("AIKO_FAULTS",
                           "seed=3;process_kill:node=victim:frame=0")
        faults_module.reset_injector()
        exits = []
        manager = ProcessManager(
            process_exit_handler=lambda pid, code: exits.append(
                (pid, code)))
        manager.spawn("victim", "-c",
                      ["import time; time.sleep(30)"],
                      use_interpreter=True)
        try:
            wait_for(lambda: exits, timeout=15)
            process_id, return_code = exits[0]
            assert process_id == "victim"
            assert return_code != 0      # SIGKILL, not a clean exit
            stats = faults_module.get_injector().stats()
            assert stats.get("process_kill") == 1
        finally:
            manager.terminate(grace=2)

    def test_broker_partition_point_drops_heals_and_fires_lwt(
            self, monkeypatch):
        monkeypatch.setenv(
            "AIKO_FAULTS",
            "seed=5;broker_partition:node=clientA:frame=2")
        faults_module.reset_injector()
        received = []
        receiver = LoopbackTransport(
            on_message=lambda topic, payload: received.append(
                (topic, payload)))
        receiver.connect()
        receiver.subscribe("chaos/#")
        transport = LoopbackTransport()
        transport.connect()
        transport.set_last_will_and_testament("chaos/lwt", "(absent)")
        transport.chaos_name = "clientA"
        transport.publish("chaos/m", "0")
        transport.publish("chaos/m", "1")
        transport.publish("chaos/m", "2")   # third publish: partition
        get_broker().drain()
        payloads = [payload for topic, payload in received
                    if topic == "chaos/m"]
        assert payloads == ["0", "1"]       # "2" died on the wire
        assert ("chaos/lwt", "(absent)") in received
        assert transport.partitioned
        assert transport.partition_dropped == 1
        # while partitioned, nothing flows either way
        transport.publish("chaos/m", "3")
        get_broker().drain()
        assert transport.partition_dropped == 2
        transport.heal()
        transport.publish("chaos/m", "4")
        get_broker().drain()
        assert [payload for topic, payload in received
                if topic == "chaos/m"] == ["0", "1", "4"]

    def test_process_rejoin_reasserts_presence(self):
        process = Process(transport_kind="loopback")
        process.run(in_thread=True)
        try:
            state_topic = f"{process.topic_path_process}/0/state"
            get_broker().drain()
            process.transport.partition()
            get_broker().drain()
            assert get_broker().retained(state_topic) == "(absent)"
            process.transport.heal()
            process.rejoin()
            get_broker().drain()
            assert get_broker().retained(state_topic) == "(present)"
        finally:
            process.terminate()


# -- minimqtt offline publish queue (satellite) ------------------------------


class TestMinimqttOfflineQueue:
    def test_outage_queue_bounded_drop_oldest_and_reconciled(
            self, monkeypatch):
        from aiko_services_tpu.transport.minimqtt import (
            Client, MiniMqttBroker)
        monkeypatch.setenv("AIKO_MQTT_OFFLINE_MAX", "4")
        registry = get_registry()
        queued_0 = registry.counter("mqtt.offline_queued").value
        dropped_0 = registry.counter("mqtt.offline_dropped").value
        replayed_0 = registry.counter("mqtt.offline_replayed").value
        broker = MiniMqttBroker()
        publisher = Client()
        publisher.connect_async("127.0.0.1", broker.port, keepalive=5)
        publisher.loop_start()
        wait_for(lambda: publisher._connected.is_set(), timeout=10)
        try:
            broker.stop()
            wait_for(lambda: not publisher._connected.is_set(),
                     timeout=10)
            # six publishes into a max-4 queue: the two OLDEST drop
            for index in range(6):
                publisher.publish("offline/t", f"m{index}", retain=True)
            assert (registry.counter("mqtt.offline_queued").value
                    - queued_0) == 6
            assert (registry.counter("mqtt.offline_dropped").value
                    - dropped_0) == 2
            # the broker returns (fresh port; the paho surface retargets
            # the reconnect loop) and the queue replays on CONNACK
            broker2 = MiniMqttBroker()
            publisher.connect_async("127.0.0.1", broker2.port,
                                    keepalive=5)
            try:
                wait_for(lambda: publisher._connected.is_set(),
                         timeout=20)
                wait_for(lambda: (
                    registry.counter("mqtt.offline_replayed").value
                    - replayed_0) == 4, timeout=10)
                # the newest survivor is the retained value: ordering
                # held through the drop-oldest + replay cycle
                publisher.flush()
                assert broker2.retained.get("offline/t") == b"m5"
                # reconcile: queued == replayed + dropped
                queued = (registry.counter("mqtt.offline_queued").value
                          - queued_0)
                dropped = (registry.counter(
                    "mqtt.offline_dropped").value - dropped_0)
                replayed = (registry.counter(
                    "mqtt.offline_replayed").value - replayed_0)
                assert queued == replayed + dropped
            finally:
                broker2.stop()
        finally:
            publisher.loop_stop()


# -- aiko deadletter ls|replay (satellite) -----------------------------------


class TestDeadLetterCli:
    def test_ls_and_replay_through_gateway(self, tmp_path):
        from aiko_services_tpu.cli import (
            fetch_dead_letters, replay_dead_letter)
        registrar_process = Process(transport_kind="loopback")
        Registrar(registrar_process, search_timeout=0.05)
        registrar_process.run(in_thread=True)
        recorder_process = Process(transport_kind="loopback")
        recorder = Recorder(recorder_process)
        recorder_process.run(in_thread=True)
        replica_process = Process(transport_kind="loopback")
        # frame 2 fails EXACTLY once (seeded transient): the dead
        # letter embeds the encoded inputs, and the operator replay of
        # the same frame succeeds
        replica = create_pipeline(replica_process, _replica_definition(
            "replica0",
            parameters={"faults":
                        "seed=5;element_raise:node=scale:frame=2:times=1"},
            element_parameters={"on_error": "drop_frame"}))
        replica_process.run(in_thread=True)
        gateway_process = Process(transport_kind="loopback")
        gateway = Gateway(gateway_process)
        gateway.attach_replica(replica)
        gateway_process.run(in_thread=True)
        client = WireClient()
        try:
            client.create(gateway.topic_path, "s1")
            for frame_id in range(4):
                client.submit(gateway.topic_path, "s1", frame_id,
                              frame_id)
            wait_for(lambda: client.acked(
                [("s1", fid) for fid in range(4)]), timeout=30)
            with client.lock:
                assert client.responses[("s1", 2)][0][0] == "error"
            wait_for(lambda: recorder.dead_letters(), timeout=10)
            records = fetch_dead_letters(client.process, wait=10.0)
            assert len(records) == 1
            meta = records[0]["meta"]
            assert meta["stream_id"] == "s1"
            assert int(meta["frame_id"]) == 2
            assert meta["reason"] == "drop_frame"
            assert meta.get("data")    # small frame: inputs embedded
            # drain: destroy the errored stream, then replay the dead
            # letter through the gateway under a fresh stream
            client.destroy(gateway.topic_path, "s1")
            wait_for(lambda: "s1" not in gateway.streams, timeout=10)
            assert replay_dead_letter(client.process, records[0],
                                      gateway.topic_path,
                                      topic_response=client.topic)
            wait_for(lambda: client.responses.get(("s1", 2))
                     and client.responses[("s1", 2)][-1][0] == "ok",
                     timeout=30)
            with client.lock:
                status, outputs = client.responses[("s1", 2)][-1]
            assert np.allclose(np.asarray(outputs["y"]),
                               np.ones((1, 2), np.float32) * 20.0)
        finally:
            client.stop()
            for process in (gateway_process, replica_process,
                            recorder_process, registrar_process):
                process.terminate()


# -- delivered-floor dedupe compaction ---------------------------------------


class TestDeliveredFloor:
    def test_contiguous_prefix_collapses_into_floor(self):
        replica_process = Process(transport_kind="loopback")
        replica = create_pipeline(replica_process,
                                  _replica_definition("replica0"))
        replica_process.run(in_thread=True)
        gateway_process = Process(transport_kind="loopback")
        gateway = Gateway(gateway_process)
        gateway.attach_replica(replica)
        gateway_process.run(in_thread=True)
        try:
            responses = queue.Queue()
            gateway.submit_stream("s1", {}, queue_response=responses)
            wait_for(lambda: "s1" in gateway.streams, timeout=10)
            for frame_id in range(8):
                gateway.submit_frame("s1", _frame_data(frame_id))
            for _ in range(8):
                assert responses.get(timeout=30)[3] == "ok"
            stream = gateway.streams["s1"]
            wait_for(lambda: stream.delivered_floor == 7, timeout=10)
            assert stream.delivered == set()    # all collapsed
        finally:
            gateway_process.terminate()
            replica_process.terminate()
