# Native S-expression parser: build the C++ extension, then run the SAME
# corpus through the native and pure-Python parsers and require identical
# results (including error behavior).

import importlib

import pytest

from aiko_services_tpu.native.build import build
from aiko_services_tpu.utils import sexpr

CORPUS = [
    "",
    "(test)",
    "(add topic name protocol transport owner (a=b c=d))",
    "(process_frame (stream_id: 1 frame_id: 0) (a: 0))",
    '(say "hello world" "quo\\"ted")',
    "(share response/topic 300 *)",
    "(nested (a (b (c))) ())",
    "(mixed (a: 1) plain (b: 2))",
    "atom_only",
    "(numbers 1 2.5 -3 1e-6)",
    "(canon 5:ab cd x)",
    "(canon 3:\x00\x01\xff end)",
    "  ( spaced   out )  ",
    "(empty ())",
    "(keyword_odd a: 1 b:)",
]

MALFORMED = ["((((", "(unterminated", '("unclosed)', "(a) trailing",
             "(overrun 99:x)"]


@pytest.fixture(scope="module")
def native_parse():
    target = build(verbose=False)
    if target is None:
        pytest.skip("native toolchain unavailable")
    import aiko_services_tpu.native as native_package
    importlib.reload(native_package)
    if native_package.sexpr_parse_native is None:
        pytest.skip("extension failed to load")
    native_package.install_parse_error(sexpr.ParseError)
    return native_package.sexpr_parse_native


def test_native_matches_python_on_corpus(native_parse):
    for payload in CORPUS:
        expected = sexpr._parse_python(payload)
        actual = native_parse(payload)
        assert actual == expected, f"mismatch on {payload!r}"


def test_native_roundtrip_generate(native_parse):
    payload = sexpr.generate(
        "process_frame",
        [{"stream_id": "7", "frame_id": "3"}, {"x": "1", "y": "2"}])
    assert native_parse(payload) == sexpr._parse_python(payload)


def test_native_malformed_raises_parse_error(native_parse):
    for payload in MALFORMED:
        with pytest.raises(sexpr.ParseError):
            native_parse(payload)
        with pytest.raises(sexpr.ParseError):
            sexpr._parse_python(payload)


def test_native_binary_canonical_symbols(native_parse):
    blob = bytes(range(256)).decode("latin-1")
    payload = f"(blob {len(blob)}:{blob})"
    command, parameters = native_parse(payload)
    assert command == "blob"
    assert parameters[0] == blob


def test_native_faster_than_python(native_parse):
    import time
    payload = sexpr.generate(
        "add", ["namespace/host/1234/5", "pipeline_worker",
                "github.com/x/protocol/pipeline:0", "mqtt", "owner",
                ["ec=true", "stage=3"]])
    iterations = 3000
    start = time.perf_counter()
    for _ in range(iterations):
        native_parse(payload)
    native_seconds = time.perf_counter() - start
    start = time.perf_counter()
    for _ in range(iterations):
        sexpr._parse_python(payload)
    python_seconds = time.perf_counter() - start
    # regression guard only: native must not be slower
    assert native_seconds < python_seconds
