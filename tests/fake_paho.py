# In-memory paho-mqtt stand-in for exercising transport/mqtt.py without a
# broker or the paho package (VERDICT round-1 item 9: the MQTT transport
# had never executed).  Implements the slice of the paho 2.x client API
# that MqttTransport uses -- connect_async/loop_start, VERSION2 callbacks,
# will_set, publish/subscribe with MQTT wildcard semantics, retained
# messages -- against a process-local FakeMqttBroker that also simulates
# ABNORMAL drops (socket loss) so Last-Will semantics are testable.

from __future__ import annotations

import threading


class CallbackAPIVersion:
    VERSION1 = 1
    VERSION2 = 2


class _Message:
    __slots__ = ("topic", "payload", "retain")

    def __init__(self, topic: str, payload: bytes, retain: bool = False):
        self.topic = topic
        self.payload = payload
        self.retain = retain


def _matches(pattern: str, topic: str) -> bool:
    """MQTT wildcard match: + = one level, # = rest (must be last)."""
    p_parts = pattern.split("/")
    t_parts = topic.split("/")
    for index, part in enumerate(p_parts):
        if part == "#":
            return True
        if index >= len(t_parts):
            return False
        if part != "+" and part != t_parts[index]:
            return False
    return len(p_parts) == len(t_parts)


class FakeMqttBroker:
    """One broker per (host, port); retained store + LWT registry."""

    _brokers: dict = {}
    _lock = threading.Lock()

    def __init__(self):
        self.clients: list = []
        self.retained: dict[str, bytes] = {}
        self.log: list[tuple[str, bytes]] = []

    @classmethod
    def get(cls, host: str, port: int) -> "FakeMqttBroker":
        with cls._lock:
            return cls._brokers.setdefault((host, port), cls())

    @classmethod
    def reset_all(cls):
        with cls._lock:
            cls._brokers.clear()

    def attach(self, client):
        if client not in self.clients:
            self.clients.append(client)

    def detach(self, client):
        if client in self.clients:
            self.clients.remove(client)

    def publish(self, topic: str, payload: bytes, retain: bool):
        self.log.append((topic, payload))
        if retain:
            if payload in (b"", None):
                self.retained.pop(topic, None)
            else:
                self.retained[topic] = payload
        for client in list(self.clients):
            client._deliver(topic, payload)

    def deliver_retained(self, client, pattern: str):
        for topic, payload in list(self.retained.items()):
            if _matches(pattern, topic):
                client._deliver(topic, payload, force_pattern=pattern)

    def drop(self, client):
        """Simulate abnormal socket loss: fire the client's will."""
        self.detach(client)
        if client._will is not None:
            topic, payload, retain = client._will
            self.publish(topic, payload, retain)
        client._abnormal_disconnect()


class Client:
    """The paho 2.x surface MqttTransport touches."""

    def __init__(self, callback_api_version=CallbackAPIVersion.VERSION2):
        self.callback_api_version = callback_api_version
        self.on_connect = None
        self.on_disconnect = None
        self.on_message = None
        self._will = None
        self._broker: FakeMqttBroker | None = None
        self._subscriptions: set[str] = set()
        self._username = None
        self._password = None
        self._tls = False
        self._loop_running = False

    # -- configuration --------------------------------------------------

    def username_pw_set(self, username, password=None):
        self._username = username
        self._password = password

    def tls_set(self, *args, **kwargs):
        self._tls = True

    def will_set(self, topic, payload=None, qos=0, retain=False):
        payload = (payload.encode("latin-1")
                   if isinstance(payload, str) else (payload or b""))
        self._will = (topic, payload, retain)

    # -- connection lifecycle -------------------------------------------

    def connect_async(self, host, port=1883, keepalive=60):
        self._pending = (host, port)

    def loop_start(self):
        self._loop_running = True
        host, port = self._pending
        self._broker = FakeMqttBroker.get(host, port)
        self._broker.attach(self)
        if self.on_connect is not None:
            # VERSION2 signature: (client, userdata, flags, reason, props)
            self.on_connect(self, None, {}, 0, None)

    def loop_stop(self):
        self._loop_running = False

    def disconnect(self):
        # clean disconnect: NO will (MQTT spec)
        if self._broker is not None:
            self._broker.detach(self)
        if self.on_disconnect is not None:
            self.on_disconnect(self, None, {}, 0, None)

    def _abnormal_disconnect(self):
        if self.on_disconnect is not None:
            self.on_disconnect(self, None, {}, 1, None)

    # -- messaging ------------------------------------------------------

    def publish(self, topic, payload=None, qos=0, retain=False):
        payload = (payload.encode("latin-1")
                   if isinstance(payload, str) else (payload or b""))
        self._broker.publish(topic, payload, retain)

    def subscribe(self, topic, qos=0):
        self._subscriptions.add(topic)
        self._broker.deliver_retained(self, topic)

    def unsubscribe(self, topic):
        self._subscriptions.discard(topic)

    def _deliver(self, topic, payload, force_pattern=None):
        if self.on_message is None:
            return
        patterns = ([force_pattern] if force_pattern
                    else self._subscriptions)
        if any(_matches(pattern, topic) for pattern in patterns):
            self.on_message(self, None, _Message(topic, payload))
