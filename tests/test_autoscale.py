# Elastic replica fleet suite (ISSUE 7): load-driven autoscaling over
# the serving gateway -- watermark scale-up/down through a
# ReplicaFactory, warm-start replicas (persistent compile cache +
# live sibling weight hand-off over the transfer plane), loss-free
# scale-down through the shared failover migration path -- plus the
# satellite hooks: ProcessManager env overlay, the AIKO406 autoscale
# policy grammar, pool telemetry/dashboard/status surfacing.

import json
import os
import queue
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aiko_services_tpu import faults as faults_module
from aiko_services_tpu.pipeline import (
    PipelineElement, StreamEvent, create_pipeline)
from aiko_services_tpu.pipeline.tpu_element import ComputeElement
from aiko_services_tpu.runtime import (
    Process, ProcessManager, cache_stats, disable_compile_cache,
    enable_compile_cache)
from aiko_services_tpu.serve import (
    AutoScaler, Gateway, InProcessReplicaFactory, ProcessReplicaFactory,
    ScalePolicy)
from aiko_services_tpu.transport import reset_brokers
from helpers import wait_for


@pytest.fixture(autouse=True)
def clean():
    faults_module.reset_injector()
    reset_brokers()
    disable_compile_cache()
    yield
    faults_module.reset_injector()
    reset_brokers()
    disable_compile_cache()


class Scale(PipelineElement):
    """x -> x*10 (deterministic: migration replay must be
    bit-identical)."""

    def process_frame(self, stream, x):
        return StreamEvent.OKAY, {"y": x * 10.0}


class SlowScale(Scale):
    """Fixed host cost per frame so saturation (and therefore the
    autoscaler's utilization signal) is test-controlled."""

    def process_frame(self, stream, x):
        time.sleep(float(self.get_parameter("work_ms", 5, stream))
                   / 1000.0)
        return super().process_frame(stream, x)


class Affine(ComputeElement):
    """Stateful device element: y = x * w + b.  The state pytree is
    deliberately nested (dict + list) to exercise the hand-off tree
    walk."""

    def setup(self):
        return {"w": jnp.full((1, 2), 2.0, jnp.float32),
                "b": [jnp.zeros((1, 2), jnp.float32)]}

    def compute(self, state, x):
        return {"y": x * state["w"] + state["b"][0]}


class SlowAffine(Affine):
    """Affine plus a fixed host cost, so gateway load builds while the
    device math stays deterministic."""

    def process_frame(self, stream, **inputs):
        time.sleep(0.02)
        return super().process_frame(stream, **inputs)


def _definition(name, class_name="Scale", element="scale",
                element_parameters=None):
    return {
        "name": name,
        "graph": [f"({element})"],
        "elements": [
            {"name": element, "input": [{"name": "x"}],
             "output": [{"name": "y"}],
             "parameters": dict(element_parameters or {}),
             "deploy": {"local": {"module": "tests.test_autoscale",
                                  "class_name": class_name}}},
        ],
    }


def _frame(value):
    return {"x": np.ones((1, 2), np.float32) * value}


def _attach_pool(gateway, count, class_name="Scale",
                 element_parameters=None):
    """`count` in-process replicas attached directly (the fixed-pool
    baseline the autoscaler grows/shrinks)."""
    processes, replicas = [], []
    for index in range(count):
        process = Process(transport_kind="loopback")
        processes.append(process)
        pipeline = create_pipeline(process, _definition(
            f"replica{index}", class_name=class_name,
            element_parameters=element_parameters))
        replicas.append(pipeline)
        gateway.attach_replica(pipeline)
        process.run(in_thread=True)
    return processes, replicas


# -- policy grammar (AIKO406) ------------------------------------------------


class TestScalePolicy:
    def test_defaults_and_parse(self):
        policy = ScalePolicy.parse(None)
        assert (policy.min_replicas, policy.max_replicas) == (1, 2)
        policy = ScalePolicy.parse(
            "min_replicas=2;max_replicas=8;high_water=0.9;"
            "low_water=0.1;cooldown=3;drain_timeout=1;interval=0.25;"
            "warm_start=false")
        assert policy.max_replicas == 8
        assert policy.high_water == pytest.approx(0.9)
        assert policy.warm_start is False
        assert ScalePolicy.parse({"max_replicas": 3}).max_replicas == 3

    def test_cross_field_constraints_rejected(self):
        with pytest.raises(ValueError, match="must not exceed"):
            ScalePolicy.parse("min_replicas=4;max_replicas=2")
        with pytest.raises(ValueError, match="below"):
            ScalePolicy.parse("low_water=0.8;high_water=0.5")

    def test_construction_error_codes_match_offline_lint(self):
        from aiko_services_tpu.analyze.policies import (
            check_autoscale_policy)
        bad_value = "min_replicas=4;max_replicas=2"
        unknown = "replicas=4"
        process = Process(transport_kind="loopback")
        process.run(in_thread=True)
        with pytest.raises(ValueError, match="AIKO406"):
            Gateway(process, autoscale=bad_value)
        with pytest.raises(ValueError, match="AIKO404"):
            Gateway(process, name="gw2", autoscale=unknown)
        assert check_autoscale_policy(bad_value)[0][0] == "AIKO406"
        assert check_autoscale_policy(unknown)[0][0] == "AIKO404"
        assert check_autoscale_policy(
            "min_replicas=1;max_replicas=4") == []
        process.terminate()


# -- persistent compile cache ------------------------------------------------


class TestCompileCache:
    def test_hit_miss_counters_and_idempotence(self, tmp_path):
        directory = enable_compile_cache(str(tmp_path / "cache"))
        assert directory == str(tmp_path / "cache")
        assert enable_compile_cache(directory) == directory  # idempotent

        def fresh_program():
            # a NEW closure per call defeats the in-memory jit cache,
            # which is exactly a new replica's position
            def f(x):
                return jnp.sin(x) @ jnp.cos(x).T
            return jax.jit(f)

        before = cache_stats()
        fresh_program()(jnp.ones((32, 32))).block_until_ready()
        mid = cache_stats()
        assert mid["misses"] > before["misses"]  # cold: XLA compiled
        fresh_program()(jnp.ones((32, 32))).block_until_ready()
        after = cache_stats()
        assert after["hits"] > mid["hits"]       # warm: deserialized
        assert after["misses"] == mid["misses"]  # zero recompiles

    def test_disabled_without_directory(self):
        assert enable_compile_cache(None) is None
        assert cache_stats()["dir"] is None


# -- live weight hand-off ----------------------------------------------------


class TestWeightHandoff:
    def _pipeline(self, name):
        process = Process(transport_kind="loopback")
        pipeline = create_pipeline(process, _definition(
            name, class_name="Affine", element="affine"))
        process.run(in_thread=True)
        return process, pipeline

    def _serve_one(self, pipeline, value):
        responses = queue.Queue()
        stream = pipeline.create_stream(
            f"probe{value}", queue_response=responses)
        pipeline.create_frame(stream, _frame(value))
        outputs = responses.get(timeout=30)[2]
        pipeline.destroy_stream(f"probe{value}")
        return np.asarray(outputs["y"])

    def test_handoff_is_bit_identical_and_really_transfers(self):
        source_process, source = self._pipeline("source")
        sibling_process, sibling = self._pipeline("sibling")
        try:
            baseline = self._serve_one(source, 3.0)
            # mutate the source's params AFTER setup: a hand-off that
            # secretly re-ran setup() would reproduce the fresh init,
            # not these values
            element = source.elements["affine"]
            element.state = jax.tree_util.tree_map(
                lambda leaf: leaf * 3.0, element.state)
            mutated = self._serve_one(source, 3.0)
            assert not np.array_equal(baseline, mutated)

            exported = source.export_weights()
            assert set(exported) == {"affine"}
            # the descriptor tree is wire-safe (the OS-process path
            # ships it through a JSON file)
            exported = json.loads(json.dumps(exported))
            from aiko_services_tpu.observe.metrics import get_registry
            registry = get_registry()
            connections_before = registry.counter(
                "transfer.connections").value
            batched_before = registry.counter(
                "transfer.batched_fetches").value
            installed = sibling.import_weights(exported)
            assert installed == ["affine"]
            handed_off = self._serve_one(sibling, 3.0)
            assert np.array_equal(handed_off, mutated)  # bit-identical
            # the whole hand-off rode fetch_many: ONE connection per
            # producing peer, not one TCP handshake per leaf
            leaves = json.dumps(exported).count('"__tensorref__"')
            assert leaves >= 2
            connections = (registry.counter("transfer.connections").value
                           - connections_before)
            assert connections < leaves, (
                f"{connections} connections for {leaves} leaves: the "
                f"hand-off is not batching")
            assert (registry.counter("transfer.batched_fetches").value
                    > batched_before)
        finally:
            source_process.terminate()
            sibling_process.terminate()

    def test_missing_element_is_skipped_not_fatal(self):
        source_process, source = self._pipeline("source2")
        try:
            self._serve_one(source, 1.0)  # state exists only once served
            exported = source.export_weights()
            exported["ghost"] = exported["affine"]
            other_process, other = self._pipeline("other2")
            try:
                assert other.import_weights(exported) == ["affine"]
            finally:
                other_process.terminate()
        finally:
            source_process.terminate()


# -- scale up under load -----------------------------------------------------


class TestScaleUp:
    def test_overload_spawns_replica_and_completes_all(self):
        gateway_process = Process(transport_kind="loopback")
        gateway = Gateway(gateway_process,
                          policy="max_inflight=2;queue=128",
                          router_seed=7)
        processes, _ = _attach_pool(
            gateway, 1, class_name="SlowScale",
            element_parameters={"work_ms": 20})
        processes.append(gateway_process)
        factory = InProcessReplicaFactory(
            _definition("template", class_name="SlowScale",
                        element_parameters={"work_ms": 20}),
            warmup=_frame(0.0))
        gateway.enable_autoscale(
            "min_replicas=1;max_replicas=2;high_water=0.5;"
            "low_water=0.01;cooldown=0.2;interval=0.05;"
            "warm_start=false", factory)
        for process in processes:
            process.run(in_thread=True)
        try:
            responses = queue.Queue()
            streams_n, per_stream = 4, 8
            for index in range(streams_n):
                gateway.submit_stream(f"s{index}",
                                      queue_response=responses)
            for frame_id in range(per_stream):
                for index in range(streams_n):
                    gateway.submit_frame(f"s{index}", _frame(frame_id),
                                         frame_id=frame_id)
            # the burst saturates the single replica; the controller
            # must grow the pool without any manual attach
            wait_for(lambda: len(gateway.replicas) == 2, timeout=60)
            assert gateway.telemetry.scale_ups.value >= 1
            statuses = [responses.get(timeout=60)[3]
                        for _ in range(streams_n * per_stream)]
            assert statuses == ["ok"] * (streams_n * per_stream)
            spawn = gateway.autoscaler.spawns[0]
            assert spawn["time_to_healthy_ms"] > 0
            assert gateway.telemetry.last_time_to_healthy_ms is not None
            # new streams spread over the grown pool
            gateway.submit_stream("late", queue_response=responses)
            wait_for(lambda: "late" in gateway.streams, timeout=10)
        finally:
            # gateway first: its stop() retires every factory-owned
            # (autoscaler-spawned) replica process
            for process in reversed(processes):
                process.terminate()


# -- warm start --------------------------------------------------------------


class TestWarmStart:
    def test_warm_spawn_zero_recompiles_and_identical_outputs(
            self, tmp_path):
        cache_dir = str(tmp_path / "compile_cache")
        gateway_process = Process(transport_kind="loopback")
        gateway = Gateway(gateway_process,
                          policy="max_inflight=2;queue=256",
                          router_seed=7)
        factory = InProcessReplicaFactory(
            lambda name: _definition(name, class_name="SlowAffine",
                                     element="affine"),
            warmup=_frame(0.0), compile_cache=cache_dir)

        # replica0 comes up COLD through the same factory: it pays the
        # XLA compiles once and populates the shared cache
        cold_ready = queue.Queue()
        factory.spawn("replica0",
                      ready=lambda handle, info: cold_ready.put(
                          (handle, info)))
        handle0, info0 = cold_ready.get(timeout=120)
        assert handle0 is not None, info0
        assert info0["cache_misses"] > 0  # the cold arm really compiled
        gateway.attach_replica(handle0.pipeline)

        # mutate replica0's params so only a REAL hand-off can match
        element = handle0.pipeline.elements["affine"]
        element.state = jax.tree_util.tree_map(
            lambda leaf: leaf * 3.0, element.state)

        gateway.enable_autoscale(
            "min_replicas=1;max_replicas=2;high_water=0.5;"
            "low_water=0.01;cooldown=0.2;interval=0.05", factory)
        gateway_process.run(in_thread=True)
        try:
            responses = queue.Queue()
            streams_n, per_stream = 4, 6
            for index in range(streams_n):
                gateway.submit_stream(f"s{index}",
                                      queue_response=responses)
            for frame_id in range(per_stream):
                for index in range(streams_n):
                    gateway.submit_frame(f"s{index}", _frame(frame_id),
                                         frame_id=frame_id)
            wait_for(lambda: len(gateway.replicas) == 2, timeout=120)
            for _ in range(streams_n * per_stream):
                assert responses.get(timeout=120)[3] == "ok"
            spawn = gateway.autoscaler.spawns[0]
            assert spawn["warm"] is True
            assert spawn["imported_elements"] == ["affine"]
            # the warm-start proof: a populated compile cache + sibling
            # hand-off means the new replica served its warmup frame
            # with ZERO recompiles of fleet-known shapes
            assert spawn["cache_misses"] == 0, spawn
            assert spawn["cache_hits"] > 0, spawn
            assert gateway.telemetry.warm_spawns.value == 1

            warm_replica = next(
                replica for replica in gateway.replicas.values()
                if replica.name != "replica0")
            assert warm_replica.warm is True
            # hand-off correctness: the warm replica's outputs are
            # bit-identical to the mutated source, frame for frame
            probe = _frame(7.0)
            source_out = self._direct(handle0.pipeline, probe)
            warm_out = self._direct(warm_replica.pipeline, probe)
            assert np.array_equal(source_out, warm_out)
        finally:
            # gateway stop retires the autoscaler-spawned replica;
            # replica0 was factory-spawned directly, so it is ours
            gateway_process.terminate()
            handle0.process.terminate()

    @staticmethod
    def _direct(pipeline, frame_data):
        responses = queue.Queue()
        stream_id = f"direct_{pipeline.name}"
        stream = pipeline.create_stream(stream_id,
                                        queue_response=responses)
        pipeline.create_frame(stream, dict(frame_data))
        outputs = responses.get(timeout=60)[2]
        pipeline.destroy_stream(stream_id)
        return np.asarray(outputs["y"])


# -- loss-free scale-down ----------------------------------------------------


class TestScaleDown:
    def _run(self, drain_mid_stream: bool):
        """20 frames through a 2-replica pool; optionally drain the
        stream's pinned replica after frame 9 (extends the seeded
        replica_kill family: same harness, graceful trigger)."""
        gateway_process = Process(transport_kind="loopback")
        gateway = Gateway(gateway_process,
                          policy="max_inflight=4;queue=64",
                          router_seed=7)
        processes, _ = _attach_pool(gateway, 2)
        processes.append(gateway_process)
        for process in processes:
            process.run(in_thread=True)
        try:
            responses = queue.Queue()
            gateway.submit_stream("s1", {}, queue_response=responses)
            wait_for(lambda: "s1" in gateway.streams, timeout=10)
            owner = gateway.streams["s1"].replica.topic_path
            for frame_id in range(20):
                gateway.submit_frame("s1", _frame(frame_id))
                if drain_mid_stream and frame_id == 9:
                    # mailbox routing keeps the drain ordered with the
                    # in-flight submissions, like every other command
                    gateway.post_message("drain_replica", [owner])
            got = {}
            for _ in range(20):
                _, frame_id, outputs, status = responses.get(timeout=60)
                assert status == "ok"
                got[frame_id] = np.asarray(outputs["y"]).tolist()
            summary = gateway.telemetry.summary()
            return got, summary
        finally:
            for process in processes:
                process.terminate()

    def test_drain_mid_stream_is_bit_identical_to_unscaled_run(self):
        baseline, base_summary = self._run(False)
        reset_brokers()
        drained, drain_summary = self._run(True)
        assert set(drained) == set(baseline)   # zero lost frames
        assert drained == baseline             # bit-identical replay
        assert base_summary["pool_size"] == 2
        assert drain_summary["pool_size"] == 1
        assert drain_summary["completed"] == 20
        assert drain_summary["replica_deaths"] == 0  # graceful, not a death

    def test_low_watermark_drains_pool_to_min(self):
        gateway_process = Process(transport_kind="loopback")
        gateway = Gateway(gateway_process,
                          policy="max_inflight=4;queue=16")
        processes, _ = _attach_pool(gateway, 2)
        processes.append(gateway_process)
        gateway.enable_autoscale(
            "min_replicas=1;max_replicas=2;high_water=0.9;"
            "low_water=0.5;cooldown=0.1;interval=0.05;drain_timeout=0",
            None)
        for process in processes:
            process.run(in_thread=True)
        try:
            # idle pool: utilization 0 <= low_water -> drain ONE (min
            # floor holds the last replica)
            wait_for(lambda: len(gateway.replicas) == 1, timeout=30)
            time.sleep(0.3)  # more ticks must not dip below min
            assert len(gateway.replicas) == 1
            assert gateway.telemetry.scale_downs.value == 1
            # the pool still serves
            responses = queue.Queue()
            gateway.submit_stream("s", {}, queue_response=responses)
            gateway.submit_frame("s", _frame(1.0))
            assert responses.get(timeout=30)[3] == "ok"
        finally:
            for process in processes:
                process.terminate()


# -- pool observability ------------------------------------------------------


class TestPoolObservability:
    def test_summary_pool_and_dashboard_row_and_status(self):
        from aiko_services_tpu.dashboard import _gateway_plugin

        gateway_process = Process(transport_kind="loopback")
        gateway = Gateway(gateway_process,
                          policy="max_inflight=4;queue=16",
                          metrics_interval=0.2)
        processes, _ = _attach_pool(gateway, 2)
        processes.append(gateway_process)
        gateway.enable_autoscale(
            "min_replicas=2;max_replicas=2;high_water=0.9;"
            "low_water=0.01", None)
        for process in processes:
            process.run(in_thread=True)
        try:
            summary = gateway.telemetry.summary()
            assert summary["pool_size"] == 2
            assert set(summary["pool"]) == {"replica0", "replica1"}
            row = summary["pool"]["replica0"]
            assert row["state"] == "live"
            assert row["warm"] is False
            assert "inflight" in row and "queue_depth" in row

            class _Model:
                selected_share = {"replica_count": 2, "stream_count": 0,
                                  "policy": "", "metrics": summary}

            lines = _gateway_plugin(_Model())
            pool_lines = [line for line in lines if "pool:" in line]
            assert pool_lines and "scale_up" in pool_lines[0]
            assert any("replica0" in line and "cold" in line
                       for line in lines)
        finally:
            for process in processes:
                process.terminate()

    def test_system_status_pool_discovers_gateway(self, tmp_path):
        from click.testing import CliRunner
        from aiko_services_tpu.cli import main as cli_main
        from aiko_services_tpu.runtime import Registrar

        registrar_process = Process(transport_kind="loopback")
        Registrar(registrar_process, search_timeout=0.05)
        registrar_process.run(in_thread=True)
        gateway_process = Process(transport_kind="loopback")
        gateway = Gateway(gateway_process, metrics_interval=0.2)
        gateway_process.run(in_thread=True)
        try:
            wait_for(lambda: gateway.ec_producer is not None, timeout=10)
            result = CliRunner().invoke(cli_main, [
                "system", "status", "--pool", "--transport", "loopback",
                "--wait", "5", "--state-file",
                str(tmp_path / "none.json")])
            # success-path content ONLY: the no-discovery message also
            # contains the word "pool", which once masked a filter bug
            assert gateway.topic_path in result.output, result.output
            assert "replicas:" in result.output, result.output
            assert "no gateway services" not in result.output
        finally:
            gateway_process.terminate()
            registrar_process.terminate()


# -- satellites: ProcessManager env overlay + process factory glue -----------


class TestProcessManagerEnv:
    def test_env_overlay_merges_and_removes(self, monkeypatch):
        monkeypatch.setenv("AIKO_ENV_KEEP", "inherited")
        monkeypatch.setenv("AIKO_ENV_DROP", "doomed")
        exits = []
        manager = ProcessManager(
            lambda process_id, code: exits.append((process_id, code)))
        probe = ("import os, sys; sys.exit(0 if "
                 "os.environ.get('AIKO_ENV_NEW') == 'set' and "
                 "os.environ.get('AIKO_ENV_KEEP') == 'inherited' and "
                 "'AIKO_ENV_DROP' not in os.environ else 3)")
        manager.spawn("probe", sys.executable, arguments=["-c", probe],
                      use_interpreter=False,
                      env={"AIKO_ENV_NEW": "set", "AIKO_ENV_DROP": None})
        wait_for(lambda: exits, timeout=30)
        assert exits[0] == ("probe", 0)
        manager.terminate()

    def test_process_factory_spawn_env_and_handoff_file(self, tmp_path):
        """ProcessReplicaFactory glue, hermetically: the lifecycle
        manager is a recorder, so the test asserts exactly what a real
        spawn would inherit -- the compile-cache env overlay, the
        warm-weights descriptor file, and name-keyed retirement."""

        class _Recorder:
            def __init__(self):
                self.created, self.deleted = [], []

            def create_client(self, command, arguments,
                              use_interpreter=True, env=None):
                self.created.append((command, list(arguments), env))
                return len(self.created) - 1

            def delete_client(self, client_id):
                self.deleted.append(client_id)

        recorder = _Recorder()
        factory = ProcessReplicaFactory(
            recorder, "/tmp/defn.json", transport="mqtt",
            env={"JAX_PLATFORMS": "cpu"},
            compile_cache=str(tmp_path / "cache"))
        exports = {"affine": {"w": {"__tensorref__": {
            "host": "127.0.0.1", "port": 1, "key": "00" * 16,
            "dtype": "float32", "shape": [1, 2]}}}}
        launch = factory.spawn("gw-r1", warm_source=exports)
        launch.join(timeout=30)
        command, arguments, env = recorder.created[0]
        assert command == sys.executable
        assert arguments[:3] == ["-m", "aiko_services_tpu", "pipeline"]
        assert "--name" in arguments and "gw-r1" in arguments
        assert "--transport" in arguments and "mqtt" in arguments
        assert env["JAX_PLATFORMS"] == "cpu"
        assert env["AIKO_COMPILE_CACHE"] == str(tmp_path / "cache")
        with open(env["AIKO_WARM_WEIGHTS"]) as handoff:
            assert json.load(handoff) == exports
        os.unlink(env["AIKO_WARM_WEIGHTS"])
        factory.retire("gw-r1")
        assert recorder.deleted == [0]
        factory.retire("gw-r1")  # idempotent
        assert recorder.deleted == [0]
