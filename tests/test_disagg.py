# Prefill/decode disaggregation (ISSUE 12): PrefillEngine KV export ->
# batched transfer-plane fetch -> DecodeEngine.adopt_request, the
# AIKO408 disagg grammar, the gateway's two-pool scheduling, and the
# per-pool autoscaler signals.
#
# The acceptance invariant everywhere: tokens from the split fleet are
# BIT-IDENTICAL to the co-located continuous engine (which the decode
# suite pins to closed-batch generate()), and every failure mode --
# expired handoff keys, a dead prefill replica, an exhausted adopting
# pool -- degrades to a local re-prefill, never to a lost stream.

import queue

import numpy as np
import pytest

import jax

from aiko_services_tpu.decode import (
    DecodeEngine, PrefillEngine, fetch_kv_blocks)
from aiko_services_tpu.models import (
    TransformerConfig, generate, init_params)
from aiko_services_tpu.observe.metrics import get_registry
from aiko_services_tpu.pipeline import create_pipeline
from aiko_services_tpu.pipeline.transfer import (
    fetch_many, get_transfer_server, reset_transfer_server)
from aiko_services_tpu.runtime import Process
from aiko_services_tpu.serve import DisaggPolicy, Gateway
from aiko_services_tpu.transport import reset_brokers

from helpers import wait_for

ELEMENTS = "aiko_services_tpu.elements"

TINY = dict(vocab_size=64, n_layers=2, n_heads=2, n_kv_heads=2,
            d_model=32, d_ff=64, max_seq_len=64, dtype="float32")


@pytest.fixture(autouse=True)
def clean_brokers():
    reset_brokers()
    yield
    reset_brokers()


@pytest.fixture(scope="module")
def tiny_model():
    config = TransformerConfig(**TINY)
    return init_params(config, jax.random.PRNGKey(0)), config


def reference(params, config, prompt, max_new):
    out, _ = generate(params, config, np.asarray(prompt)[None],
                      max_new_tokens=max_new)
    return np.asarray(out)[0]


def run_split(params, config, prompts, max_new, *, adopt_timeout=5,
              prefill_kwargs=None, decode_kwargs=None):
    """Prefill every prompt on a PrefillEngine, adopt each handoff into
    a DecodeEngine, and drain; returns (handoffs, completions, engines)."""
    prefill = PrefillEngine(params, config, kv_block_size=8,
                            **(prefill_kwargs or {}))
    decode = DecodeEngine(params, config, decode_slots=len(prompts),
                          kv_block_size=8, **(decode_kwargs or {}))
    for index, prompt in enumerate(prompts):
        prefill.submit(index, prompt, max_new)
    handoffs = []
    while prefill.has_work():
        handoffs += prefill.step()
    done = {}
    for handoff in handoffs:
        report = decode.adopt_request(handoff["request_id"], handoff,
                                      timeout=adopt_timeout)
        for completion in report.completions:
            done[completion.request_id] = completion
    steps = 0
    while decode.has_work():
        for completion in decode.step().completions:
            done[completion.request_id] = completion
        steps += 1
        assert steps < 4000
    return handoffs, done, (prefill, decode)


# -- the round trip: export -> fetch -> adopt, bit-identical ----------------


class TestAdoptRoundTrip:
    PROMPT_LENGTHS = (5, 9, 12)

    @pytest.mark.parametrize("kv_dtype", ("", "int8"))
    def test_bit_identical_f32_and_int8(self, kv_dtype):
        """The tentpole invariant: adopted decode continues the
        migrated KV bit-identically to the co-located engine for both
        the f32 and the int8 (codes + scales) pool layouts."""
        config = TransformerConfig(**{**TINY, "kv_dtype": kv_dtype})
        params = init_params(config, jax.random.PRNGKey(0))
        rng = np.random.default_rng(7)
        prompts = [rng.integers(1, 64, size=n).astype(np.int32)
                   for n in self.PROMPT_LENGTHS]
        handoffs, done, (prefill, decode) = run_split(
            params, config, prompts, 8)
        assert len(done) == len(prompts)
        for index, prompt in enumerate(prompts):
            np.testing.assert_array_equal(
                done[index].tokens,
                reference(params, config, prompt, 8))
        assert decode.counters["adopted"] == len(prompts)
        assert decode.counters["adopt_fallbacks"] == 0
        assert decode.counters["kv_migrated_bytes"] > 0
        assert prefill.counters["exported"] == len(prompts)
        # every block returned on BOTH sides
        assert prefill.blocks.free_count == prefill.blocks.capacity
        assert decode.stats()["free_blocks"] == decode.blocks.capacity

    def test_chunked_prefill_export_matches(self, tiny_model):
        """A prefill replica running paged_prefill_chunk exports the
        same KV a monolithic prefill would: adopted output stays
        bit-identical."""
        params, config = tiny_model
        rng = np.random.default_rng(3)
        prompts = [rng.integers(1, 64, size=n).astype(np.int32)
                   for n in (21, 33)]
        handoffs, done, (prefill, _) = run_split(
            params, config, prompts, 6,
            prefill_kwargs={"prefill_chunk_size": 8})
        assert prefill.counters["chunks"] > 0
        for index, prompt in enumerate(prompts):
            np.testing.assert_array_equal(
                done[index].tokens,
                reference(params, config, prompt, 6))

    def test_handoff_survives_the_frame_codec_inert(self, tiny_model):
        """The wire-path regression: the frame codec must carry the
        handoff's KV descriptors INERT (raw descriptor dicts, not
        `__tensorref__` marker nodes the codec would eagerly
        materialize leaf-by-leaf on the event loop) so the adopting
        engine still does ONE batched fetch."""
        from aiko_services_tpu.pipeline.tensors import (
            decode_frame_data, encode_frame_data)
        params, config = tiny_model
        prompt = np.arange(1, 10, dtype=np.int32)
        prefill = PrefillEngine(params, config, kv_block_size=8)
        prefill.submit("r", prompt, 4)
        [handoff] = prefill.step()
        handoff = dict(handoff, request_id=0)
        wire = decode_frame_data(encode_frame_data(
            {"handoff": [handoff]}))
        record = wire["handoff"][0]
        assert isinstance(record["kv_blocks"][0]["k"], dict), (
            "codec materialized the KV descriptors")
        registry = get_registry()
        batched_before = registry.counter(
            "transfer.batched_fetches").value
        decode = DecodeEngine(params, config, decode_slots=1,
                              kv_block_size=8)
        report = decode.adopt_request("r", record, timeout=5)
        assert decode.counters["adopted"] == 1
        assert (registry.counter("transfer.batched_fetches").value
                == batched_before + 1)
        done = {c.request_id: c for c in report.completions}
        steps = 0
        while decode.has_work():
            for completion in decode.step().completions:
                done[completion.request_id] = completion
            steps += 1
            assert steps < 2000
        np.testing.assert_array_equal(
            done["r"].tokens, reference(params, config, prompt, 4))

    def test_handoff_is_json_safe(self, tiny_model):
        """The handoff record must survive the frame codec: a JSON
        round trip (what the wire path does) adopts identically."""
        import json
        params, config = tiny_model
        prompt = np.arange(1, 10, dtype=np.int32)
        prefill = PrefillEngine(params, config, kv_block_size=8)
        prefill.submit("r", prompt, 4)
        [handoff] = prefill.step()
        handoff = json.loads(json.dumps(
            {**handoff, "request_id": None}))
        decode = DecodeEngine(params, config, decode_slots=1,
                              kv_block_size=8)
        report = decode.adopt_request("r", handoff, timeout=5)
        done = {c.request_id: c for c in report.completions}
        steps = 0
        while decode.has_work():
            for completion in decode.step().completions:
                done[completion.request_id] = completion
            steps += 1
            assert steps < 2000
        np.testing.assert_array_equal(
            done["r"].tokens, reference(params, config, prompt, 4))
        assert decode.counters["adopted"] == 1


def test_adopt_mid_storm_zero_recompiles(tiny_model):
    """A request adopted INTO A BUSY engine mid-storm: co-scheduled
    slots keep decoding, outputs stay bit-identical, and the adoption
    triggers ZERO engine recompiles (the pool scatter is not an engine
    executable; the decode step shapes never change)."""
    params, config = tiny_model
    rng = np.random.default_rng(42)
    engine = DecodeEngine(params, config, decode_slots=3,
                          kv_block_size=8)
    # warmup: every bucket + the decode step
    for index, length in enumerate((3, 9, 17)):
        engine.submit(("warm", index),
                      np.arange(1, length + 1, dtype=np.int32), 3)
    while engine.has_work():
        engine.step()
    prefill = PrefillEngine(params, config, kv_block_size=8)
    warm_handoffs = []
    prefill.submit("warm_adopt", np.arange(1, 6, dtype=np.int32), 2)
    while prefill.has_work():
        warm_handoffs += prefill.step()
    engine.adopt_request("warm_adopt", warm_handoffs[0], timeout=5)
    while engine.has_work():
        engine.step()
    warm = engine.compile_count

    workload = {}
    done = {}
    adopted = 0
    submitted = 0
    while submitted < 12:
        length = int(rng.integers(1, 21))
        prompt = rng.integers(1, 64, size=length).astype(np.int32)
        max_new = int(rng.integers(2, 8))
        workload[submitted] = (prompt, max_new)
        if submitted % 3 == 0:
            # every third request arrives as a MIGRATION into the
            # running storm
            prefill.submit(submitted, prompt, max_new)
            while prefill.has_work():
                for handoff in prefill.step():
                    report = engine.adopt_request(
                        handoff["request_id"], handoff, timeout=5)
                    adopted += 1
                    for completion in report.completions:
                        done[completion.request_id] = completion
        else:
            engine.submit(submitted, prompt, max_new)
        submitted += 1
        for _ in range(int(rng.integers(1, 4))):
            for completion in engine.step().completions:
                done[completion.request_id] = completion
    steps = 0
    while engine.has_work():
        for completion in engine.step().completions:
            done[completion.request_id] = completion
        steps += 1
        assert steps < 4000
    assert adopted >= 3
    assert engine.counters["adopted"] >= 3
    for index, (prompt, max_new) in workload.items():
        np.testing.assert_array_equal(
            done[index].tokens,
            reference(params, config, prompt, max_new))
    assert engine.compile_count == warm, (
        f"adoption storm recompiled {engine.compile_count - warm} "
        f"signatures")


def test_adopt_failure_falls_back_to_local_prefill(tiny_model):
    """Expired transfer keys (the producer died / ttl lapsed) and a
    dead producer port both fall back to a LOCAL re-prefill through
    the ordinary admission path: the request still completes,
    bit-identical, and the granted blocks are returned first."""
    params, config = tiny_model
    prompt = np.arange(1, 10, dtype=np.int32)
    prefill = PrefillEngine(params, config, kv_block_size=8)
    prefill.submit("r", prompt, 5)
    [handoff] = prefill.step()
    # consume every key so the adopt-side fetch sees expired entries
    reset_transfer_server()
    decode = DecodeEngine(params, config, decode_slots=1,
                          kv_block_size=8)
    free_before = decode.blocks.free_count
    report = decode.adopt_request("r", handoff, timeout=1)
    assert decode.counters["adopt_fallbacks"] == 1
    assert decode.counters["adopted"] == 0
    assert decode.blocks.free_count == free_before  # grant returned
    done = {c.request_id: c for c in report.completions}
    steps = 0
    while decode.has_work():
        for completion in decode.step().completions:
            done[completion.request_id] = completion
        steps += 1
        assert steps < 2000
    np.testing.assert_array_equal(
        done["r"].tokens, reference(params, config, prompt, 5))

    # a block-size mismatch (mixed fleet) takes the same fallback
    other = DecodeEngine(params, config, decode_slots=1,
                         kv_block_size=16)
    prefill.submit("r2", prompt, 4)
    [handoff2] = prefill.step()
    other.adopt_request("r2", handoff2, timeout=1)
    assert other.counters["adopt_fallbacks"] == 1
    done2 = {}
    while other.has_work():
        for completion in other.step().completions:
            done2[completion.request_id] = completion
    np.testing.assert_array_equal(
        done2["r2"].tokens, reference(params, config, prompt, 4))


# -- fetch_many: the batched transfer path ----------------------------------


class TestFetchMany:
    def test_one_connection_per_peer_and_input_order(self):
        server = get_transfer_server()
        registry = get_registry()
        arrays = [np.full((64, 64), fill, np.float32)
                  for fill in range(7)]
        descriptors = [server.offer(array) for array in arrays]
        connections_before = registry.counter(
            "transfer.connections").value
        fetched = fetch_many(descriptors)
        connections = (registry.counter("transfer.connections").value
                       - connections_before)
        assert connections == 1, (
            f"{connections} connections for 7 same-peer descriptors")
        for array, result in zip(arrays, fetched):
            np.testing.assert_array_equal(array, result)

    def test_expired_key_raises_keyerror(self):
        server = get_transfer_server()
        good = server.offer(np.ones((64, 64), np.float32))
        bad = dict(good, key="f" * 32)
        with pytest.raises(KeyError):
            fetch_many([good, bad])

    def test_dead_peer_raises_transfer_error(self):
        from aiko_services_tpu.pipeline.transfer import TransferError
        descriptor = {"host": "127.0.0.1", "port": 1,
                      "key": "a" * 32, "dtype": "float32",
                      "shape": [2]}
        with pytest.raises(TransferError):
            fetch_many([descriptor], timeout=0.2, retries=0)


# -- the AIKO408 grammar -----------------------------------------------------


class TestDisaggGrammar:
    def test_policy_parses(self):
        policy = DisaggPolicy.parse(
            "adopt_timeout=2;min_replicas:prefill=1;"
            "min_replicas:decode=2")
        assert policy.adopt_timeout_s == 2.0
        assert policy.min_replicas == {"prefill": 1, "decode": 2}
        assert policy.role is None
        replica = DisaggPolicy.parse("role=prefill")
        assert replica.role == "prefill"

    def test_bad_specs_fail_like_lint(self):
        from aiko_services_tpu.analyze.policies import (
            check_decode_parameters, check_disagg_policy)
        with pytest.raises(ValueError, match="one of"):
            DisaggPolicy.parse("role=gpu")
        with pytest.raises(ValueError, match="replica-side"):
            DisaggPolicy.parse("role=prefill;adopt_timeout=2")
        problems = check_disagg_policy("adopt_timeout=-1")
        assert any(code == "AIKO408" for code, _ in problems)
        problems = check_disagg_policy("min_replicas:gpu=1")
        assert any(code == "AIKO404" for code, _ in problems)
        # element-level cross-field rules
        problems = check_decode_parameters({"role": "decode"})
        assert any(code == "AIKO408" for code, _ in problems)
        problems = check_decode_parameters(
            {"role": "prefill", "continuous": True})
        assert any(code == "AIKO408" for code, _ in problems)
        problems = check_decode_parameters(
            {"role": "prefill", "prefill_chunk_size": 16})
        assert problems == []  # the prefill engine chunks, no engine
        problems = check_decode_parameters(
            {"adopt_timeout": 2.0, "continuous": True})
        assert any(code == "AIKO408" for code, _ in problems)

    def test_gateway_construction_matches_lint(self):
        # same idiom as the AIKO403/406 construction tests: the
        # half-constructed gateways are abandoned with the process
        process = Process(transport_kind="loopback")
        with pytest.raises(ValueError, match="AIKO408"):
            Gateway(process, name="bad", disagg="adopt_timeout=-1")
        with pytest.raises(ValueError, match="AIKO404"):
            Gateway(process, name="bad2", disagg="warp=9")
        with pytest.raises(ValueError, match="AIKO408"):
            Gateway(process, name="bad3", disagg="role=prefill")


# -- gateway two-pool scheduling --------------------------------------------


LM_PARAMS = {"vocab_size": 300, "d_model": 32, "n_layers": 1,
             "n_heads": 2, "n_kv_heads": 1, "d_ff": 64,
             "max_seq_len": 128, "dtype": "float32",
             "max_new_tokens": 6}


def lm_definition(name, extra, prefill=False):
    if prefill:
        ports = {"input": [{"name": "tokens"}],
                 "output": [{"name": "handoff"}]}
        pipe_params = {"disagg": "role=prefill"}
    else:
        ports = {"input": [{"name": "tokens"},
                           {"name": "handoff", "optional": True}],
                 "output": [{"name": "generated"}]}
        pipe_params = {}
    return {
        "name": name,
        "graph": ["(lm)"],
        "parameters": pipe_params,
        "elements": [
            {"name": "lm", **ports,
             "parameters": {**LM_PARAMS, **extra},
             "deploy": {"local": {"module": ELEMENTS,
                                  "class_name": "LMGenerate"}}},
        ],
    }


def make_prefill_pipeline(process, name):
    return create_pipeline(process, lm_definition(
        name, {"role": "prefill", "kv_block_size": 8}, prefill=True))


def make_decode_pipeline(process, name):
    return create_pipeline(process, lm_definition(
        name, {"role": "decode", "continuous": True, "decode_slots": 4,
               "kv_block_size": 8, "adopt_timeout": 5}))


def closed_batch_reference(frames):
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, lm_definition("ref", {}))
    process.run(in_thread=True)
    responses = queue.Queue()
    stream = pipeline.create_stream("s", queue_response=responses,
                                    grace_time=300)
    for frame in frames:
        pipeline.create_frame(stream, {"tokens": frame})
    expected = [np.asarray(responses.get(timeout=120)[2]["generated"])
                for _ in frames]
    process.terminate()
    reset_brokers()
    return expected


class TestGatewayDisagg:
    def test_split_pools_bit_identical(self):
        """The serving-tier composition: a disagg gateway fronting a
        prefill pool and a decode pool serves the same completions as
        the plain pipeline -- streams pin to decode, prompts route to
        prefill, KV migrates over the transfer plane."""
        rng = np.random.default_rng(9)
        frames = [rng.integers(1, 300, size=(1, 6)).astype(np.int32)
                  for _ in range(4)]
        expected = closed_batch_reference(frames)

        processes = []
        prefill_process = Process(transport_kind="loopback")
        processes.append(prefill_process)
        prefill_pipe = make_prefill_pipeline(prefill_process, "pre0")
        decode_process = Process(transport_kind="loopback")
        processes.append(decode_process)
        decode_pipe = make_decode_pipeline(decode_process, "dec0")
        gateway_process = Process(transport_kind="loopback")
        processes.append(gateway_process)
        gateway = Gateway(gateway_process,
                          policy="max_inflight=8;queue=32",
                          disagg="adopt_timeout=5")
        gateway.attach_replica(prefill_pipe)   # role from the share
        gateway.attach_replica(decode_pipe)
        roles = {replica.name: replica.pool_role()
                 for replica in gateway.replicas.values()}
        assert roles == {"pre0": "prefill", "dec0": "decode"}
        for process in processes:
            process.run(in_thread=True)
        try:
            responses = queue.Queue()
            gateway.submit_stream("g1", {}, queue_response=responses)
            for frame_id, frame in enumerate(frames):
                gateway.submit_frame("g1", {"tokens": frame},
                                     frame_id=frame_id)
            got = {}
            for _ in frames:
                _, frame_id, outputs, status = responses.get(
                    timeout=120)
                assert status == "ok", (frame_id, outputs)
                got[frame_id] = np.asarray(outputs["generated"])
            for frame_id, reference_out in enumerate(expected):
                np.testing.assert_array_equal(got[frame_id],
                                              reference_out)
            # the data plane really split: prompts prefilled on the
            # prefill replica, KV migrated, decode adopted (slot-full
            # arrivals legitimately fall back)
            engine = decode_pipe.elements["lm"].engine_stats()
            assert engine["adopted"] >= 1
            assert engine["kv_migrated_bytes"] > 0
            prefill = prefill_pipe.elements["lm"].prefill_stats()
            assert prefill["exported"] == len(frames)
            assert gateway.telemetry.prefill_routed.value == len(frames)
            assert gateway.telemetry.kv_migrations.value == len(frames)
            snapshot = gateway.pool_snapshot()
            assert snapshot["pre0"]["role"] == "prefill"
            assert snapshot["dec0"]["role"] == "decode"
        finally:
            for process in processes:
                process.terminate()

    def test_prefill_replica_death_degrades_not_loses(self):
        """Killing the ONLY prefill replica mid-stream: in-flight and
        later frames all complete through the decode replica's local
        prefill -- bit-identical, zero lost frames."""
        rng = np.random.default_rng(11)
        frames = [rng.integers(1, 300, size=(1, 6)).astype(np.int32)
                  for _ in range(4)]
        expected = closed_batch_reference(frames)

        processes = []
        prefill_process = Process(transport_kind="loopback")
        processes.append(prefill_process)
        prefill_pipe = make_prefill_pipeline(prefill_process, "pre1")
        decode_process = Process(transport_kind="loopback")
        processes.append(decode_process)
        decode_pipe = make_decode_pipeline(decode_process, "dec1")
        gateway_process = Process(transport_kind="loopback")
        processes.append(gateway_process)
        gateway = Gateway(gateway_process,
                          policy="max_inflight=8;queue=32",
                          disagg="adopt_timeout=2")
        gateway.attach_replica(prefill_pipe)
        gateway.attach_replica(decode_pipe)
        for process in processes:
            process.run(in_thread=True)
        try:
            responses = queue.Queue()
            gateway.submit_stream("g1", {}, queue_response=responses)
            gateway.submit_frame("g1", {"tokens": frames[0]},
                                 frame_id=0)
            responses.get(timeout=120)
            # kill the prefill pool, then keep submitting
            gateway.post_message("_replica_lost", [
                prefill_pipe.topic_path, "test kill"])
            wait_for(lambda: prefill_pipe.topic_path
                     not in gateway.replicas, timeout=30)
            for frame_id, frame in enumerate(frames[1:], start=1):
                gateway.submit_frame("g1", {"tokens": frame},
                                     frame_id=frame_id)
            got = {0: None}
            for _ in frames[1:]:
                _, frame_id, outputs, status = responses.get(
                    timeout=120)
                assert status == "ok", (frame_id, outputs)
                got[frame_id] = np.asarray(outputs["generated"])
            for frame_id in range(1, len(frames)):
                np.testing.assert_array_equal(got[frame_id],
                                              expected[frame_id])
        finally:
            for process in processes:
                process.terminate()


# -- per-pool autoscaling ----------------------------------------------------


def test_autoscaler_scales_pools_on_their_own_signals():
    """With a disagg gateway and a factory dict, the controller reads
    each pool's OWN signal: prefill queue pressure spawns a prefill
    replica without touching the decode pool, and per-pool floors are
    repaired independently."""
    from aiko_services_tpu.serve import AutoScaler

    process = Process(transport_kind="loopback")
    gateway = Gateway(process, policy="max_inflight=2;queue=64",
                      disagg=("adopt_timeout=2;min_replicas:prefill=1;"
                              "min_replicas:decode=1"))
    process.run(in_thread=True)

    spawned = []

    class Factory:
        def __init__(self, role):
            self.role = role

        def spawn(self, name, warm_source=None, ready=None):
            spawned.append((self.role, name))
            return None

        def retire(self, handle):
            pass

    scaler = AutoScaler(
        gateway, "min_replicas=1;max_replicas=3;cooldown=0.05;"
        "interval=30;high_water=0.75",
        {"prefill": Factory("prefill"), "decode": Factory("decode")})
    gateway.autoscaler = scaler
    try:
        # empty fleet: BOTH pool floors repair, each through its own
        # factory
        scaler._tick()
        assert ("decode", f"{gateway.name}-decode-r1") in spawned
        scaler._tick()
        assert any(role == "prefill" for role, _ in spawned)
        assert scaler._pending_roles == {"prefill": 1, "decode": 1}
        # fake both pools healthy
        class Stub:
            consumer = None
            pipeline = None

            def __init__(self, role, topic):
                self.role_value, self.topic_path = role, topic
                self.name = topic
                self.outstanding = 0
                self.dead = self.draining = False
                self.streams = set()

            def pool_role(self):
                return self.role_value

            def reported_queue_depth(self):
                return 0

        for record in list(scaler._pending_spawns):
            scaler._close_pending(record)
        decode_replica = Stub("decode", "t/decode")
        prefill_replica = Stub("prefill", "t/prefill")
        gateway.replicas["t/decode"] = decode_replica
        gateway.replicas["t/prefill"] = prefill_replica
        spawned.clear()
        # prefill pressure only: fallbacks accumulated since the last
        # tick read as unmet prefill demand; decode stays idle
        gateway.telemetry.prefill_fallbacks.inc(8)
        import time as time_module
        time_module.sleep(0.06)     # clear both cooldowns
        scaler._tick()
        assert [role for role, _ in spawned] == ["prefill"], spawned
        # decode pressure only: outstanding frames over capacity
        for record in list(scaler._pending_spawns):
            scaler._close_pending(record)
        spawned.clear()
        decode_replica.outstanding = 4
        time_module.sleep(0.06)
        scaler._tick()
        assert [role for role, _ in spawned] == ["decode"], spawned
    finally:
        gateway.replicas.pop("t/decode", None)
        gateway.replicas.pop("t/prefill", None)
        scaler.stop()
        gateway.autoscaler = None
        process.terminate()


def test_import_weights_batches_connections():
    """Satellite: the warm-start hand-off path rides fetch_many -- one
    connection for a whole multi-leaf export (see also the autoscale
    suite's end-to-end assertion)."""
    server = get_transfer_server()
    registry = get_registry()
    from aiko_services_tpu.pipeline.transfer import TENSOR_REF_KEY
    leaves = {f"leaf{i}": np.full((32, 32), i, np.float32)
              for i in range(6)}
    tree = {name: {TENSOR_REF_KEY: server.offer(array)}
            for name, array in leaves.items()}
    descriptors = [node[TENSOR_REF_KEY] for node in tree.values()]
    before = registry.counter("transfer.connections").value
    fetched = fetch_many(descriptors)
    assert (registry.counter("transfer.connections").value
            - before) == 1
    for (name, array), result in zip(leaves.items(), fetched):
        np.testing.assert_array_equal(array, result)
