import queue
import time

import pytest

from aiko_services_tpu.pipeline import (
    AsyncHostElement, DefinitionError, PipelineElement, StreamEvent,
    StreamState, create_pipeline, parse_pipeline_definition)
from aiko_services_tpu.runtime import Process, Registrar
from aiko_services_tpu.transport import reset_brokers
from helpers import wait_for

ELEMENTS = "aiko_services_tpu.elements"


@pytest.fixture(autouse=True)
def clean_brokers():
    reset_brokers()
    yield
    reset_brokers()


def local(class_name):
    return {"local": {"module": ELEMENTS, "class_name": class_name}}


def text_pipeline_definition(items, transform="upper"):
    return {
        "name": "text_pipeline",
        "graph": ["(source (transform output))"],
        "elements": [
            {"name": "source", "output": [{"name": "text", "type": "str"}],
             "parameters": {"data_sources": items},
             "deploy": local("TextSource")},
            {"name": "transform",
             "input": [{"name": "text", "type": "str"}],
             "output": [{"name": "text", "type": "str"}],
             "parameters": {"transform": transform},
             "deploy": local("TextTransform")},
            {"name": "output",
             "input": [{"name": "text", "type": "str"}],
             "output": [{"name": "text", "type": "str"}],
             "deploy": local("TextOutput")},
        ],
    }


def drain(response_queue, count, timeout=5.0):
    results = []
    for _ in range(count):
        results.append(response_queue.get(timeout=timeout))
    return results


def test_definition_validation_rejects_unlinked_input():
    definition = text_pipeline_definition(["x"])
    definition["elements"][1]["input"] = [{"name": "nope", "type": "str"}]
    with pytest.raises(DefinitionError, match="nope"):
        parse_pipeline_definition(definition)


def test_definition_validation_rejects_unknown_node():
    definition = text_pipeline_definition(["x"])
    definition["graph"] = ["(source (transform missing_node))"]
    with pytest.raises(DefinitionError, match="missing_node"):
        parse_pipeline_definition(definition)


def test_text_pipeline_end_to_end_single_frame():
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, text_pipeline_definition(["hello"]))
    process.run(in_thread=True)
    responses = queue.Queue()
    pipeline.create_stream("s1", queue_response=responses)
    stream, frame, outputs = responses.get(timeout=5)
    assert outputs["text"] == "HELLO"
    assert frame.metrics["time_pipeline"] > 0
    assert "time_transform" in frame.metrics
    process.terminate()


def test_text_pipeline_multiple_frames_via_generator():
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(
        process, text_pipeline_definition(["a", "b", "c"], "upper"))
    process.run(in_thread=True)
    responses = queue.Queue()
    pipeline.create_stream("s1", queue_response=responses)
    results = drain(responses, 3)
    texts = sorted(outputs["text"] for _, _, outputs in results)
    assert texts == ["A", "B", "C"]
    # generator exhaustion destroys the stream
    wait_for(lambda: "s1" not in pipeline.streams)
    process.terminate()


def test_diamond_fanout_fanin_with_mapping():
    definition = {
        "name": "diamond",
        "graph": ["(source (add_a join) (add_b join))"],
        "elements": [
            {"name": "source", "output": [{"name": "number"}],
             "parameters": {"data_sources": [10]},
             "deploy": local("PE_Number")},
            {"name": "add_a", "input": [{"name": "number"}],
             "output": [{"name": "number"}],
             "map_out": {"number": "number_a"},
             "parameters": {"constant": 1},
             "deploy": local("PE_Add")},
            {"name": "add_b", "input": [{"name": "number"}],
             "output": [{"name": "number"}],
             "map_out": {"number": "number_b"},
             "parameters": {"constant": 100},
             "deploy": local("PE_Add")},
            {"name": "join", "input": [{"name": "a"}, {"name": "b"}],
             "output": [{"name": "number"}],
             "map_in": {"a": "number_a", "b": "number_b"},
             "deploy": local("PE_Sum2")},
        ],
    }
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, definition)
    process.run(in_thread=True)
    responses = queue.Queue()
    pipeline.create_stream("s1", queue_response=responses)
    _, _, outputs = responses.get(timeout=5)
    assert outputs["number"] == (10 + 1) + (10 + 100)
    process.terminate()


def test_drop_frame_skips_rest_of_graph():
    definition = {
        "name": "sampled",
        "graph": ["(source (sample output))"],
        "elements": [
            {"name": "source", "output": [{"name": "text"}],
             "parameters": {"data_sources": ["a", "b", "c", "d"],
                            "rate": 200},
             "deploy": local("TextSource")},
            {"name": "sample", "input": [{"name": "text"}],
             "output": [{"name": "text"}],
             "parameters": {"sample_rate": 2},
             "deploy": local("TextSample")},
            {"name": "output", "input": [{"name": "text"}],
             "output": [{"name": "text"}],
             "deploy": local("TextOutput")},
        ],
    }
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, definition)
    process.run(in_thread=True)
    responses = queue.Queue()
    pipeline.create_stream("s1", queue_response=responses)
    results = drain(responses, 2)
    texts = sorted(outputs["text"] for _, _, outputs in results)
    assert texts == ["a", "c"]  # every 2nd frame dropped
    process.terminate()


def test_element_error_destroys_stream():
    definition = text_pipeline_definition(["x"], transform="EXPLODE")
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, definition)
    process.run(in_thread=True)
    responses = queue.Queue()
    pipeline.create_stream("s1", queue_response=responses)
    wait_for(lambda: "s1" not in pipeline.streams)
    assert responses.empty()
    process.terminate()


def test_parameter_resolution_order():
    process = Process(transport_kind="loopback")
    definition = text_pipeline_definition(["x"])
    definition["parameters"] = {"transform": "lower"}   # pipeline level
    del definition["elements"][1]["parameters"]["transform"]
    pipeline = create_pipeline(process, definition)
    process.run(in_thread=True)
    responses = queue.Queue()

    # pipeline-level parameter applies
    pipeline.create_stream("s1", queue_response=responses)
    _, _, outputs = responses.get(timeout=5)
    assert outputs["text"] == "x"

    # stream-level parameter overrides pipeline level
    pipeline.create_stream("s2", parameters={"transform": "upper"},
                           queue_response=responses)
    _, _, outputs = responses.get(timeout=5)
    assert outputs["text"] == "X"

    # element-scoped stream parameter wins over bare stream parameter
    pipeline.create_stream(
        "s3", parameters={"transform": "upper",
                          "transform.transform": "title"},
        queue_response=responses)
    _, _, outputs = responses.get(timeout=5)
    assert outputs["text"] == "X"  # scoped key is "transform.transform"
    process.terminate()


def test_default_stream_auto_created():
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, text_pipeline_definition(["seed"]))
    process.run(in_thread=True)
    # inject a frame for the "*" stream without create_stream
    pipeline.process_frame({"stream_id": "*"}, {"text": "direct"})
    wait_for(lambda: "*" in pipeline.streams)
    process.terminate()


def test_remote_element_pause_resume():
    registrar_process = Process(transport_kind="loopback")
    Registrar(registrar_process, search_timeout=0.05)
    registrar_process.run(in_thread=True)

    remote_definition = {
        "name": "pipeline_b",
        "graph": ["(add)"],
        "elements": [
            {"name": "add", "input": [{"name": "number"}],
             "output": [{"name": "number"}],
             "parameters": {"constant": 5},
             "deploy": local("PE_Add")},
        ],
    }
    process_b = Process(transport_kind="loopback")
    create_pipeline(process_b, remote_definition)
    process_b.run(in_thread=True)

    local_definition = {
        "name": "pipeline_a",
        "graph": ["(source (remote_add (double)))"],
        "elements": [
            {"name": "source", "output": [{"name": "number"}],
             "parameters": {"data_sources": [7]},
             "deploy": local("PE_Number")},
            {"name": "remote_add", "input": [{"name": "number"}],
             "output": [{"name": "number"}],
             "deploy": {"remote": {
                 "service_filter": {"name": "pipeline_b"}}}},
            {"name": "double", "input": [{"name": "number"}],
             "output": [{"name": "number"}],
             "parameters": {"constant": 2},
             "deploy": local("PE_Multiply")},
        ],
    }
    process_a = Process(transport_kind="loopback")
    pipeline_a = create_pipeline(process_a, local_definition)
    process_a.run(in_thread=True)
    wait_for(lambda: pipeline_a.ready, timeout=10)

    responses = queue.Queue()
    pipeline_a.create_stream("s1", queue_response=responses)
    _, frame, outputs = responses.get(timeout=10)
    assert outputs["number"] == (7 + 5) * 2
    assert frame.paused_pe_name is None

    for process in (registrar_process, process_b, process_a):
        process.terminate()


def test_remote_drop_frame_releases_parked_parent_frame():
    """A frame dropped by a remote pipeline must not leak in the caller."""
    registrar_process = Process(transport_kind="loopback")
    Registrar(registrar_process, search_timeout=0.05)
    registrar_process.run(in_thread=True)

    remote_definition = {
        "name": "dropper",
        "graph": ["(sample)"],
        "elements": [
            {"name": "sample", "input": [{"name": "text"}],
             "output": [{"name": "text"}],
             "parameters": {"sample_rate": 2},
             "deploy": local("TextSample")},
        ],
    }
    process_b = Process(transport_kind="loopback")
    create_pipeline(process_b, remote_definition)
    process_b.run(in_thread=True)

    local_definition = {
        "name": "drop_caller",
        "graph": ["(remote_sample)"],
        "elements": [
            {"name": "remote_sample", "input": [{"name": "text"}],
             "output": [{"name": "text"}],
             "deploy": {"remote": {"service_filter": {"name": "dropper"}}}},
        ],
    }
    process_a = Process(transport_kind="loopback")
    pipeline_a = create_pipeline(process_a, local_definition)
    process_a.run(in_thread=True)
    wait_for(lambda: pipeline_a.ready, timeout=10)

    responses = queue.Queue()
    stream = pipeline_a.create_stream("s1", queue_response=responses)
    for index in range(4):
        pipeline_a.process_frame(
            {"stream_id": "s1"}, {"text": f"t{index}"})
    results = drain(responses, 2)
    texts = sorted(outputs["text"] for _, _, outputs in results)
    assert texts == ["t0", "t2"]
    # dropped frames released: nothing parked, pending back to zero
    wait_for(lambda: len(stream.frames) == 0)
    wait_for(lambda: stream.pending == 0)

    for process in (registrar_process, process_b, process_a):
        process.terminate()


def test_stream_lease_expires_without_frames():
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, text_pipeline_definition(["x"]))
    process.run(in_thread=True)
    responses = queue.Queue()
    pipeline.create_stream("short", grace_time=0.1,
                           queue_response=responses)
    responses.get(timeout=5)  # single frame flows, then stream idles
    wait_for(lambda: "short" not in pipeline.streams, timeout=5)
    process.terminate()


class SlowHostSink(AsyncHostElement):
    """Test double: a host-boundary element that blocks 0.2 s off-loop."""

    def process_async(self, stream, number):
        import time as time_module
        time_module.sleep(0.2)
        return {"number": int(number) * 10}


class ExplodingHostSink(AsyncHostElement):
    def process_async(self, stream, number):
        raise RuntimeError("host boundary failed")


def test_async_host_element_parks_and_resumes_with_map_out():
    definition = {
        "name": "async_pipe",
        "graph": ["(source (sink))"],
        "elements": [
            {"name": "source", "output": [{"name": "number"}],
             "parameters": {"data_sources": [7]},
             "deploy": local("PE_Number")},
            {"name": "sink", "input": [{"name": "number"}],
             "output": [{"name": "number"}],
             "map_out": {"number": "scaled"},
             "deploy": {"local": {"module": "tests.test_pipeline",
                                  "class_name": "SlowHostSink"}}},
        ],
    }
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, definition)
    process.run(in_thread=True)
    responses = queue.Queue()
    pipeline.create_stream("s1", queue_response=responses)
    _, frame, outputs = responses.get(timeout=10)
    assert outputs["scaled"] == 70
    assert frame.metrics["time_sink"] >= 0.2  # worker time recorded
    assert frame.paused_pe_name is None
    process.terminate()


def test_async_host_element_overlaps_frames():
    """Five frames through a 0.2 s host boundary must overlap (parked
    frames free the event loop), not serialize to >= 1 s."""
    definition = {
        "name": "overlap_pipe",
        "graph": ["(source (sink))"],
        "elements": [
            {"name": "source", "output": [{"name": "number"}],
             "parameters": {"data_sources": [1, 2, 3, 4, 5]},
             "deploy": local("PE_Number")},
            {"name": "sink", "input": [{"name": "number"}],
             "output": [{"name": "number"}],
             "parameters": {"workers": 5},
             "deploy": {"local": {"module": "tests.test_pipeline",
                                  "class_name": "SlowHostSink"}}},
        ],
    }
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, definition)
    process.run(in_thread=True)
    responses = queue.Queue()
    start = time.monotonic()
    pipeline.create_stream("s1", queue_response=responses)
    results = sorted(outputs["number"]
                     for _, _, outputs in drain(responses, 5))
    elapsed = time.monotonic() - start
    assert results == [10, 20, 30, 40, 50]
    assert elapsed < 0.8, f"frames serialized: {elapsed:.2f}s"
    process.terminate()


def test_async_host_element_error_releases_frame():
    definition = {
        "name": "boom_pipe",
        "graph": ["(source (sink))"],
        "elements": [
            {"name": "source", "output": [{"name": "number"}],
             "parameters": {"data_sources": [1]},
             "deploy": local("PE_Number")},
            {"name": "sink", "input": [{"name": "number"}],
             "output": [{"name": "number"}],
             "deploy": {"local": {"module": "tests.test_pipeline",
                                  "class_name": "ExplodingHostSink"}}},
        ],
    }
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, definition)
    process.run(in_thread=True)
    responses = queue.Queue()
    pipeline.create_stream("s1", queue_response=responses)
    wait_for(lambda: ("s1" not in pipeline.streams
                      or not pipeline.streams["s1"].frames), timeout=10)
    stream = pipeline.streams.get("s1")
    assert stream is None or not stream.frames  # no parked-frame leak
    process.terminate()


# -- micro-batching ----------------------------------------------------------

class BatchRecorder(PipelineElement):
    """Multiplies x by 10; records the leading (batch) size of every call
    on the stream (shared with the test; load_module imports a second
    copy of this module, so class attributes are NOT shared)."""

    def process_frame(self, stream, x):
        stream.variables.setdefault("batches", []).append(int(x.shape[0]))
        return StreamEvent.OKAY, {
            "y": x * 10, "tag": "shared",
            "nested": {"z": x + 1, "count": int(x.shape[0])},
            "labels": [f"row{i}" for i in range(x.shape[0])]}


class ExplodingBatcher(PipelineElement):
    def process_frame(self, stream, x):
        raise RuntimeError("bad batch")


def _micro_definition(micro_batch, class_name="BatchRecorder",
                      pad_full=True):
    return {
        "name": "micro_pipe",
        "graph": ["(batcher)"],
        "elements": [
            {"name": "batcher", "input": [{"name": "x"}],
             "output": [{"name": "y"}, {"name": "labels"},
                        {"name": "tag"}, {"name": "nested"}],
             "parameters": {"micro_batch": micro_batch,
                            "micro_batch_pad_full": pad_full},
             "deploy": {"local": {"module": "tests.test_pipeline",
                                  "class_name": class_name}}},
        ],
    }


def test_micro_batch_coalesces_queued_frames():
    """12 frames queued ahead of the event loop coalesce into 2 jit-sized
    calls (8-frame cap, then the 4 remaining), each frame getting exactly
    its own rows back."""
    import numpy as np
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, _micro_definition(micro_batch=8))
    responses = queue.Queue()
    stream = pipeline.create_stream("s1", queue_response=responses)
    for index in range(12):  # queued BEFORE the loop starts: all park
        pipeline.create_frame(
            stream, {"x": np.full((2, 3), float(index), np.float32)})
    process.run(in_thread=True)
    got = {}
    for _ in range(12):
        _, frame, outputs = responses.get(timeout=10)
        got[frame.frame_id] = outputs
    assert sorted(got) == list(range(12))
    for index in range(12):
        value = np.asarray(got[index]["y"])
        assert value.shape == (2, 3)
        assert float(value[0, 0]) == index * 10  # own rows, not a neighbor's
        assert got[index]["tag"] == "shared"  # non-batch output shared
        nested = got[index]["nested"]  # dicts split recursively per frame
        assert np.asarray(nested["z"]).shape == (2, 3)
        assert float(np.asarray(nested["z"])[0, 0]) == index + 1
        pos = index if index < 8 else index - 8  # row slice within group
        assert got[index]["labels"] == [f"row{2 * pos}", f"row{2 * pos + 1}"]
    # both groups pad to the FULL micro_batch rows (8 frames x 2 = 16):
    # the 4-frame remainder reuses the steady-state compilation
    assert stream.variables["batches"] == [16, 16], stream.variables
    assert "s1" not in pipeline.streams or not pipeline.streams["s1"].frames
    process.terminate()


def test_micro_batch_single_frame_latency_path():
    """An unloaded stream must run batches of one (no waiting for more
    frames); with pad_full off the call is genuinely single-row."""
    import numpy as np
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(
        process, _micro_definition(micro_batch=8, pad_full=False))
    process.run(in_thread=True)
    responses = queue.Queue()
    stream = pipeline.create_stream("s1", queue_response=responses)
    for index in range(3):
        pipeline.create_frame(
            stream, {"x": np.ones((1, 3), np.float32) * index})
        _, frame, outputs = responses.get(timeout=10)
        assert float(np.asarray(outputs["y"])[0, 0]) == index * 10
    assert stream.variables["batches"] == [1, 1, 1], stream.variables
    process.terminate()


def test_micro_batch_error_releases_all_parked_frames():
    import numpy as np
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(
        process, _micro_definition(micro_batch=4,
                                   class_name="ExplodingBatcher"))
    responses = queue.Queue()
    stream = pipeline.create_stream("s1", queue_response=responses)
    for _ in range(3):
        pipeline.create_frame(stream, {"x": np.zeros((2, 2), np.float32)})
    process.run(in_thread=True)
    wait_for(lambda: ("s1" not in pipeline.streams
                      or not pipeline.streams["s1"].frames), timeout=10)
    stream = pipeline.streams.get("s1")
    assert stream is None or not stream.frames  # no parked-frame leak
    assert not pipeline._micro_pending
    process.terminate()


def test_micro_batch_mixed_shapes_group_separately():
    """Frames whose trailing shapes differ must not concatenate."""
    import numpy as np
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, _micro_definition(micro_batch=8))
    responses = queue.Queue()
    stream = pipeline.create_stream("s1", queue_response=responses)
    shapes = [(2, 3), (2, 3), (2, 5), (2, 5), (2, 3)]
    for index, shape in enumerate(shapes):
        pipeline.create_frame(
            stream, {"x": np.full(shape, float(index), np.float32)})
    process.run(in_thread=True)
    seen = {}
    for _ in range(len(shapes)):
        _, frame, outputs = responses.get(timeout=10)
        seen[frame.frame_id] = np.asarray(outputs["y"]).shape
    assert seen == {0: (2, 3), 1: (2, 3), 2: (2, 5), 3: (2, 5), 4: (2, 3)}
    # gather-by-signature: [0,1,4] coalesce (same trailing shape, FIFO
    # by first occurrence) and [2,3] separately, each padded to the full
    # 16 rows (one compilation per trailing shape)
    assert stream.variables["batches"] == [16, 16], stream.variables
    process.terminate()


# -- fan-out branch concurrency ----------------------------------------------

class SlowBranch(AsyncHostElement):
    def process_async(self, stream, number):
        time.sleep(0.3)
        stream.variables.setdefault("slow_done", []).append(
            time.monotonic())
        return {"slow": number * 2}


class FastBranch(PipelineElement):
    def process_frame(self, stream, number):
        stream.variables.setdefault("fast_ran", []).append(
            time.monotonic())
        return StreamEvent.OKAY, {"fast": number + 1}


class Join2(PipelineElement):
    def process_frame(self, stream, slow, fast):
        return StreamEvent.OKAY, {"joined": slow + fast}


def test_parked_branch_does_not_block_siblings():
    """A slow async branch must not delay its SIBLING's dispatch (the
    reference executes branches sequentially; here the fast branch runs
    while the slow one is parked), and the join still waits for both."""
    definition = {
        "name": "fanout_pipe",
        "graph": ["(source (slow join) (fast join))"],
        "elements": [
            {"name": "source", "output": [{"name": "number"}],
             "parameters": {"data_sources": [10]},
             "deploy": local("PE_Number")},
            {"name": "slow", "input": [{"name": "number"}],
             "output": [{"name": "slow"}],
             "deploy": {"local": {"module": "tests.test_pipeline",
                                  "class_name": "SlowBranch"}}},
            {"name": "fast", "input": [{"name": "number"}],
             "output": [{"name": "fast"}],
             "deploy": {"local": {"module": "tests.test_pipeline",
                                  "class_name": "FastBranch"}}},
            {"name": "join", "input": [{"name": "slow"}, {"name": "fast"}],
             "output": [{"name": "joined"}],
             "deploy": {"local": {"module": "tests.test_pipeline",
                                  "class_name": "Join2"}}},
        ],
    }
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, definition)
    process.run(in_thread=True)
    responses = queue.Queue()
    pipeline.create_stream("s1", queue_response=responses)
    stream, frame, outputs = responses.get(timeout=10)
    assert outputs["joined"] == (10 * 2) + (10 + 1)
    fast_ran = stream.variables["fast_ran"][0]
    slow_done = stream.variables["slow_done"][0]
    # the fast sibling executed while the slow branch was still parked
    assert fast_ran < slow_done - 0.25, (fast_ran, slow_done)
    assert not frame.pending_nodes
    process.terminate()


class SlowRewriter(AsyncHostElement):
    """Rewrites the SAME key it consumes (text -> text) off-loop."""

    def process_async(self, stream, text):
        time.sleep(0.2)
        return {"text": f"GENERATED({text})"}


class TextTap(PipelineElement):
    def process_frame(self, stream, text):
        stream.variables.setdefault("seen", []).append(text)
        return StreamEvent.OKAY, {"final": text}


def test_descendant_of_pending_branch_defers_on_rewritten_key():
    """A consumer downstream of an in-flight async element that REWRITES
    a key it consumes (text -> text) must wait for the rewrite -- a swag
    hit on the stale pre-branch value is not input availability (graph
    order defines the data dependency)."""
    definition = {
        "name": "rewrite_pipe",
        "graph": ["(source (rewriter (consumer)))"],
        "elements": [
            {"name": "source", "output": [{"name": "text"}],
             "parameters": {"data_sources": ["PROMPT"]},
             "deploy": local("TextSource")},
            {"name": "rewriter", "input": [{"name": "text"}],
             "output": [{"name": "text"}],
             "deploy": {"local": {"module": "tests.test_pipeline",
                                  "class_name": "SlowRewriter"}}},
            {"name": "consumer", "input": [{"name": "text"}],
             "output": [{"name": "final"}],
             "deploy": {"local": {"module": "tests.test_pipeline",
                                  "class_name": "TextTap"}}},
        ],
    }
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, definition)
    process.run(in_thread=True)
    responses = queue.Queue()
    pipeline.create_stream("s1", queue_response=responses)
    stream, _, outputs = responses.get(timeout=10)
    assert outputs["final"] == "GENERATED(PROMPT)"
    assert stream.variables["seen"] == ["GENERATED(PROMPT)"]
    process.terminate()


class ParkForever(PipelineElement):
    """Custom element that parks the frame and never resumes it itself
    (a misbehaving PENDING element)."""

    def process_frame(self, stream, number):
        return StreamEvent.PENDING, {}


def test_unroutable_response_arms_watchdog_then_releases_frame():
    """An UN-NAMED process_frame_response with two nameless parks in
    flight is unroutable; it must not kill the frame instantly (could be
    a stale/duplicate reply while healthy branches are in flight) but a
    watchdog must RELEASE the frame (freeing its backpressure slot) if
    nothing resumes it, not leave it parked forever."""
    definition = {
        "name": "ambiguous_pipe",
        "graph": ["(source (park_a) (park_b))"],
        "elements": [
            {"name": "source", "output": [{"name": "number"}],
             "parameters": {"data_sources": [1]},
             "deploy": local("PE_Number")},
            {"name": "park_a", "input": [{"name": "number"}],
             "output": [{"name": "a"}],
             "deploy": {"local": {"module": "tests.test_pipeline",
                                  "class_name": "ParkForever"}}},
            {"name": "park_b", "input": [{"name": "number"}],
             "output": [{"name": "b"}],
             "deploy": {"local": {"module": "tests.test_pipeline",
                                  "class_name": "ParkForever"}}},
        ],
    }
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, definition)
    process.run(in_thread=True)
    responses = queue.Queue()
    stream = pipeline.create_stream(
        "s1", queue_response=responses,
        parameters={"park_timeout": 0.3})
    wait_for(lambda: 0 in stream.frames
             and len(stream.frames[0].pending_nodes) == 2,
             timeout=10)
    # un-named response: with two response-capable parks, unroutable
    pipeline.process_frame_response(
        {"stream_id": "s1", "frame_id": 0}, "")
    # NOT released synchronously: a duplicate reply must not kill a
    # healthy frame -- the watchdog is armed instead
    assert 0 in stream.frames
    assert stream.frames[0].park_watchdog is not None
    wait_for(lambda: not stream.frames, timeout=10)
    assert not stream.frames     # frame released, not leaked
    assert stream.pending == 0   # backpressure slot reclaimed
    assert "s1" in pipeline.streams  # stream survives (frame-level error)
    process.terminate()


def test_unnamed_response_routes_to_single_remaining_park():
    """After a named async branch resumes (clearing the fallback slot),
    an un-named response with exactly ONE remaining response-capable park
    is unambiguous and must route to it -- not be dropped."""
    definition = {
        "name": "single_park_pipe",
        "graph": ["(source (a) (b))"],
        "elements": [
            {"name": "source", "output": [{"name": "number"}],
             "parameters": {"data_sources": [4]},
             "deploy": local("PE_Number")},
            {"name": "a", "input": [{"name": "number"}],
             "output": [{"name": "number"}],
             "map_out": {"number": "scaled"},
             "deploy": {"local": {"module": "tests.test_pipeline",
                                  "class_name": "SlowHostSink"}}},
            {"name": "b", "input": [{"name": "number"}],
             "output": [{"name": "b"}],
             "deploy": {"local": {"module": "tests.test_pipeline",
                                  "class_name": "ParkForever"}}},
        ],
    }
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, definition)
    process.run(in_thread=True)
    responses = queue.Queue()
    stream = pipeline.create_stream("s1", queue_response=responses)
    # wait until the named async branch (a) resumed: only b remains
    wait_for(lambda: 0 in stream.frames
             and stream.frames[0].pending_nodes == {"b"}
             and stream.frames[0].paused_pe_name is None,
             timeout=10)
    pipeline.process_frame_response(
        {"stream_id": "s1", "frame_id": 0}, {"b": 99})
    _, frame, outputs = responses.get(timeout=10)
    assert outputs["scaled"] == 40
    assert outputs["b"] == 99
    assert not frame.pending_nodes
    process.terminate()


def test_park_watchdog_scoped_to_doubtful_parks():
    """Watchdog expiry must only kill the frame if the parks that were
    IN DOUBT at arming are still pending -- a later healthy park (slow
    async element) outliving the timeout is not a leak."""
    definition = {
        "name": "scoped_watchdog_pipe",
        "graph": ["(source (a (slow)) (b))"],
        "elements": [
            {"name": "source", "output": [{"name": "number"}],
             "parameters": {"data_sources": [3]},
             "deploy": local("PE_Number")},
            {"name": "a", "input": [{"name": "number"}],
             "output": [{"name": "number"}],
             "map_out": {"number": "routed"},
             "deploy": {"local": {"module": "tests.test_pipeline",
                                  "class_name": "ParkForever"}}},
            {"name": "b", "input": [{"name": "number"}],
             "output": [{"name": "b"}],
             "deploy": {"local": {"module": "tests.test_pipeline",
                                  "class_name": "ParkForever"}}},
            {"name": "slow", "input": [{"name": "number"}],
             "output": [{"name": "number"}],
             "map_in": {"number": "routed"},
             "map_out": {"number": "scaled"},
             "deploy": {"local": {"module": "tests.test_pipeline",
                                  "class_name": "SlowHostSink"}}},
        ],
    }
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, definition)
    process.run(in_thread=True)
    responses = queue.Queue()
    stream = pipeline.create_stream(
        "s1", queue_response=responses,
        parameters={"park_timeout": 0.1})
    wait_for(lambda: 0 in stream.frames
             and {"a", "b"} <= stream.frames[0].pending_nodes,
             timeout=10)
    # stray un-named response: ambiguous over {a, b} -> watchdog armed
    pipeline.process_frame_response({"stream_id": "s1", "frame_id": 0}, "")
    assert stream.frames[0].park_watchdog is not None
    # both doubtful parks then resolve NAMED; "slow" (0.2 s async, longer
    # than park_timeout) runs after -- the watchdog must not kill it
    pipeline.process_frame_response(
        {"stream_id": "s1", "frame_id": 0, "node": "a"}, {"number": 5})
    pipeline.process_frame_response(
        {"stream_id": "s1", "frame_id": 0, "node": "b"}, {"b": 1})
    _, frame, outputs = responses.get(timeout=10)
    assert outputs["scaled"] == 50   # slow ran to completion
    assert outputs["b"] == 1
    process.terminate()


def test_stale_unnamed_response_dropped_when_only_async_parks():
    """An un-named reply while only ASYNC parks are in flight cannot be
    theirs (async replies always name their node): it must be dropped as
    stale, and the frame must complete with the REAL branch outputs."""
    definition = {
        "name": "stale_pipe",
        "graph": ["(source (a) (b))"],
        "elements": [
            {"name": "source", "output": [{"name": "number"}],
             "parameters": {"data_sources": [6]},
             "deploy": local("PE_Number")},
            {"name": "a", "input": [{"name": "number"}],
             "output": [{"name": "number"}],
             "map_out": {"number": "a10"},
             "deploy": {"local": {"module": "tests.test_pipeline",
                                  "class_name": "SlowHostSink"}}},
            {"name": "b", "input": [{"name": "number"}],
             "output": [{"name": "number"}],
             "map_out": {"number": "b10"},
             "deploy": {"local": {"module": "tests.test_pipeline",
                                  "class_name": "SlowHostSink"}}},
        ],
    }
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, definition)
    process.run(in_thread=True)
    responses = queue.Queue()
    stream = pipeline.create_stream("s1", queue_response=responses)
    wait_for(lambda: 0 in stream.frames
             and len(stream.frames[0].pending_nodes) == 2,
             timeout=10)
    # stray un-named reply with a poison payload: must NOT be merged
    pipeline.process_frame_response(
        {"stream_id": "s1", "frame_id": 0}, {"number": -999})
    assert stream.frames[0].park_watchdog is None  # no watchdog either
    _, frame, outputs = responses.get(timeout=10)
    assert outputs["a10"] == 60 and outputs["b10"] == 60  # real replies
    process.terminate()


class SharedMatrixBatcher(PipelineElement):
    """Returns a per-row output AND a matrix whose leading dim equals the
    coalesced batch size but is NOT batch-major ("batched": false)."""

    def process_frame(self, stream, x):
        import numpy as np
        n = int(x.shape[0])
        return StreamEvent.OKAY, {
            "y": x * 10,
            "affinity": np.eye(n, dtype=np.float32)}


def test_micro_batch_shared_output_not_split():
    """An output port declared "batched": false is shared whole by every
    coalesced frame even when its leading dim equals the batch size."""
    import numpy as np
    definition = {
        "name": "shared_pipe",
        "graph": ["(batcher)"],
        "elements": [
            {"name": "batcher", "input": [{"name": "x"}],
             "output": [{"name": "y"},
                        {"name": "affinity", "batched": False}],
             "parameters": {"micro_batch": 4},
             "deploy": {"local": {"module": "tests.test_pipeline",
                                  "class_name": "SharedMatrixBatcher"}}},
        ],
    }
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, definition)
    responses = queue.Queue()
    stream = pipeline.create_stream("s1", queue_response=responses)
    for index in range(4):  # queued before the loop: coalesce to one call
        pipeline.create_frame(
            stream, {"x": np.full((1, 2), float(index), np.float32)})
    process.run(in_thread=True)
    for _ in range(4):
        _, frame, outputs = responses.get(timeout=10)
        # per-row output split: one row each
        assert np.asarray(outputs["y"]).shape == (1, 2)
        assert float(np.asarray(outputs["y"])[0, 0]) == frame.frame_id * 10
        # NxN matrix (N == coalesced batch) arrives WHOLE, not sliced
        assert np.asarray(outputs["affinity"]).shape == (4, 4)
    process.terminate()


def test_micro_batch_coalesces_across_streams():
    """The serving scenario: N streams, one small frame each, coalescing
    into ONE jit call at the shared element, with each frame's rows
    routed back to ITS stream's response queue."""
    import numpy as np
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, _micro_definition(micro_batch=8))
    queues = {}
    for index in range(4):
        sid = f"s{index}"
        queues[sid] = queue.Queue()
        stream = pipeline.create_stream(sid, queue_response=queues[sid])
        pipeline.create_frame(
            stream, {"x": np.full((2, 3), float(index), np.float32)})
    process.run(in_thread=True)
    for index in range(4):
        sid = f"s{index}"
        stream, frame, outputs = queues[sid].get(timeout=10)
        assert stream.stream_id == sid          # per-stream routing
        value = np.asarray(outputs["y"])
        assert value.shape == (2, 3)
        assert float(value[0, 0]) == index * 10  # own rows, not a neighbor's
    # all four streams' frames ran as ONE coalesced call (4 x 2 rows,
    # padded to the full 8 x 2 = 16)
    batches = []
    for sid in queues:
        stream = pipeline.streams.get(sid)
        if stream and "batches" in stream.variables:
            batches.extend(stream.variables["batches"])
    assert batches == [16], batches
    process.terminate()


def test_micro_batch_param_fingerprint_segregates_streams():
    """Streams resolving the element's parameters differently must NOT
    share a jit call (the element reads parameters from one lead
    stream)."""
    import numpy as np
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, _micro_definition(micro_batch=8))
    responses = queue.Queue()
    s_default = pipeline.create_stream("plain", queue_response=responses)
    s_scoped = pipeline.create_stream(
        "tuned", queue_response=responses,
        parameters={"batcher.gain": 5})  # element-scoped override
    for stream in (s_default, s_scoped):
        pipeline.create_frame(
            stream, {"x": np.ones((2, 3), np.float32)})
    process.run(in_thread=True)
    seen = set()
    for _ in range(2):
        stream, _, _ = responses.get(timeout=10)
        seen.add(stream.stream_id)
    assert seen == {"plain", "tuned"}
    # two separate coalesced calls: the fingerprints differ
    lead_batches = []
    for sid in ("plain", "tuned"):
        stream = pipeline.streams.get(sid)
        if stream and "batches" in stream.variables:
            lead_batches.extend(stream.variables["batches"])
    assert lead_batches == [16, 16], lead_batches
    process.terminate()

def test_micro_batch_undeclared_param_segregates_streams():
    """ADVICE r4 (medium): a per-stream override of a knob the element
    reads via get_parameter(name, default) that is DECLARED NOWHERE
    (neither element- nor pipeline-level, not node-prefixed) must still
    block cross-stream coalescing -- the old declared-only fingerprint
    silently shared one jit call resolved under the lead stream's
    values."""
    import numpy as np
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, _micro_definition(micro_batch=8))
    responses = queue.Queue()
    s_default = pipeline.create_stream("plain", queue_response=responses)
    s_tuned = pipeline.create_stream(
        "tuned", queue_response=responses,
        parameters={"gain": 5})  # bare key, declared nowhere
    for stream in (s_default, s_tuned):
        pipeline.create_frame(
            stream, {"x": np.ones((2, 3), np.float32)})
    process.run(in_thread=True)
    seen = set()
    for _ in range(2):
        stream, _, _ = responses.get(timeout=10)
        seen.add(stream.stream_id)
    assert seen == {"plain", "tuned"}
    lead_batches = []
    for sid in ("plain", "tuned"):
        stream = pipeline.streams.get(sid)
        if stream and "batches" in stream.variables:
            lead_batches.extend(stream.variables["batches"])
    # two separate coalesced calls, NOT one shared one
    assert lead_batches == [16, 16], lead_batches
    process.terminate()


def test_micro_batch_array_param_fingerprint_by_content():
    """ADVICE r4 (medium): ndarray-valued stream parameters fingerprint
    by CONTENT; repr() truncates large arrays, letting different values
    compare equal and share a call."""
    import numpy as np
    big_a = np.zeros(10_000, np.float32)
    big_b = np.zeros(10_000, np.float32)
    big_b[5_000] = 1.0  # differs only in repr's truncated middle
    assert repr(big_a) == repr(big_b)  # the failure mode being fixed
    from aiko_services_tpu.pipeline.pipeline import _canonical_value
    assert _canonical_value(big_a) != _canonical_value(big_b)
    assert _canonical_value(big_a) == _canonical_value(np.zeros(
        10_000, np.float32))
    # dict ordering is canonical
    assert _canonical_value({"a": 1, "b": 2}) == _canonical_value(
        {"b": 2, "a": 1})


def test_micro_batch_per_signature_capacity_flush():
    """ADVICE r4 (low): capacity must count per SIGNATURE -- two
    interleaved shape cohorts at micro_batch=4 each fill to a full
    4-frame group instead of chronically flushing 2+2 partials when the
    combined count hits 4."""
    import numpy as np
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, _micro_definition(micro_batch=4))
    responses = queue.Queue()
    stream = pipeline.create_stream("s1", queue_response=responses)
    shapes = [(2, 3), (2, 5)] * 4  # A B A B A B A B
    for index, shape in enumerate(shapes):
        pipeline.create_frame(
            stream, {"x": np.full(shape, float(index), np.float32)})
    process.run(in_thread=True)
    for _ in range(len(shapes)):
        responses.get(timeout=10)
    # one FULL group per cohort (4 frames x 2 rows = 8), not 4 partials
    assert stream.variables["batches"] == [8, 8], stream.variables
    process.terminate()


def test_micro_batch_capacity_flush_keeps_window_for_other_cohort():
    """A capacity flush of one ripe signature must leave the hold-down
    window covering the OTHER cohort's parked frames -- they flush at
    the window deadline, not never (starvation guard for the
    per-signature capacity fix)."""
    import numpy as np
    process = Process(transport_kind="loopback")
    definition = _micro_definition(micro_batch=4)
    definition["elements"][0]["parameters"]["micro_batch_wait_ms"] = 150
    pipeline = create_pipeline(process, definition)
    responses = queue.Queue()
    stream = pipeline.create_stream("s1", queue_response=responses)
    process.run(in_thread=True)
    # cohort B: two frames (below capacity), then cohort A fills to 4
    # while B's window is open
    for shape in [(2, 5), (2, 5), (2, 3), (2, 3), (2, 3), (2, 3)]:
        pipeline.create_frame(
            stream, {"x": np.zeros(shape, np.float32)})
    got = 0
    deadline = time.monotonic() + 20
    while got < 6 and time.monotonic() < deadline:
        try:
            responses.get(timeout=5)
            got += 1
        except queue.Empty:
            break
    assert got == 6, f"only {got}/6 frames returned (cohort starved?)"
    # cohort A (4 frames) flushed at capacity as one full group (8
    # rows); cohort B (2 frames) flushed by the window timer, padded to
    # full (8 rows)
    assert sorted(stream.variables["batches"]) == [8, 8], stream.variables
    process.terminate()


# -- fused whole-group execution ----------------------------------------------

class FusedRecorder(PipelineElement):
    """Same math on both paths: chained process_frame multiplies by 10
    (and records the coalesced batch size); group_kernel exposes the
    identical math as a pure kernel.  kernel_traces counts TRACE-time
    executions of the kernel body -- one per compiled (names, arity,
    shapes) signature -- so tests can assert partial groups reuse the
    steady-state executable."""

    def __init__(self, *args):
        super().__init__(*args)
        self.kernel_traces = 0
        self._kernel = None

    def process_frame(self, stream, x):
        stream.variables.setdefault("batches", []).append(int(x.shape[0]))
        return StreamEvent.OKAY, {
            "y": x * 10.0, "nested": {"z": x + 1.0}}

    def group_kernel(self, stream):
        if self._kernel is None:
            def kernel(context, x):
                self.kernel_traces += 1  # runs at trace time only
                return {"y": x * 10.0, "nested": {"z": x + 1.0}}

            self._kernel = kernel
        return self._kernel, ()


class BrokenKernelRecorder(FusedRecorder):
    def group_kernel(self, stream):
        raise RuntimeError("no kernel today")


class AsyncWithKernel(AsyncHostElement):
    def process_async(self, stream, x):
        return {"y": x}

    def group_kernel(self, stream):
        return (lambda context, x: {"y": x}), ()


def _fused_definition(micro_batch, fused=True,
                      class_name="FusedRecorder"):
    return {
        "name": "fused_pipe",
        "graph": ["(batcher)"],
        "elements": [
            {"name": "batcher", "input": [{"name": "x"}],
             "output": [{"name": "y"}, {"name": "nested"}],
             "parameters": {"micro_batch": micro_batch,
                            "micro_batch_fused": fused},
             "deploy": {"local": {"module": "tests.test_pipeline",
                                  "class_name": class_name}}},
        ],
    }


def _run_fused_pipe(definition, frames):
    """Queue `frames` before the event loop starts (all park), return
    {frame_id: outputs} plus the pipeline for introspection."""
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, definition)
    responses = queue.Queue()
    stream = pipeline.create_stream("s1", queue_response=responses)
    for frame_data in frames:
        pipeline.create_frame(stream, frame_data)
    process.run(in_thread=True)
    got = {}
    for _ in range(len(frames)):
        _, frame, outputs = responses.get(timeout=30)
        got[frame.frame_id] = outputs
    return got, pipeline, stream, process


def test_fused_group_matches_chained_bit_for_bit():
    """The tentpole correctness gate: the fused concat+kernel+split
    program must produce byte-identical outputs to the chained
    jitted-concat -> process_frame -> jitted-split path."""
    import numpy as np
    frames = [{"x": np.full((2, 3), float(index), np.float32)}
              for index in range(6)]
    fused_got, fused_pipe, fused_stream, p1 = _run_fused_pipe(
        _fused_definition(micro_batch=4, fused=True), frames)
    chained_got, _, chained_stream, p2 = _run_fused_pipe(
        _fused_definition(micro_batch=4, fused=False), frames)
    assert sorted(fused_got) == sorted(chained_got) == list(range(6))
    for index in range(6):
        for key_path in (("y",), ("nested", "z")):
            fused_value = fused_got[index]
            chained_value = chained_got[index]
            for key in key_path:
                fused_value = fused_value[key]
                chained_value = chained_value[key]
            fused_value = np.asarray(fused_value)
            chained_value = np.asarray(chained_value)
            assert fused_value.dtype == chained_value.dtype
            assert fused_value.shape == chained_value.shape
            assert fused_value.tobytes() == chained_value.tobytes()
    # the fused arm never entered process_frame; the chained arm did
    assert "batches" not in fused_stream.variables
    assert chained_stream.variables["batches"] == [8, 8]
    assert fused_pipe.elements["batcher"].kernel_traces >= 1
    p1.terminate()
    p2.terminate()


def test_fused_partial_group_reuses_compilation():
    """Partial (rampup/drain) groups pad the entry list with fillers to
    the full micro arity, so the fused program compiles ONCE: a full
    4-frame group and a later 2-frame partial share the executable."""
    import numpy as np
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, _fused_definition(micro_batch=4))
    responses = queue.Queue()
    stream = pipeline.create_stream("s1", queue_response=responses)
    for index in range(4):  # full group
        pipeline.create_frame(
            stream, {"x": np.full((2, 3), float(index), np.float32)})
    process.run(in_thread=True)
    for _ in range(4):
        responses.get(timeout=30)
    element = pipeline.elements["batcher"]
    assert element.kernel_traces == 1
    # drain tail: 2 frames park and flush as a PARTIAL group
    for index in range(4, 6):
        pipeline.create_frame(
            stream, {"x": np.full((2, 3), float(index), np.float32)})
    got = {}
    for _ in range(2):
        _, frame, outputs = responses.get(timeout=30)
        got[frame.frame_id] = outputs
    for index in (4, 5):  # own rows, not a filler's zeros
        assert float(np.asarray(got[index]["y"])[0, 0]) == index * 10
    assert element.kernel_traces == 1, (
        "partial group recompiled instead of reusing the padded arity")
    process.terminate()


def test_fused_falls_back_when_kernel_raises():
    """A raising group_kernel must degrade to the chained path (frames
    still complete), not error the stream."""
    import numpy as np
    frames = [{"x": np.full((1, 2), float(index), np.float32)}
              for index in range(3)]
    got, _, stream, process = _run_fused_pipe(
        _fused_definition(micro_batch=4,
                          class_name="BrokenKernelRecorder"), frames)
    for index in range(3):
        assert float(np.asarray(got[index]["y"])[0, 0]) == index * 10
    assert stream.variables["batches"] == [4]  # chained path ran
    process.terminate()


def test_fused_shared_output_not_split():
    """Ports declared "batched": false arrive whole from the fused
    program, matching the chained path's shared-output contract."""
    import numpy as np
    definition = {
        "name": "fused_shared",
        "graph": ["(batcher)"],
        "elements": [
            {"name": "batcher", "input": [{"name": "x"}],
             "output": [{"name": "y"},
                        {"name": "affinity", "batched": False}],
             "parameters": {"micro_batch": 4},
             "deploy": {"local": {"module": "tests.test_pipeline",
                                  "class_name": "FusedAffinity"}}},
        ],
    }
    frames = [{"x": np.full((1, 2), float(index), np.float32)}
              for index in range(4)]
    got, _, _, process = _run_fused_pipe(definition, frames)
    for index in range(4):
        assert np.asarray(got[index]["y"]).shape == (1, 2)
        assert float(np.asarray(got[index]["y"])[0, 0]) == index * 10
        # (N, N) matrix with N == coalesced batch arrives WHOLE
        assert np.asarray(got[index]["affinity"]).shape == (4, 4)
    process.terminate()


class FusedAffinity(PipelineElement):
    def process_frame(self, stream, x):
        raise AssertionError("fused path must not call process_frame")

    def group_kernel(self, stream):
        import jax.numpy as jnp

        def kernel(context, x):
            n = x.shape[0]
            return {"y": x * 10.0, "affinity": jnp.eye(n)}

        return kernel, ()


def test_async_host_element_group_kernel_rejected():
    """AsyncHostElement work leaves the event loop -- a group kernel on
    one is a contract violation, rejected at pipeline build time."""
    definition = _fused_definition(micro_batch=4,
                                   class_name="AsyncWithKernel")
    process = Process(transport_kind="loopback")
    process.run(in_thread=True)
    with pytest.raises(TypeError, match="group kernel"):
        create_pipeline(process, definition)
    process.terminate()


class FusedListBatcher(PipelineElement):
    """Returns a batched output as a per-row Python LIST on both paths
    (the chained split slices host lists of length == target; the fused
    split must match)."""

    def process_frame(self, stream, x):
        return StreamEvent.OKAY, {
            "rows": [x[index] * 2.0 for index in range(x.shape[0])]}

    def group_kernel(self, stream):
        if not hasattr(self, "_kernel"):
            def kernel(context, x):
                return {"rows": [x[index] * 2.0
                                 for index in range(x.shape[0])]}

            self._kernel = kernel
        return self._kernel, ()


def test_fused_list_output_sliced_per_frame_like_chained():
    import numpy as np
    frames = [{"x": np.full((2, 3), float(index), np.float32)}
              for index in range(4)]

    def run(fused):
        definition = _fused_definition(micro_batch=4, fused=fused,
                                       class_name="FusedListBatcher")
        definition["elements"][0]["output"] = [{"name": "rows"}]
        got, _, _, process = _run_fused_pipe(definition, frames)
        process.terminate()
        return got

    fused_got = run(True)
    chained_got = run(False)
    for index in range(4):
        for arm_got in (fused_got, chained_got):
            rows = arm_got[index]["rows"]
            assert len(rows) == 2  # own per-row slice, not all 8
            assert float(np.asarray(rows[0])[0]) == index * 2
        for fused_row, chained_row in zip(fused_got[index]["rows"],
                                          chained_got[index]["rows"]):
            assert (np.asarray(fused_row).tobytes()
                    == np.asarray(chained_row).tobytes())


class TwoKernelRecorder(FusedRecorder):
    """One cached kernel PER value of the per-stream "mode" parameter
    (the SpeechToText/LMGenerate caching shape): alternating cohorts
    must not evict each other's fused programs."""

    def group_kernel(self, stream):
        mode = int(self.get_parameter("mode", 1, stream))
        kernels = getattr(self, "_kernels", None)
        if kernels is None:
            kernels = self._kernels = {}
        kernel = kernels.get(mode)
        if kernel is None:
            def kernel(context, x, _mode=mode):
                self.kernel_traces += 1  # trace-time only
                return {"y": x * (10.0 * _mode),
                        "nested": {"z": x + 1.0}}

            kernels[mode] = kernel
        return kernel, ()


def test_fused_program_cache_survives_alternating_cohorts():
    """Two parameter-fingerprint cohorts with DIFFERENT kernels on one
    element: each keeps its own compiled fused program (per-node dict,
    not a single slot) -- alternation must not retrace per group."""
    import numpy as np
    process = Process(transport_kind="loopback")
    definition = _fused_definition(micro_batch=2,
                                   class_name="TwoKernelRecorder")
    pipeline = create_pipeline(process, definition)
    responses = queue.Queue()
    streams = {}
    for sid, mode in (("m1", 1), ("m2", 2)):
        streams[sid] = pipeline.create_stream(
            sid, queue_response=responses,
            parameters={} if mode == 1 else {"mode": mode})
    process.run(in_thread=True)
    element = pipeline.elements["batcher"]
    for round_index in range(3):  # alternating cohort traffic
        for sid in ("m1", "m2"):
            for _ in range(2):
                pipeline.create_frame(
                    streams[sid],
                    {"x": np.full((1, 3), 1.0, np.float32)})
        for _ in range(4):
            stream, _, outputs = responses.get(timeout=30)
            expected = 10.0 if stream.stream_id == "m1" else 20.0
            assert float(np.asarray(outputs["y"])[0, 0]) == expected
    # one trace per kernel, not one per group
    assert element.kernel_traces == 2, element.kernel_traces
    process.terminate()


class MalformedKernelRecorder(FusedRecorder):
    def group_kernel(self, stream):
        # contract violation: bare callable instead of (kernel, context)
        return lambda context, x: {"y": x * 10.0}


def test_fused_falls_back_on_malformed_kernel_spec():
    """A group_kernel returning something other than (kernel, context)
    must degrade to the chained path -- never strand the parked frames
    (they are already popped from _micro_pending when the group runs)."""
    import numpy as np
    frames = [{"x": np.full((1, 2), float(index), np.float32)}
              for index in range(3)]
    got, pipeline, stream, process = _run_fused_pipe(
        _fused_definition(micro_batch=4,
                          class_name="MalformedKernelRecorder"), frames)
    for index in range(3):
        assert float(np.asarray(got[index]["y"])[0, 0]) == index * 10
    assert stream.variables["batches"] == [4]  # chained path ran
    assert not pipeline._fused_programs
    process.terminate()
