# TTS / classical vision / robot seat tests (VERDICT round-1 items 7 +
# missing #6): the Coqui-seat TextToSpeech chain, face + ArUco detectors
# with the overlay contract, and the simulated robot actor driven by
# (action ...) commands.

import queue
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aiko_services_tpu.models.tts import (
    TTSConfig, encode_chars, init_tts_params, synthesize, synthesize_mel)


class TestTTS:
    CONFIG = TTSConfig(d_model=64, n_conv_layers=2, n_mels=40, n_fft=256,
                       hop=128, frames_per_char=4, griffin_lim_iters=8)

    def test_mel_shapes(self):
        params = init_tts_params(self.CONFIG, jax.random.PRNGKey(0))
        chars = encode_chars("hello world", max_len=16)
        mel = synthesize_mel(params, self.CONFIG, jnp.asarray(chars))
        assert mel.shape == (1, 40, 16 * 4)

    def test_waveform_end_to_end(self):
        params = init_tts_params(self.CONFIG, jax.random.PRNGKey(0))
        chars = encode_chars("aloha honua", max_len=16)
        waveform = synthesize(params, self.CONFIG, jnp.asarray(chars))
        samples = (16 * 4 - 1) * 128 + 256
        assert waveform.shape == (1, samples)
        wave = np.asarray(waveform)
        assert np.isfinite(wave).all()
        assert np.abs(wave).max() <= 1.0 + 1e-5
        assert np.abs(wave).max() > 1e-3  # actually produced signal

    def test_deterministic(self):
        params = init_tts_params(self.CONFIG, jax.random.PRNGKey(0))
        chars = jnp.asarray(encode_chars("abc", max_len=8))
        a = np.asarray(synthesize(params, self.CONFIG, chars))
        b = np.asarray(synthesize(params, self.CONFIG, chars))
        np.testing.assert_array_equal(a, b)

    def test_element_in_pipeline(self):
        from aiko_services_tpu.runtime import Process
        from aiko_services_tpu.pipeline import create_pipeline
        definition = {
            "name": "tts_pipe",
            "graph": ["(text (speak))"],
            "elements": [
                {"name": "text", "output": [{"name": "text"}],
                 "parameters": {"data_sources": ["hello"]},
                 "deploy": {"local": {
                     "module": "aiko_services_tpu.elements",
                     "class_name": "TextSource"}}},
                {"name": "speak", "input": [{"name": "text"}],
                 "output": [{"name": "audio"},
                            {"name": "sample_rate"}],
                 "parameters": {"d_model": 64, "n_conv_layers": 2,
                                "frames_per_char": 4,
                                "griffin_lim_iters": 4,
                                "max_chars": 16},
                 "deploy": {"local": {
                     "module": "aiko_services_tpu.elements",
                     "class_name": "TextToSpeech"}}},
            ],
        }
        process = Process(transport_kind="loopback")
        pipeline = create_pipeline(process, definition)
        process.run(in_thread=True)
        responses = queue.Queue()
        pipeline.create_stream("s1", queue_response=responses)
        _, _, outputs = responses.get(timeout=60)
        audio = np.asarray(outputs["audio"])
        assert audio.ndim == 2 and audio.shape[1] > 1000
        assert outputs["sample_rate"] == 16000
        assert np.isfinite(audio).all()
        process.terminate()


class TestVision:
    def test_aruco_detects_rendered_marker(self):
        cv2 = pytest.importorskip("cv2")
        from aiko_services_tpu.elements.vision import ArucoDetect
        dictionary = cv2.aruco.getPredefinedDictionary(
            cv2.aruco.DICT_4X4_50)
        marker = cv2.aruco.generateImageMarker(dictionary, 7, 120)
        canvas = np.full((300, 300), 255, np.uint8)
        canvas[90:210, 90:210] = marker
        element = ArucoDetect.__new__(ArucoDetect)
        element._detector = None
        element.get_parameter = (
            lambda name, default=None, stream=None: default)
        _, outputs = ArucoDetect.process_frame(element, None, canvas)
        assert outputs["markers"]["ids"] == [7]
        detections = outputs["detections"]
        assert bool(detections["valid"][0])
        assert int(detections["classes"][0]) == 7
        x0, y0, x1, y1 = detections["boxes"][0]
        assert 80 <= x0 <= 100 and 200 <= x1 <= 220
        assert outputs["overlay"]["objects"][0]["name"] == "aruco_7"

    def test_aruco_no_markers(self):
        pytest.importorskip("cv2")
        from aiko_services_tpu.elements.vision import ArucoDetect
        element = ArucoDetect.__new__(ArucoDetect)
        element._detector = None
        element.get_parameter = (
            lambda name, default=None, stream=None: default)
        _, outputs = ArucoDetect.process_frame(
            element, None, np.zeros((64, 64), np.uint8))
        assert outputs["markers"]["ids"] == []
        assert not outputs["detections"]["valid"].any()

    @staticmethod
    def _face_element():
        from aiko_services_tpu.elements.vision import FaceDetect
        element = FaceDetect.__new__(FaceDetect)
        element._cascade = None
        element.get_parameter = (
            lambda name, default=None, stream=None: default)
        return element

    @staticmethod
    def _face_image():
        """Skin-tone ellipse (a face-shaped blob) on a blue background,
        CHW float -- the Detector-side image convention."""
        height, width = 120, 160
        yy, xx = np.mgrid[0:height, 0:width]
        ellipse = (((yy - 60) / 35.0) ** 2
                   + ((xx - 80) / 25.0) ** 2) <= 1.0
        image = np.zeros((height, width, 3), np.float32)
        image[...] = (0.1, 0.2, 0.8)                   # background
        image[ellipse] = (224 / 255, 160 / 255, 130 / 255)  # skin
        return image.transpose(2, 0, 1)

    def test_face_detect_finds_skin_ellipse(self):
        from aiko_services_tpu.elements.vision import FaceDetect
        element = self._face_element()
        _, outputs = FaceDetect.process_frame(
            element, None, self._face_image())
        objects = outputs["overlay"]["objects"]
        assert len(objects) == 1 and objects[0]["name"] == "face"
        rect = outputs["overlay"]["rectangles"][0]
        # ellipse bbox ~ x:[55,105], y:[25,95]
        assert 50 <= rect["x"] <= 60 and 20 <= rect["y"] <= 30
        assert 44 <= rect["w"] <= 56 and 64 <= rect["h"] <= 76
        detections = outputs["detections"]
        assert bool(detections["valid"][0])
        assert float(detections["scores"][0]) > 0.5

    def test_face_detect_rejects_non_face_shapes(self):
        # a thin skin-colored bar fails the aspect/fill face gates
        from aiko_services_tpu.elements.vision import FaceDetect
        element = self._face_element()
        image = np.zeros((120, 160, 3), np.float32)
        image[...] = (0.1, 0.2, 0.8)
        image[58:62, 10:150] = (224 / 255, 160 / 255, 130 / 255)
        _, outputs = FaceDetect.process_frame(
            element, None, image.transpose(2, 0, 1))
        assert outputs["overlay"]["objects"] == []
        assert not outputs["detections"]["valid"].any()


class TestRobot:
    def _start(self):
        from aiko_services_tpu.runtime import Process, Registrar
        from aiko_services_tpu.elements.robot import RobotActor
        process = Process(transport_kind="loopback")
        Registrar(process, search_timeout=0.05)
        robot = RobotActor(process, name="xgo")
        process.run(in_thread=True)
        return process, robot

    def test_actions_update_kinematics_and_share(self):
        process, robot = self._start()
        try:
            robot.action("move", 1.0)
            robot.action("turn", 90)
            robot.action("move", 2.0)
            robot.action("pose", "sit")
            assert robot.share["x"] == pytest.approx(1.0)
            assert robot.share["y"] == pytest.approx(2.0)
            assert robot.share["heading"] == 90.0
            assert robot.share["odometer"] == pytest.approx(3.0)
            assert robot.share["pose"] == "sit"
            assert robot.share["actions"] == 4
        finally:
            process.terminate()

    def test_unknown_action_is_ignored(self):
        process, robot = self._start()
        try:
            robot.action("self_destruct")
            assert robot.share["actions"] == 0
        finally:
            process.terminate()

    def test_remote_action_via_proxy(self):
        from aiko_services_tpu.runtime.proxy import make_proxy
        from aiko_services_tpu.transport.loopback import get_broker
        process, robot = self._start()
        try:
            proxy = make_proxy(process, robot.topic_path)
            proxy.action("move", 0.5)
            deadline = time.monotonic() + 5
            while (robot.share["actions"] == 0
                   and time.monotonic() < deadline):
                get_broker().drain()
                time.sleep(0.01)
            assert robot.share["x"] == pytest.approx(0.5)
        finally:
            process.terminate()

    def test_parse_actions_grammar(self):
        from aiko_services_tpu.elements.robot import parse_actions
        text = ("Sure! I'll do that: (action move 0.5) then "
                "(action turn 45) and finally (action stop)")
        assert parse_actions(text) == [
            ("move", ["0.5"]), ("turn", ["45"]), ("stop", [])]
        assert parse_actions("no actions here") == []
        assert parse_actions("") == []

    def test_robot_control_dispatches_to_discovered_robot(self):
        from aiko_services_tpu.pipeline import create_pipeline
        from aiko_services_tpu.transport.loopback import get_broker
        process, robot = self._start()
        definition = {
            "name": "robot_pipe",
            "graph": ["(control)"],
            "elements": [
                {"name": "control", "input": [{"name": "text"}],
                 "output": [{"name": "actions"},
                            {"name": "dispatched"}],
                 "parameters": {"robot_topic": None},
                 "deploy": {"local": {
                     "module": "aiko_services_tpu.elements",
                     "class_name": "RobotControl"}}},
            ],
        }
        definition["elements"][0]["parameters"] = {
            "robot_topic": robot.topic_path}
        pipeline = create_pipeline(process, definition)
        try:
            responses = queue.Queue()
            pipeline.create_stream("s1", queue_response=responses)
            pipeline.process_frame(
                {"stream_id": "s1", "frame_id": 0},
                {"text": "(action move 2.0) (action turn 180)"})
            _, _, outputs = responses.get(timeout=5)
            assert outputs["dispatched"] == 2
            assert outputs["actions"] == [["move", "2.0"],
                                          ["turn", "180"]]
            deadline = time.monotonic() + 5
            while (robot.share["actions"] < 2
                   and time.monotonic() < deadline):
                get_broker().drain()
                time.sleep(0.01)
            assert robot.share["x"] == pytest.approx(2.0)
            assert robot.share["heading"] == 180.0
        finally:
            process.terminate()


class TestTTSWeights:
    def test_save_load_pytree_roundtrip(self, tmp_path):
        """TTS params must round-trip through the shared checkpoint
        machinery (stacked conv layers, no Python-list leaves)."""
        from aiko_services_tpu.models.weights import (
            load_pytree, save_pytree)
        config = TestTTS.CONFIG
        params = init_tts_params(config, jax.random.PRNGKey(0))
        path = tmp_path / "tts.npz"
        save_pytree(str(path), params)
        restored = load_pytree(str(path))
        chars = jnp.asarray(encode_chars("roundtrip", max_len=16))
        want = np.asarray(synthesize(params, config, chars))
        got = np.asarray(synthesize(restored, config, chars))
        np.testing.assert_allclose(got, want, atol=1e-6)


class TestReviewHardening:
    def test_overlapping_bbox_does_not_inflate_other_component(self):
        """A thin bar whose bbox overlaps the face blob must still be
        rejected: area/fill are computed per component, not per bbox."""
        from aiko_services_tpu.elements.vision import FaceDetect
        element = TestVision._face_element()
        image = np.zeros((120, 160, 3), np.float32)
        image[...] = (0.1, 0.2, 0.8)
        skin = (224 / 255, 160 / 255, 130 / 255)
        yy, xx = np.mgrid[0:120, 0:160]
        ellipse = (((yy - 60) / 30.0) ** 2 + ((xx - 60) / 22.0) ** 2) <= 1
        image[ellipse] = skin
        image[10:13, 30:150] = skin   # bar overlapping the face's columns
        _, outputs = FaceDetect.process_frame(
            element, None, image.transpose(2, 0, 1))
        names = [o["name"] for o in outputs["overlay"]["objects"]]
        assert names == ["face"]      # bar rejected, face kept

    def test_robot_bad_argument_is_rejected_without_state_change(self):
        from aiko_services_tpu.runtime import Process
        from aiko_services_tpu.elements.robot import RobotActor
        process = Process(transport_kind="loopback")
        robot = RobotActor(process, name="xgo2")
        process.run(in_thread=True)
        try:
            robot.action("move", "forward")   # LM hallucinated arg
            assert robot.share["actions"] == 0
            assert robot.history == []
            robot.action("move", "1.25")      # numeric string is fine
            assert robot.share["x"] == pytest.approx(1.25)
        finally:
            process.terminate()

    def test_aruco_dictionary_parameter_is_stream_scoped(self):
        cv2 = pytest.importorskip("cv2")
        from aiko_services_tpu.elements.vision import ArucoDetect
        dictionary = cv2.aruco.getPredefinedDictionary(
            cv2.aruco.DICT_6X6_250)
        marker = cv2.aruco.generateImageMarker(dictionary, 11, 120)
        canvas = np.full((300, 300), 255, np.uint8)
        canvas[90:210, 90:210] = marker
        element = ArucoDetect.__new__(ArucoDetect)
        element._detectors = None
        params = {"dictionary": "DICT_6X6_250"}
        element.get_parameter = (
            lambda name, default=None, stream=None:
            params.get(name, default))
        _, outputs = ArucoDetect.process_frame(element, None, canvas)
        assert outputs["markers"]["ids"] == [11]


# -- trainable TTS: learned spectra distinguish phonemes ---------------------
# (VERDICT r2 next-item 6: "a test that synthesized audio of 'aaaa'
# differs structurally from 'ssss' beyond random-weight noise")

def _spectral_centroid(waveform, sample_rate=16000):
    import numpy as np
    spectrum = np.abs(np.fft.rfft(np.asarray(waveform, np.float64)))
    freqs = np.fft.rfftfreq(len(waveform), 1.0 / sample_rate)
    power = spectrum ** 2
    return float((freqs * power).sum() / max(power.sum(), 1e-12))


def test_tts_training_learns_phoneme_spectra():
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from aiko_services_tpu.models import (
        TTSConfig, encode_chars, init_tts_params, make_tts_train_step,
        synthesize, synthesize_mel)

    config = TTSConfig(d_model=64, n_conv_layers=2, frames_per_char=4,
                       griffin_lim_iters=8)
    params = init_tts_params(config, jax.random.PRNGKey(0))

    # phoneme templates: 'a' = vowel energy in LOW mel bands,
    # 's' = sibilant energy in HIGH mel bands (log-mel space)
    chars = np.concatenate([encode_chars("aaaaaaaa"),
                            encode_chars("ssssssss")])
    frames = chars.shape[1] * config.frames_per_char
    target = np.full((2, config.n_mels, frames), -6.0, np.float32)
    target[0, 4:16] = 1.5    # 'a' rows
    target[1, 60:76] = 1.5   # 's' rows
    target = jnp.asarray(target)
    chars = jnp.asarray(chars)

    untrained_a = synthesize(params, config, chars[:1])[0]
    untrained_s = synthesize(params, config, chars[1:])[0]
    untrained_gap = abs(_spectral_centroid(untrained_s)
                        - _spectral_centroid(untrained_a))

    optimizer = optax.adam(3e-3)
    train_step = make_tts_train_step(config, optimizer)
    opt_state = optimizer.init(params)
    first_loss = None
    for _ in range(300):
        params, opt_state, loss = train_step(params, opt_state, chars,
                                             target)
        if first_loss is None:
            first_loss = float(loss)
    assert float(loss) < first_loss * 0.1, (first_loss, float(loss))

    trained_a = synthesize(params, config, chars[:1])[0]
    trained_s = synthesize(params, config, chars[1:])[0]
    centroid_a = _spectral_centroid(trained_a)
    centroid_s = _spectral_centroid(trained_s)
    # sibilant must sit far above the vowel -- and far beyond whatever
    # accidental gap random weights produced
    assert centroid_s > centroid_a * 1.5, (centroid_a, centroid_s)
    assert centroid_s - centroid_a > 4 * untrained_gap, (
        untrained_gap, centroid_a, centroid_s)


# -- robot camera over binary topics -----------------------------------------
# (reference xgo_robot.py ships zlib'd numpy camera frames over binary
# MQTT topics into the vision pipelines)

def test_robot_camera_frames_flow_into_pipeline():
    import queue
    import numpy as np
    from aiko_services_tpu.elements.robot import (
        decode_camera_frame, encode_camera_frame)
    from aiko_services_tpu.pipeline import create_pipeline
    from aiko_services_tpu.runtime import Process

    # codec round-trips through the broker's latin-1 text path
    frame = np.random.default_rng(0).random((3, 8, 8)).astype(np.float32)
    wire = encode_camera_frame(frame).decode("latin-1")
    np.testing.assert_array_equal(decode_camera_frame(wire), frame)

    process = Process(transport_kind="loopback")
    from aiko_services_tpu.elements import RobotActor
    robot = RobotActor(process, name="dog")
    definition = {
        "name": "robot_vision",
        "graph": ["(camera (stats))"],
        "elements": [
            {"name": "camera", "output": [{"name": "image"}],
             "parameters": {"topic": f"{robot.topic_path}/video"},
             "deploy": {"local": {"module": "aiko_services_tpu.elements",
                                  "class_name": "RobotCameraSource"}}},
            {"name": "stats", "input": [{"name": "image"}],
             "output": [{"name": "image"}],
             "deploy": {"local": {"module": "aiko_services_tpu.elements",
                                  "class_name": "PE_Inspect"}}},
        ],
    }
    pipeline = create_pipeline(process, definition)
    process.run(in_thread=True)
    responses = queue.Queue()
    pipeline.create_stream("s", queue_response=responses)
    robot.start_camera(period=0.05, height=16, width=16)
    seen = [responses.get(timeout=20) for _ in range(3)]
    robot.stop_camera()
    for _, _, outputs in seen:
        assert np.asarray(outputs["image"]).shape == (3, 16, 16)
    assert int(robot.share["camera_frames"]) >= 3
    assert robot.share["camera"] == "off"
    process.terminate()


def test_dft_matmul_matches_jnp_fft():
    """The Griffin-Lim transforms run as real DFT matmuls (MXU) -- they
    must agree with the jnp.fft reference they replaced."""
    import jax.numpy as jnp
    import numpy as np
    from aiko_services_tpu.models.tts import (
        _dft_matrices, _frame, _irfft_weights, _stft_ri)

    n_fft, hop = 400, 100
    rng = np.random.default_rng(0)
    signal = jnp.asarray(rng.standard_normal((2, 2000)), jnp.float32)
    window = jnp.hanning(n_fft).astype(jnp.float32)
    cos_m, sin_m = _dft_matrices(n_fft)

    real, imag = _stft_ri(signal, n_fft, hop, window, cos_m, sin_m)
    reference = jnp.fft.rfft(_frame(signal, n_fft, hop) * window, axis=-1)
    np.testing.assert_allclose(np.asarray(real), np.asarray(reference.real),
                               atol=2e-3)
    np.testing.assert_allclose(np.asarray(imag), np.asarray(reference.imag),
                               atol=2e-3)

    # inverse: weighted matmul reconstructs the framed signal
    weights = _irfft_weights(n_fft)
    frames = ((real * weights) @ cos_m.T + (imag * weights) @ sin_m.T)
    expected = np.asarray(jnp.fft.irfft(reference, n=n_fft, axis=-1))
    np.testing.assert_allclose(np.asarray(frames), expected, atol=1e-4)
