# Region-aware graceful degradation (ISSUE 18): the WAN fault plane
# (link_latency / link_loss / link_jitter / region_partition, seeded and
# deterministic), region-labeled federation groups with region-aware
# placement, cross-group adoption of a LOST group's journaled streams
# (warm-restore hints armed for the client's resubmission), multi-tenant
# admission isolation, the destroy-while-paced accounting fix, and the
# soak-harness machinery behind `bench.py soak`.
#
# The acceptance invariant for the fault plane: two runs with the same
# seed produce IDENTICAL fault firing sequences -- `faults.stats()`
# equality is asserted directly.

import json
import queue
import sys
import time
from pathlib import Path

import pytest

from aiko_services_tpu import faults as faults_module
from aiko_services_tpu.faults import create_injector, link_name
from aiko_services_tpu.decode import CheckpointKeeper, reset_keepers
from aiko_services_tpu.observe.metrics import get_registry
from aiko_services_tpu.pipeline import (
    PipelineElement, StreamEvent, create_pipeline)
from aiko_services_tpu.runtime import Process
from aiko_services_tpu.serve import (
    FederationPolicy, FederationRouter, Gateway, assign_group)
from aiko_services_tpu.serve.policy import AdmissionPolicy
from aiko_services_tpu.transport import get_broker, reset_brokers
from aiko_services_tpu.transport.loopback import LoopbackTransport
from helpers import wait_for


@pytest.fixture(autouse=True)
def clean(monkeypatch):
    monkeypatch.delenv("AIKO_FAULTS", raising=False)
    reset_brokers()
    reset_keepers()
    faults_module.reset_injector()
    yield
    reset_brokers()
    reset_keepers()
    faults_module.reset_injector()


# -- WAN fault plane: seeded determinism --------------------------------------


WAN_SPEC = ("seed=29;"
            "link_loss:src=us:dst=eu:rate=0.3;"
            "link_latency:node=us>eu:ms=2;"
            "link_jitter:node=us>eu:ms=3;"
            "region_partition:node=eu:rate=0.05")


def _drive(injector):
    """One fixed call schedule over the WAN points; returns the full
    per-call outcome sequence (the firing tape)."""
    tape = []
    for ordinal in range(40):
        for subscriber in ("gw_c", "dec_eu", "client_7"):
            tape.append(injector.link_drop(
                "us", "eu", frame_id=ordinal, scope=subscriber))
            tape.append(round(injector.link_delay(
                "us", "eu", frame_id=ordinal, scope=subscriber), 9))
    for ordinal in range(40):
        for member in ("gw_c", "dec_eu"):
            tape.append(injector.region_partition(
                "eu", frame_id=ordinal, scope=member))
    return tape


class TestWanDeterminism:
    def test_same_seed_identical_firing_and_stats(self):
        """The acceptance criterion: same seed, same spec -> identical
        firing sequences AND equal faults.stats(), independent of run."""
        first = create_injector(WAN_SPEC)
        second = create_injector(WAN_SPEC)
        assert _drive(first) == _drive(second)
        stats = first.stats()
        assert stats == second.stats()
        # the plan actually fired: a dead injector is trivially equal
        assert stats.get("link_loss", 0) > 0
        assert stats.get("link_latency", 0) > 0
        assert stats.get("region_partition", 0) > 0

    def test_different_seed_changes_the_tape(self):
        first = create_injector(WAN_SPEC)
        other = create_injector(WAN_SPEC.replace("seed=29", "seed=30"))
        assert _drive(first) != _drive(other)

    def test_src_dst_and_node_arrow_are_the_same_link(self):
        assert link_name("us", "eu") == "us>eu"
        by_pair = create_injector(
            "link_loss:src=us:dst=eu:frame=2")
        by_node = create_injector("link_loss:node=us>eu:frame=2")
        for injector in (by_pair, by_node):
            fired = [injector.link_drop("us", "eu", frame_id=ordinal,
                                        scope="s")
                     for ordinal in range(4)]
            assert fired == [False, False, True, False]
        # the wrong direction never fires
        assert not by_pair.link_drop("eu", "us", frame_id=2, scope="s")

    def test_link_field_validation(self):
        with pytest.raises(ValueError, match="BOTH src= and dst="):
            create_injector("link_loss:src=us:rate=0.5")
        with pytest.raises(ValueError, match="node= OR src=/dst="):
            create_injector("link_loss:src=us:dst=eu:node=us>eu")

    def test_continuous_points_default_to_unlimited(self):
        """A link HAS latency -- no times= means every delivery, not
        the one-shot default the transient points use."""
        injector = create_injector("link_latency:node=us>eu:ms=1")
        delays = [injector.link_delay("us", "eu", frame_id=ordinal,
                                      scope="s")
                  for ordinal in range(5)]
        assert delays == [0.001] * 5

    def test_region_partition_return_contract(self):
        # no ms= -> -1.0 (until heal); ms= -> seconds; miss -> 0.0
        until_heal = create_injector("region_partition:node=eu:frame=0")
        assert until_heal.region_partition(
            "eu", frame_id=0, scope="m") == -1.0
        assert until_heal.region_partition(
            "us", frame_id=0, scope="m") == 0.0
        timed = create_injector(
            "region_partition:node=eu:frame=0:ms=50:times=-1")
        assert timed.region_partition(
            "eu", frame_id=1, scope="m") == 0.0
        assert timed.region_partition(
            "eu", frame_id=0, scope="m") == pytest.approx(0.05)


# -- region grammar and placement ---------------------------------------------


class TestRegionGrammar:
    def test_labeled_groups_parse(self):
        policy = FederationPolicy.parse(
            "groups=us:a,us:b,eu:c;group=eu:c")
        assert policy.groups == ("a", "b", "c")
        assert policy.group == "c"
        assert policy.region_of("a") == "us"
        assert policy.region_of("c") == "eu"
        assert policy.region_groups("us") == ("a", "b")
        assert policy.region_groups("eu") == ("c",)

    def test_unlabeled_spec_is_backward_compatible(self):
        policy = FederationPolicy.parse("groups=g0,g1,g2;group=g1")
        assert all(policy.region_of(group) == ""
                   for group in policy.groups)
        for index in range(200):
            stream_id = f"s{index}"
            assert policy.owner_of(stream_id) == assign_group(
                stream_id, policy.groups)

    def test_rejections(self):
        with pytest.raises(ValueError, match="empty group name"):
            FederationPolicy.parse("groups=us:,eu:c")
        with pytest.raises(ValueError, match="duplicate group names"):
            FederationPolicy.parse("groups=us:a,eu:a")
        with pytest.raises(ValueError, match="disagrees"):
            FederationPolicy.parse("groups=us:a,eu:c;group=eu:a")
        with pytest.raises(ValueError, match="AIKO410"):
            Gateway(Process(transport_kind="loopback"),
                    federation="groups=us:a,eu:c;group=eu:a")


class TestRegionPlacement:
    POLICY = FederationPolicy.parse("groups=us:a,us:b,eu:c")

    def test_region_affinity_narrows_the_domain(self):
        for index in range(200):
            stream_id = f"s{index}"
            assert self.POLICY.owner_of(stream_id, region="eu") == "c"
            assert self.POLICY.owner_of(stream_id,
                                        region="us") in ("a", "b")

    def test_region_loss_remaps_only_its_streams(self):
        """Losing eu moves ONLY eu-affine streams; every us stream and
        every unlabeled stream owned by a survivor keeps its pin."""
        moved = 0
        for index in range(300):
            stream_id = f"s{index}"
            before = self.POLICY.owner_of(stream_id)
            after = self.POLICY.owner_of(stream_id, lost=("c",))
            if before != "c":
                assert after == before, stream_id
            else:
                moved += 1
                assert after in ("a", "b")
            # declared us affinity: the eu loss changes nothing at all
            assert (self.POLICY.owner_of(stream_id, region="us",
                                         lost=("c",))
                    == self.POLICY.owner_of(stream_id, region="us"))
            # eu affinity degrades cross-region onto the survivors
            assert self.POLICY.owner_of(stream_id, region="eu",
                                        lost=("c",)) in ("a", "b")
        assert moved > 0
        with pytest.raises(ValueError, match="every group is lost"):
            self.POLICY.owner_of("s0", lost=("a", "b", "c"))

    def test_router_records_affinity_and_injects_region(self):
        class Stub:
            def __init__(self):
                self.created = {}

            def submit_stream(self, stream_id, **kwargs):
                self.created[stream_id] = kwargs

        stubs = {"a": Stub(), "b": Stub(), "c": Stub()}
        router = FederationRouter(stubs,
                                  policy="groups=us:a,us:b,eu:c")
        group = router.submit_stream("r1", region="eu")
        assert group == "c"
        assert stubs["c"].created["r1"]["parameters"]["region"] == "eu"
        # the recorded affinity sticks for later frame routing
        assert router.group_for("r1") == "c"
        router.fail_group("c")
        assert router.group_for("r1") in ("a", "b")
        router.heal_group("c")
        assert router.group_for("r1") == "c"


# -- link faults through the loopback broker ----------------------------------


class _RegionClient:
    def __init__(self, broker_name, region, name, pattern="wan/#"):
        self.received = []
        self.transport = LoopbackTransport(
            on_message=lambda topic, payload: self.received.append(
                (topic, payload)),
            broker=broker_name)
        self.transport.chaos_region = region
        self.transport.chaos_name = name
        self.transport.subscribe(pattern)
        self.transport.connect()


class TestLinkFaultPlane:
    def test_link_loss_drops_only_cross_region(self, monkeypatch):
        monkeypatch.setenv(
            "AIKO_FAULTS", "seed=5;link_loss:src=us:dst=eu:rate=1.0")
        faults_module.reset_injector()
        drops_before = get_registry().counter(
            "faults.link_drops").value
        publisher = _RegionClient("wan_loss", "us", "pub", pattern="x")
        local = _RegionClient("wan_loss", "us", "sub_us")
        remote = _RegionClient("wan_loss", "eu", "sub_eu")
        for index in range(5):
            publisher.transport.publish("wan/t", f"m{index}")
        get_broker("wan_loss").drain()
        assert len(local.received) == 5, "intra-region must not drop"
        assert remote.received == [], "rate=1.0 drops every crossing"
        assert (get_registry().counter("faults.link_drops").value
                - drops_before) == 5
        stats = faults_module.get_injector().stats()
        assert stats.get("link_loss") == 5

    def test_link_latency_delays_and_counts(self, monkeypatch):
        monkeypatch.setenv(
            "AIKO_FAULTS", "seed=5;link_latency:src=us:dst=eu:ms=1")
        faults_module.reset_injector()
        delays_before = get_registry().counter(
            "faults.link_delays").value
        publisher = _RegionClient("wan_lat", "us", "pub", pattern="x")
        remote = _RegionClient("wan_lat", "eu", "sub_eu")
        for index in range(3):
            publisher.transport.publish("wan/t", f"m{index}")
        get_broker("wan_lat").drain()
        assert [payload for _t, payload in remote.received] == [
            "m0", "m1", "m2"], "latency delays, never drops"
        assert (get_registry().counter("faults.link_delays").value
                - delays_before) == 3

    def test_lossy_link_is_deterministic_across_runs(self, monkeypatch):
        monkeypatch.setenv(
            "AIKO_FAULTS", "seed=11;link_loss:src=us:dst=eu:rate=0.5")
        delivered = []
        for run in range(2):
            faults_module.reset_injector()
            name = f"wan_det{run}"
            publisher = _RegionClient(name, "us", "pub", pattern="x")
            remote = _RegionClient(name, "eu", "sub_eu")
            for index in range(30):
                publisher.transport.publish("wan/t", f"m{index}")
            get_broker(name).drain()
            delivered.append([payload for _t, payload
                              in remote.received])
        assert delivered[0] == delivered[1]
        assert 0 < len(delivered[0]) < 30, "rate=0.5 must be partial"


class TestRegionPartitionTransport:
    def test_whole_region_severs_as_a_unit(self, monkeypatch):
        """One spec, per-client ordinals: EVERY eu client partitions at
        its own first publish; us clients never do."""
        monkeypatch.setenv(
            "AIKO_FAULTS", "seed=3;region_partition:node=eu:frame=0")
        faults_module.reset_injector()
        eu_a = _RegionClient("wan_part", "eu", "eu_a")
        eu_b = _RegionClient("wan_part", "eu", "eu_b")
        us = _RegionClient("wan_part", "us", "us_a")
        listener = _RegionClient("wan_part", None, "listen")
        for client in (eu_a, eu_b, us):
            client.transport.publish("wan/t", f"from_{client}")
        get_broker("wan_part").drain()
        assert eu_a.transport._partitioned
        assert eu_b.transport._partitioned
        assert not us.transport._partitioned
        assert eu_a.transport.partition_dropped == 1
        # only the us publish crossed; both eu publishes died severed
        assert len(listener.received) == 1
        stats = faults_module.get_injector().stats()
        assert stats.get("region_partition") == 2

    def test_ms_schedules_the_heal(self, monkeypatch):
        monkeypatch.setenv(
            "AIKO_FAULTS",
            "seed=3;region_partition:node=eu:frame=0:ms=60")
        faults_module.reset_injector()
        eu = _RegionClient("wan_heal", "eu", "eu_a")
        listener = _RegionClient("wan_heal", None, "listen")
        eu.transport.publish("wan/t", "severed")
        assert eu.transport._partitioned
        wait_for(lambda: not eu.transport._partitioned, timeout=5)
        eu.transport.publish("wan/t", "healed")
        get_broker("wan_heal").drain()
        assert [payload for _t, payload in listener.received] == [
            "healed"]


# -- cross-group adoption (region loss -> survivors take the streams) ---------


class Echo(PipelineElement):
    def process_frame(self, stream, number):
        return StreamEvent.OKAY, {"number": int(number) + 1}


def _echo_definition(name):
    return {
        "name": name,
        "parameters": {"telemetry": False},
        "graph": ["(echo)"],
        "elements": [
            {"name": "echo", "input": [{"name": "number"}],
             "output": [{"name": "number"}],
             "deploy": {"local": {"module": "tests.test_region",
                                  "class_name": "Echo"}}},
        ],
    }


JOURNAL = "backend=retained;interval=0.02;replay_timeout=0.2"
GROUPS = "groups=us:a,eu:c"


def _region_tier(processes, keeper="region_k"):
    """Two-region tier over shared echo replicas.  Gateways are NAMED
    after their groups so each journal root is {ns}/gateway/<group>/...
    -- the root a survivor's note_group_lost mirrors."""
    replicas = []
    for index in range(2):
        process = Process(transport_kind="loopback")
        processes.append(process)
        replicas.append(create_pipeline(
            process, _echo_definition(f"region_replica{index}")))
    gateways = {}
    for group, region in (("a", "us"), ("c", "eu")):
        process = Process(transport_kind="loopback")
        processes.append(process)
        gateways[group] = Gateway(
            process, name=group, policy="max_inflight=64;queue=256",
            federation=f"{GROUPS};group={region}:{group}",
            journal=JOURNAL,
            checkpoint=f"recovery_rate=8;keeper={keeper}")
        for replica in replicas:
            gateways[group].attach_replica(replica)
    for process in processes:
        process.run(in_thread=True)
    return FederationRouter(gateways, policy=GROUPS), gateways, replicas


class TestCrossGroupAdoption:
    def test_lost_region_streams_adopt_with_warm_hints(self):
        """Region loss end to end at the gateway layer: eu's journaled
        streams are adopted by the us survivor (rendezvous over the
        survivors), each with the one-shot warm-restore hint armed for
        the client's resubmission, the foreign journal purged so the
        healed group cannot re-pin, and frames keep serving."""
        keeper = CheckpointKeeper("region_k")
        assert keeper.kept_count() == 0
        processes = []
        try:
            router, gateways, replicas = _region_tier(processes)
            responses = queue.Queue()
            eu_ids = [f"eu{index}" for index in range(3)]
            for stream_id in eu_ids:
                group = router.submit_stream(
                    stream_id, region="eu", queue_response=responses,
                    grace_time=300)
                assert group == "c"
                router.submit_frame(stream_id, {"number": 1},
                                    frame_id=0)
            us_id = "us0"
            assert router.submit_stream(
                us_id, region="us", queue_response=responses,
                grace_time=300) == "a"
            for _ in range(len(eu_ids)):
                reply = responses.get(timeout=30)
                assert reply[3] == "ok" and reply[2]["number"] == 2
            gateways["c"].journal_flush()
            wait_for(lambda: gateways["c"].journal.entry_count()
                     >= len(eu_ids), timeout=10)
            affinity_before = (
                gateways["a"].telemetry.region_affinity_misses.value)

            # the region dies: no clean shutdown, retained journal stays
            gateways["c"].process.crash()
            router.fail_group("c")
            wait_for(lambda: gateways["a"].telemetry
                     .region_migrations.value >= len(eu_ids),
                     timeout=30)
            survivor = gateways["a"]
            for stream_id in eu_ids:
                stream = survivor.streams[stream_id]
                # empty-inflight adoption arms the ONE-SHOT hint: the
                # resubmitted first frame will carry data["restore"]
                assert stream.restore_hint == {"keeper": "region_k"}
                assert stream.parameters.get("region") == "eu"
            assert us_id in survivor.streams

            # the client replays against the survivor: dedupe absorbs
            # the already-delivered frame 0, frame 1 serves -- and the
            # one-shot hint is consumed by the first dispatch
            replays = queue.Queue()
            for stream_id in eu_ids:
                survivor.streams[stream_id].queue_response = replays
                assert router.group_for(stream_id) == "a"
                survivor.submit_frame(stream_id, {"number": 10},
                                      frame_id=1)
            for _ in range(len(eu_ids)):
                reply = replays.get(timeout=30)
                assert reply[3] == "ok" and reply[2]["number"] == 11
            assert all(survivor.streams[stream_id].restore_hint is None
                       for stream_id in eu_ids)
            # cross-region adoption is the affinity MISS evidence
            assert (survivor.telemetry.region_affinity_misses.value
                    == affinity_before)

            # heal: adopted streams STAY adopted -- a fresh eu gateway
            # over the same journal root finds only purged tombstones
            router.heal_group("c")
            wait_for(lambda: "c" not in survivor._lost_groups,
                     timeout=10)
            process = Process(transport_kind="loopback")
            processes.append(process)
            healed = Gateway(
                process, name="c", policy="max_inflight=64;queue=256",
                federation=f"{GROUPS};group=eu:c", journal=JOURNAL)
            for replica in replicas:
                healed.attach_replica(replica)
            process.run(in_thread=True)
            router.gateways["c"] = healed
            get_broker().drain()
            time.sleep(0.1)   # retained mirror warm-up
            assert healed.recover_now() == 0, (
                "purged journal records must not re-pin adopted "
                "streams (double-pin)")
            assert not set(eu_ids) & set(healed.streams)
            # placement flows back: NEW eu streams land on the healed
            # group again
            fresh = queue.Queue()
            new_id = "eu_new"
            assert router.submit_stream(
                new_id, region="eu", queue_response=fresh,
                grace_time=300) == "c"
            wait_for(lambda: new_id in healed.streams, timeout=10)
        finally:
            for process in processes:
                process.terminate()

    def test_heal_before_adoption_leaves_ownership_alone(self):
        """fail_group then heal_group inside the replay window: the
        scheduled _adopt_group_ready finds the group healed and adopts
        NOTHING -- no stream ever double-pins mid-migration."""
        keeper = CheckpointKeeper("region_k")
        assert keeper is not None
        processes = []
        try:
            router, gateways, _replicas = _region_tier(processes)
            responses = queue.Queue()
            router.submit_stream("eu0", region="eu",
                                 queue_response=responses,
                                 grace_time=300)
            gateways["c"].journal_flush()
            wait_for(lambda: gateways["c"].journal.entry_count() >= 1,
                     timeout=10)
            router.fail_group("c")
            router.heal_group("c")     # back before the window closed
            time.sleep(0.5)            # let any scheduled adoption fire
            assert gateways["a"].adopt_group_now("c") == 0
            assert "eu0" not in gateways["a"].streams
            assert gateways["a"].telemetry.region_migrations.value == 0
            assert "eu0" in gateways["c"].streams
        finally:
            for process in processes:
                process.terminate()


# -- multi-tenant admission isolation -----------------------------------------


class TestTenantIsolation:
    def test_grammar_and_bucket_lookup(self):
        policy = AdmissionPolicy.parse(
            "max_inflight=4;bucket:tenant:gold=100/20;"
            "bucket:tenant:free=10/4;bucket:2=10/4")
        assert sorted(policy.tenant_buckets) == ["free", "gold"]
        assert policy.tenant_bucket_for("gold").burst == 20
        assert policy.tenant_bucket_for("unnamed") is None
        assert policy.tenant_bucket_for(None) is None
        assert policy.bucket_for(2) is not None
        with pytest.raises(ValueError, match="non-empty tenant name"):
            AdmissionPolicy.parse("bucket:tenant:=5/2")

    def test_storm_exhausts_only_its_own_tenant(self):
        """The isolation proof: a 2x storm from one tenant sheds
        rate_limited_tenant against ITS bucket while the other
        tenant's admission (and tenant-less streams) are untouched --
        and completed frames land per-tenant SLO counters."""
        process_r = Process(transport_kind="loopback")
        replica = create_pipeline(process_r,
                                  _echo_definition("tenant_replica"))
        process_g = Process(transport_kind="loopback")
        gateway = Gateway(
            process_g, name="tenants",
            policy=("max_inflight=64;queue=256;"
                    "bucket:tenant:noisy=0.1/2;"
                    "bucket:tenant:quiet=0.1/2"))
        gateway.attach_replica(replica)
        for process in (process_r, process_g):
            process.run(in_thread=True)
        try:
            responses = queue.Queue()

            def submit(stream_id, tenant):
                parameters = {"slo_ms": 60000.0}
                if tenant:
                    parameters["tenant"] = tenant
                gateway.submit_stream(stream_id, parameters,
                                      queue_response=responses,
                                      grace_time=300)

            # the storm: 2x the noisy tenant's burst.  Admitted creates
            # reply nothing until a frame; sheds reply immediately with
            # the typed reason
            for index in range(4):
                submit(f"noisy{index}", "noisy")
            shed = [responses.get(timeout=30) for _ in range(2)]
            assert all(r[3] == "overloaded"
                       and r[2]["reason"] == "rate_limited_tenant"
                       for r in shed)
            wait_for(lambda: len(gateway.streams) == 2, timeout=10)

            # the OTHER tenant's budget is untouched: both admit
            for index in range(2):
                submit(f"quiet{index}", "quiet")
            for index in range(2):
                gateway.submit_frame(f"quiet{index}", {"number": 5},
                                     frame_id=0)
            oks = 0
            while oks < 2:
                reply = responses.get(timeout=30)
                assert reply[3] == "ok", reply
                oks += 1
            # tenant-less and unbucketed-tenant streams admit freely
            submit("anon", None)
            submit("bronze0", "bronze")
            gateway.submit_frame("anon", {"number": 1}, frame_id=0)
            reply = responses.get(timeout=30)
            assert reply[3] == "ok"
            # per-tenant SLO attainment rode the completions
            registry = gateway.telemetry.registry
            assert registry.counter("gateway.slo_ok:t:quiet").value == 2
            assert registry.counter("gateway.slo_ok:p0").value >= 3
            assert (registry.counter("gateway.slo_ok:t:noisy").value
                    == 0)
        finally:
            for process in (process_r, process_g):
                process.terminate()


# -- destroy-while-paced: the accounting regression ---------------------------


class TestDestroyWhilePaced:
    def test_destroyed_stream_never_leaks_a_paced_replay(self):
        """A stream destroyed while its recovery wave is still
        scheduled: the pending-cohort gauge drops immediately, the
        scheduled _paced_replay is a no-op, and the dead stream's
        frames are never dispatched to the survivor."""
        # replica processes NEVER run: submitted frames stay inflight,
        # so the failover has something to pace
        process_r0 = Process(transport_kind="loopback")
        replica0 = create_pipeline(process_r0,
                                   _echo_definition("paced_r0"))
        process_r1 = Process(transport_kind="loopback")
        replica1 = create_pipeline(process_r1,
                                   _echo_definition("paced_r1"))
        process_g = Process(transport_kind="loopback")
        gateway = Gateway(process_g, name="paced",
                          policy="max_inflight=16;queue=32",
                          checkpoint="recovery_rate=2;keeper=paced_k")
        gateway.attach_replica(replica0)
        process_g.run(in_thread=True)
        try:
            ids = [f"pc{index}" for index in range(4)]
            for stream_id in ids:
                gateway.submit_stream(stream_id, {},
                                      queue_response=queue.Queue(),
                                      grace_time=300)
                gateway.submit_frame(stream_id, {"number": 1},
                                     frame_id=0)
            wait_for(lambda: sum(
                len(stream.inflight)
                for stream in gateway.streams.values()) == 4,
                timeout=10)
            gateway.attach_replica(replica1)
            gateway.post_message("_replica_lost",
                                 [replica0.topic_path, "test kill"])
            # recovery_rate=2 over 4 migrated streams: 2 replay
            # immediately, 2 join the paced cohort
            gauge = gateway.telemetry.recovery_paced_pending
            wait_for(lambda: gauge.value == 2, timeout=10)
            survivor = gateway.replicas[replica1.topic_path]
            assert survivor.routed == 2
            # destroy the LATER-scheduled cohort member (insertion
            # order = schedule order) before its wave fires
            victim = list(gateway._paced_frames)[-1]
            gateway.post_message("destroy_stream", [victim])
            wait_for(lambda: victim not in gateway.streams, timeout=10)
            assert gauge.value == 1, (
                "destroy must drop the stream's cohort entry")
            # the remaining wave fires; the victim's never does
            wait_for(lambda: gauge.value == 0, timeout=10)
            wait_for(lambda: survivor.routed == 3, timeout=10)
            time.sleep(0.3)    # past the victim's original schedule
            assert survivor.routed == 3, (
                "a destroyed stream's paced replay must be a no-op")
            assert not gateway._paced_frames
        finally:
            # the replica processes were never run (that is the point:
            # frames had to stay inflight) -- only the gateway's stops
            process_g.terminate()


# -- soak harness machinery ---------------------------------------------------


class TestSoakHarness:
    def test_short_window_runs_clean_and_writes_ledger(
            self, monkeypatch, tmp_path):
        sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
        import bench
        monkeypatch.setattr(bench, "SMOKE", True)
        ledger_path = tmp_path / "soak_ledger.json"
        monkeypatch.setenv("AIKO_SOAK_SECONDS", "3")
        monkeypatch.setenv("AIKO_SOAK_LEDGER", str(ledger_path))
        block = bench.bench_soak(None)
        assert block["findings"] == [], block["findings"]
        assert block["drift_ok"] is True
        assert block["waves"] >= 1
        assert block["probes"] == block["waves"]
        assert block["streams_total"] > 0
        entry = block["ledger"][-1]
        assert entry["journal_entries"] == 0
        assert entry["pool_free"] + entry["pool_cached"] == \
            entry["pool_capacity"]
        artifact = json.loads(ledger_path.read_text())
        assert artifact["findings"] == []
        assert len(artifact["ledger"]) == block["waves"]
