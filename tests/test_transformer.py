# Transformer LM tests: forward shape/sanity, prefill-vs-decode parity
# (the KV-cache path must reproduce the flash prefill path), generation
# determinism, sharded train step on the virtual 8-device mesh.

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from aiko_services_tpu.models import (
    TransformerConfig, cache_specs, count_params, forward, generate,
    init_cache, init_params, make_train_step, param_specs)
from aiko_services_tpu.parallel import create_mesh, shard_pytree

CONFIG = TransformerConfig(
    vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=128, max_seq_len=64, dtype="float32")


def _params():
    return init_params(CONFIG, jax.random.PRNGKey(0))


def test_param_count_and_specs_match_structure():
    params = _params()
    specs = param_specs(CONFIG)
    # same tree structure: tree_map must not raise
    jax.tree_util.tree_map(lambda leaf, spec: None, params, specs)
    assert count_params(params) > CONFIG.vocab_size * CONFIG.d_model


def test_forward_shapes_and_finite():
    params = _params()
    tokens = jnp.arange(24, dtype=jnp.int32).reshape(2, 12) % 256
    logits = forward(params, CONFIG, tokens)
    assert logits.shape == (2, 12, 256)
    assert bool(jnp.isfinite(logits).all())


def test_prefill_and_cached_decode_agree():
    """Scoring token t via full prefill must equal scoring it incrementally
    through the KV cache."""
    params = _params()
    tokens = (jax.random.randint(jax.random.PRNGKey(1), (1, 10), 0, 256)
              .astype(jnp.int32))
    full_logits = forward(params, CONFIG, tokens)

    cache = init_cache(CONFIG, batch=1, max_len=16)
    step_logits = []
    for position in range(10):
        logits, cache = forward(
            params, CONFIG, tokens[:, position:position + 1],
            cache=cache, pos=position)
        step_logits.append(logits[:, 0])
    stacked = jnp.stack(step_logits, axis=1)
    np.testing.assert_allclose(np.asarray(stacked),
                               np.asarray(full_logits),
                               atol=2e-3, rtol=2e-3)


def test_generate_greedy_deterministic():
    params = _params()
    prompt = jnp.array([[1, 2, 3, 4]], dtype=jnp.int32)
    out1, _ = generate(params, CONFIG, prompt, max_new_tokens=8)
    out2, _ = generate(params, CONFIG, prompt, max_new_tokens=8)
    assert out1.shape == (1, 8)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    assert int(out1.min()) >= 0 and int(out1.max()) < 256


def test_train_step_reduces_loss():
    params = _params()
    optimizer = optax.adam(1e-2)
    opt_state = optimizer.init(params)
    train_step = make_train_step(CONFIG, optimizer)
    tokens = (jax.random.randint(jax.random.PRNGKey(2), (4, 16), 0, 256)
              .astype(jnp.int32))
    losses = []
    for _ in range(5):
        params, opt_state, loss = train_step(params, opt_state, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()


def test_sharded_train_step_on_mesh():
    """Full TP+FSDP+DP+SP train step over the 8-device mesh: params sharded
    by param_specs, batch sharded on data, runs and stays finite."""
    mesh = create_mesh({"data": 2, "fsdp": 1, "seq": 2, "model": 2})
    config = TransformerConfig(
        vocab_size=128, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=64, max_seq_len=32, dtype="float32")
    with jax.set_mesh(mesh):
        params = init_params(config, jax.random.PRNGKey(0))
        params = shard_pytree(params, mesh, param_specs(config))
        optimizer = optax.adam(1e-2)
        opt_state = optimizer.init(params)
        train_step = make_train_step(config, optimizer, sharded=True)
        tokens = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(3), (4, 16), 0, 128)
            .astype(jnp.int32),
            NamedSharding(mesh, P("data", None)))
        params, opt_state, loss = train_step(params, opt_state, tokens)
        assert np.isfinite(float(loss))
        # TP sharding preserved through the update
        wq = params["layers"]["wq"]["w"]
        assert not wq.sharding.is_fully_replicated


def test_sequence_parallel_matches_dense():
    """Ring-attention prefill over the seq axis must reproduce the dense
    flash prefill (the long-context path is exact, not approximate)."""
    import dataclasses
    mesh = create_mesh({"seq": 8})
    config = TransformerConfig(
        vocab_size=128, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=64, max_seq_len=64, dtype="float32")
    sp_config = dataclasses.replace(config, sequence_parallel=True)
    params = init_params(config, jax.random.PRNGKey(0))
    tokens = (jax.random.randint(jax.random.PRNGKey(5), (2, 32), 0, 128)
              .astype(jnp.int32))
    dense = forward(params, config, tokens)
    with jax.set_mesh(mesh):
        ringed = forward(params, sp_config, tokens)
    np.testing.assert_allclose(np.asarray(ringed), np.asarray(dense),
                               atol=2e-3, rtol=2e-3)


def test_sequence_parallel_train_step():
    import dataclasses
    mesh = create_mesh({"data": 2, "fsdp": 1, "seq": 2, "model": 2})
    config = TransformerConfig(
        vocab_size=128, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=64, max_seq_len=32, dtype="float32", sequence_parallel=True)
    with jax.set_mesh(mesh):
        params = shard_pytree(init_params(config, jax.random.PRNGKey(0)),
                              mesh, param_specs(config))
        optimizer = optax.adam(1e-2)
        opt_state = optimizer.init(params)
        train_step = make_train_step(config, optimizer, sharded=True)
        tokens = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(3), (4, 17), 0, 128)
            .astype(jnp.int32),
            NamedSharding(mesh, P("data", None)))
        params, opt_state, loss = train_step(params, opt_state, tokens)
        assert np.isfinite(float(loss))


def test_moe_forward_and_train():
    config = TransformerConfig(
        vocab_size=128, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=64, max_seq_len=32, dtype="float32", n_experts=4)
    params = init_params(config, jax.random.PRNGKey(0))
    assert params["layers"]["w_gate"]["w"].shape == (2, 4, 32, 64)
    tokens = (jax.random.randint(jax.random.PRNGKey(6), (2, 16), 0, 128)
              .astype(jnp.int32))
    logits = forward(params, config, tokens)
    assert logits.shape == (2, 16, 128)
    assert bool(jnp.isfinite(logits).all())
    optimizer = optax.adam(1e-2)
    train_step = make_train_step(config, optimizer)
    losses = []
    opt_state = optimizer.init(params)
    for _ in range(4):
        params, opt_state, loss = train_step(params, opt_state, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_moe_expert_parallel_on_mesh():
    """EP: expert weights sharded on the 'expert' axis; the sharded train
    step runs and the expert dimension stays partitioned."""
    mesh = create_mesh({"data": 2, "expert": 2, "model": 2})
    config = TransformerConfig(
        vocab_size=128, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=64, max_seq_len=32, dtype="float32", n_experts=4)
    with jax.set_mesh(mesh):
        params = shard_pytree(init_params(config, jax.random.PRNGKey(0)),
                              mesh,
                              __import__("aiko_services_tpu.parallel",
                                         fromlist=["filter_specs"])
                              .filter_specs(param_specs(config), mesh))
        gate = params["layers"]["w_gate"]["w"]
        assert not gate.sharding.is_fully_replicated
        optimizer = optax.adam(1e-2)
        opt_state = optimizer.init(params)
        train_step = make_train_step(config, optimizer)
        tokens = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(3), (4, 16), 0, 128)
            .astype(jnp.int32),
            NamedSharding(mesh, P("data", None)))
        params, opt_state, loss = train_step(params, opt_state, tokens)
        assert np.isfinite(float(loss))


def test_sharded_decode_on_mesh():
    mesh = create_mesh({"data": 2, "fsdp": 1, "seq": 2, "model": 2})
    config = TransformerConfig(
        vocab_size=128, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=64, max_seq_len=32, dtype="float32")
    with jax.set_mesh(mesh):
        params = shard_pytree(
            init_params(config, jax.random.PRNGKey(0)), mesh,
            param_specs(config))
        cache = shard_pytree(init_cache(config, batch=2, max_len=16),
                             mesh, cache_specs())
        prompt = jnp.ones((2, 4), jnp.int32)
        out, cache = generate(params, config, prompt, max_new_tokens=4,
                              cache=cache)
        assert out.shape == (2, 4)
        assert cache is not None


def test_sequence_parallel_generate():
    """Long-context generation with the KV cache sharded over the mesh
    "seq" axis (sp_decode_attention) must reproduce the unsharded greedy
    decode exactly (VERDICT round-1 item 4: SP decode path)."""
    import dataclasses
    mesh = create_mesh({"data": 2, "fsdp": 1, "seq": 2, "model": 2})
    config = TransformerConfig(
        vocab_size=128, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=64, max_seq_len=64, dtype="float32")
    sp_config = dataclasses.replace(config, sequence_parallel=True)
    params = init_params(config, jax.random.PRNGKey(0))
    prompt = (jax.random.randint(jax.random.PRNGKey(4), (2, 16), 0, 128)
              .astype(jnp.int32))
    dense_out, _ = generate(params, config, prompt, max_new_tokens=8)
    with jax.set_mesh(mesh):
        sp_params = shard_pytree(params, mesh, param_specs(config))
        cache = shard_pytree(
            init_cache(config, batch=2, max_len=24), mesh,
            cache_specs(sequence_parallel=True))
        sp_out, _ = generate(sp_params, sp_config, prompt,
                             max_new_tokens=8, cache=cache)
    np.testing.assert_array_equal(np.asarray(sp_out),
                                  np.asarray(dense_out))
