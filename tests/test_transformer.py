# Transformer LM tests: forward shape/sanity, prefill-vs-decode parity
# (the KV-cache path must reproduce the flash prefill path), generation
# determinism, sharded train step on the virtual 8-device mesh.

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from aiko_services_tpu.models import (
    TransformerConfig, cache_specs, count_params, forward, generate,
    init_cache, init_params, make_train_step, param_specs)
from aiko_services_tpu.parallel import create_mesh, shard_pytree

CONFIG = TransformerConfig(
    vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=128, max_seq_len=64, dtype="float32")


def _params():
    return init_params(CONFIG, jax.random.PRNGKey(0))


def test_param_count_and_specs_match_structure():
    params = _params()
    specs = param_specs(CONFIG)
    # same tree structure: tree_map must not raise
    jax.tree_util.tree_map(lambda leaf, spec: None, params, specs)
    assert count_params(params) > CONFIG.vocab_size * CONFIG.d_model


def test_forward_shapes_and_finite():
    params = _params()
    tokens = jnp.arange(24, dtype=jnp.int32).reshape(2, 12) % 256
    logits = forward(params, CONFIG, tokens)
    assert logits.shape == (2, 12, 256)
    assert bool(jnp.isfinite(logits).all())


def test_prefill_and_cached_decode_agree():
    """Scoring token t via full prefill must equal scoring it incrementally
    through the KV cache."""
    params = _params()
    tokens = (jax.random.randint(jax.random.PRNGKey(1), (1, 10), 0, 256)
              .astype(jnp.int32))
    full_logits = forward(params, CONFIG, tokens)

    cache = init_cache(CONFIG, batch=1, max_len=16)
    step_logits = []
    for position in range(10):
        logits, cache = forward(
            params, CONFIG, tokens[:, position:position + 1],
            cache=cache, pos=position)
        step_logits.append(logits[:, 0])
    stacked = jnp.stack(step_logits, axis=1)
    np.testing.assert_allclose(np.asarray(stacked),
                               np.asarray(full_logits),
                               atol=2e-3, rtol=2e-3)


def test_generate_greedy_deterministic():
    params = _params()
    prompt = jnp.array([[1, 2, 3, 4]], dtype=jnp.int32)
    out1, _ = generate(params, CONFIG, prompt, max_new_tokens=8)
    out2, _ = generate(params, CONFIG, prompt, max_new_tokens=8)
    assert out1.shape == (1, 8)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    assert int(out1.min()) >= 0 and int(out1.max()) < 256


def test_train_step_reduces_loss():
    params = _params()
    optimizer = optax.adam(1e-2)
    opt_state = optimizer.init(params)
    train_step = make_train_step(CONFIG, optimizer)
    tokens = (jax.random.randint(jax.random.PRNGKey(2), (4, 16), 0, 256)
              .astype(jnp.int32))
    losses = []
    for _ in range(5):
        params, opt_state, loss = train_step(params, opt_state, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()


class TestRematPolicySweep:
    """ROADMAP #3b groundwork: make_train_step(remat_policy=) accepts
    named jax.checkpoint_policies entries.  Remat only changes WHEN
    activations are (re)computed, never WHAT is computed, so every
    policy must produce bit-identical losses -- the sweep is purely a
    step-time/HBM frontier the bench `remat` knob walks."""

    POLICIES = ("none", "nothing_saveable", "dots_saveable",
                "dots_with_no_batch_dims_saveable")

    def test_policies_produce_bit_identical_losses(self):
        tokens = (jax.random.randint(jax.random.PRNGKey(5), (2, 17),
                                     0, 256).astype(jnp.int32))
        optimizer = optax.adamw(1e-3)
        losses = {}
        for policy in self.POLICIES:
            params = _params()
            opt_state = optimizer.init(params)
            step = make_train_step(CONFIG, optimizer,
                                   remat_policy=policy)
            trail = []
            for _ in range(3):
                params, opt_state, loss = step(params, opt_state, tokens)
                trail.append(np.asarray(loss))
            losses[policy] = trail
        baseline = losses["none"]
        for policy in self.POLICIES[1:]:
            np.testing.assert_array_equal(
                np.asarray(losses[policy]), np.asarray(baseline),
                err_msg=f"remat_policy={policy} drifted from baseline")

    def test_unknown_policy_fails_fast(self):
        from aiko_services_tpu.models import REMAT_POLICIES
        with pytest.raises(ValueError, match="remat_policy"):
            make_train_step(CONFIG, optax.adam(1e-3),
                            remat_policy="dots_savable")  # typo
        assert "nothing_saveable" in REMAT_POLICIES

    def test_remat_rejected_on_decode_path(self):
        params = _params()
        cache = init_cache(CONFIG, 1, max_len=8)
        tokens = jnp.ones((1, 1), jnp.int32)
        with pytest.raises(ValueError, match="cache-less"):
            forward(params, CONFIG, tokens, cache=cache, pos=0,
                    remat_policy="nothing_saveable")


def test_sharded_train_step_on_mesh():
    """Full TP+FSDP+DP+SP train step over the 8-device mesh: params sharded
    by param_specs, batch sharded on data, runs and stays finite."""
    mesh = create_mesh({"data": 2, "fsdp": 1, "seq": 2, "model": 2})
    config = TransformerConfig(
        vocab_size=128, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=64, max_seq_len=32, dtype="float32")
    with jax.set_mesh(mesh):
        params = init_params(config, jax.random.PRNGKey(0))
        params = shard_pytree(params, mesh, param_specs(config))
        optimizer = optax.adam(1e-2)
        opt_state = optimizer.init(params)
        train_step = make_train_step(config, optimizer, sharded=True)
        tokens = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(3), (4, 16), 0, 128)
            .astype(jnp.int32),
            NamedSharding(mesh, P("data", None)))
        params, opt_state, loss = train_step(params, opt_state, tokens)
        assert np.isfinite(float(loss))
        # TP sharding preserved through the update
        wq = params["layers"]["wq"]["w"]
        assert not wq.sharding.is_fully_replicated


def test_sequence_parallel_matches_dense():
    """Ring-attention prefill over the seq axis must reproduce the dense
    flash prefill (the long-context path is exact, not approximate)."""
    import dataclasses
    mesh = create_mesh({"seq": 8})
    config = TransformerConfig(
        vocab_size=128, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=64, max_seq_len=64, dtype="float32")
    sp_config = dataclasses.replace(config, sequence_parallel=True)
    params = init_params(config, jax.random.PRNGKey(0))
    tokens = (jax.random.randint(jax.random.PRNGKey(5), (2, 32), 0, 128)
              .astype(jnp.int32))
    dense = forward(params, config, tokens)
    with jax.set_mesh(mesh):
        ringed = forward(params, sp_config, tokens)
    np.testing.assert_allclose(np.asarray(ringed), np.asarray(dense),
                               atol=2e-3, rtol=2e-3)


def test_sequence_parallel_train_step():
    import dataclasses
    mesh = create_mesh({"data": 2, "fsdp": 1, "seq": 2, "model": 2})
    config = TransformerConfig(
        vocab_size=128, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=64, max_seq_len=32, dtype="float32", sequence_parallel=True)
    with jax.set_mesh(mesh):
        params = shard_pytree(init_params(config, jax.random.PRNGKey(0)),
                              mesh, param_specs(config))
        optimizer = optax.adam(1e-2)
        opt_state = optimizer.init(params)
        train_step = make_train_step(config, optimizer, sharded=True)
        tokens = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(3), (4, 17), 0, 128)
            .astype(jnp.int32),
            NamedSharding(mesh, P("data", None)))
        params, opt_state, loss = train_step(params, opt_state, tokens)
        assert np.isfinite(float(loss))


def test_moe_forward_and_train():
    config = TransformerConfig(
        vocab_size=128, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=64, max_seq_len=32, dtype="float32", n_experts=4)
    params = init_params(config, jax.random.PRNGKey(0))
    assert params["layers"]["w_gate"]["w"].shape == (2, 4, 32, 64)
    tokens = (jax.random.randint(jax.random.PRNGKey(6), (2, 16), 0, 128)
              .astype(jnp.int32))
    logits = forward(params, config, tokens)
    assert logits.shape == (2, 16, 128)
    assert bool(jnp.isfinite(logits).all())
    optimizer = optax.adam(1e-2)
    train_step = make_train_step(config, optimizer)
    losses = []
    opt_state = optimizer.init(params)
    for _ in range(4):
        params, opt_state, loss = train_step(params, opt_state, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_moe_expert_parallel_on_mesh():
    """EP: expert weights sharded on the 'expert' axis; the sharded train
    step runs and the expert dimension stays partitioned."""
    mesh = create_mesh({"data": 2, "expert": 2, "model": 2})
    config = TransformerConfig(
        vocab_size=128, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=64, max_seq_len=32, dtype="float32", n_experts=4)
    with jax.set_mesh(mesh):
        params = shard_pytree(init_params(config, jax.random.PRNGKey(0)),
                              mesh,
                              __import__("aiko_services_tpu.parallel",
                                         fromlist=["filter_specs"])
                              .filter_specs(param_specs(config), mesh))
        gate = params["layers"]["w_gate"]["w"]
        assert not gate.sharding.is_fully_replicated
        optimizer = optax.adam(1e-2)
        opt_state = optimizer.init(params)
        train_step = make_train_step(config, optimizer)
        tokens = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(3), (4, 16), 0, 128)
            .astype(jnp.int32),
            NamedSharding(mesh, P("data", None)))
        params, opt_state, loss = train_step(params, opt_state, tokens)
        assert np.isfinite(float(loss))


def test_sharded_decode_on_mesh():
    mesh = create_mesh({"data": 2, "fsdp": 1, "seq": 2, "model": 2})
    config = TransformerConfig(
        vocab_size=128, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=64, max_seq_len=32, dtype="float32")
    with jax.set_mesh(mesh):
        params = shard_pytree(
            init_params(config, jax.random.PRNGKey(0)), mesh,
            param_specs(config))
        cache = shard_pytree(init_cache(config, batch=2, max_len=16),
                             mesh, cache_specs())
        prompt = jnp.ones((2, 4), jnp.int32)
        out, cache = generate(params, config, prompt, max_new_tokens=4,
                              cache=cache)
        assert out.shape == (2, 4)
        assert cache is not None


def test_sequence_parallel_generate():
    """Long-context generation with the KV cache sharded over the mesh
    "seq" axis (sp_decode_attention) must reproduce the unsharded greedy
    decode exactly (VERDICT round-1 item 4: SP decode path)."""
    import dataclasses
    mesh = create_mesh({"data": 2, "fsdp": 1, "seq": 2, "model": 2})
    config = TransformerConfig(
        vocab_size=128, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=64, max_seq_len=64, dtype="float32")
    sp_config = dataclasses.replace(config, sequence_parallel=True)
    params = init_params(config, jax.random.PRNGKey(0))
    prompt = (jax.random.randint(jax.random.PRNGKey(4), (2, 16), 0, 128)
              .astype(jnp.int32))
    dense_out, _ = generate(params, config, prompt, max_new_tokens=8)
    with jax.set_mesh(mesh):
        sp_params = shard_pytree(params, mesh, param_specs(config))
        cache = shard_pytree(
            init_cache(config, batch=2, max_len=24), mesh,
            cache_specs(sequence_parallel=True))
        sp_out, _ = generate(sp_params, sp_config, prompt,
                             max_new_tokens=8, cache=cache)
    np.testing.assert_array_equal(np.asarray(sp_out),
                                  np.asarray(dense_out))


class TestMoECapacityDispatch:
    """VERDICT round-1 item 8: capacity-based gather/scatter dispatch
    replacing masked-dense."""

    @staticmethod
    def _config(**kw):
        base = dict(vocab_size=128, d_model=32, n_layers=2, n_heads=4,
                    n_kv_heads=2, d_ff=64, max_seq_len=32,
                    dtype="float32", n_experts=4)
        base.update(kw)
        return TransformerConfig(**base)

    def test_capacity_matches_dense_oracle_when_unconstrained(self):
        """With capacity >= L no token is ever dropped, so capacity
        dispatch must agree exactly with the masked-dense oracle."""
        import dataclasses
        cap = self._config(moe_capacity_factor=8.0)  # C = L
        dense = dataclasses.replace(cap, moe_capacity_factor=0.0)
        params = init_params(cap, jax.random.PRNGKey(0))
        tokens = (jax.random.randint(jax.random.PRNGKey(6), (2, 16),
                                     0, 128).astype(jnp.int32))
        got = forward(params, cap, tokens)
        want = forward(params, dense, tokens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-4, rtol=1e-4)

    def test_aux_loss_reported_and_balanced_routing_lowers_it(self):
        config = self._config()
        params = init_params(config, jax.random.PRNGKey(0))
        tokens = (jax.random.randint(jax.random.PRNGKey(2), (2, 16),
                                     0, 128).astype(jnp.int32))
        _, aux = forward(params, config, tokens, return_aux=True)
        # Switch aux loss is >= 1 (perfectly balanced) for top-1 routing
        assert float(aux) >= 1.0 - 1e-5

    def test_capacity_train_step_learns(self):
        config = self._config(moe_capacity_factor=1.25)
        params = init_params(config, jax.random.PRNGKey(0))
        tokens = (jax.random.randint(jax.random.PRNGKey(6), (2, 16),
                                     0, 128).astype(jnp.int32))
        optimizer = optax.adam(1e-2)
        train_step = make_train_step(config, optimizer)
        opt_state = optimizer.init(params)
        losses = []
        for _ in range(4):
            params, opt_state, loss = train_step(params, opt_state,
                                                 tokens)
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_capacity_flops_scale_with_capacity_not_experts(self):
        """The compiled FLOP count of the capacity forward must be far
        below masked-dense (which pays E x the FFN): per-device FLOPs
        follow E_local x C, i.e. ~capacity_factor x one dense FFN."""
        import dataclasses
        cap = self._config(n_experts=8, d_ff=128,
                           moe_capacity_factor=1.0)
        dense = dataclasses.replace(cap, moe_capacity_factor=0.0)
        params = init_params(cap, jax.random.PRNGKey(0))
        tokens = jnp.zeros((2, 64), jnp.int32)

        def flops(config):
            compiled = (jax.jit(lambda p, t: forward(p, config, t))
                        .lower(params, tokens).compile())
            analysis = compiled.cost_analysis()
            return analysis["flops"]

        ratio = flops(cap) / flops(dense)
        assert ratio < 0.55, f"capacity dispatch not cheaper: {ratio}"

    def test_overflow_tokens_are_dropped_from_moe_output(self):
        """Identical tokens all route to one expert; with capacity 1 only
        the first is processed -- the MoE output rows for every dropped
        token must be exactly zero (they ride the residual in forward)."""
        from aiko_services_tpu.models.transformer import _switch_moe
        config = self._config(moe_capacity_factor=1e-9)  # C floors at 1
        params = init_params(config, jax.random.PRNGKey(0))
        layer0 = jax.tree_util.tree_map(lambda leaf: leaf[0],
                                        params["layers"])
        x = jnp.broadcast_to(
            jax.random.normal(jax.random.PRNGKey(3), (32,), jnp.float32),
            (1, 8, 32))
        out, _ = _switch_moe(config, layer0, x)
        assert float(jnp.abs(out[0, 0]).max()) > 0
        np.testing.assert_array_equal(np.asarray(out[0, 1:]),
                                      np.zeros((7, 32), np.float32))

    def test_decode_gather_matches_dense_oracle(self):
        """L < E routes through the per-token weight-gather path; it
        must agree with the masked-dense oracle (no capacity drops at
        L=1/L=2)."""
        import dataclasses
        cap = self._config(n_experts=8)
        dense = dataclasses.replace(cap, moe_capacity_factor=0.0)
        params = init_params(cap, jax.random.PRNGKey(0))
        tokens = (jax.random.randint(jax.random.PRNGKey(7), (2, 2),
                                     0, 128).astype(jnp.int32))
        got = forward(params, cap, tokens)
        want = forward(params, dense, tokens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-4, rtol=1e-4)

    def test_return_aux_with_cache_fails_fast(self):
        config = self._config()
        params = init_params(config, jax.random.PRNGKey(0))
        cache = init_cache(config, batch=1, max_len=8)
        with pytest.raises(ValueError, match="cache-less"):
            forward(params, config, jnp.zeros((1, 1), jnp.int32),
                    cache=cache, return_aux=True)


def test_ulysses_sp_mechanism_matches_dense():
    """sp_mechanism="ulysses": all-to-all sequence parallelism in the
    flagship prefill must match the dense forward (heads divisible by
    the seq axis)."""
    import dataclasses
    mesh = create_mesh({"seq": 4}, devices=jax.devices()[:4])
    config = TransformerConfig(
        vocab_size=128, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=64, max_seq_len=64, dtype="float32")
    sp_config = dataclasses.replace(config, sequence_parallel=True,
                                    sp_mechanism="ulysses")
    params = init_params(config, jax.random.PRNGKey(0))
    tokens = (jax.random.randint(jax.random.PRNGKey(5), (2, 32), 0, 128)
              .astype(jnp.int32))
    dense = forward(params, config, tokens)
    with jax.set_mesh(mesh):
        sharded = forward(params, sp_config, tokens)
    np.testing.assert_allclose(np.asarray(sharded), np.asarray(dense),
                               atol=2e-3, rtol=2e-3)


def test_ulysses_sp_generate_matches_dense():
    import dataclasses
    mesh = create_mesh({"data": 2, "fsdp": 1, "seq": 2, "model": 2})
    config = TransformerConfig(
        vocab_size=128, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=64, max_seq_len=64, dtype="float32")
    sp_config = dataclasses.replace(config, sequence_parallel=True,
                                    sp_mechanism="ulysses")
    params = init_params(config, jax.random.PRNGKey(0))
    prompt = (jax.random.randint(jax.random.PRNGKey(4), (2, 16), 0, 128)
              .astype(jnp.int32))
    dense_out, _ = generate(params, config, prompt, max_new_tokens=8)
    with jax.set_mesh(mesh):
        sp_params = shard_pytree(params, mesh, param_specs(config))
        cache = shard_pytree(
            init_cache(config, batch=2, max_len=24), mesh,
            cache_specs(sequence_parallel=True))
        sp_out, _ = generate(sp_params, sp_config, prompt,
                             max_new_tokens=8, cache=cache)
    np.testing.assert_array_equal(np.asarray(sp_out),
                                  np.asarray(dense_out))


def test_sp_mechanism_typo_fails_fast():
    with pytest.raises(ValueError, match="sp_mechanism"):
        TransformerConfig(sp_mechanism="Ulysses")


# -- sharded serving: llama32_1b ARCHITECTURE decode under param_specs -------
# (BASELINE config 4: mesh-sharded decode; tiny dims, real structure --
# GQA 4:1 ratio, tied embeddings, rope_theta 500000, scan-stacked layers)

class TestShardedServing:
    def _arch_config(self):
        from dataclasses import replace
        from aiko_services_tpu.models.configs import LLAMA32_1B
        return replace(
            LLAMA32_1B, vocab_size=256, d_model=64, n_layers=2,
            n_heads=8, n_kv_heads=2, d_ff=128, max_seq_len=128,
            dtype="float32")

    def test_decode_parity_with_param_specs_sharding(self):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from aiko_services_tpu.models import (
            cache_specs, generate, init_cache, init_params, param_specs)
        from aiko_services_tpu.parallel import filter_specs, shard_pytree
        from aiko_services_tpu.parallel.mesh import create_mesh

        config = self._arch_config()
        params = init_params(config, jax.random.PRNGKey(7))
        prompt = jnp.asarray(
            np.random.default_rng(0).integers(3, 250, (4, 12)), jnp.int32)
        dense_tokens, _ = generate(params, config, prompt, 8)

        mesh = create_mesh({"data": 2, "fsdp": 2, "seq": 1, "model": 2})
        sharded_params = shard_pytree(
            params, mesh, filter_specs(param_specs(config), mesh))
        cache = shard_pytree(
            init_cache(config, 4, max_len=32), mesh,
            filter_specs(cache_specs(), mesh))
        with jax.set_mesh(mesh):
            sharded_tokens, _ = generate(
                sharded_params, config, prompt, 8, cache=cache)
        np.testing.assert_array_equal(np.asarray(dense_tokens),
                                      np.asarray(sharded_tokens))

    def test_decode_step_collective_count_is_bounded(self):
        """TP decode must cost O(n_layers) small all-reduces per step --
        not O(matmuls).  Megatron sharding: one fused all-reduce after
        attention out-proj + one after the MLP down-proj per layer, plus
        the logits reduction."""
        import re
        import jax
        import jax.numpy as jnp
        from functools import partial
        from aiko_services_tpu.models import (
            cache_specs, decode_step, init_cache, init_params, param_specs)
        from aiko_services_tpu.parallel import filter_specs, shard_pytree
        from aiko_services_tpu.parallel.mesh import create_mesh

        config = self._arch_config()
        mesh = create_mesh({"data": 2, "fsdp": 2, "seq": 1, "model": 2})
        params = shard_pytree(
            init_params(config, jax.random.PRNGKey(0)), mesh,
            filter_specs(param_specs(config), mesh))
        cache = shard_pytree(
            init_cache(config, 4, max_len=32), mesh,
            filter_specs(cache_specs(), mesh))
        token = jnp.ones((4, 1), jnp.int32)
        pos = jnp.int32(5)
        with jax.set_mesh(mesh):
            step = jax.jit(partial(decode_step, config=config))
            hlo = step.lower(params, cache=cache, token=token,
                             pos=pos).compile().as_text()
        # count instruction DEFINITIONS only ("%x = ty[] all-reduce(" --
        # bare name mentions recur at every operand use site)
        collectives = re.findall(
            r"= \S+ (all-reduce|all-gather|reduce-scatter|all-to-all|"
            r"collective-permute)\(", hlo)
        # fusion may merge but must never EXCEED the megatron budget:
        # 2 per layer + logits (+1 slack for the embedding gather path)
        budget = 2 * config.n_layers + 2
        assert 1 <= len(collectives) <= budget, (
            f"{len(collectives)} collectives per decode step "
            f"(budget {budget}): {collectives}")


class TestLlama38BArchitecture:
    """The flagship LLAMA3_8B preset instantiated (tiny width, REAL
    structure: 32 scan layers, 4:1 GQA, untied lm_head, rope 500k) --
    sharded decode over the full mesh vocabulary with an 8B-style
    param_specs tree including the untied head."""

    def test_8b_architecture_sharded_decode(self):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from dataclasses import replace
        from aiko_services_tpu.models import (
            cache_specs, generate, init_cache, init_params, param_specs)
        from aiko_services_tpu.models.configs import LLAMA3_8B
        from aiko_services_tpu.parallel import filter_specs, shard_pytree
        from aiko_services_tpu.parallel.mesh import create_mesh

        config = replace(
            LLAMA3_8B, vocab_size=128, d_model=64, n_layers=32,
            n_heads=8, n_kv_heads=2, d_ff=96, max_seq_len=64,
            dtype="float32")
        assert config.n_layers == LLAMA3_8B.n_layers  # real depth
        assert (config.n_heads // config.n_kv_heads
                == LLAMA3_8B.n_heads // LLAMA3_8B.n_kv_heads)  # GQA 4:1
        params = init_params(config, jax.random.PRNGKey(1))
        prompt = jnp.asarray(
            np.random.default_rng(2).integers(3, 120, (2, 8)), jnp.int32)
        dense_tokens, _ = generate(params, config, prompt, 6)

        mesh = create_mesh({"data": 2, "fsdp": 2, "seq": 1, "model": 2})
        sharded = shard_pytree(
            params, mesh,
            filter_specs(param_specs(config, lm_head="lm_head" in params),
                         mesh))
        cache = shard_pytree(
            init_cache(config, 2, max_len=16), mesh,
            filter_specs(cache_specs(), mesh))
        with jax.set_mesh(mesh):
            sharded_tokens, _ = generate(sharded, config, prompt, 6,
                                         cache=cache)
        np.testing.assert_array_equal(np.asarray(dense_tokens),
                                      np.asarray(sharded_tokens))


class TestLlama8BFeasibility:
    """BASELINE config 4 feasibility: the REAL Llama-3-8B layout must
    FIT a v5e-8 serving mesh (VERDICT r3 item 7) -- checked by
    eval_shape (no weights materialize) against the published
    param_specs sharding and the serving KV cache."""

    V5E_HBM_BYTES = 16 * 1024**3          # per chip
    BUDGET = 0.90                          # leave 10% for XLA scratch

    def _per_device_bytes(self, shapes, specs, mesh_axes):
        """Bytes per device for a pytree of ShapeDtypeStructs sharded by
        PartitionSpecs over named mesh axis sizes (replicated where the
        spec names no axis)."""
        import numpy as np

        import jax

        total = 0
        flat_shapes, _ = jax.tree_util.tree_flatten(shapes)
        flat_specs, _ = jax.tree_util.tree_flatten(
            specs, is_leaf=lambda x: isinstance(
                x, jax.sharding.PartitionSpec))
        assert len(flat_shapes) == len(flat_specs)
        for struct, spec in zip(flat_shapes, flat_specs):
            divisor = 1
            for entry in tuple(spec):
                names = (entry if isinstance(entry, tuple)
                         else (entry,) if entry else ())
                for name in names:
                    divisor *= mesh_axes.get(name, 1)
            total += (int(np.prod(struct.shape)) // divisor
                      * struct.dtype.itemsize)
        return total

    def test_8b_params_and_cache_fit_v5e8(self):
        import jax

        from aiko_services_tpu.models import (
            cache_specs, init_cache, init_params, param_specs)
        from aiko_services_tpu.models.configs import LLAMA3_8B

        config = LLAMA3_8B
        # the serving mesh from examples/pipeline_llm_8b.json
        mesh_axes = {"data": 1, "fsdp": 2, "seq": 1, "model": 4}
        shapes = jax.eval_shape(
            lambda: init_params(config, jax.random.PRNGKey(0)))
        has_head = "lm_head" in shapes
        specs = param_specs(config, lm_head=has_head)
        specs = {key: specs[key] for key in shapes}  # align partial tree
        param_bytes = self._per_device_bytes(shapes, specs, mesh_axes)

        # serving KV cache: batch 8, full 8k context
        batch, max_len = 8, config.max_seq_len
        cache_shapes = jax.eval_shape(
            lambda: init_cache(config, batch, max_len=max_len))
        cache_bytes = self._per_device_bytes(
            cache_shapes, cache_specs(), mesh_axes)

        # activations at decode (1 token) are noise; prefill peak ~
        # batch x seq x d x a-few in bf16 under remat -- bound it
        # generously
        activation_bytes = 2 * batch * max_len * config.d_model * 8

        used = param_bytes + cache_bytes + activation_bytes
        budget = self.V5E_HBM_BYTES * self.BUDGET
        # HBM budget table (mirrored in BENCH_NOTES.md):
        #   params/device   2.11 GiB (8.03B bf16 over fsdp2 x model4)
        #   kv cache/device 2.00 GiB (batch 8 x 8k, GQA 8 kv heads / 4)
        #   activations     4.00 GiB bound
        #   total           8.11 GiB vs 14.4 GiB budget
        assert used < budget, (
            f"8B does not fit: params {param_bytes/2**30:.2f} GiB + "
            f"cache {cache_bytes/2**30:.2f} GiB + activations "
            f"{activation_bytes/2**30:.2f} GiB = {used/2**30:.2f} GiB "
            f"> budget {budget/2**30:.2f} GiB")
        # and the whole thing genuinely needed sharding: replicated
        # (params 15.0 GiB + cache + activations) blows the same budget
        replicated = self._per_device_bytes(shapes, specs, {})
        assert replicated + cache_bytes + activation_bytes > budget

    def test_8b_int8_fits_four_chips(self):
        """int8 weights + int8 KV shrink the REAL Llama-3-8B serving
        footprint enough for a v5e-4 (half the mesh the bf16 layout
        needs): quantization buys mesh size, not just batch."""
        import jax
        from dataclasses import replace

        from aiko_services_tpu.models import (
            cache_specs, init_cache, init_params, quantize_weights_int8,
            quantized_param_specs)
        from aiko_services_tpu.models.configs import LLAMA3_8B

        config = replace(LLAMA3_8B, kv_dtype="int8")
        mesh_axes = {"data": 1, "fsdp": 2, "seq": 1, "model": 2}  # 4 chips
        shapes = jax.eval_shape(lambda: quantize_weights_int8(
            init_params(config, jax.random.PRNGKey(0)), config))
        has_head = "lm_head" in shapes
        specs = quantized_param_specs(config, lm_head=has_head)
        specs = {key: specs[key] for key in shapes}
        param_bytes = self._per_device_bytes(shapes, specs, mesh_axes)

        batch, max_len = 8, config.max_seq_len
        cache_shapes = jax.eval_shape(
            lambda: init_cache(config, batch, max_len=max_len))
        cache_bytes = self._per_device_bytes(
            cache_shapes, cache_specs(quantized=True), mesh_axes)
        activation_bytes = 2 * batch * max_len * config.d_model * 8

        used = param_bytes + cache_bytes + activation_bytes
        budget = self.V5E_HBM_BYTES * self.BUDGET
        assert used < budget, (
            f"int8 8B does not fit 4 chips: params "
            f"{param_bytes/2**30:.2f} GiB + cache "
            f"{cache_bytes/2**30:.2f} GiB + activations "
            f"{activation_bytes/2**30:.2f} GiB = {used/2**30:.2f} GiB "
            f"> budget {budget/2**30:.2f} GiB")

    def test_8b_pipeline_definition_compiles_on_virtual_mesh(self):
        """examples/pipeline_llm_8b.json executes end to end on the
        virtual 8-CPU mesh at ARCHITECTURE dims (real depth/GQA/mesh
        layout, tiny width -- materializing 16 GB of weights on the
        test host is the only thing skipped)."""
        import json
        import pathlib
        import queue

        from aiko_services_tpu.pipeline import create_pipeline
        from aiko_services_tpu.runtime import Process

        path = (pathlib.Path(__file__).parent.parent / "examples"
                / "pipeline_llm_8b.json")
        definition = json.loads(path.read_text())
        lm = next(element for element in definition["elements"]
                  if element["name"] == "lm")
        # architecture dims: REAL depth + GQA ratio + the json's mesh
        # layout; width shrunk so the test host can materialize it
        lm["parameters"].pop("preset")
        lm["parameters"].update({
            "vocab_size": 256, "d_model": 64, "n_layers": 32,
            "n_heads": 8, "n_kv_heads": 2, "d_ff": 224,
            "max_seq_len": 512, "dtype": "float32",
            "max_new_tokens": 4, "tokenizer": "default",
            "stream_tokens": False})
        process = Process(transport_kind="loopback")
        pipeline = create_pipeline(process, definition)
        process.run(in_thread=True)
        responses = queue.Queue()
        pipeline.create_stream("s1", queue_response=responses)
        _, _, outputs = responses.get(timeout=300)
        assert "generated" in outputs and "text" in outputs
        process.terminate()


class TestLlama8BRealDimsLowering:
    """VERDICT r4 item 5: the TRUE-dims Llama-3-8B (4096 d_model, 32
    layers, 32/8 GQA heads, 128k vocab, untied head) decode and prefill
    programs must LOWER AND COMPILE over the 8-device serving mesh with
    megatron-bounded collectives -- proven from ABSTRACT inputs
    (ShapeDtypeStruct + NamedSharding; zero weight bytes materialize),
    completing the eval_shape HBM-budget proof with a program-level
    artifact.  Reference seat: BASELINE config 4 / the reference's LLM
    element (examples/llm/elements_llm.py:137)."""

    BATCH = 8

    def _mesh(self):
        from aiko_services_tpu.parallel.mesh import create_mesh
        # the serving mesh from examples/pipeline_llm_8b.json
        return create_mesh({"data": 1, "fsdp": 2, "seq": 1, "model": 4})

    def _abstract(self, shapes, specs_tree, mesh):
        import jax
        flat_shapes, treedef = jax.tree_util.tree_flatten(shapes)
        flat_specs, _ = jax.tree_util.tree_flatten(
            specs_tree, is_leaf=lambda x: isinstance(
                x, jax.sharding.PartitionSpec))
        assert len(flat_shapes) == len(flat_specs)
        return treedef.unflatten([
            jax.ShapeDtypeStruct(
                struct.shape, struct.dtype,
                sharding=jax.sharding.NamedSharding(mesh, spec))
            for struct, spec in zip(flat_shapes, flat_specs)])

    def _structs(self, mesh, max_len):
        import jax
        from aiko_services_tpu.models import (
            cache_specs, init_cache, init_params, param_specs)
        from aiko_services_tpu.models.configs import LLAMA3_8B
        from aiko_services_tpu.parallel import filter_specs

        config = LLAMA3_8B
        param_shapes = jax.eval_shape(
            lambda: init_params(config, jax.random.PRNGKey(0)))
        specs = filter_specs(
            param_specs(config, lm_head="lm_head" in param_shapes), mesh)
        specs = {key: specs[key] for key in param_shapes}
        params = self._abstract(param_shapes, specs, mesh)
        cache_shapes = jax.eval_shape(
            lambda: init_cache(config, self.BATCH, max_len=max_len))
        cache = self._abstract(
            cache_shapes, filter_specs(cache_specs(), mesh), mesh)
        return config, params, cache

    def _collectives(self, hlo):
        import re
        found = re.findall(
            r"= \S+ (all-reduce|all-gather|reduce-scatter|all-to-all|"
            r"collective-permute)\(", hlo)
        counts = {}
        for kind in found:
            counts[kind] = counts.get(kind, 0) + 1
        return found, counts

    def test_8b_decode_step_compiles_at_true_dims(self):
        from functools import partial

        import jax
        import jax.numpy as jnp

        from aiko_services_tpu.models import decode_step

        mesh = self._mesh()
        config, params, cache = self._structs(mesh, max_len=8192)
        replicated = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec())
        token = jax.ShapeDtypeStruct((self.BATCH, 1), jnp.int32,
                                     sharding=replicated)
        pos = jax.ShapeDtypeStruct((), jnp.int32, sharding=replicated)
        with jax.set_mesh(mesh):
            step = jax.jit(partial(decode_step, config=config))
            hlo = step.lower(params, cache=cache, token=token,
                             pos=pos).compile().as_text()
        found, counts = self._collectives(hlo)
        print(f"8B decode step collectives over {dict(data=1, fsdp=2, seq=1, model=4)}: "
              f"{counts}")
        budget = 2 * config.n_layers + 2
        assert 1 <= len(found) <= budget, (
            f"{len(found)} collectives per 8B decode step "
            f"(budget {budget}): {counts}")

    def test_8b_prefill_compiles_at_true_dims(self):
        import jax
        import jax.numpy as jnp

        from aiko_services_tpu.models import forward

        mesh = self._mesh()
        config, params, cache = self._structs(mesh, max_len=8192)
        replicated = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec())
        tokens = jax.ShapeDtypeStruct((self.BATCH, 512), jnp.int32,
                                      sharding=replicated)
        with jax.set_mesh(mesh):
            prefill = jax.jit(
                lambda p, t, c: forward(p, config, t, cache=c, pos=0))
            hlo = prefill.lower(params, tokens, cache).compile().as_text()
        found, counts = self._collectives(hlo)
        print(f"8B prefill (512 tokens) collectives: {counts}")
        assert found, "sharded 8B prefill must lower with collectives"


class TestKVCacheInt8:
    """VERDICT r4 item 4: int8 KV cache -- halves cache HBM (doubling
    feasible decode batch) with numerics pinned against the
    full-precision cache."""

    def _config(self):
        from dataclasses import replace
        from aiko_services_tpu.models.configs import LLAMA32_1B
        return replace(
            LLAMA32_1B, vocab_size=256, d_model=64, n_layers=2,
            n_heads=8, n_kv_heads=2, d_ff=128, max_seq_len=128,
            dtype="float32")

    def test_int8_cache_halves_bytes(self):
        import jax
        from dataclasses import replace
        from aiko_services_tpu.models import init_cache

        config = self._config()

        def nbytes(cache):
            return sum(leaf.size * leaf.dtype.itemsize
                       for leaf in jax.tree_util.tree_leaves(cache))

        dense_bytes = nbytes(init_cache(config, 4, max_len=64))
        quant_bytes = nbytes(init_cache(
            replace(config, kv_dtype="int8"), 4, max_len=64))
        # int8 codes (1/4 of f32) + f32 scale per position (1/head_dim)
        assert quant_bytes < dense_bytes * 0.5

    def test_int8_cache_generation_matches_full_precision(self):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from dataclasses import replace
        from aiko_services_tpu.models import generate, init_params

        config = self._config()
        params = init_params(config, jax.random.PRNGKey(3))
        prompt = jnp.asarray(
            np.random.default_rng(1).integers(3, 250, (4, 12)), jnp.int32)
        tokens_fp, _ = generate(params, config, prompt, 12)
        tokens_q, _ = generate(
            params, replace(config, kv_dtype="int8"), prompt, 12)
        np.testing.assert_array_equal(np.asarray(tokens_fp),
                                      np.asarray(tokens_q))

    def test_int8_decode_logits_drift_pinned(self):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from dataclasses import replace
        from aiko_services_tpu.models import (
            decode_step, forward, init_cache, init_params)

        config = self._config()
        config_q = replace(config, kv_dtype="int8")
        params = init_params(config, jax.random.PRNGKey(5))
        prompt = jnp.asarray(
            np.random.default_rng(2).integers(3, 250, (2, 16)), jnp.int32)
        caches = {}
        logits = {}
        for name, cfg in (("fp", config), ("q", config_q)):
            cache = init_cache(cfg, 2, max_len=32)
            _, cache = forward(params, cfg, prompt, cache=cache, pos=0)
            token = jnp.full((2, 1), 7, jnp.int32)
            _, step_logits, cache = decode_step(
                params, cfg, cache, token, jnp.int32(16))
            caches[name], logits[name] = cache, np.asarray(step_logits)
        drift = np.max(np.abs(logits["q"] - logits["fp"]))
        span = np.max(np.abs(logits["fp"])) + 1e-9
        assert drift / span < 0.02, f"relative drift {drift / span:.4f}"

    def test_int8_rejects_sequence_parallel(self):
        import pytest
        from dataclasses import replace
        with pytest.raises(ValueError, match="sequence-parallel"):
            replace(self._config(), kv_dtype="int8",
                    sequence_parallel=True)

    def test_int8_sharded_decode_matches_unsharded(self):
        """cache_specs(quantized=True) lays the int8 cache (codes +
        scale planes) onto the serving mesh: sharded decode must equal
        the single-device int8 path -- the batch-headroom use case the
        quantized cache exists for."""
        import jax
        import jax.numpy as jnp
        import numpy as np
        from dataclasses import replace
        from aiko_services_tpu.models import (
            cache_specs, generate, init_cache, init_params, param_specs)
        from aiko_services_tpu.parallel import filter_specs, shard_pytree
        from aiko_services_tpu.parallel.mesh import create_mesh

        config = replace(self._config(), kv_dtype="int8")
        params = init_params(config, jax.random.PRNGKey(7))
        prompt = jnp.asarray(
            np.random.default_rng(4).integers(3, 250, (4, 12)), jnp.int32)
        dense_tokens, _ = generate(params, config, prompt, 8)

        mesh = create_mesh({"data": 2, "fsdp": 2, "seq": 1, "model": 2})
        sharded_params = shard_pytree(
            params, mesh, filter_specs(param_specs(config), mesh))
        cache = shard_pytree(
            init_cache(config, 4, max_len=32), mesh,
            filter_specs(cache_specs(quantized=True), mesh))
        with jax.set_mesh(mesh):
            sharded_tokens, _ = generate(
                sharded_params, config, prompt, 8, cache=cache)
        np.testing.assert_array_equal(np.asarray(dense_tokens),
                                      np.asarray(sharded_tokens))


class TestWeightOnlyInt8:
    """Weight-only int8 for serving decode (beyond-parity round 5):
    small-batch decode is weight-streaming-bound, so 8-bit weights are
    ~2x step throughput at fixed batch.  Numerics pinned against the
    full-precision weights."""

    def _config(self):
        from dataclasses import replace
        from aiko_services_tpu.models.configs import LLAMA32_1B
        return replace(
            LLAMA32_1B, vocab_size=256, d_model=64, n_layers=2,
            n_heads=8, n_kv_heads=2, d_ff=128, max_seq_len=128,
            dtype="float32")

    def test_quantized_tree_halves_bytes(self):
        import jax
        from aiko_services_tpu.models import (
            init_params, quantize_weights_int8)

        config = self._config()
        params = init_params(config, jax.random.PRNGKey(0))

        def nbytes(tree):
            return sum(leaf.size * leaf.dtype.itemsize
                       for leaf in jax.tree_util.tree_leaves(tree))

        quantized = quantize_weights_int8(params, config)
        # f32 reference weights -> int8 codes + thin f32 scales
        assert nbytes(quantized) < nbytes(params) * 0.30

    def test_int8_weights_logits_drift_pinned(self):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from aiko_services_tpu.models import (
            forward, init_params, quantize_weights_int8)

        config = self._config()
        params = init_params(config, jax.random.PRNGKey(9))
        quantized = quantize_weights_int8(params, config)
        tokens = jnp.asarray(
            np.random.default_rng(3).integers(3, 250, (2, 16)), jnp.int32)
        logits_fp = np.asarray(forward(params, config, tokens))
        logits_q = np.asarray(forward(quantized, config, tokens))
        drift = np.max(np.abs(logits_q - logits_fp))
        span = np.max(np.abs(logits_fp)) + 1e-9
        assert drift / span < 0.05, f"relative drift {drift / span:.4f}"

    def test_int8_weights_generation_functional(self):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from aiko_services_tpu.models import (
            generate, init_params, quantize_weights_int8)

        config = self._config()
        params = init_params(config, jax.random.PRNGKey(2))
        quantized = quantize_weights_int8(params, config)
        prompt = jnp.asarray(
            np.random.default_rng(5).integers(3, 250, (2, 8)), jnp.int32)
        tokens, _ = generate(quantized, config, prompt, 8)
        assert tokens.shape == (2, 8)
        values = np.asarray(tokens)
        assert ((values >= 0) & (values < config.vocab_size)).all()

    def test_int8_weights_sharded_decode_matches_unsharded(self):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from aiko_services_tpu.models import (
            generate, init_params, quantize_weights_int8,
            quantized_param_specs)
        from aiko_services_tpu.parallel import filter_specs, shard_pytree
        from aiko_services_tpu.parallel.mesh import create_mesh

        config = self._config()
        params = quantize_weights_int8(
            init_params(config, jax.random.PRNGKey(4)), config)
        prompt = jnp.asarray(
            np.random.default_rng(6).integers(3, 250, (4, 12)), jnp.int32)
        dense_tokens, _ = generate(params, config, prompt, 8)

        mesh = create_mesh({"data": 2, "fsdp": 2, "seq": 1, "model": 2})
        sharded = shard_pytree(
            params, mesh,
            filter_specs(quantized_param_specs(config), mesh))
        with jax.set_mesh(mesh):
            sharded_tokens, _ = generate(sharded, config, prompt, 8)
        np.testing.assert_array_equal(np.asarray(dense_tokens),
                                      np.asarray(sharded_tokens))

    def test_int8_weights_with_int8_kv_cache(self):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from dataclasses import replace
        from aiko_services_tpu.models import (
            generate, init_params, quantize_weights_int8)

        config = self._config()
        config_q = replace(config, kv_dtype="int8")
        params = quantize_weights_int8(
            init_params(config, jax.random.PRNGKey(8)), config)
        prompt = jnp.asarray(
            np.random.default_rng(7).integers(3, 250, (2, 8)), jnp.int32)
        both_q, _ = generate(params, config_q, prompt, 8)
        weights_only, _ = generate(params, config, prompt, 8)
        # both quantizations compose: valid tokens, and the int8 cache's
        # rounding stays a PERTURBATION, not a derailment (exact
        # equality would be platform-fragile -- a near-tie argmax can
        # legitimately flip under different backend matmul numerics)
        values = np.asarray(both_q)
        assert values.shape == (2, 8)
        assert ((values >= 0) & (values < config.vocab_size)).all()
        agreement = float(np.mean(values == np.asarray(weights_only)))
        assert agreement >= 0.5, f"token agreement {agreement:.2f}"
