# Deliberately-defective pipeline elements for the actor-safety lint's
# golden corpus (tests/assets/lint_golden): each class violates exactly
# one AIKO3xx rule so tests can prove the rule fires.  NEVER deploy
# these in a real pipeline.

import time

from aiko_services_tpu.pipeline import (
    AsyncHostElement, PipelineElement, StreamEvent)

_SHARED_COUNTER = 0


class BlockingElement(PipelineElement):
    """AIKO301: time.sleep on the pipeline event loop."""

    def process_frame(self, stream, text):
        time.sleep(0.01)
        return StreamEvent.OKAY, {"text": text}


class AllowedBlockingElement(PipelineElement):
    """AIKO301 suppressed by the inline `# aiko: allow` marker."""

    def process_frame(self, stream, text):
        time.sleep(0.001)  # aiko: allow
        return StreamEvent.OKAY, {"text": text}


class GlobalMutator(PipelineElement):
    """AIKO303: cross-stream shared state mutated on the frame path."""

    def process_frame(self, stream, text):
        global _SHARED_COUNTER
        _SHARED_COUNTER += 1
        self.pipeline.last_text = text
        return StreamEvent.OKAY, {"text": text}


class TupleMutator(PipelineElement):
    """AIKO303: shared-state attribute targets hidden inside an
    unpacking assignment."""

    def process_frame(self, stream, text):
        self.pipeline.last_text, self.process.frames = text, 1
        return StreamEvent.OKAY, {"text": text}


class AsyncWithKernel(AsyncHostElement):
    """AIKO302: an async host element cannot trace into a fused device
    program."""

    def process_async(self, stream, text):
        return {"text": text}

    def group_kernel(self, stream):
        return (lambda context, **batch: batch), None
