# Generator for the two VERDICT-roofline case-study traces (committed
# next to this script) and their tune reports (committed under
# reports/).  Run from the repo root:
#
#   python tests/assets/traces/make_case_studies.py
#
# The spans are SYNTHESIZED -- deterministically, no wall clock -- from
# the round-5 on-chip measurements the repo already records
# (BENCH_DETAIL.json / BENCH_NOTES.md), so `aiko tune` can classify the
# two unexplained rooflines VERDICT named:
#
#   1. longcontext: 16k-token prefill MFU 0.0647 vs 4k 0.1308
#      (BENCH_DETAIL longcontext.prefill: 176.1 ms vs 1941.8 ms per
#      call at batch 1 on v5e, peak 197 TFLOP/s bf16)
#   2. train: MFU 0.3845 vs the >= 0.45 target (243.1 ms/step,
#      batch 4 x seq 1024 on the 749M llama arch)
#
# The static FLOP estimates handed to the cost model are the SAME
# analytic counts the bench derived its MFU numbers from
# (models.transformer_flops_per_token at the recorded dims), so the
# achieved-utilization evidence in the reports reproduces the recorded
# MFU exactly.  What the reports add is the mechanical part: both
# elements classify compute-bound -- dispatch, queue, and compile
# floors are ruled out by the span evidence -- so the MFU gap is the
# KERNEL's efficiency at those operating points (the quadratic
# attention share at 16k; remat recompute at train), not a pipeline
# knob.  That is the "explain the floor" outcome ISSUE 10 asks for;
# the knob-level fix lives with the kernels (ROADMAP #5 case studies).

import json
import os
import sys

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "..", "..", "..")
sys.path.insert(0, os.path.abspath(REPO))

from aiko_services_tpu.observe.trace import (           # noqa: E402
    chrome_trace_document, trace_metadata)
from aiko_services_tpu.tune import (                    # noqa: E402
    SloSpec, report_json, run_tune)

PEAK_TFLOPS = 197.0  # v5e bf16 peak (bench.py table)
HERE = os.path.dirname(os.path.abspath(__file__))
REPORTS = os.path.abspath(os.path.join(REPO, "reports"))


def _events(stages, calls):
    """Serial frame spans, each wrapping one call per stage:
    stages = [(element_name, per_call_ms)]."""
    events = [{"ph": "M", "name": "process_name", "pid": 1, "tid": 0,
               "args": {"name": "pipeline:case_study"}}]
    ts = 0.0
    for frame_id in range(calls):
        frame_start = ts
        for name, per_call_ms in stages:
            duration = per_call_ms * 1000.0  # us
            events.append({
                "ph": "X", "name": name, "cat": "element",
                "ts": round(ts, 3), "dur": round(duration, 3),
                "pid": 1, "tid": 1,
                "args": {"trace_id": f"1-{frame_id + 1:x}",
                         "frame_id": frame_id, "path": "inline",
                         "group": 1}})
            ts += duration
        events.append({
            "ph": "X", "name": f"frame {frame_id}", "cat": "frame",
            "ts": round(frame_start, 3),
            "dur": round(ts - frame_start, 3), "pid": 1, "tid": 1,
            "args": {"trace_id": f"1-{frame_id + 1:x}",
                     "status": "ok", "stream": "bench"}})
        ts += 100.0  # 0.1 ms between frames
    return events


def _element(name, inputs, outputs):
    return {
        "name": name,
        "input": [{"name": port, "type": "any"} for port in inputs],
        "output": [{"name": port, "type": "any"} for port in outputs],
        "deploy": {"local": {"module": "aiko_services_tpu.elements",
                             "class_name": "LMGenerate"}},
    }


def _write(path, document):
    with open(path, "w") as handle:
        json.dump(document, handle, sort_keys=True)
    print(f"wrote {os.path.relpath(path, REPO)}")


def longcontext():
    """Roofline 1: the 4k and 16k prefill operating points as two
    stages of one recorded run (measured per-call medians, batch 1)."""
    definition = {
        "name": "case_longcontext_prefill",
        "graph": ["(prefill_4k (prefill_16k))"],
        "elements": [
            _element("prefill_4k", ["tokens"], ["hidden"]),
            _element("prefill_16k", ["hidden"], ["hidden16"]),
        ],
    }
    config = {
        "source": "BENCH_DETAIL.json longcontext (round 5, v5e)",
        "model": "llama32_1b architecture, 8 layers (749M params)",
        "batch": 1,
        "prefill_4k_ms": 176.1, "prefill_4k_mfu": 0.1308,
        "prefill_16k_ms": 1941.8, "prefill_16k_mfu": 0.0647,
        "peak_tflops_assumed": PEAK_TFLOPS,
    }
    events = _events([("prefill_4k", 176.1), ("prefill_16k", 1941.8)],
                     calls=12)
    path = os.path.join(HERE, "longcontext_16k.json")
    _write(path, chrome_trace_document(events, metadata=trace_metadata(
        definition_document=definition, config=config,
        config_name="longcontext")))
    # the analytic flop counts the recorded MFU was derived from:
    # MFU = flops / (time * peak)  =>  flops = MFU * time * peak
    static = {
        "prefill_4k": {"rows": 1, "bytes_in": 4096 * 4,
                       "bytes_out": 4096 * 2048 * 2,
                       "param_bytes": int(749e6 * 2),
                       "flops": 0.1308 * 0.1761 * PEAK_TFLOPS * 1e12},
        "prefill_16k": {"rows": 1, "bytes_in": 16384 * 4,
                        "bytes_out": 16384 * 2048 * 2,
                        "param_bytes": int(749e6 * 2),
                        "flops": 0.0647 * 1.9418 * PEAK_TFLOPS * 1e12},
    }
    report = run_tune(path, slo_spec=SloSpec.parse("throughput"),
                      static_costs=static)
    _write_report("tune_longcontext_16k.json", report)


def train():
    """Roofline 2: the recorded train step (batch 4 x seq 1024,
    243.1 ms, MFU 0.3845 vs the >= 0.45 target)."""
    definition = {
        "name": "case_train_step",
        "graph": ["(train_step)"],
        "elements": [_element("train_step", ["batch"], ["loss"])],
    }
    config = {
        "source": "BENCH_DETAIL.json train (round 5, v5e)",
        "model": "llama32_1b architecture, 8 layers (749M params)",
        "batch": 4, "seq_len": 1024,
        "step_ms": 243.1, "train_mfu": 0.3845,
        "tokens_per_sec": 16847.4,
        "peak_tflops_assumed": PEAK_TFLOPS,
    }
    events = _events([("train_step", 243.1)], calls=20)
    path = os.path.join(HERE, "train_step.json")
    _write(path, chrome_trace_document(events, metadata=trace_metadata(
        definition_document=definition, config=config,
        config_name="train")))
    static = {
        "train_step": {"rows": 1, "bytes_in": 4 * 1024 * 4,
                       "bytes_out": 4,
                       "param_bytes": int(749e6 * 2),
                       "flops": 0.3845 * 0.2431 * PEAK_TFLOPS * 1e12},
    }
    report = run_tune(path, slo_spec=SloSpec.parse("throughput"),
                      static_costs=static)
    _write_report("tune_train_step.json", report)


def _write_report(name, report):
    os.makedirs(REPORTS, exist_ok=True)
    path = os.path.join(REPORTS, name)
    with open(path, "w") as handle:
        handle.write(report_json(report) + "\n")
    print(f"wrote {os.path.relpath(path, REPO)}: "
          + ", ".join(f"{element}={record['floor']}"
                      for element, record
                      in sorted(report["elements"].items())))


if __name__ == "__main__":
    longcontext()
    train()
