# Generator for the two VERDICT-roofline case-study traces (committed
# next to this script) and their tune reports (committed under
# reports/).  Run from the repo root:
#
#   python tests/assets/traces/make_case_studies.py
#
# The spans are SYNTHESIZED -- deterministically, no wall clock -- from
# the round-5 on-chip measurements the repo already records
# (BENCH_DETAIL.json / BENCH_NOTES.md), so `aiko tune` can classify the
# two unexplained rooflines VERDICT named:
#
#   1. longcontext: 16k-token prefill MFU 0.0647 vs 4k 0.1308
#      (BENCH_DETAIL longcontext.prefill: 176.1 ms vs 1941.8 ms per
#      call at batch 1 on v5e, peak 197 TFLOP/s bf16)
#   2. train: MFU 0.3845 vs the >= 0.45 target (243.1 ms/step,
#      batch 4 x seq 1024 on the 749M llama arch)
#
# The static FLOP estimates handed to the cost model are the SAME
# analytic counts the bench derived its MFU numbers from
# (models.transformer_flops_per_token at the recorded dims), so the
# achieved-utilization evidence in the reports reproduces the recorded
# MFU exactly.  What the reports add is the mechanical part: both
# elements classify compute-bound -- dispatch, queue, and compile
# floors are ruled out by the span evidence -- so the MFU gap is the
# KERNEL's efficiency at those operating points (the quadratic
# attention share at 16k; remat recompute at train), not a pipeline
# knob.  That is the "explain the floor" outcome ISSUE 10 asks for;
# the knob-level fix lives with the kernels (ROADMAP #5 case studies).

import json
import os
import sys

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "..", "..", "..")
sys.path.insert(0, os.path.abspath(REPO))

from aiko_services_tpu.observe.trace import (           # noqa: E402
    chrome_trace_document, trace_metadata)
from aiko_services_tpu.tune import (                    # noqa: E402
    SloSpec, report_json, run_tune)

PEAK_TFLOPS = 197.0  # v5e bf16 peak (bench.py table)
HERE = os.path.dirname(os.path.abspath(__file__))
REPORTS = os.path.abspath(os.path.join(REPO, "reports"))


def _events(stages, calls):
    """Serial frame spans, each wrapping one call per stage:
    stages = [(element_name, per_call_ms)]."""
    events = [{"ph": "M", "name": "process_name", "pid": 1, "tid": 0,
               "args": {"name": "pipeline:case_study"}}]
    ts = 0.0
    for frame_id in range(calls):
        frame_start = ts
        for name, per_call_ms in stages:
            duration = per_call_ms * 1000.0  # us
            events.append({
                "ph": "X", "name": name, "cat": "element",
                "ts": round(ts, 3), "dur": round(duration, 3),
                "pid": 1, "tid": 1,
                "args": {"trace_id": f"1-{frame_id + 1:x}",
                         "frame_id": frame_id, "path": "inline",
                         "group": 1}})
            ts += duration
        events.append({
            "ph": "X", "name": f"frame {frame_id}", "cat": "frame",
            "ts": round(frame_start, 3),
            "dur": round(ts - frame_start, 3), "pid": 1, "tid": 1,
            "args": {"trace_id": f"1-{frame_id + 1:x}",
                     "status": "ok", "stream": "bench"}})
        ts += 100.0  # 0.1 ms between frames
    return events


def _element(name, inputs, outputs):
    return {
        "name": name,
        "input": [{"name": port, "type": "any"} for port in inputs],
        "output": [{"name": port, "type": "any"} for port in outputs],
        "deploy": {"local": {"module": "aiko_services_tpu.elements",
                             "class_name": "LMGenerate"}},
    }


def _write(path, document):
    with open(path, "w") as handle:
        json.dump(document, handle, sort_keys=True)
    print(f"wrote {os.path.relpath(path, REPO)}")


def longcontext():
    """Roofline 1: the 4k and 16k prefill operating points as two
    stages of one recorded run (measured per-call medians, batch 1)."""
    definition = {
        "name": "case_longcontext_prefill",
        "graph": ["(prefill_4k (prefill_16k))"],
        "elements": [
            _element("prefill_4k", ["tokens"], ["hidden"]),
            _element("prefill_16k", ["hidden"], ["hidden16"]),
        ],
    }
    config = {
        "source": "BENCH_DETAIL.json longcontext (round 5, v5e)",
        "model": "llama32_1b architecture, 8 layers (749M params)",
        "batch": 1,
        "prefill_4k_ms": 176.1, "prefill_4k_mfu": 0.1308,
        "prefill_16k_ms": 1941.8, "prefill_16k_mfu": 0.0647,
        "peak_tflops_assumed": PEAK_TFLOPS,
    }
    events = _events([("prefill_4k", 176.1), ("prefill_16k", 1941.8)],
                     calls=12)
    path = os.path.join(HERE, "longcontext_16k.json")
    _write(path, chrome_trace_document(events, metadata=trace_metadata(
        definition_document=definition, config=config,
        config_name="longcontext")))
    # the analytic flop counts the recorded MFU was derived from:
    # MFU = flops / (time * peak)  =>  flops = MFU * time * peak
    static = {
        "prefill_4k": {"rows": 1, "bytes_in": 4096 * 4,
                       "bytes_out": 4096 * 2048 * 2,
                       "param_bytes": int(749e6 * 2),
                       "flops": 0.1308 * 0.1761 * PEAK_TFLOPS * 1e12},
        "prefill_16k": {"rows": 1, "bytes_in": 16384 * 4,
                        "bytes_out": 16384 * 2048 * 2,
                        "param_bytes": int(749e6 * 2),
                        "flops": 0.0647 * 1.9418 * PEAK_TFLOPS * 1e12},
    }
    report = run_tune(path, slo_spec=SloSpec.parse("throughput"),
                      static_costs=static)
    _write_report("tune_longcontext_16k.json", report)


def train():
    """Roofline 2: the recorded train step (batch 4 x seq 1024,
    243.1 ms, MFU 0.3845 vs the >= 0.45 target)."""
    definition = {
        "name": "case_train_step",
        "graph": ["(train_step)"],
        "elements": [_element("train_step", ["batch"], ["loss"])],
    }
    config = {
        "source": "BENCH_DETAIL.json train (round 5, v5e)",
        "model": "llama32_1b architecture, 8 layers (749M params)",
        "batch": 4, "seq_len": 1024,
        "step_ms": 243.1, "train_mfu": 0.3845,
        "tokens_per_sec": 16847.4,
        "peak_tflops_assumed": PEAK_TFLOPS,
    }
    events = _events([("train_step", 243.1)], calls=20)
    path = os.path.join(HERE, "train_step.json")
    _write(path, chrome_trace_document(events, metadata=trace_metadata(
        definition_document=definition, config=config,
        config_name="train")))
    static = {
        "train_step": {"rows": 1, "bytes_in": 4 * 1024 * 4,
                       "bytes_out": 4,
                       "param_bytes": int(749e6 * 2),
                       "flops": 0.3845 * 0.2431 * PEAK_TFLOPS * 1e12},
    }
    report = run_tune(path, slo_spec=SloSpec.parse("throughput"),
                      static_costs=static)
    _write_report("tune_train_step.json", report)


def chunked_prefill():
    """Round-15 follow-up to the longcontext study: the SAME 16k
    operating point prefilled in 2k-token chunks (paged_prefill_chunk)
    instead of one monolithic kernel.

    Per-chunk times are derived from the two RECORDED operating points
    by fitting t(L) = a*L + b*L^2 through (4096, 176.1 ms) and
    (16384, 1941.8 ms) -- a is the token-linear share (projections,
    FFN), b*L^2 the quadratic attention share the round-14 study
    blamed for the floor.  Chunk i of C tokens then costs
    C*(a + b*C*i): the linear work is unchanged, but each chunk's
    attention touches only the KV written so far (C x i*C) instead of
    the full L x L rectangle, so the summed cost AND the per-call bound
    both drop.  Total FLOPs are the recorded count split evenly across
    chunks (the work is the same causal attention + matmuls).  The
    evidence the report must move: per-call cost 1941.8 ms -> one
    bounded chunk, achieved utilization 0.0647 -> the chunked value
    (~0.10) -- still compute-bound, but no longer AT the recorded
    floor, which is what CI asserts (tune as the regression harness
    for kernel work)."""
    L, C = 16384, 2048
    chunks = L // C
    t_4k, t_16k = 0.1761, 1.9418
    b = (t_16k - 4.0 * t_4k) / (16384.0 ** 2 - 4.0 * 4096.0 ** 2)
    a = (t_4k - b * 4096.0 ** 2) / 4096.0
    chunk_ms = [(C * (a + b * C * (i + 1))) * 1000.0
                for i in range(chunks)]
    flops_16k = 0.0647 * t_16k * PEAK_TFLOPS * 1e12  # recorded MFU inverted
    definition = {
        "name": "case_chunked_prefill",
        "graph": ["(prefill_16k_chunked)"],
        "elements": [
            _element("prefill_16k_chunked", ["tokens"], ["hidden"]),
        ],
    }
    config = {
        "source": ("BENCH_DETAIL.json longcontext (round 5, v5e), "
                   "chunked via the fitted t(L) = a*L + b*L^2 model"),
        "model": "llama32_1b architecture, 8 layers (749M params)",
        "batch": 1, "prompt": L, "prefill_chunk_size": C,
        "chunks_per_prompt": chunks,
        "fit_a_s_per_token": a, "fit_b_s_per_token2": b,
        "monolithic_ms": t_16k * 1000.0,
        "monolithic_mfu": 0.0647,
        "chunked_total_ms": round(sum(chunk_ms), 1),
        "peak_tflops_assumed": PEAK_TFLOPS,
    }
    # one frame = the 16k prompt = `chunks` successive chunk calls
    events = _events([("prefill_16k_chunked", ms) for ms in chunk_ms],
                     calls=12)
    path = os.path.join(HERE, "chunked_prefill_16k.json")
    _write(path, chrome_trace_document(events, metadata=trace_metadata(
        definition_document=definition, config=config,
        config_name="chunked_prefill")))
    static = {
        "prefill_16k_chunked": {
            "rows": 1, "bytes_in": C * 4,
            "bytes_out": C * 2048 * 2,
            "param_bytes": int(749e6 * 2),
            "flops": flops_16k / chunks},
    }
    report = run_tune(path, slo_spec=SloSpec.parse("throughput"),
                      static_costs=static)
    _write_report("tune_chunked_prefill.json", report)


def spec_decode():
    """The decode weight-streaming floor (BENCH_NOTES: llama32_1b 481
    tok/s at batch 4; 8.5 ms/step with the 2.47 GB weight stream +
    fixed decode-loop work dominating) vs greedy-exact speculative
    decoding at the acceptance ceiling.

    The per-step cost model is fitted from the two RECORDED batch
    points (8.5 ms at 4 tokens/step, 28.2 ms at 128 with int8 KV):
    t(n) = f + c*n with f = 7.86 ms of batch-independent work (weight
    stream + loop) and c = 0.159 ms per token-position.  A verify
    window of k+1 = 5 positions x 4 slots pays f ONCE for 20
    positions; the quarter-depth draft costs 0.25*t per call (ingest
    window + k-1 singles).  At full acceptance every round emits 20
    tokens -- the floor stops being per-token weight streaming and
    becomes prefill-shaped compute, which shows up as achieved
    utilization rising ~2x while the classification stays
    compute-bound.  CI asserts the verify element's utilization
    evidence exceeds the plain decode element's."""
    f_ms, c_ms = 7.86, 0.159    # fitted from 8.5@4 and 28.2@128
    slots, k = 4, 4
    window = k + 1
    flops_per_token = 2.47e9    # ~2 FLOPs/param, 1.24B params
    # plain arm: one generate_stream chunk of 8 steps per call
    steps_per_call = 8
    decode_call_ms = steps_per_call * (f_ms + c_ms * slots)
    decode_tokens_per_call = steps_per_call * slots
    # speculative arm at the acceptance ceiling: 8 rounds per call;
    # each round = target verify (f + 20c) + quarter-depth draft
    # (ingest window of 2 x slots + (k-1) single steps)
    verify_ms = f_ms + c_ms * slots * window
    draft_ms = 0.25 * ((f_ms + c_ms * slots * 2)
                       + (k - 1) * (f_ms + c_ms * slots))
    spec_call_ms = steps_per_call * (verify_ms + draft_ms)
    spec_tokens_per_call = steps_per_call * slots * window
    definition = {
        "name": "case_spec_decode",
        "graph": ["(decode_step (verify_step))"],
        "elements": [
            _element("decode_step", ["tokens"], ["plain"]),
            _element("verify_step", ["plain"], ["spec"]),
        ],
    }
    config = {
        "source": ("BENCH_NOTES round 5/6 decode rows (8.5 ms/step at "
                   "batch 4; 28.2 ms at batch 128) fitted as "
                   "t(n) = f + c*n"),
        "model": "llama32_1b (1.24B params)",
        "batch": slots, "spec_k": k,
        "fit_fixed_ms": f_ms, "fit_per_token_ms": c_ms,
        "accepted_len_mean": float(window),  # acceptance ceiling
        "draft_overhead_frac": round(
            draft_ms / (verify_ms + draft_ms), 3),
        "plain_tok_s": round(
            decode_tokens_per_call / decode_call_ms * 1000.0, 1),
        "spec_tok_s": round(
            spec_tokens_per_call / spec_call_ms * 1000.0, 1),
        "peak_tflops_assumed": PEAK_TFLOPS,
    }
    events = _events([("decode_step", decode_call_ms),
                      ("verify_step", spec_call_ms)], calls=20)
    path = os.path.join(HERE, "spec_decode.json")
    _write(path, chrome_trace_document(events, metadata=trace_metadata(
        definition_document=definition, config=config,
        config_name="spec_decode")))
    static = {
        "decode_step": {
            "rows": 1, "bytes_in": slots * 4,
            "bytes_out": slots * steps_per_call * 4,
            "param_bytes": int(2.47e9),
            "flops": decode_tokens_per_call * flops_per_token},
        "verify_step": {
            "rows": 1, "bytes_in": slots * window * 4,
            "bytes_out": slots * window * 4,
            "param_bytes": int(2.47e9),
            "flops": spec_tokens_per_call * flops_per_token},
    }
    report = run_tune(path, slo_spec=SloSpec.parse("throughput"),
                      static_costs=static)
    _write_report("tune_spec_decode.json", report)


def disagg_adopt():
    """Round-16 study: prefill/decode disaggregation's KV migration as
    a CLASSIFIABLE floor (`aiko tune` distinguishes migration-bound
    from queue-bound, tune/model.py).

    Two decode-pool elements over one synthesized serving window, both
    with the recorded 8.5 ms/step decode compute (BENCH_NOTES
    llama32_1b batch 4):

      lm_adopt   adopts CROSS-HOST handoffs: llama32_1b KV is 32 KiB
                 per token (2 sides x 16 layers x 8 kv-heads x 64 dims
                 x bf16), so a 2k-token prompt migrates 64 MiB -- at a
                 10 GbE transfer plane that is ~52 ms per adoption,
                 dominating both compute and slot-queue wait ->
                 migration-bound (fix the wire or the pool placement,
                 NOT decode_slots)
      lm_queued  same compute but a saturated slot pool: 30 ms median
                 slot wait -> queue-bound (raise decode_slots)

    The report's value is the DISTINCTION: identical compute medians,
    different dominant floors, different recommended knobs."""
    decode_ms = 8.5          # BENCH_NOTES round 5/6 decode row
    adopt_ms = 52.4          # 64 MiB / 10 GbE + scatter
    slot_wait_ms = 30.0
    light_wait_ms = 2.0
    definition = {
        "name": "case_disagg_adopt",
        "graph": ["(lm_adopt (lm_queued))"],
        "elements": [
            _element("lm_adopt", ["handoff"], ["tokens"]),
            _element("lm_queued", ["tokens"], ["generated"]),
        ],
    }
    config = {
        "source": ("BENCH_NOTES decode row (8.5 ms/step, llama32_1b "
                   "batch 4); adopt = 64 MiB KV per 2k prompt over "
                   "10 GbE"),
        "model": "llama32_1b (1.24B params, int8-free KV sizing)",
        "kv_bytes_per_token": 32 * 1024,
        "prompt_tokens": 2048,
        "adopt_ms": adopt_ms,
        "decode_step_ms": decode_ms,
        "peak_tflops_assumed": PEAK_TFLOPS,
    }
    events = [{"ph": "M", "name": "process_name", "pid": 1, "tid": 0,
               "args": {"name": "pipeline:case_study"}}]
    ts = 0.0
    for frame_id in range(16):
        frame_start = ts
        args = {"trace_id": f"1-{frame_id + 1:x}",
                "frame_id": frame_id}
        # lm_adopt: a light slot wait, the MIGRATION, then compute
        events.append({"ph": "X", "name": "queue:lm_adopt",
                       "cat": "queue", "ts": round(ts, 3),
                       "dur": light_wait_ms * 1000.0, "pid": 1,
                       "tid": 1, "args": dict(args)})
        ts += light_wait_ms * 1000.0
        events.append({"ph": "X", "name": "adopt:lm_adopt",
                       "cat": "engine", "ts": round(ts, 3),
                       "dur": adopt_ms * 1000.0, "pid": 1, "tid": 1,
                       "args": dict(args)})
        ts += adopt_ms * 1000.0
        events.append({"ph": "X", "name": "lm_adopt",
                       "cat": "element", "ts": round(ts, 3),
                       "dur": decode_ms * 1000.0, "pid": 1, "tid": 1,
                       "args": {**args, "path": "inline", "group": 1}})
        ts += decode_ms * 1000.0
        # lm_queued: the same compute behind a saturated slot pool
        events.append({"ph": "X", "name": "queue:lm_queued",
                       "cat": "queue", "ts": round(ts, 3),
                       "dur": slot_wait_ms * 1000.0, "pid": 1,
                       "tid": 1, "args": dict(args)})
        ts += slot_wait_ms * 1000.0
        events.append({"ph": "X", "name": "lm_queued",
                       "cat": "element", "ts": round(ts, 3),
                       "dur": decode_ms * 1000.0, "pid": 1, "tid": 1,
                       "args": {**args, "path": "inline", "group": 1}})
        ts += decode_ms * 1000.0
        events.append({"ph": "X", "name": f"frame {frame_id}",
                       "cat": "frame", "ts": round(frame_start, 3),
                       "dur": round(ts - frame_start, 3), "pid": 1,
                       "tid": 1,
                       "args": {**args, "status": "ok",
                                "stream": "bench"}})
        ts += 100.0
    path = os.path.join(HERE, "disagg_adopt.json")
    _write(path, chrome_trace_document(events, metadata=trace_metadata(
        definition_document=definition, config=config,
        config_name="disagg")))
    report = run_tune(path, slo_spec=SloSpec.parse("throughput"))
    floors = {name: record["floor"]
              for name, record in report["elements"].items()}
    assert floors == {"lm_adopt": "migration-bound",
                      "lm_queued": "queue-bound"}, floors
    _write_report("tune_disagg_adopt.json", report)


def _write_report(name, report):
    os.makedirs(REPORTS, exist_ok=True)
    path = os.path.join(REPORTS, name)
    with open(path, "w") as handle:
        handle.write(report_json(report) + "\n")
    print(f"wrote {os.path.relpath(path, REPO)}: "
          + ", ".join(f"{element}={record['floor']}"
                      for element, record
                      in sorted(report["elements"].items())))


if __name__ == "__main__":
    longcontext()
    train()
    chunked_prefill()
    spec_decode()
    disagg_adopt()
