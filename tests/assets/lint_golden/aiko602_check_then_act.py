# Golden fixture: AIKO602 -- check-then-act on a shared attribute
# across thread roles without a lock.  The timer may expire the
# session between the `is not None` check and the dereference.


class Worker:  # stand-in fleet base so the class is analyzed
    pass


class SessionWorker(Worker):

    def __init__(self):
        self._session = None
        self.add_timer_handler(self._expire, 5.0)

    def _expire(self):
        # timer role: drops the session
        self._session = None

    def lookup(self, key):
        # wire role: TOCTOU against the timer -> AIKO602
        if self._session is not None:
            return self._session.fetch(key)
        return None
