# Golden fixture: AIKO604 -- lock-order inversion.  `credit` takes
# A then B; `debit` takes B then A: two threads interleaving the
# outer acquires deadlock.

import threading


class Manager:  # stand-in fleet base so the class is analyzed
    pass


class LedgerManager(Manager):

    def __init__(self):
        self._lock_a = threading.Lock()
        self._lock_b = threading.Lock()
        self._balance = 0

    def credit(self, amount):
        with self._lock_a:
            with self._lock_b:
                self._balance += amount

    def debit(self, amount):
        with self._lock_b:  # AIKO604: reversed acquire order
            with self._lock_a:
                self._balance -= amount
