# Golden fixture: AIKO603 -- blocking call while holding a lock.
# Sleeping under the mutex stalls every thread contending for it.

import threading
import time


class Keeper:  # stand-in fleet base so the class is analyzed
    pass


class SnapshotKeeper(Keeper):

    def __init__(self):
        self._lock = threading.Lock()
        self._blobs = {}

    def flush(self):
        with self._lock:
            time.sleep(0.5)  # AIKO603: blocking while holding _lock
            self._blobs.clear()
