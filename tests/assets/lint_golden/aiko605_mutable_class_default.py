# Golden fixture: AIKO605 -- mutable class-level default mutated
# through self.  Every instance shares ONE list; `join` on one actor
# is visible from (and races with) every other instance.


class Actor:  # stand-in fleet base so the class is analyzed
    pass


class RosterActor(Actor):

    members = []  # shared across instances

    def join(self, name):
        self.members.append(name)  # AIKO605
