# Golden fixture: AIKO601 -- unsynchronized iteration of a container
# attribute mutated from another thread role.
#
# Historical repro: `Pipeline.load()` iterated the live `streams` map
# while the event-loop timer reaped finished streams underneath it --
# "RuntimeError: dictionary changed size during iteration" on a
# gateway-driven restore.  The fix is a `list()` snapshot before the
# loop; this fixture preserves the broken shape so the rule keeps
# firing.


class Pipeline:  # stand-in fleet base so the class is analyzed
    pass


class ReplayPipeline(Pipeline):

    def __init__(self):
        self.streams = {}
        self.add_timer_handler(self._reap, 1.0)

    def _reap(self):
        # timer role: mutates the stream map on the event loop
        for stream_id in list(self.streams):
            if self.streams[stream_id] is None:
                del self.streams[stream_id]

    def load(self, checkpoint):
        # wire role (public, callable from any thread): live iteration
        # of the same map the timer mutates -> AIKO601
        for stream_id, stream in self.streams.items():
            stream.restore(checkpoint, stream_id)
