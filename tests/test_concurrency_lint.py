# Thread-role-aware static race detector (ISSUE 20): the AIKO6xx
# concurrency pass over Python source -- role inference from dispatch
# registration sites, the five rule families on golden fixtures
# (including the historical `Pipeline.load()` live-dict repro),
# baseline add/expire, `# aiko: allow` statement suppression, and
# byte-identical JSON reports -- plus churn-storm regression tests for
# the in-tree `list()`-snapshot fixes the pass surfaced.

import ast
import json
import threading
from pathlib import Path
from types import SimpleNamespace

import pytest

from aiko_services_tpu.analyze import (
    apply_baseline, finding_fingerprint, load_baseline, role_map,
    run_code_pass, write_baseline)
from aiko_services_tpu.analyze.actor_lint import statement_suppressed

REPO = Path(__file__).resolve().parents[1]
PACKAGE = REPO / "aiko_services_tpu"
GOLDEN = REPO / "tests" / "assets" / "lint_golden"
BASELINE = REPO / "tests" / "assets" / "lint_code_baseline.json"

_ROLE_SOURCE = '''
import threading


class PumpActor:

    def __init__(self):
        self.add_mailbox_handler(self._on_mail, "topic")
        self.add_timer_handler(self._tick, 1.0)
        threading.Thread(target=self._drain, daemon=True).start()

    def _on_mail(self, message):
        self._log(message)

    def _tick(self):
        pass

    def _drain(self):
        pass

    def _log(self, message):
        pass

    def expose(self):
        pass

    def _manual(self):  # aiko: role=worker
        pass
'''


class TestRoleInference:
    def test_registration_sites_and_wire(self):
        roles = role_map(_ROLE_SOURCE)["PumpActor"]
        assert roles["_on_mail"] == ["mailbox"]
        assert roles["_tick"] == ["timer"]
        assert roles["_drain"] == ["worker:_drain"]
        assert roles["expose"] == ["wire"]
        assert roles["__init__"] == []      # dunders carry no role

    def test_roles_propagate_through_self_calls(self):
        roles = role_map(_ROLE_SOURCE)["PumpActor"]
        # _log is only ever called from the mailbox handler
        assert roles["_log"] == ["mailbox"]

    def test_explicit_role_comment_escape_hatch(self):
        roles = role_map(_ROLE_SOURCE)["PumpActor"]
        assert roles["_manual"] == ["worker"]

    def test_role_comment_above_def_line(self):
        source = (
            "class FlushActor:\n"
            "    # aiko: role=timer\n"
            "    def flush(self):\n"
            "        pass\n")
        assert role_map(source)["FlushActor"]["flush"] == ["timer"]


class TestRuleFixtures:
    @pytest.mark.parametrize("code,stem", [
        ("AIKO601", "aiko601_live_dict_iteration"),
        ("AIKO602", "aiko602_check_then_act"),
        ("AIKO603", "aiko603_blocking_under_lock"),
        ("AIKO604", "aiko604_lock_inversion"),
        ("AIKO605", "aiko605_mutable_class_default"),
    ])
    def test_rule_fires_on_golden_fixture(self, code, stem):
        report = run_code_pass([GOLDEN / f"{stem}.py"], root=GOLDEN)
        assert code in {d.code for d in report.findings}, \
            report.render()

    def test_historical_pipeline_load_repro_is_aiko601(self):
        """The round-19 `Pipeline.load()` bug -- live iteration of the
        stream dict the event loop mutates -- must stay detected."""
        report = run_code_pass(
            [GOLDEN / "aiko601_live_dict_iteration.py"], root=GOLDEN)
        hits = [d for d in report.findings if d.code == "AIKO601"]
        assert hits, report.render()
        finding = hits[0]
        assert finding.definition == "ReplayPipeline"
        assert finding.element == "load"
        assert finding.port == "streams"

    def test_loop_affine_roles_never_race_each_other(self):
        """A timer iterating a dict only the mailbox mutates shares
        the one event-loop thread: no finding."""
        source = (
            "class QuietActor:\n"
            "    def __init__(self):\n"
            "        self.add_mailbox_handler(self._on_mail, 't')\n"
            "        self.add_timer_handler(self._tick, 1.0)\n"
            "        self.jobs = {}\n"
            "    def _on_mail(self, message):\n"
            "        self.jobs[message] = 1\n"
            "    def _tick(self):\n"
            "        for job in self.jobs.values():\n"
            "            job.poke()\n")
        report = _run_on_source(source)
        assert not report.findings, report.render()

    def test_snapshot_iteration_is_clean(self):
        """`list()` before iterating -- the prescribed fix -- clears
        the finding even against a worker-thread mutator."""
        source = (
            "import threading\n"
            "class SnapActor:\n"
            "    def __init__(self):\n"
            "        self.jobs = {}\n"
            "        threading.Thread(target=self._pump).start()\n"
            "    def _pump(self):\n"
            "        self.jobs.clear()\n"
            "    def walk(self):\n"
            "        for job in list(self.jobs.values()):\n"
            "            job.poke()\n")
        report = _run_on_source(source)
        assert "AIKO601" not in {d.code for d in report.findings}, \
            report.render()


def _run_on_source(source, tmp_path=None, name="fixture_module.py"):
    import tempfile
    with tempfile.TemporaryDirectory() as directory:
        path = Path(directory) / name
        path.write_text(source)
        return run_code_pass([path], root=Path(directory))


class TestSuppression:
    def test_allow_comment_suppresses_finding(self):
        source = (GOLDEN / "aiko601_live_dict_iteration.py").read_text()
        patched = source.replace(
            "for stream_id, stream in self.streams.items():",
            "for stream_id, stream in self.streams.items():"
            "  # aiko: allow")
        assert not _run_on_source(patched).findings

    def test_allow_comment_on_any_line_of_multiline_statement(self):
        source = (
            "import threading\n"
            "class SpanActor:\n"
            "    def __init__(self):\n"
            "        self.jobs = {}\n"
            "        threading.Thread(target=self._pump).start()\n"
            "    def _pump(self):\n"
            "        self.jobs.clear()\n"
            "    def walk(self):\n"
            "        for job in (\n"
            "                self.jobs.values()):  # aiko: allow\n"
            "            job.poke()\n")
        assert not _run_on_source(source).findings

    def test_statement_suppressed_helper_spans_statements(self):
        source = ("value = [\n"
                  "    1,\n"
                  "    2,  # aiko: allow\n"
                  "]\n"
                  "other = 3\n")
        lines = source.splitlines()
        statements = ast.parse(source).body
        assert statement_suppressed(lines, statements[0])
        assert not statement_suppressed(lines, statements[1])


class TestBaseline:
    def test_write_then_apply_filters_everything(self, tmp_path):
        report = run_code_pass([GOLDEN], root=GOLDEN)
        assert report.findings
        baseline_path = tmp_path / "baseline.json"
        count = write_baseline(baseline_path, report)
        assert count == len({finding_fingerprint(d)
                             for d in report.findings})
        entries = load_baseline(baseline_path)
        fresh = run_code_pass([GOLDEN], root=GOLDEN)
        filtered = apply_baseline(fresh, entries)
        assert filtered == len(report.findings)
        assert not fresh.failures(strict=True)

    def test_stale_entry_surfaces_as_aiko600_info(self):
        report = run_code_pass([GOLDEN], root=GOLDEN)
        stale = "AIKO601 gone.py Gone.method attribute"
        apply_baseline(report, [stale])
        notes = [d for d in report.findings if d.code == "AIKO600"]
        assert any(stale in d.message for d in notes)
        # stale entries nag but never fail the build
        assert all(d.severity == "info" for d in notes)

    def test_new_finding_not_masked_by_unrelated_baseline(self):
        report = run_code_pass(
            [GOLDEN / "aiko601_live_dict_iteration.py"], root=GOLDEN)
        apply_baseline(
            report, ["AIKO602 other.py Other.method attribute"])
        assert "AIKO601" in {d.code for d in report.findings}

    def test_committed_baseline_matches_tree(self):
        """CI contract: `aiko lint --code aiko_services_tpu/ --strict`
        against the committed baseline reports nothing new."""
        report = run_code_pass([PACKAGE], root=REPO)
        apply_baseline(report, load_baseline(BASELINE))
        leftovers = report.failures(strict=True)
        assert not leftovers, "\n".join(d.render() for d in leftovers)
        stale = [d for d in report.findings if d.code == "AIKO600"]
        assert not stale, "\n".join(d.render() for d in stale)


class TestDeterminism:
    def test_two_runs_render_byte_identical_json(self):
        first = run_code_pass([PACKAGE], root=REPO).to_json()
        second = run_code_pass([PACKAGE], root=REPO).to_json()
        assert first == second

    def test_cli_code_mode_clean_against_baseline(self, tmp_path):
        from click.testing import CliRunner

        from aiko_services_tpu.cli import main

        output = tmp_path / "report.json"
        result = CliRunner().invoke(main, [
            "lint", "--code", str(PACKAGE), "--strict", "--format",
            "json", "--baseline", str(BASELINE),
            "--output", str(output)])
        assert result.exit_code == 0, result.output
        document = json.loads(output.read_text())
        assert document["summary"]["errors"] == 0
        assert document["summary"]["warnings"] == 0


class TestChurnStormRegressions:
    """The fixed `Pipeline.load()`-class sites, exercised the way the
    detector says they break: a thread mutating the container while
    the (now snapshotting) reader iterates.  Dict/set iteration
    raises RuntimeError mid-churn without the `list()` fix."""

    ROUNDS = 300

    def _storm(self, mutate, read):
        stop = threading.Event()
        errors = []

        def churn():
            index = 0
            while not stop.is_set():
                try:
                    mutate(index)
                except Exception as error:  # pragma: no cover
                    errors.append(error)
                    return
                index += 1

        thread = threading.Thread(target=churn, daemon=True)
        thread.start()
        try:
            for _ in range(self.ROUNDS):
                read()
        finally:
            stop.set()
            thread.join(timeout=5.0)
        assert not errors, errors

    def _gateway(self):
        from aiko_services_tpu.runtime import Process
        from aiko_services_tpu.serve import Gateway
        from aiko_services_tpu.transport import reset_brokers

        reset_brokers()
        process = Process(transport_kind="loopback")
        return Gateway(process, policy="max_inflight=64;queue=256",
                       router_seed=1, metrics_interval=3600.0)

    def test_signal_throttle_survives_stream_churn(self):
        gateway = self._gateway()

        def mutate(index):
            key = f"s{index % 8}"
            if key in gateway.streams:
                del gateway.streams[key]
            else:
                gateway.streams[key] = SimpleNamespace(throttled=False)

        self._storm(mutate, lambda: gateway._signal_throttle(0.0))

    def test_set_replica_parameter_survives_replica_churn(self):
        gateway = self._gateway()

        def mutate(index):
            key = f"r{index % 8}"
            if key in gateway.replicas:
                del gateway.replicas[key]
            else:
                gateway.replicas[key] = SimpleNamespace(
                    dead=True, draining=False)

        self._storm(
            mutate,
            lambda: gateway.set_replica_parameter("lm", "k", "v"))

    def test_bucket_levels_survives_bucket_churn(self):
        from aiko_services_tpu.serve import TokenBucket

        gateway = self._gateway()

        def mutate(index):
            key = index % 8
            if key in gateway.policy.buckets:
                del gateway.policy.buckets[key]
            else:
                gateway.policy.buckets[key] = TokenBucket(10.0, 10.0)

        self._storm(mutate, gateway._bucket_levels)

    def test_queue_depth_survives_parked_churn(self):
        gateway = self._gateway()

        def mutate(index):
            if gateway._parked and index % 2:
                gateway._parked.pop()
            else:
                gateway._parked.append((index % 3, index, f"s{index}",
                                        f"f{index}"))

        self._storm(mutate, gateway._note_queue_depth)

    def test_ec_consumer_notify_survives_handler_self_removal(self):
        """A change handler de-registering DURING notification must
        not starve the handlers behind it (live-list iteration used
        to skip the next handler)."""
        from aiko_services_tpu.runtime.share import ECConsumer

        consumer = ECConsumer.__new__(ECConsumer)
        calls = []

        def selfish(consumer_, command, name, value):
            calls.append("selfish")
            consumer_._change_handlers.remove(selfish)

        def bystander(consumer_, command, name, value):
            calls.append("bystander")

        consumer._change_handlers = [selfish, bystander]
        consumer._notify("add", "x", 1)
        assert calls == ["selfish", "bystander"]

    def test_process_rejoin_survives_service_churn(self):
        from aiko_services_tpu.runtime import Process
        from aiko_services_tpu.transport import reset_brokers

        reset_brokers()
        process = Process(transport_kind="loopback")
        process.publish = lambda *args, **kwargs: None
        process._register_service = lambda fields: None
        process.registrar = SimpleNamespace()
        process.connection.is_connected = lambda state: True

        def mutate(index):
            key = f"svc{index % 8}"
            if key in process._services:
                del process._services[key]
            else:
                process._services[key] = SimpleNamespace(
                    service_fields=lambda: None)

        self._storm(mutate, process.rejoin)
