# Functional speech correctness: the pipeline must TRANSCRIBE, not
# just produce token-shaped output (VERDICT r3 item 5: nothing failed
# if every transcription was wrong).  The committed checkpoint
# (tests/assets/asr_tones.safetensors, trained by
# examples/train_asr_tones.py to exact held-out accuracy on tone ->
# word labels) flows through the REAL element path: audio in ->
# SpeechToText(weights=...) -> TokensToText -> correct text out.
#
# Reference parity: the reference's speech seat transcribes because it
# loads pretrained WhisperX (speech_elements.py:229-262); with no
# published checkpoints in this image, a trained-to-correctness tiny
# model proves the same capability end to end.

import pathlib
import queue

import numpy as np
import pytest

from aiko_services_tpu.pipeline import create_pipeline
from aiko_services_tpu.runtime import Process
from aiko_services_tpu.transport import reset_brokers

ASSET = pathlib.Path(__file__).parent / "assets" / "asr_tones.safetensors"


def _asset_metadata() -> dict:
    """The authoritative training config/labels ride in the checkpoint's
    safetensors metadata (examples/train_asr_tones.py) -- retraining
    with different dims cannot drift from this test."""
    import ast

    from aiko_services_tpu.models import SafetensorsFile
    container = SafetensorsFile(ASSET)
    metadata = {key: ast.literal_eval(value)
                for key, value in container.metadata.items()}
    container.close()
    return metadata


_METADATA = _asset_metadata()
LABELS = {float(freq): label
          for freq, label in _METADATA["labels"].items()}
SECONDS, SAMPLE_RATE = float(_METADATA["seconds"]), 16000
_CONFIG = _METADATA["config"]
ASR_PARAMETERS = {**{key: value for key, value in _CONFIG.items()
                     if key != "max_text_len"},
                  "max_tokens": 9, "weights": str(ASSET)}


@pytest.fixture(autouse=True)
def clean_brokers():
    reset_brokers()
    yield
    reset_brokers()


def _tone(frequency: float) -> np.ndarray:
    t = np.arange(int(SECONDS * SAMPLE_RATE)) / SAMPLE_RATE
    return np.sin(2 * np.pi * frequency * t).astype(np.float32)


def test_pipeline_transcribes_audio_to_correct_text():
    """Audio in -> CORRECT text out: fails if the pipeline stops
    transcribing (wrong text, not just wrong shapes)."""
    definition = {
        "name": "asr_correct",
        "graph": ["(asr (text))"],
        "elements": [
            {"name": "asr", "input": [{"name": "audio"}],
             "output": [{"name": "tokens"}],
             "parameters": ASR_PARAMETERS,
             "deploy": {"local": {
                 "module": "aiko_services_tpu.elements",
                 "class_name": "SpeechToText"}}},
            {"name": "text", "input": [{"name": "tokens"}],
             "output": [{"name": "text"}],
             "deploy": {"local": {
                 "module": "aiko_services_tpu.elements",
                 "class_name": "TokensToText"}}},
        ],
    }
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, definition)
    process.run(in_thread=True)
    responses = queue.Queue()
    stream = pipeline.create_stream("s1", queue_response=responses)
    for frequency in LABELS:
        pipeline.create_frame(stream, {"audio": _tone(frequency)[None]})
    got = {}
    for _ in LABELS:
        _, frame, outputs = responses.get(timeout=120)
        got[frame.frame_id] = outputs["text"]
    transcripts = [got[index][0] for index in range(len(LABELS))]
    # byte-vocab decode pads with eot which TokensToText drops, but be
    # strict about stray bytes: exact equality
    assert transcripts == list(LABELS.values()), transcripts
    process.terminate()


def test_transcription_distinguishes_held_out_jittered_tones():
    """Noisy, phase/amplitude-jittered tones (never seen in training)
    still transcribe exactly -- the model generalizes, not memorizes."""
    from aiko_services_tpu.models import AsrConfig, load_pytree
    from aiko_services_tpu.models.asr import transcribe_audio
    config = AsrConfig(**_CONFIG)
    params = load_pytree(ASSET, dtype=config.dtype)
    rng = np.random.default_rng(987654)
    t = np.arange(int(SECONDS * SAMPLE_RATE)) / SAMPLE_RATE
    audio, expected = [], []
    for frequency, label in LABELS.items():
        for _ in range(3):
            wave = (rng.uniform(0.5, 1.0)
                    * np.sin(2 * np.pi * frequency
                             * (1 + rng.uniform(-0.004, 0.004)) * t
                             + rng.uniform(0, 2 * np.pi)))
            wave += rng.normal(0, 0.01, wave.shape)
            audio.append(wave.astype(np.float32))
            expected.append(label)
    tokens = np.asarray(transcribe_audio(
        params, config, np.stack(audio), max_tokens=9))
    texts = ["".join(chr(token - 3) for token in row
                     if 3 <= token < 259)
             for row in tokens]
    assert texts == expected, list(zip(texts, expected))
