import pytest

from aiko_services_tpu.runtime import (
    Actor, ConnectionState, ECConsumer, ECProducer, Process, Registrar,
    ServiceFilter, ServicesCache, make_proxy)
from aiko_services_tpu.transport import reset_brokers
from helpers import wait_for


@pytest.fixture(autouse=True)
def clean_brokers():
    reset_brokers()
    yield
    reset_brokers()


def start_process(**kwargs):
    process = Process(transport_kind="loopback", **kwargs)
    return process


class EchoActor(Actor):
    def __init__(self, process, name="echo"):
        super().__init__(process, name)
        self.received = []

    def echo(self, *args):
        self.received.append(list(args))

    def control_reset(self):
        self.received.append("RESET")


def test_registrar_election_and_service_registration():
    registrar_process = start_process()
    registrar = Registrar(registrar_process, search_timeout=0.05)
    registrar_process.run(in_thread=True)
    wait_for(lambda: registrar.state == "primary")

    worker_process = start_process()
    actor = EchoActor(worker_process)
    worker_process.run(in_thread=True)
    wait_for(lambda: worker_process.connection.is_connected(
        ConnectionState.REGISTRAR))
    wait_for(lambda: registrar.services_table.get_service(actor.topic_path))
    fields = registrar.services_table.get_service(actor.topic_path)
    assert fields.name == "echo"

    registrar_process.terminate()
    worker_process.terminate()


def test_second_registrar_becomes_secondary():
    process_a = start_process()
    registrar_a = Registrar(process_a, search_timeout=0.05)
    process_a.run(in_thread=True)
    wait_for(lambda: registrar_a.state == "primary")

    process_b = start_process()
    registrar_b = Registrar(process_b, search_timeout=0.05)
    process_b.run(in_thread=True)
    wait_for(lambda: registrar_b.state == "secondary")

    process_a.terminate()
    process_b.terminate()


def test_registrar_failover_on_lwt():
    process_a = start_process()
    registrar_a = Registrar(process_a, search_timeout=0.05)
    process_a.run(in_thread=True)
    wait_for(lambda: registrar_a.state == "primary")

    process_b = start_process()
    registrar_b = Registrar(process_b, search_timeout=0.05)
    process_b.run(in_thread=True)
    wait_for(lambda: registrar_b.state == "secondary")

    # simulate crash: unclean disconnect fires registrar LWT
    process_a.transport.disconnect(send_lwt=True)
    process_a.event.terminate()
    wait_for(lambda: registrar_b.state == "primary", timeout=5)
    process_b.terminate()


def test_registrar_reaps_dead_process_services():
    registrar_process = start_process()
    registrar = Registrar(registrar_process, search_timeout=0.05)
    registrar_process.run(in_thread=True)
    wait_for(lambda: registrar.state == "primary")

    worker_process = start_process()
    actor = EchoActor(worker_process)
    worker_process.run(in_thread=True)
    wait_for(lambda: registrar.services_table.get_service(actor.topic_path))

    # crash the worker: LWT "(absent)" on its /0/state reaps all services
    worker_process.transport.disconnect(send_lwt=True)
    worker_process.event.terminate()
    wait_for(lambda: registrar.services_table.get_service(
        actor.topic_path) is None)
    registrar_process.terminate()


def test_remote_proxy_invocation():
    registrar_process = start_process()
    Registrar(registrar_process, search_timeout=0.05)
    registrar_process.run(in_thread=True)

    worker_process = start_process()
    actor = EchoActor(worker_process)
    worker_process.run(in_thread=True)

    caller_process = start_process()
    caller_process.run(in_thread=True)
    proxy = make_proxy(caller_process, actor.topic_path)
    proxy.echo("hello", "42")
    wait_for(lambda: actor.received)
    assert actor.received == [["hello", "42"]]

    proxy.control_reset()
    wait_for(lambda: "RESET" in actor.received)

    for process in (registrar_process, worker_process, caller_process):
        process.terminate()


def test_ec_producer_consumer_sync():
    producer_process = start_process()
    actor = EchoActor(producer_process)
    producer = ECProducer(actor)
    actor.share["metric"] = "1"
    producer_process.run(in_thread=True)

    consumer_process = start_process()
    consumer_process.run(in_thread=True)
    cache = {}
    consumer = ECConsumer(consumer_process, cache, actor.topic_path,
                          lease_time=60)
    wait_for(lambda: consumer.synced)
    assert cache["metric"] == "1"
    assert cache["lifecycle"] == "ready"

    producer.update("metric", "2")
    wait_for(lambda: cache.get("metric") == "2")

    producer.update("nested.value", "7")
    wait_for(lambda: cache.get("nested", {}).get("value") == "7")

    producer.remove("metric")
    wait_for(lambda: "metric" not in cache)

    consumer.terminate()
    producer_process.terminate()
    consumer_process.terminate()


def test_ec_remote_write_via_control_topic():
    producer_process = start_process()
    actor = EchoActor(producer_process)
    ECProducer(actor)
    producer_process.run(in_thread=True)

    writer_process = start_process()
    writer_process.run(in_thread=True)
    writer_process.publish(actor.topic_control, "(update log_level DEBUG)")
    wait_for(lambda: actor.share.get("log_level") == "DEBUG")
    producer_process.terminate()
    writer_process.terminate()


def test_services_cache_mirrors_registrar():
    registrar_process = start_process()
    registrar = Registrar(registrar_process, search_timeout=0.05)
    registrar_process.run(in_thread=True)
    wait_for(lambda: registrar.state == "primary")

    worker_process = start_process()
    actor = EchoActor(worker_process)
    worker_process.run(in_thread=True)

    observer_process = start_process()
    cache = ServicesCache(observer_process)
    events = []
    cache.add_handler(lambda command, fields: events.append(
        (command, fields.name)), ServiceFilter(name="echo"))
    observer_process.run(in_thread=True)

    wait_for(lambda: ("add", "echo") in events)

    actor.stop()
    wait_for(lambda: ("remove", "echo") in events)

    for process in (registrar_process, worker_process, observer_process):
        process.terminate()
