# Static analysis (aiko_services_tpu/analyze): tensor-spec grammar,
# graph/shape-flow verification, actor-safety lint, policy grammars,
# the golden corpus of deliberately-broken definitions, and the
# construction-time validation seam in Pipeline.__init__.

import json
import os
import sys
from pathlib import Path

import pytest

ASSETS = Path(__file__).parent / "assets"
GOLDEN = ASSETS / "lint_golden"
EXAMPLES = Path(__file__).parent.parent / "examples"
if str(ASSETS) not in sys.path:  # lint_fixture_elements deploys
    sys.path.insert(0, str(ASSETS))

from aiko_services_tpu.analyze import (  # noqa: E402
    ALL_PASSES, CHEAP_PASSES, RULES, GrammarError, SpecError,
    analyze_definition, parse_port_type)
from aiko_services_tpu.analyze.specs import check_flow  # noqa: E402
from aiko_services_tpu.pipeline import (  # noqa: E402
    DefinitionError, parse_pipeline_definition)

ELEMENTS = "aiko_services_tpu.elements"


def local(class_name, module=ELEMENTS):
    return {"local": {"module": module, "class_name": class_name}}


def tiny_definition(**overrides):
    definition = {
        "name": "tiny",
        "graph": ["(source (sink))"],
        "elements": [
            {"name": "source",
             "output": [{"name": "text", "type": "str"}],
             "parameters": {"data_sources": ["x"]},
             "deploy": local("TextSource")},
            {"name": "sink",
             "input": [{"name": "text", "type": "str"}],
             "output": [{"name": "text", "type": "str"}],
             "deploy": local("TextTransform")},
        ],
    }
    definition.update(overrides)
    return definition


# -- tensor-spec grammar -----------------------------------------------------

class TestSpecGrammar:
    def test_tensor_spec_round_trip(self):
        spec = parse_port_type("f32[b,3,224,224]")
        assert spec.is_tensor
        assert spec.dtype == "float32"
        assert spec.dims == ("b", 3, 224, 224)

    def test_long_dtype_names_and_wildcards(self):
        spec = parse_port_type("bfloat16[b,*,d]")
        assert spec.dtype == "bfloat16"
        assert spec.dims == ("b", "*", "d")

    def test_scalar_and_opaque(self):
        assert parse_port_type("f32[]").dims == ()
        assert parse_port_type("str").kind == "str"
        assert parse_port_type(None).is_any
        assert parse_port_type("any").is_any

    @pytest.mark.parametrize("bad", [
        "f33[2,2]", "f32[2,", "f32[-1]", "f32[2,]", "f32[a b]",
        "notatype",
    ])
    def test_bad_specs_raise(self, bad):
        with pytest.raises(SpecError):
            parse_port_type(bad)

    def test_flow_dtype_rank_dim(self):
        f = parse_port_type
        assert check_flow(f("f32[4,4]"), f("f32[4,4]"), {}) == []
        assert check_flow(f("f32[4,4]"), f("any"), {}) == []
        codes = [c for c, _ in check_flow(f("f32[4,4]"),
                                          f("i32[4,4]"), {})]
        assert codes == ["AIKO202"]
        codes = [c for c, _ in check_flow(f("f32[4,4]"), f("f32[4]"),
                                          {})]
        assert codes == ["AIKO203"]
        codes = [c for c, _ in check_flow(f("f32[4,8]"),
                                          f("f32[4,16]"), {})]
        assert codes == ["AIKO204"]

    def test_symbol_binds_once_per_graph(self):
        f = parse_port_type
        bindings = {}
        assert check_flow(f("f32[b,4]"), f("f32[2,4]"), bindings) == []
        assert bindings["b"][0] == 2
        codes = [c for c, _ in check_flow(f("f32[b,9]"), f("f32[3,9]"),
                                          bindings)]
        assert codes == ["AIKO205"]

    def test_tensor_into_opaque_clashes_but_opaques_duck_type(self):
        f = parse_port_type
        codes = [c for c, _ in check_flow(f("f32[4]"), f("str"), {})]
        assert codes == ["AIKO202"]
        assert check_flow(f("str"), f("list"), {}) == []


# -- golden corpus -----------------------------------------------------------

GOLDEN_FILES = sorted(GOLDEN.glob("*.json"))


class TestGoldenCorpus:
    def test_corpus_is_large_enough(self):
        assert len(GOLDEN_FILES) >= 12

    @pytest.mark.parametrize(
        "path", GOLDEN_FILES, ids=[p.stem for p in GOLDEN_FILES])
    def test_expected_rule_fires(self, path):
        expected = path.stem.split("_", 1)[0].upper()
        assert expected in RULES, f"{path.name}: bad code prefix"
        report = analyze_definition(path, passes=ALL_PASSES,
                                    source_path=str(path))
        codes = {d.code for d in report.findings}
        assert expected in codes, (
            f"{path.name}: expected {expected}, got {sorted(codes)}")


# -- shipped definitions are clean (strict mode) -----------------------------

class TestShippedDefinitionsClean:
    @pytest.mark.parametrize(
        "path", sorted(EXAMPLES.glob("pipeline_*.json")),
        ids=[p.stem for p in sorted(EXAMPLES.glob("pipeline_*.json"))])
    def test_examples_zero_findings_strict(self, path):
        report = analyze_definition(path, passes=ALL_PASSES,
                                    source_path=str(path))
        assert report.failures(strict=True) == [], report.render()

    def test_bench_definitions_zero_findings_strict(self, monkeypatch):
        import runpy
        monkeypatch.setenv("AIKO_BENCH_SMOKE", "1")
        bench = runpy.run_path(
            str(Path(__file__).parent.parent / "bench.py"))
        definitions = bench["collect_definitions"]()
        assert len(definitions) >= 6
        for name, definition in definitions.items():
            report = analyze_definition(definition, passes=ALL_PASSES)
            assert report.failures(strict=True) == [], (
                f"{name}: {report.render()}")

    def test_config5_graph_verified_by_eval_shape(self, monkeypatch):
        """Acceptance: the full config-5 bench graph passes the
        jax.eval_shape pass -- the three model stages actually trace
        (not merely skip) and no declared spec disagrees."""
        import runpy
        monkeypatch.setenv("AIKO_BENCH_SMOKE", "1")
        bench = runpy.run_path(
            str(Path(__file__).parent.parent / "bench.py"))
        definition = bench["collect_definitions"]()["multimodal"]
        report = analyze_definition(definition, passes=ALL_PASSES)
        traced = set(getattr(report, "traced_elements", ()))
        assert {"asr", "lm", "detector"} <= traced, report.render()
        assert not [d for d in report.findings
                    if d.code in ("AIKO207", "AIKO208")], report.render()


# -- actor-safety pass -------------------------------------------------------

class TestActorSafety:
    def fixture_definition(self, class_name):
        return tiny_definition(elements=[
            {"name": "source",
             "output": [{"name": "text", "type": "str"}],
             "parameters": {"data_sources": ["x"]},
             "deploy": local("TextSource")},
            {"name": "sink",
             "input": [{"name": "text", "type": "str"}],
             "output": [{"name": "text", "type": "str"}],
             "deploy": local(class_name, "lint_fixture_elements")},
        ])

    def test_blocking_call_flagged(self):
        report = analyze_definition(
            self.fixture_definition("BlockingElement"),
            passes=("graph", "actor"))
        assert [d.code for d in report.findings] == ["AIKO301"]

    def test_inline_allow_suppresses(self):
        report = analyze_definition(
            self.fixture_definition("AllowedBlockingElement"),
            passes=("graph", "actor"))
        assert report.findings == []

    def test_lint_ignore_parameter_suppresses(self):
        definition = self.fixture_definition("BlockingElement")
        definition["elements"][1]["parameters"] = {
            "lint_ignore": ["AIKO301"]}
        report = analyze_definition(definition,
                                    passes=("graph", "actor"))
        assert report.findings == []

    def test_shared_state_mutation_flagged(self):
        report = analyze_definition(
            self.fixture_definition("GlobalMutator"),
            passes=("graph", "actor"))
        codes = [d.code for d in report.findings]
        assert codes.count("AIKO303") >= 2  # global + self.pipeline.*

    def test_unpacking_assignment_mutation_flagged(self):
        report = analyze_definition(
            self.fixture_definition("TupleMutator"),
            passes=("graph", "actor"))
        codes = [d.code for d in report.findings]
        assert codes.count("AIKO303") == 2, report.render()

    def test_module_next_to_definition_file_resolves(self, tmp_path):
        """Offline lint of a definition FILE must resolve `deploy`
        modules that live next to it, without the caller arranging
        sys.path -- and must not leave the directory importable."""
        (tmp_path / "adjacent_fixture_elements.py").write_text(
            "import time\n"
            "from aiko_services_tpu.pipeline.element import "
            "PipelineElement\n\n\n"
            "class AdjacentBlocking(PipelineElement):\n"
            "    def process_frame(self, stream, frame):\n"
            "        time.sleep(1)\n"
            "        return True, {'text': frame.inputs['text']}\n")
        definition = self.fixture_definition("AdjacentBlocking")
        definition["elements"][1]["deploy"] = local(
            "AdjacentBlocking", "adjacent_fixture_elements")
        path = tmp_path / "adjacent.json"
        path.write_text(json.dumps(definition))
        report = analyze_definition(path, passes=("graph", "actor"))
        assert [d.code for d in report.findings] == ["AIKO301"], (
            report.render())
        assert str(tmp_path) not in sys.path
        assert "adjacent_fixture_elements" not in sys.modules

    def test_same_module_name_in_two_directories_not_cross_linted(
            self, tmp_path):
        """A deploy module imported from one definition's directory
        must not shadow a SAME-NAMED module next to a definition in
        another directory linted later in the same process."""
        template = (
            "{imports}\n"
            "from aiko_services_tpu.pipeline.element import "
            "PipelineElement\n\n\n"
            "class LocalElement(PipelineElement):\n"
            "    def process_frame(self, stream, frame):\n"
            "{body}\n"
            "        return True, {{'text': frame.inputs['text']}}\n")
        dir_a, dir_b = tmp_path / "a", tmp_path / "b"
        for directory, imports, body in (
                (dir_a, "import time", "        time.sleep(1)"),
                (dir_b, "", "        pass")):
            directory.mkdir()
            (directory / "local_elements.py").write_text(
                template.format(imports=imports, body=body))
            definition = self.fixture_definition("LocalElement")
            definition["elements"][1]["deploy"] = local(
                "LocalElement", "local_elements")
            (directory / "def.json").write_text(json.dumps(definition))
        report_a = analyze_definition(dir_a / "def.json",
                                      passes=("graph", "actor"))
        report_b = analyze_definition(dir_b / "def.json",
                                      passes=("graph", "actor"))
        assert [d.code for d in report_a.findings] == ["AIKO301"]
        assert report_b.findings == [], report_b.render()


# -- policy grammars (pass 4 / shared core) ----------------------------------

class TestPolicyGrammars:
    def test_faults_grammar_checks_offline(self):
        from aiko_services_tpu.faults import FAULTS_GRAMMAR
        assert FAULTS_GRAMMAR.check(
            "seed=7;element_raise:node=a:rate=0.5", "AIKO402") == []
        problems = FAULTS_GRAMMAR.check(
            "element_raise:rate=nope", "AIKO402")
        assert problems and problems[0][0] == "AIKO402"
        problems = FAULTS_GRAMMAR.check("bogus_point", "AIKO402")
        assert problems and problems[0][0] == "AIKO404"

    def test_policy_grammar_checks_offline(self):
        from aiko_services_tpu.serve.policy import POLICY_GRAMMAR
        assert POLICY_GRAMMAR.check(
            "max_inflight=8;bucket:2=10/4", "AIKO403") == []
        problems = POLICY_GRAMMAR.check("max_inflight=many", "AIKO403")
        assert problems and problems[0][0] == "AIKO403"
        problems = POLICY_GRAMMAR.check("max_inflght=4", "AIKO403")
        assert problems and problems[0][0] == "AIKO404"

    def test_decode_parameters_check(self):
        from aiko_services_tpu.analyze.policies import (
            check_decode_parameters)
        # valid continuous-batching parameter set: clean
        assert check_decode_parameters({
            "continuous": True, "decode_slots": 4, "kv_block_size": 16,
            "kv_blocks": 64, "max_new_tokens": 32}) == []
        # type/bounds violations carry AIKO405
        problems = check_decode_parameters({"decode_slots": 0})
        assert problems and problems[0][0] == "AIKO405"
        problems = check_decode_parameters({"kv_block_size": "wide"})
        assert problems and problems[0][0] == "AIKO405"
        problems = check_decode_parameters({"kv_blocks": 1})
        assert problems and problems[0][0] == "AIKO405"
        # cross-field: a pool that cannot hold ONE completion
        problems = check_decode_parameters({
            "continuous": True, "kv_blocks": 2, "kv_block_size": 4,
            "max_new_tokens": 32})
        assert problems and "ever be admitted" in problems[0][1]
        problems = check_decode_parameters({
            "continuous": True, "max_context": 8,
            "max_new_tokens": 32})
        assert problems and problems[0][0] == "AIKO405"
        # the engine rounds max_context UP to a block multiple; the
        # lint judges the rounded capacity (20 -> 32 holds 25 + 1)
        assert check_decode_parameters({
            "continuous": True, "kv_block_size": 16, "max_context": 20,
            "max_new_tokens": 25}) == []

    def test_decode_parameters_flow_through_policy_pass(self):
        from aiko_services_tpu.analyze import analyze_definition
        definition = {
            "name": "bad_decode", "graph": ["(source)"],
            "elements": [
                {"name": "source",
                 "output": [{"name": "text", "type": "str"}],
                 "parameters": {"data_sources": ["x"],
                                "continuous": True, "decode_slots": -1},
                 "deploy": {"local": {
                     "module": "aiko_services_tpu.elements",
                     "class_name": "TextSource"}}}]}
        report = analyze_definition(definition, passes=["policy"])
        assert [d.code for d in report.findings] == ["AIKO405"]

    def test_fault_injector_still_parses_through_core(self):
        from aiko_services_tpu.faults import create_injector
        injector = create_injector(
            "seed=7;element_raise:node=asr:frame=3:times=1;"
            "dispatch_delay:ms=5:rate=0.1")
        assert injector.seed == 7
        with pytest.raises(ValueError, match="unknown fault point"):
            create_injector("explode_randomly")
        with pytest.raises(ValueError):
            create_injector("element_raise:rate=2.0")  # above maximum

    def test_rate_out_of_range_rejected(self):
        from aiko_services_tpu.faults import FAULTS_GRAMMAR
        with pytest.raises(GrammarError):
            FAULTS_GRAMMAR.parse("element_raise:rate=1.5")


# -- definition-layer edge cases (satellite coverage) ------------------------

class TestDefinitionEdgeCases:
    def test_duplicate_element_names_rejected(self):
        definition = tiny_definition()
        definition["elements"].append(
            dict(definition["elements"][0]))
        definition["graph"] = ["(source (sink))"]
        with pytest.raises(DefinitionError, match="AIKO102"):
            parse_pipeline_definition(definition)

    def test_graph_node_without_element_record_rejected(self):
        definition = tiny_definition(graph=["(source (ghost))"])
        with pytest.raises(DefinitionError, match="ghost"):
            parse_pipeline_definition(definition)

    def test_map_out_undeclared_port_rejected(self):
        definition = tiny_definition()
        definition["elements"][0]["map_out"] = {"bogus": "renamed"}
        with pytest.raises(DefinitionError, match="map_out"):
            parse_pipeline_definition(definition)

    def test_map_in_undeclared_port_rejected(self):
        definition = tiny_definition()
        definition["elements"][1]["map_in"] = {"bogus": "text"}
        with pytest.raises(DefinitionError, match="map_in"):
            parse_pipeline_definition(definition)

    def test_sharding_axis_absent_from_mesh_rejected_at_construction(
            self):
        from aiko_services_tpu.runtime import Process
        from aiko_services_tpu.pipeline import create_pipeline
        definition = {
            "name": "bad_axes",
            "graph": ["(source (mlp))"],
            "elements": [
                {"name": "source", "output": [{"name": "tensor"}],
                 "parameters": {"data_sources": [[8, 16]]},
                 "deploy": local("ArraySource")},
                {"name": "mlp", "input": [{"name": "tensor"}],
                 "output": [{"name": "tensor"}],
                 "sharding": {"axes": {"data": -1},
                              "inputs": {"tensor": ["model", None]}},
                 "deploy": local("JaxMLP")},
            ],
        }
        process = Process(transport_kind="null")
        try:
            with pytest.raises(DefinitionError, match="AIKO206"):
                create_pipeline(process, definition)
        finally:
            process.terminate()


# -- parse_pipeline_definition source sniffing (satellite fix) ---------------

class TestSourceSniffing:
    def test_missing_json_path_names_the_file(self):
        with pytest.raises(DefinitionError, match="no_such_dir"):
            parse_pipeline_definition("no_such_dir/pipeline.json")

    def test_existing_path_without_json_suffix_is_read(self, tmp_path):
        path = tmp_path / "definition.pipeline"
        path.write_text(json.dumps(tiny_definition()))
        definition = parse_pipeline_definition(str(path))
        assert definition.name == "tiny"

    def test_unreadable_json_file_names_the_file(self, tmp_path):
        path = tmp_path / "broken.json"
        path.write_text("{not json")
        with pytest.raises(DefinitionError, match="broken.json"):
            parse_pipeline_definition(str(path))

    def test_json_text_still_parses(self):
        definition = parse_pipeline_definition(
            json.dumps(tiny_definition()))
        assert definition.name == "tiny"

    def test_garbage_text_mentions_both_interpretations(self):
        with pytest.raises(DefinitionError, match="neither"):
            parse_pipeline_definition("definitely not json")


# -- construction-time validation (Pipeline.__init__) ------------------------

class TestConstructionValidation:
    def dtype_clash_definition(self, validate=None):
        definition = {
            "name": "clash",
            "graph": ["(source (sink))"],
            "elements": [
                {"name": "source",
                 "output": [{"name": "x", "type": "f32[4,4]"}],
                 "parameters": {"data_sources": ["x"]},
                 "deploy": local("TextSource")},
                {"name": "sink",
                 "input": [{"name": "x", "type": "i32[4,4]"}],
                 "output": [{"name": "x", "type": "i32[4,4]"}],
                 "deploy": local("TextTransform")},
            ],
        }
        if validate is not None:
            definition["parameters"] = {"validate": validate}
        return definition

    def test_error_findings_fail_construction_with_rule_code(self):
        from aiko_services_tpu.runtime import Process
        from aiko_services_tpu.pipeline import create_pipeline
        process = Process(transport_kind="null")
        try:
            with pytest.raises(DefinitionError, match="AIKO202"):
                create_pipeline(process, self.dtype_clash_definition())
        finally:
            process.terminate()

    def test_validate_false_opts_out(self):
        from aiko_services_tpu.runtime import Process
        from aiko_services_tpu.pipeline import create_pipeline
        process = Process(transport_kind="null")
        try:
            pipeline = create_pipeline(
                process, self.dtype_clash_definition(validate=False))
            assert pipeline is not None
        finally:
            process.terminate()

    def test_warnings_admitted_and_counted_in_metrics(self):
        from aiko_services_tpu.runtime import Process
        from aiko_services_tpu.pipeline import create_pipeline
        definition = {
            "name": "warned",
            "graph": ["(source (mid))"],
            "elements": [
                {"name": "source",
                 "output": [{"name": "text", "type": "str"},
                            {"name": "extra", "type": "str"}],
                 "parameters": {"data_sources": ["x"]},
                 "deploy": local("TextSource")},
                {"name": "mid",
                 "input": [{"name": "text", "type": "str"}],
                 "output": [{"name": "text", "type": "str"},
                            {"name": "extra", "type": "str"}],
                 "deploy": local("TextTransform")},
            ],
        }
        process = Process(transport_kind="null")
        try:
            pipeline = create_pipeline(process, definition)
            counters = pipeline.telemetry.registry.snapshot()["counters"]
            assert counters.get("lint.findings", 0) >= 1
            assert counters.get("lint.findings.AIKO104", 0) >= 1
        finally:
            process.terminate()


# -- report plumbing ---------------------------------------------------------

class TestReport:
    def test_json_report_shape(self):
        report = analyze_definition(
            GOLDEN / "aiko202_dtype_clash.json", passes=CHEAP_PASSES)
        payload = json.loads(report.to_json())
        assert payload["version"] == 1
        assert payload["summary"]["errors"] >= 1
        assert payload["summary"]["by_code"].get("AIKO202", 0) >= 1
        finding = payload["findings"][0]
        assert {"code", "severity", "definition", "element", "port",
                "message", "source"} <= set(finding)

    def test_readme_documents_every_rule_code(self):
        readme = (Path(__file__).parent.parent
                  / "README.md").read_text()
        missing = [code for code in RULES if code not in readme]
        assert missing == [], f"README lacks rule codes: {missing}"
