import time


def wait_for(predicate, timeout: float = 5.0, interval: float = 0.002):
    """Poll until predicate() is truthy; return its value or raise."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        value = predicate()
        if value:
            return value
        time.sleep(interval)
    raise TimeoutError(f"Condition not met within {timeout}s: {predicate}")
