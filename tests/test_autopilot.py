# Online SLO autopilot suite (ISSUE 17): the AIKO412 policy grammar
# and its offline-lint parity, windowed burn-rate accounting
# (SlidingWindow), bounded per-tick delta clamping, the write-ahead
# delta journal (idempotent replay, committed-prefix truncation, HA
# standby adoption without re-apply), trace-collection early return,
# and deterministic tick_now() convergence on a live in-process fleet.

import time

import numpy as np
import pytest

from aiko_services_tpu import faults as faults_module
from aiko_services_tpu.observe.metrics import SlidingWindow, get_registry
from aiko_services_tpu.pipeline import create_pipeline
from aiko_services_tpu.runtime import Process
from aiko_services_tpu.serve import Gateway
from aiko_services_tpu.serve.autopilot import AutopilotPolicy
from aiko_services_tpu.transport import reset_brokers
from helpers import wait_for

ELEMENTS = "aiko_services_tpu.elements"
_JOURNAL = "backend=retained;interval=0.02;search_timeout=0.5"
_POLICY = "interval=0;apply=on;max_delta_frac=0.5;margin=0.15"


@pytest.fixture(autouse=True)
def clean():
    faults_module.reset_injector()
    reset_brokers()
    yield
    faults_module.reset_injector()
    reset_brokers()


def _definition(name, micro=16):
    """One PE_Busy replica graph: fixed host cost per frame, starved
    micro_batch groups under a closed-loop window of 2 -- the
    deterministic shrink scenario the convergence tests drive."""
    return {
        "name": name,
        "parameters": {"telemetry": True, "metrics_interval": 60.0},
        "graph": ["(busy)"],
        "elements": [
            {"name": "busy",
             "input": [{"name": "number", "type": "any"}],
             "output": [{"name": "number", "type": "any"}],
             "parameters": {"micro_batch": micro,
                            "micro_batch_wait_ms": 4,
                            "work_ms": 2, "constant": 3},
             "deploy": {"local": {"module": ELEMENTS,
                                  "class_name": "PE_Busy"}}},
        ],
    }


def _fleet(autopilot=_POLICY, micro=16, journal=None, ha=None,
           attach=True):
    """One in-process replica behind one gateway on the loopback
    broker.  Returns (gateway, pipeline, processes)."""
    processes = []
    pipeline = None
    if attach:
        process = Process(transport_kind="loopback")
        processes.append(process)
        pipeline = create_pipeline(process, _definition("ap_replica",
                                                        micro=micro))
    gateway_process = Process(transport_kind="loopback")
    processes.append(gateway_process)
    gateway = Gateway(gateway_process, policy="max_inflight=64;queue=256",
                      router_seed=3, telemetry=True,
                      metrics_interval=60.0, autopilot=autopilot,
                      journal=journal, ha=ha)
    if pipeline is not None:
        gateway.attach_replica(pipeline)
    for process in processes:
        process.run(in_thread=True)
    return gateway, pipeline, processes


def _closed_loop(gateway, total=40, window=2):
    """Closed-loop session traffic (array frames so micro-batching
    coalesces): returns {frame_id: scalar output}."""
    import queue as queue_module

    responses = queue_module.Queue()
    gateway.submit_stream("s0", queue_response=responses)
    submitted, done, outputs = 0, 0, {}
    while submitted < min(window, total):
        gateway.submit_frame(
            "s0", {"number": np.full((1, 2), float(submitted),
                                     np.float32)},
            frame_id=submitted)
        submitted += 1
    while done < total:
        _, frame_id, out, status = responses.get(timeout=60)
        done += 1
        if status == "ok":
            outputs[int(frame_id)] = float(
                np.asarray(out.get("number")).ravel()[0])
        if submitted < total:
            gateway.submit_frame(
                "s0", {"number": np.full((1, 2), float(submitted),
                                         np.float32)},
                frame_id=submitted)
            submitted += 1
    return outputs


def _terminate(processes):
    for process in processes:
        process.terminate()


# -- policy grammar (AIKO412) ------------------------------------------------


class TestAutopilotPolicy:
    def test_grammar_and_defaults(self):
        policy = AutopilotPolicy.parse(
            "interval=5;apply=on;margin=0.1;max_delta_frac=0.4;"
            "burn_window=45;burn_threshold=0.05;scope=fleet;wait=1.5;"
            "slo=latency;p99_ms=80")
        assert policy.interval_s == 5.0
        assert policy.apply is True
        assert policy.margin == 0.1
        assert policy.max_delta_frac == 0.4
        assert policy.burn_window_s == 45.0
        assert policy.burn_threshold == 0.05
        assert policy.scope == "fleet"
        assert policy.wait_s == 1.5
        assert policy.objective == "latency"
        assert policy.p99_ms == 80.0
        assert policy.slo_spec() == "slo=latency;p99_ms=80"
        defaults = AutopilotPolicy.parse(None)
        assert defaults.apply is False     # observe-only by default
        assert defaults.scope == "local"
        assert defaults.burn_window_s > 0
        assert defaults.slo_spec() == "slo=throughput"

    def test_cross_field_constraints(self):
        with pytest.raises(ValueError, match="burn_window"):
            AutopilotPolicy.parse("burn_window=0")
        with pytest.raises(ValueError, match="max_delta_frac"):
            AutopilotPolicy.parse("max_delta_frac=0")
        with pytest.raises(ValueError):
            AutopilotPolicy.parse("scope=galactic")
        with pytest.raises(ValueError):
            AutopilotPolicy.parse("warp_speed=9")

    def test_offline_lint_parity(self):
        """check_autopilot_policy reports the SAME failures Gateway
        construction raises, as AIKO412 (values/cross-field) and
        AIKO404 (unknown directive)."""
        from aiko_services_tpu.analyze.policies import (
            check_autopilot_policy)
        assert check_autopilot_policy(_POLICY) == []
        codes = [code for code, _ in
                 check_autopilot_policy("burn_window=0")]
        assert codes == ["AIKO412"]
        codes = [code for code, _ in
                 check_autopilot_policy("warp_speed=9")]
        assert "AIKO404" in codes
        codes = [code for code, _ in
                 check_autopilot_policy("margin=asdf")]
        assert "AIKO412" in codes

    def test_gateway_constructor_rejects_bad_spec(self):
        gateway_process = Process(transport_kind="loopback")
        with pytest.raises(ValueError, match="AIKO412"):
            Gateway(gateway_process, autopilot="burn_window=0")
        with pytest.raises(ValueError, match="AIKO404"):
            Gateway(gateway_process, autopilot="warp_speed=9")


# -- windowed burn accounting ------------------------------------------------


class TestSlidingWindow:
    def test_needs_two_samples(self):
        window = SlidingWindow(window_s=30.0)
        assert window.delta("miss") == 0.0
        assert window.span() == 0.0
        window.sample(0.0, {"miss": 10.0})
        assert window.delta("miss") == 0.0

    def test_windowed_delta_of_cumulative_counters(self):
        window = SlidingWindow(window_s=30.0, bucket_s=5.0)
        window.sample(0.0, {"ok": 0.0, "miss": 0.0})
        window.sample(10.0, {"ok": 95.0, "miss": 5.0})
        assert window.delta("miss") == 5.0
        assert window.delta("ok") == 95.0
        assert window.span() == 10.0

    def test_old_samples_pruned_past_the_window(self):
        window = SlidingWindow(window_s=30.0, bucket_s=5.0)
        window.sample(0.0, {"miss": 0.0})
        window.sample(10.0, {"miss": 100.0})
        # an hour later: the early 100-miss burst must NOT count
        window.sample(3600.0, {"miss": 100.0})
        window.sample(3610.0, {"miss": 101.0})
        assert window.delta("miss") == 1.0

    def test_same_bucket_latest_wins(self):
        window = SlidingWindow(window_s=30.0, bucket_s=5.0)
        window.sample(0.0, {"miss": 0.0})
        window.sample(10.0, {"miss": 3.0})
        window.sample(11.0, {"miss": 7.0})  # same 5 s bucket as 10.0
        assert window.delta("miss") == 7.0


# -- bounded per-tick steps --------------------------------------------------


class TestClampStep:
    def test_clamp_bounds_and_idempotence(self):
        gateway, _, processes = _fleet(
            autopilot="max_delta_frac=0.5", attach=False)
        try:
            clamp = gateway.autopilot._clamp_step
            # nothing in effect yet: the proposal is the first step
            assert clamp(None, 40) == (40, False)
            # no move needed
            assert clamp(16, 16) == (None, False)
            # a 16 -> 2 goal moves at most 16*0.5 = 8 per tick
            assert clamp(16, 2) == (8, True)
            assert clamp(8, 2) == (4, True)
            assert clamp(4, 2) == (2, False)
            # ints always step >= 1: small knobs are never frozen
            assert clamp(2, 1) == (1, False)
            # float knobs clamp by fraction of current
            value, clamped = clamp(100.0, 10.0)
            assert value == 50.0 and clamped
        finally:
            _terminate(processes)


# -- write-ahead delta journal -----------------------------------------------


def _delta_records(values, target="element:busy", knob="micro_batch"):
    return [{"target": target, "knob": knob, "value": value,
             "before": None, "goal": values[-1], "clamped": False,
             "seq": seq}
            for seq, value in enumerate(values, start=1)]


class TestDeltaJournal:
    def test_replay_returns_deltas_in_seq_order(self):
        gateway, _, processes = _fleet(journal=_JOURNAL, attach=False)
        try:
            records = _delta_records([8, 4, 2])
            gateway.journal.write_deltas(reversed(records))
            assert [r["seq"] for r in gateway.journal.replay_deltas()] \
                == [1, 2, 3]
            assert gateway.journal.delta_appends == 3
        finally:
            _terminate(processes)

    def test_adopt_applies_committed_prefix_and_sets_high_water(self):
        """A crash between the write-ahead log and the apply leaves a
        committed prefix; adoption replays exactly that prefix and the
        next live delta numbers ABOVE the adopted high water."""
        gateway, pipeline, processes = _fleet(journal=_JOURNAL)
        try:
            pilot = gateway.autopilot
            # seq 3 was never journaled (crash before the append)
            gateway.journal.write_deltas(_delta_records([8, 4]))
            assert pilot.adopt_journal() == 2
            assert pilot._applied[("element:busy", "micro_batch")] == 4
            assert pilot._seq == 2
            wait_for(lambda: pipeline.elements["busy"].get_parameter(
                "micro_batch") == 4)
            adopted = pilot.registry.counter(
                "autopilot.deltas_adopted").value
            assert adopted == 2
            # adopted, not re-applied
            assert pilot.registry.counter(
                "autopilot.deltas_applied").value == 0
        finally:
            _terminate(processes)

    def test_double_adoption_is_idempotent(self):
        """Absolute values make replay idempotent: adopting the same
        journal twice lands on the same configuration (never
        double-steps)."""
        gateway, pipeline, processes = _fleet(journal=_JOURNAL)
        try:
            pilot = gateway.autopilot
            gateway.journal.write_deltas(_delta_records([8, 4, 2]))
            pilot.adopt_journal()
            first = dict(pilot._applied)
            pilot.adopt_journal()
            assert pilot._applied == first
            assert pilot._seq == 3
            wait_for(lambda: pipeline.elements["busy"].get_parameter(
                "micro_batch") == 2)
        finally:
            _terminate(processes)


class TestHAPromoteAdoptsDeltas:
    def test_standby_promote_mid_apply_restores_exact_config(self):
        """Kill the HA primary after it journaled+applied deltas: the
        promoted standby adopts every journaled delta (counted as
        adopted, NOT applied) and lands on the primary's exact
        configuration -- no re-apply, no skip."""
        process = Process(transport_kind="loopback")
        pipeline = create_pipeline(process, _definition("ap_replica"))
        process.run(in_thread=True)

        def make_gateway():
            gateway_process = Process(transport_kind="loopback")
            gateway = Gateway(gateway_process,
                              policy="max_inflight=64;queue=256",
                              router_seed=3, telemetry=True,
                              metrics_interval=60.0, autopilot=_POLICY,
                              journal=_JOURNAL, ha="ap_ha")
            gateway.attach_replica(pipeline)
            gateway_process.run(in_thread=True)
            return gateway, gateway_process

        gateway_a, process_a = make_gateway()
        wait_for(lambda: gateway_a.role == "primary")
        gateway_b, process_b = make_gateway()
        wait_for(lambda: gateway_b.election.state == "secondary")
        try:
            pilot_a = gateway_a.autopilot
            records = _delta_records([8, 4])
            gateway_a.journal.write_deltas(records)
            for record in records:
                pilot_a._apply_delta(record)
            # the standby's retained mirror has both deltas
            wait_for(lambda: len(gateway_b.journal.replay_deltas()) == 2)
            process_a.crash()
            wait_for(lambda: gateway_b.role == "primary", timeout=15)
            pilot_b = gateway_b.autopilot
            wait_for(lambda: pilot_b.registry.counter(
                "autopilot.deltas_adopted").value == 2)
            assert pilot_b._applied == pilot_a._applied
            assert pilot_b._seq == 2
            assert pilot_b.registry.counter(
                "autopilot.deltas_applied").value == 0
            assert pipeline.elements["busy"].get_parameter(
                "micro_batch") == 4
        finally:
            _terminate([process, process_a, process_b])


# -- trace collection --------------------------------------------------------


class TestCollectTraces:
    def test_explicit_targets_return_early_and_count_responses(self):
        from aiko_services_tpu.observe import collect_traces
        process = Process(transport_kind="loopback")
        pipeline = create_pipeline(process, _definition("ap_replica"))
        process.run(in_thread=True)
        collector_process = Process(transport_kind="loopback")
        collector_process.run(in_thread=True)
        try:
            registry = get_registry()
            responses_before = registry.counter(
                "collector.responses").value
            wait_for(lambda: pipeline.topic_path)
            start = time.perf_counter()
            collected = collect_traces(
                collector_process, wait=10.0,
                targets=[pipeline.topic_path])
            elapsed = time.perf_counter() - start
            assert len(collected) == 1
            # DEADLINE semantics: one healthy target answered, so the
            # collector must return in round-trip time, not wait 10 s
            assert elapsed < 5.0
            assert registry.counter("collector.responses").value \
                == responses_before + 1
        finally:
            _terminate([process, collector_process])

    def test_dead_target_counts_a_timeout(self):
        from aiko_services_tpu.observe import collect_traces
        collector_process = Process(transport_kind="loopback")
        collector_process.run(in_thread=True)
        try:
            registry = get_registry()
            timeouts_before = registry.counter(
                "collector.timeouts").value
            collected = collect_traces(
                collector_process, wait=0.2,
                targets=["aiko_test/nowhere/1"])
            assert collected == {}
            assert registry.counter("collector.timeouts").value \
                == timeouts_before + 1
        finally:
            _terminate([collector_process])


# -- the live control loop ---------------------------------------------------


class TestTickConvergence:
    def test_tick_now_converges_to_the_recommender_fixed_point(self):
        """The proven scenario: micro_batch=16 under closed-loop
        window-2 traffic is queue-bound starved; each tick_now() steps
        the live knob by at most max_delta_frac until the recommender's
        pow2-occupancy fixed point (2) -- every step clamped, applied
        through set_replica_parameter, visible to the running
        scheduler, and accounted in the ledger."""
        gateway, pipeline, processes = _fleet()
        try:
            _closed_loop(gateway, total=40)
            pilot = gateway.autopilot
            for _ in range(10):
                pilot.tick_now()
                if pilot.converged and not pilot.ledger[-1]["applied"]:
                    break
            assert pilot.converged
            assert pilot.convergence <= pilot.policy.margin
            applied = [record for tick in pilot.ledger
                       for record in tick["applied"]]
            assert [r["value"] for r in applied] == [8, 4, 2]
            assert [r["seq"] for r in applied] == [1, 2, 3]
            assert all(r["target"] == "element:busy"
                       and r["knob"] == "micro_batch" for r in applied)
            # the first two steps were clamped by max_delta_frac=0.5
            assert [r["clamped"] for r in applied] == [True, True,
                                                       False]
            assert pipeline.elements["busy"].get_parameter(
                "micro_batch") == 2
            summary = pilot.summary()
            assert summary["deltas_applied"] == 3
            assert summary["deltas_clamped"] == 2
            assert summary["converged"] is True
            # the gateway telemetry summary exposes the same block
            assert gateway.telemetry.summary()["autopilot"][
                "deltas_applied"] == 3
        finally:
            _terminate(processes)

    def test_dry_run_mode_never_touches_the_fleet(self):
        """apply=off (the default) harvests, tunes, and publishes
        convergence distance -- but applies nothing and journals
        nothing."""
        gateway, pipeline, processes = _fleet(
            autopilot="interval=0;margin=0.15", journal=_JOURNAL)
        try:
            _closed_loop(gateway, total=40)
            pilot = gateway.autopilot
            pilot.tick_now()
            assert pilot.convergence > pilot.policy.margin
            assert pilot.ledger[-1]["applied"] == []
            assert pilot.ledger[-1]["skipped"] >= 1
            assert pilot.registry.counter(
                "autopilot.deltas_applied").value == 0
            assert gateway.journal.replay_deltas() == []
            assert pipeline.elements["busy"].get_parameter(
                "micro_batch") == 16
        finally:
            _terminate(processes)

    def test_interval_zero_never_arms_the_timer(self):
        gateway, _, processes = _fleet(attach=False)
        try:
            pilot = gateway.autopilot
            pilot.start()
            assert pilot._timer_installed is False
        finally:
            _terminate(processes)


# -- dashboard ---------------------------------------------------------------


class TestDashboardRow:
    def test_gateway_plugin_renders_the_autopilot_row(self):
        from aiko_services_tpu.dashboard import _gateway_plugin

        class _Model:
            selected_share = {
                "replica_count": 1, "stream_count": 0, "policy": "",
                "metrics": {
                    "admitted": 10, "shed_frames": 0, "routed": 10,
                    "completed": 10, "parked": 0, "failovers": 0,
                    "autopilot": {
                        "apply": True, "scope": "local",
                        "deltas_applied": 3, "deltas_clamped": 2,
                        "deltas_skipped": 0, "backoffs": 1,
                        "convergence": 0.0, "converged": True,
                        "rebalances": 0},
                },
            }

        lines = _gateway_plugin(_Model())
        autopilot_line = next(line for line in lines
                              if line.startswith("autopilot:"))
        assert "apply/local" in autopilot_line
        assert "deltas 3 applied 2 clamped 0 skipped" in autopilot_line
        assert "convergence 0.0 (converged)" in autopilot_line
        assert "backoffs 1" in autopilot_line


# -- CLI ---------------------------------------------------------------------


class TestTuneLiveCli:
    def test_trace_and_live_are_mutually_exclusive(self, tmp_path):
        from click.testing import CliRunner
        from aiko_services_tpu.cli import main
        runner = CliRunner()
        result = runner.invoke(main, ["tune"])
        assert result.exit_code == 2
        assert "exactly one trace source" in result.output
        trace = tmp_path / "trace.json"
        trace.write_text("{}")
        result = runner.invoke(main, ["tune", str(trace),
                                      "--live", "discover"])
        assert result.exit_code == 2
        assert "exactly one trace source" in result.output

    def test_live_rejects_what_if(self):
        from click.testing import CliRunner
        from aiko_services_tpu.cli import main
        result = CliRunner().invoke(
            main, ["tune", "--live", "discover",
                   "--what-if", "busy:micro_batch=4"])
        assert result.exit_code == 2
