# Parallelism layer tests: mesh construction, flash-attention kernel
# (interpreter mode on CPU), ring attention and Ulysses attention over the
# virtual 8-device mesh -- all checked against the plain-XLA oracle.

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aiko_services_tpu.parallel import (
    attention_reference, create_mesh, flash_attention, get_mesh,
    named_sharding, ring_attention, shard_pytree, ulysses_attention)


def _qkv(batch=1, heads=4, seq=64, dim=16, seed=0):
    keys = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (batch, heads, seq, dim)
    return tuple(jax.random.normal(key, shape, jnp.float32) for key in keys)


class TestMesh:
    def test_create_mesh_fill_axis(self):
        mesh = create_mesh({"data": -1, "model": 2})
        assert mesh.shape["model"] == 2
        assert mesh.shape["data"] == len(jax.devices()) // 2

    def test_axis_order_canonical(self):
        mesh = create_mesh({"model": 2, "data": 2, "seq": 2})
        assert tuple(mesh.axis_names) == ("data", "seq", "model")

    def test_get_mesh_cached(self):
        assert get_mesh({"data": -1}) is get_mesh({"data": -1})

    def test_bad_divisibility(self):
        with pytest.raises(ValueError):
            create_mesh({"data": -1, "model": 3})

    def test_shard_pytree(self):
        mesh = get_mesh({"data": -1})
        tree = {"w": jnp.zeros((8, 4)), "b": jnp.zeros((4,))}
        sharded = shard_pytree(tree, mesh, None)
        assert sharded["w"].sharding.is_fully_replicated

    def test_named_sharding_spec_coercion(self):
        mesh = get_mesh({"data": -1})
        sharding = named_sharding(mesh, ["data", None])
        assert sharding.spec == jax.sharding.PartitionSpec("data", None)


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, causal):
        q, k, v = _qkv(seq=96)
        expected = attention_reference(q, k, v, causal=causal)
        actual = flash_attention(q, k, v, causal=causal, block_q=32,
                                 block_k=32)
        np.testing.assert_allclose(actual, expected, atol=2e-3, rtol=2e-3)

    def test_ragged_seq_padding(self):
        q, k, v = _qkv(seq=50)  # not a block multiple
        expected = attention_reference(q, k, v, causal=True)
        actual = flash_attention(q, k, v, causal=True, block_q=16,
                                 block_k=16)
        np.testing.assert_allclose(actual, expected, atol=2e-3, rtol=2e-3)

    def test_cross_attention_kv_longer(self):
        q, _, _ = _qkv(seq=32)
        _, k, v = _qkv(seq=80, seed=1)
        expected = attention_reference(q, k, v, causal=False)
        actual = flash_attention(q, k, v, block_q=16, block_k=16)
        np.testing.assert_allclose(actual, expected, atol=2e-3, rtol=2e-3)


class TestSequenceParallel:
    @pytest.mark.parametrize("causal", [False, True])
    def test_ring_attention(self, causal):
        mesh = create_mesh({"seq": 8})
        q, k, v = _qkv(batch=2, heads=2, seq=64, dim=8)
        expected = attention_reference(q, k, v, causal=causal)
        actual = ring_attention(q, k, v, mesh, causal=causal)
        np.testing.assert_allclose(actual, expected, atol=2e-3, rtol=2e-3)

    def test_ulysses_attention(self):
        mesh = create_mesh({"seq": 8})
        q, k, v = _qkv(batch=1, heads=8, seq=64, dim=8)
        expected = attention_reference(q, k, v, causal=True)
        actual = ulysses_attention(q, k, v, mesh, causal=True)
        np.testing.assert_allclose(actual, expected, atol=2e-3, rtol=2e-3)


class TestFlashBackward:
    """Pallas backward kernels (dq; dk/dv) vs jax.grad of the XLA oracle
    (VERDICT round 1 item 3)."""

    @pytest.mark.parametrize("causal", [False, True])
    def test_grad_parity(self, causal):
        q, k, v = _qkv(seq=96)

        def loss_flash(q, k, v):
            out = flash_attention(q, k, v, causal=causal, block_q=32,
                                  block_k=32)
            return jnp.sum(out * jnp.cos(out.astype(jnp.float32)))

        def loss_ref(q, k, v):
            out = attention_reference(q, k, v, causal=causal)
            return jnp.sum(out * jnp.cos(out.astype(jnp.float32)))

        grads_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        grads_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for actual, expected, name in zip(grads_flash, grads_ref,
                                          ("dq", "dk", "dv")):
            np.testing.assert_allclose(
                np.asarray(actual), np.asarray(expected),
                atol=5e-3, rtol=5e-3, err_msg=name)

    def test_grad_parity_ragged_and_cross(self):
        # q/k lengths differ and are not block multiples
        q, _, _ = _qkv(seq=50)
        _, k, v = _qkv(seq=70, seed=3)

        def loss(fn):
            def inner(q, k, v):
                return jnp.sum(fn(q, k, v) ** 2)
            return inner

        flash = loss(lambda q, k, v: flash_attention(
            q, k, v, causal=True, block_q=16, block_k=16))
        ref = loss(lambda q, k, v: attention_reference(q, k, v,
                                                       causal=True))
        got = jax.grad(flash, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(ref, argnums=(0, 1, 2))(q, k, v)
        for actual, expected in zip(got, want):
            np.testing.assert_allclose(np.asarray(actual),
                                       np.asarray(expected),
                                       atol=5e-3, rtol=5e-3)

    def test_grad_parity_seq_4k(self):
        # the VERDICT done-criterion sequence length, batch/head-reduced
        q, k, v = _qkv(batch=1, heads=1, seq=4096, dim=16, seed=7)

        def loss_flash(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=True) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)

        got = jax.grad(loss_flash)(q, k, v)
        want = jax.grad(loss_ref)(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-2, rtol=2e-2)

    def test_backward_memory_is_blockwise(self):
        # the jaxpr of the flash grad must contain no (L, L) intermediate:
        # residuals are q/k/v/o (L, D) + lse (L,) -- O(L x block) peak
        seq = 1024
        q, k, v = _qkv(batch=1, heads=1, seq=seq, dim=16, seed=1)

        def loss(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=True) ** 2)

        jaxpr = jax.make_jaxpr(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)

        def max_intermediate(jaxpr):
            worst = 0
            for eqn in jaxpr.eqns:
                for var in eqn.outvars:
                    shape = getattr(var.aval, "shape", ())
                    size = 1
                    for dim in shape:
                        size *= dim
                    worst = max(worst, size)
                for sub in eqn.params.values():
                    if hasattr(sub, "jaxpr"):
                        worst = max(worst, max_intermediate(sub.jaxpr))
            return worst

        worst = max_intermediate(jaxpr.jaxpr)
        # seq*seq would be 1M elements; blockwise peak is O(seq x 128)
        assert worst < seq * seq, (
            f"O(L^2) intermediate found: {worst} elements")
        assert worst <= seq * 256


class TestRingAttentionScale:
    """VERDICT round-1 item 4: flash-kernel inner hops, causal hop
    skipping, ring gradients, and sequence-parallel decode."""

    def test_causal_hops_are_skipped(self):
        # device i executes i+1 of the n hops under causal masking:
        # sum over 8 devices = 36 executed hops, vs 64 for dense
        from aiko_services_tpu.parallel import attention as attn_mod
        mesh = create_mesh({"seq": 8})
        q, k, v = _qkv(batch=1, heads=2, seq=64, dim=8)
        executed = []
        attn_mod._RING_HOP_CALLBACK = lambda step: executed.append(
            int(step))
        try:
            out = ring_attention(q, k, v, mesh, causal=True)
            jax.block_until_ready(out)
        finally:
            attn_mod._RING_HOP_CALLBACK = None
        n = mesh.shape["seq"]
        assert len(executed) == n * (n + 1) // 2, (
            f"expected {n * (n + 1) // 2} executed hops, "
            f"got {len(executed)}")
        expected = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(out, expected, atol=2e-3, rtol=2e-3)

    @pytest.mark.parametrize("causal", [False, True])
    def test_ring_grad_parity(self, causal):
        mesh = create_mesh({"seq": 4}, devices=jax.devices()[:4])
        q, k, v = _qkv(batch=1, heads=2, seq=64, dim=8, seed=11)

        def loss_ring(q, k, v):
            out = ring_attention(q, k, v, mesh, causal=causal)
            return jnp.sum(out * jnp.cos(out.astype(jnp.float32)))

        def loss_ref(q, k, v):
            out = attention_reference(q, k, v, causal=causal)
            return jnp.sum(out * jnp.cos(out.astype(jnp.float32)))

        got = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for actual, expected, name in zip(got, want, ("dq", "dk", "dv")):
            np.testing.assert_allclose(
                np.asarray(actual), np.asarray(expected),
                atol=5e-3, rtol=5e-3, err_msg=name)

    @pytest.mark.parametrize("q_len", [1, 4])
    def test_sp_decode_attention_parity(self, q_len):
        from aiko_services_tpu.parallel import sp_decode_attention
        mesh = create_mesh({"seq": 8})
        cache_len, pos = 64, 37
        _, k, v = _qkv(batch=2, heads=2, seq=cache_len, dim=8, seed=5)
        q = jax.random.normal(jax.random.PRNGKey(9),
                              (2, 2, q_len, 8), jnp.float32)
        got = sp_decode_attention(q, k, v, pos, mesh=mesh)
        # oracle: dense masked attention over positions <= pos(+i)
        want = attention_reference(
            q, k[:, :, :pos + q_len], v[:, :, :pos + q_len],
            causal=True, q_offset=0)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-3, rtol=2e-3)

    def test_sp_decode_gqa_expands_in_shard(self):
        # kv cache stays at n_kv_heads through the shard_map boundary;
        # GQA expansion happens on the local shard only
        from aiko_services_tpu.parallel import sp_decode_attention
        mesh = create_mesh({"seq": 8})
        _, k, v = _qkv(batch=1, heads=2, seq=32, dim=8, seed=8)
        q = jax.random.normal(jax.random.PRNGKey(3), (1, 4, 1, 8),
                              jnp.float32)
        got = sp_decode_attention(q, k, v, 21, mesh=mesh)
        k_rep = jnp.repeat(k, 2, axis=1)
        v_rep = jnp.repeat(v, 2, axis=1)
        want = attention_reference(q, k_rep[:, :, :22], v_rep[:, :, :22],
                                   causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-3, rtol=2e-3)

    def test_sp_decode_collective_count(self):
        # decode steps are collective-LATENCY bound (Lq=1 payloads are
        # tiny): the merge must cost exactly one pmax + one fused psum,
        # and the cache must cross the shard_map boundary un-expanded
        # (no jnp.repeat of KV in the jaxpr)
        from aiko_services_tpu.parallel import sp_decode_attention
        mesh = create_mesh({"seq": 8})
        _, k, v = _qkv(batch=1, heads=2, seq=32, dim=8, seed=8)
        q = jax.random.normal(jax.random.PRNGKey(3), (1, 4, 1, 8),
                              jnp.float32)
        jaxpr = str(jax.make_jaxpr(
            lambda q, k, v: sp_decode_attention(q, k, v, 21, mesh=mesh)
        )(q, k, v))
        assert jaxpr.count("psum(") + jaxpr.count("psum[") == 1, jaxpr
        assert jaxpr.count("pmax(") + jaxpr.count("pmax[") == 1, jaxpr

    def test_sp_decode_composes_with_tp(self):
        from aiko_services_tpu.parallel import sp_decode_attention
        mesh = create_mesh({"data": 2, "seq": 2, "model": 2})
        _, k, v = _qkv(batch=2, heads=2, seq=32, dim=8, seed=6)
        q = jax.random.normal(jax.random.PRNGKey(2), (2, 2, 1, 8),
                              jnp.float32)
        got = sp_decode_attention(q, k, v, 19, mesh=mesh)
        want = attention_reference(q, k[:, :, :20], v[:, :, :20],
                                   causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-3, rtol=2e-3)


class TestShardPytreeSemantics:
    """shard_pytree spec-tree semantics: prefix broadcast (the old
    device_put behavior), partial trees (missing leaves replicate), and
    per-item structural lists."""

    def _mesh(self):
        from aiko_services_tpu.parallel.mesh import create_mesh
        return create_mesh({"data": 2, "model": 4})

    def test_axis_list_broadcasts_over_collection(self):
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from aiko_services_tpu.parallel import shard_pytree
        mesh = self._mesh()
        tree = {"a": [jnp.zeros((4, 8)), jnp.zeros((4, 8))]}
        out = shard_pytree(tree, mesh, {"a": ["data", None]})
        for leaf in out["a"]:
            assert leaf.sharding.spec == P("data", None), (
                leaf.sharding.spec)

    def test_partial_tree_missing_leaves_replicate(self):
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from aiko_services_tpu.parallel import shard_pytree
        mesh = self._mesh()
        tree = {"w": jnp.zeros((4, 8)), "b": jnp.zeros((8,))}
        out = shard_pytree(tree, mesh, {"w": P(None, "model")})
        assert out["w"].sharding.spec == P(None, "model")
        assert out["b"].sharding.is_fully_replicated

    def test_per_item_structural_list(self):
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from aiko_services_tpu.parallel import shard_pytree
        mesh = self._mesh()
        tree = {"stages": [{"w": jnp.zeros((4, 8))},
                           {"w": jnp.zeros((8, 4))}]}
        out = shard_pytree(tree, mesh, {"stages": [
            {"w": P("data", None)}, {"w": P(None, "data")}]})
        assert out["stages"][0]["w"].sharding.spec == P("data", None)
        assert out["stages"][1]["w"].sharding.spec == P(None, "data")

    def test_spec_broadcast_through_subtree(self):
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from aiko_services_tpu.parallel import shard_pytree
        mesh = self._mesh()
        tree = {"block": {"w1": jnp.zeros((4, 8)),
                          "w2": jnp.zeros((4, 8))}}
        out = shard_pytree(tree, mesh, {"block": P("data", None)})
        assert out["block"]["w1"].sharding.spec == P("data", None)
        assert out["block"]["w2"].sharding.spec == P("data", None)

    def test_namedtuple_rebuilt_with_positional_fields(self):
        # optax opt_states are namedtuples: type(node)(iterable) raises
        # TypeError for them, the rebuild must splat positionally
        import collections
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from aiko_services_tpu.parallel import shard_pytree
        State = collections.namedtuple("State", ["mu", "nu"])
        mesh = self._mesh()
        tree = {"opt": State(mu=jnp.zeros((4, 8)), nu=jnp.zeros((4, 8)))}
        out = shard_pytree(tree, mesh, {"opt": P("data", None)})
        assert isinstance(out["opt"], State)
        assert out["opt"].mu.sharding.spec == P("data", None)


class TestFlashMultiBlock:
    """Parity BEYOND one kernel block (block_q = block_k = 128): the
    grid loops and causal block-skipping only engage at seq > 128, and
    the long-context claim rests on them."""

    def _naive(self, q, k, v, causal):
        import jax.numpy as jnp
        import numpy as np
        scale = 1.0 / np.sqrt(q.shape[-1])
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
        if causal:
            q_len, k_len = q.shape[2], k.shape[2]
            mask = (jnp.arange(k_len)[None, :]
                    <= (jnp.arange(q_len)[:, None]
                        + (k_len - q_len)))
            scores = jnp.where(mask[None, None], scores, -jnp.inf)
        weights = jax.nn.softmax(scores, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", weights, v)

    @pytest.mark.parametrize("causal", [False, True])
    def test_multi_block_parity_512(self, causal):
        import numpy as np
        from aiko_services_tpu.parallel.attention import flash_attention
        q, k, v = _qkv(batch=1, heads=2, seq=512, dim=32, seed=11)
        actual = np.asarray(flash_attention(q, k, v, causal=causal))
        expected = np.asarray(self._naive(q, k, v, causal))
        np.testing.assert_allclose(actual, expected, atol=2e-3, rtol=2e-3)

    def test_multi_block_ragged_641(self):
        import numpy as np
        from aiko_services_tpu.parallel.attention import flash_attention
        # 641 = 5 blocks + 1 row: exercises the padded tail block
        q, k, v = _qkv(batch=1, heads=2, seq=641, dim=32, seed=12)
        actual = np.asarray(flash_attention(q, k, v, causal=True))
        expected = np.asarray(self._naive(q, k, v, True))
        np.testing.assert_allclose(actual, expected, atol=2e-3, rtol=2e-3)
