import pytest

from aiko_services_tpu.utils import Graph, GraphError


def test_linear_path():
    graph = Graph.traverse(["(PE_0 PE_1 PE_2)"])
    # PE_0 fans out to PE_1 and PE_2 (both direct successors)
    assert graph.get_node("PE_0").successors == ["PE_1", "PE_2"]


def test_chain():
    graph = Graph.traverse(["(PE_0 (PE_1 (PE_2 PE_3)))"])
    assert graph.get_path() == ["PE_0", "PE_1", "PE_2", "PE_3"]


def test_diamond():
    graph = Graph.traverse(["(PE_0 (PE_1 PE_3) (PE_2 PE_3))"])
    order = graph.get_path()
    assert order[0] == "PE_0"
    assert order[-1] == "PE_3"
    assert set(order) == {"PE_0", "PE_1", "PE_2", "PE_3"}
    assert order.index("PE_1") < order.index("PE_3")
    assert order.index("PE_2") < order.index("PE_3")
    assert graph.predecessors("PE_3") == ["PE_1", "PE_2"]


def test_iterate_after():
    graph = Graph.traverse(["(PE_0 (PE_1 PE_3) (PE_2 PE_3))"])
    order = graph.get_path()
    resumed = graph.iterate_after(order[1])
    assert resumed == order[2:]
    assert graph.iterate_after(order[-1]) == []


def test_iterate_after_unknown_raises():
    graph = Graph.traverse(["(A B)"])
    with pytest.raises(GraphError):
        graph.iterate_after("ZZZ")


def test_cycle_detected():
    graph = Graph.traverse(["(A B)"])
    graph.get_node("B").add_successor("A")
    graph._order_cache = None
    with pytest.raises(GraphError):
        graph.topological_order()


def test_multiple_paths():
    graph = Graph.traverse(["(A B)", "(C B)"])
    assert set(graph.head_nodes()) == {"A", "C"}
    order = graph.get_path()
    assert order.index("A") < order.index("B")
    assert order.index("C") < order.index("B")


def test_deterministic_order():
    orders = [
        Graph.traverse(["(PE_0 (PE_1 PE_3) (PE_2 PE_3))"]).get_path()
        for _ in range(5)]
    assert all(order == orders[0] for order in orders)


def test_remote_annotation():
    graph = Graph.traverse(["(A B:remote_x)"])
    assert "B" in graph
    node = graph.get_node("B")
    assert node.properties["remote_paths"] == ["B:remote_x"]
