# Fault-tolerance suite (ISSUE 3): per-element error policies
# (stop_stream | drop_frame | retry with backoff), dead-lettering on
# {topic_path}/dead_letter, the per-stream error budget, frame
# deadlines over parked branches, the fused-path circuit breaker, and
# transfer-plane fetch retry -- all proven under the DETERMINISTIC
# fault-injection harness (aiko_services_tpu/faults.py), so every
# failure here is seeded and reproducible.

import queue
import time

import numpy as np
import pytest

from aiko_services_tpu import faults as faults_module
from aiko_services_tpu.pipeline import (
    AsyncHostElement, DefinitionError, PipelineElement, StreamEvent,
    StreamState, create_pipeline, parse_pipeline_definition)
from aiko_services_tpu.runtime import Process
from aiko_services_tpu.transport import reset_brokers
from aiko_services_tpu.utils import parse
from helpers import wait_for


@pytest.fixture(autouse=True)
def clean(monkeypatch):
    # each test declares its own plan (pipeline parameter or env);
    # the cached AIKO_FAULTS plan must never leak between tests
    faults_module.reset_injector()
    reset_brokers()
    yield
    faults_module.reset_injector()
    reset_brokers()


class Scale(PipelineElement):
    """x -> x*10, recording every call's leading (batch) size."""

    def process_frame(self, stream, x):
        stream.variables.setdefault("calls", []).append(int(x.shape[0]))
        return StreamEvent.OKAY, {"y": x * 10.0}


class BadKernelScale(Scale):
    """Chained math works; the fused group kernel fails at RUN time
    (inside the compiled-program trace) -- the fused-breaker shape."""

    def group_kernel(self, stream):
        def kernel(context, x):
            raise RuntimeError("kernel exploded at trace time")

        return kernel, ()


class AsyncEcho(AsyncHostElement):
    def process_async(self, stream, x):
        return {"y": x}


class ParkForever(PipelineElement):
    def process_frame(self, stream, x):
        return StreamEvent.PENDING, {}


class Identity(PipelineElement):
    def process_frame(self, stream, x):
        return StreamEvent.OKAY, {"x": x}


def _definition(micro_batch=1, class_name="Scale", element_params=None,
                pipeline_params=None):
    definition = {
        "name": "fault_pipe",
        "graph": ["(scale)"],
        "elements": [
            {"name": "scale", "input": [{"name": "x"}],
             "output": [{"name": "y"}],
             "parameters": {"micro_batch": micro_batch,
                            **(element_params or {})},
             "deploy": {"local": {"module": "tests.test_faults",
                                  "class_name": class_name}}},
        ],
    }
    if pipeline_params:
        definition["parameters"] = dict(pipeline_params)
    return definition


RETRY_PARAMS = {"on_error": "retry", "max_retries": 3,
                "retry_backoff_ms": 1}


def _run_collect(definition, frames, expect, stream_params=None,
                 timeout=30):
    """Create the pipeline, queue `frames` before the loop starts,
    collect `expect` responses.  Returns (outputs by frame_id, pipeline,
    stream, process, dead_letters list)."""
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, definition)
    dead_letters = []

    def capture(topic, payload):
        if topic.endswith("/dead_letter"):
            dead_letters.append(parse(
                payload if isinstance(payload, str) else str(payload)))

    process.add_message_handler(capture, "#")
    responses = queue.Queue()
    stream = pipeline.create_stream("s1", queue_response=responses,
                                    parameters=stream_params or {})
    for frame_data in frames:
        pipeline.create_frame(stream, frame_data)
    process.run(in_thread=True)
    got = {}
    for _ in range(expect):
        _, frame, outputs = responses.get(timeout=timeout)
        got[frame.frame_id] = outputs
    return got, pipeline, stream, process, dead_letters


def _frames(count, shape=(2, 3)):
    return [{"x": np.full(shape, float(index), np.float32)}
            for index in range(count)]


# -- the harness itself ------------------------------------------------------

class TestFaultInjector:
    def test_spec_parsing_and_counts(self):
        injector = faults_module.create_injector(
            "seed=5;element_raise:node=a:frame=2:times=1;fetch_drop")
        assert injector.seed == 5
        # frame-targeted rule: only (a, 2), consumed once
        assert not injector.element_raise("a", 1)
        assert not injector.element_raise("b", 2)
        assert injector.element_raise_pending("a", 2)
        assert injector.element_raise("a", 2)
        assert not injector.element_raise("a", 2)  # times=1 consumed
        assert injector.fetch_drop()
        assert not injector.fetch_drop()
        assert injector.stats() == {"element_raise": 1, "fetch_drop": 1}

    def test_empty_spec_is_none(self):
        assert faults_module.create_injector(None) is None
        assert faults_module.create_injector("") is None

    def test_unknown_point_rejected(self):
        with pytest.raises(ValueError, match="unknown fault point"):
            faults_module.create_injector("explode_everything")

    def test_rate_selection_is_deterministic_in_seed(self):
        spec = "seed=11;element_raise:node=n:rate=0.3:times=-1"
        first = faults_module.create_injector(spec)
        second = faults_module.create_injector(spec)
        other = faults_module.create_injector(
            "seed=12;element_raise:node=n:rate=0.3:times=-1")
        picks = [{frame for frame in range(400)
                  if injector.element_raise_pending("n", frame)}
                 for injector in (first, second, other)]
        assert picks[0] == picks[1]          # same seed, same frames
        assert picks[0] != picks[2]          # seed changes the draw
        assert 40 < len(picks[0]) < 200      # ~30% of 400

    def test_rate_on_identityless_point_draws_per_call(self):
        """fetch_drop has no frame identity: the per-rule call ordinal
        must stand in, so rate= is a genuine per-call probability (not
        an all-or-nothing constant) while staying seed-deterministic."""
        spec = "seed=5;fetch_drop:rate=0.5:times=-1"
        first = faults_module.create_injector(spec)
        second = faults_module.create_injector(spec)
        fires_a = [first.fetch_drop() for _ in range(200)]
        fires_b = [second.fetch_drop() for _ in range(200)]
        assert fires_a == fires_b           # deterministic in seed
        assert 40 < sum(fires_a) < 160      # ~50% of 200
        assert True in fires_a and False in fires_a

    def test_once_rule_fires_once_per_frame(self):
        injector = faults_module.create_injector(
            "element_raise:node=n:rate=1.0:once=1:times=-1")
        assert injector.element_raise("n", 7)
        assert not injector.element_raise("n", 7)  # frame 7 already hit
        assert injector.element_raise("n", 8)      # fresh frame still hit


def test_on_error_grammar_validated_at_definition_time():
    definition = _definition(element_params={"on_error": "explode"})
    with pytest.raises(DefinitionError, match="on_error"):
        parse_pipeline_definition(definition)


# -- retry policy ------------------------------------------------------------

def test_transient_fault_with_retry_is_bit_identical():
    """The tentpole gate: a seeded transient element fault (frame 2
    fails once) under `on_error: retry` yields BIT-IDENTICAL stream
    output to the no-fault run, with zero destroyed streams."""
    frames = _frames(6)
    faulted, fault_pipe, fault_stream, p1, dead = _run_collect(
        _definition(element_params=RETRY_PARAMS,
                    pipeline_params={"faults": (
                        "seed=11;element_raise:node=scale"
                        ":frame=2:times=1")}),
        frames, expect=6)
    clean_got, _, _, p2, _ = _run_collect(
        _definition(element_params=RETRY_PARAMS), frames, expect=6)
    assert sorted(faulted) == sorted(clean_got) == list(range(6))
    for index in range(6):
        left = np.asarray(faulted[index]["y"])
        right = np.asarray(clean_got[index]["y"])
        assert left.dtype == right.dtype and left.shape == right.shape
        assert left.tobytes() == right.tobytes()
    # zero destroyed streams; the fault shows in telemetry, not output
    assert "s1" in fault_pipe.streams
    assert fault_stream.state == StreamState.RUN
    registry = fault_pipe.telemetry.registry
    assert registry.counter("pipeline.retries").value == 1
    assert registry.counter("pipeline.frames_errored").value == 0
    assert registry.counter("pipeline.dead_letters").value == 0
    assert not dead
    assert fault_pipe.faults.stats()["element_raise"] == 1
    p1.terminate()
    p2.terminate()


def test_transient_fault_in_micro_batch_group_retries_transparently():
    """A poisoned frame inside a coalesced group: the whole-group
    attempts fail, isolation completes the siblings, and the poisoned
    frame's retry re-enters the scheduler -- output still bit-identical
    to the clean run."""
    frames = _frames(4)
    definition = _definition(micro_batch=4, element_params=RETRY_PARAMS,
                             pipeline_params={"faults": (
                                 "seed=3;element_raise:node=scale"
                                 ":frame=1:times=1")})
    faulted, pipeline, stream, p1, dead = _run_collect(
        definition, frames, expect=4)
    clean_got, _, _, p2, _ = _run_collect(
        _definition(micro_batch=4, element_params=RETRY_PARAMS),
        frames, expect=4)
    for index in range(4):
        assert (np.asarray(faulted[index]["y"]).tobytes()
                == np.asarray(clean_got[index]["y"]).tobytes())
    assert "s1" in pipeline.streams
    assert not dead
    assert pipeline.telemetry.registry.counter(
        "pipeline.retries").value == 1
    p1.terminate()
    p2.terminate()


# -- drop_frame + dead-lettering ---------------------------------------------

def test_permanent_fault_drops_only_poisoned_frame_and_dead_letters():
    """`on_error: drop_frame` with a PERMANENT fault on frame 1: the
    sibling frames of the same micro-batch group complete, frame 1 is
    dead-lettered with its trace id, and the stream survives."""
    frames = _frames(4)
    definition = _definition(
        micro_batch=4,
        element_params={"on_error": "drop_frame"},
        pipeline_params={"faults": (
            "seed=3;element_raise:node=scale:frame=1:times=-1")})
    got, pipeline, stream, process, dead = _run_collect(
        definition, frames, expect=3)
    assert sorted(got) == [0, 2, 3]  # siblings completed
    for index in (0, 2, 3):
        value = np.asarray(got[index]["y"])
        assert float(value[0, 0]) == index * 10
    wait_for(lambda: dead)
    command, parameters = dead[0]
    assert command == "dead_letter"
    meta, descriptor = parameters[0], parameters[1]
    assert meta["node"] == "scale"
    assert meta["reason"] == "drop_frame"
    assert int(meta["frame_id"]) == 1
    assert meta["trace_id"]  # joins the frame's trace
    assert "injected fault" in meta["diagnostic"]
    # inputs DESCRIPTOR, not payload: dtype + shape evidence
    assert descriptor["x"] == "float32[2, 3]"
    assert "s1" in pipeline.streams       # stream survived the poison
    assert stream.state == StreamState.RUN
    registry = pipeline.telemetry.registry
    assert registry.counter("pipeline.dead_letters").value == 1
    assert registry.counter("pipeline.frames_errored").value == 1
    wait_for(lambda: stream.pending == 0)  # backpressure slot returned
    process.terminate()


def test_recorder_consumes_dead_letters():
    from aiko_services_tpu.runtime import Recorder
    recorder_process = Process(transport_kind="loopback")
    recorder = Recorder(recorder_process)
    recorder_process.run(in_thread=True)
    definition = _definition(
        element_params={"on_error": "drop_frame"},
        pipeline_params={"faults":
                         "element_raise:node=scale:frame=0:times=1"})
    got, pipeline, stream, process, dead = _run_collect(
        definition, _frames(2), expect=1)
    assert sorted(got) == [1]  # frame 0 dead-lettered, frame 1 flowed
    wait_for(lambda: recorder.dead_letters(), timeout=10)
    topic, meta, descriptor = recorder.dead_letters()[0]
    assert topic.endswith("/dead_letter")
    assert meta["node"] == "scale" and meta["reason"] == "drop_frame"
    assert descriptor["x"] == "float32[2, 3]"
    recorder_process.terminate()
    process.terminate()


# -- error budget / stream quarantine ----------------------------------------

def test_error_budget_quarantines_flapping_stream():
    """drop_frame keeps a stream alive per failure -- but N errors
    inside the sliding window must QUARANTINE it (destroyed with
    StreamState.ERROR) instead of flapping forever."""
    definition = _definition(
        element_params={"on_error": "drop_frame"},
        pipeline_params={"faults":
                         "element_raise:node=scale:times=-1"})
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, definition)
    responses = queue.Queue()
    stream = pipeline.create_stream(
        "s1", queue_response=responses,
        parameters={"error_budget": 3, "error_window": 30})
    for frame_data in _frames(5):
        pipeline.create_frame(stream, frame_data)
    process.run(in_thread=True)
    wait_for(lambda: "s1" not in pipeline.streams, timeout=15)
    assert stream.state == StreamState.ERROR
    registry = pipeline.telemetry.registry
    assert registry.counter("pipeline.breaker_trips").value == 1
    assert registry.counter("pipeline.dead_letters").value >= 3
    assert responses.empty()
    process.terminate()


# -- frame deadline over parked branches -------------------------------------

def test_frame_deadline_releases_blackholed_async_frame():
    """A reply blackhole (a dead remote / lost async reply) parks the
    frame forever; `frame_deadline` must release it as an error,
    dead-lettered, with the stream surviving."""
    definition = _definition(class_name="AsyncEcho",
                             pipeline_params={"faults": (
                                 "reply_blackhole:node=scale:times=1")})
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, definition)
    dead = []
    process.add_message_handler(
        lambda topic, payload: dead.append(parse(str(payload)))
        if topic.endswith("/dead_letter") else None, "#")
    responses = queue.Queue()
    stream = pipeline.create_stream(
        "s1", queue_response=responses,
        parameters={"frame_deadline": 0.4})
    pipeline.create_frame(stream, {"x": np.ones((1, 2), np.float32)})
    process.run(in_thread=True)
    # the async reply is swallowed; the deadline must reap the frame
    wait_for(lambda: not stream.frames, timeout=10)
    assert stream.pending == 0          # backpressure slot reclaimed
    assert "s1" in pipeline.streams     # frame-level error only
    wait_for(lambda: dead)
    meta = dead[0][1][0]
    assert meta["reason"] == "frame_deadline"
    assert pipeline.telemetry.registry.counter(
        "pipeline.deadline_expired").value == 1
    assert responses.empty()
    process.terminate()


def test_frame_deadline_does_not_kill_healthy_frames():
    definition = _definition(class_name="AsyncEcho")
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, definition)
    responses = queue.Queue()
    stream = pipeline.create_stream(
        "s1", queue_response=responses,
        parameters={"frame_deadline": 5.0})
    pipeline.create_frame(stream, {"x": np.ones((1, 2), np.float32)})
    process.run(in_thread=True)
    _, frame, outputs = responses.get(timeout=10)
    assert float(np.asarray(outputs["y"])[0, 0]) == 1.0
    assert frame.deadline_lease is None  # terminated at finish
    assert pipeline.telemetry.registry.counter(
        "pipeline.deadline_expired").value == 0
    process.terminate()


# -- park watchdog telemetry (satellite) -------------------------------------

def test_park_watchdog_expiry_counted_and_dead_lettered():
    definition = {
        "name": "watchdog_pipe",
        "graph": ["(head (a) (b))"],
        "elements": [
            {"name": "head", "input": [{"name": "x"}],
             "output": [{"name": "x"}],
             "deploy": {"local": {"module": "tests.test_faults",
                                  "class_name": "Identity"}}},
            {"name": "a", "input": [{"name": "x"}],
             "output": [{"name": "ya"}],
             "deploy": {"local": {"module": "tests.test_faults",
                                  "class_name": "ParkForever"}}},
            {"name": "b", "input": [{"name": "x"}],
             "output": [{"name": "yb"}],
             "deploy": {"local": {"module": "tests.test_faults",
                                  "class_name": "ParkForever"}}},
        ],
    }
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, definition)
    process.run(in_thread=True)
    responses = queue.Queue()
    stream = pipeline.create_stream(
        "s1", queue_response=responses,
        parameters={"park_timeout": 0.2})
    pipeline.process_frame({"stream_id": "s1"},
                           {"x": np.ones((1, 1), np.float32)})
    wait_for(lambda: 0 in stream.frames
             and len(stream.frames[0].pending_nodes) == 2, timeout=10)
    pipeline.process_frame_response(
        {"stream_id": "s1", "frame_id": 0}, "")  # unroutable: arm
    wait_for(lambda: not stream.frames, timeout=10)
    registry = pipeline.telemetry.registry
    assert registry.counter("pipeline.park_expired").value == 1
    assert registry.counter("pipeline.dead_letters").value == 1
    process.terminate()


# -- fused-path circuit breaker ----------------------------------------------

def test_fused_runtime_failure_retries_chained_then_breaker_pins():
    """A group kernel failing at RUN time must not lose the group: it
    retries on the chained path (frames complete).  After
    FUSED_FLAP_LIMIT failures the breaker pins the element chained --
    no more fused attempts, no more failures."""
    from aiko_services_tpu.pipeline.pipeline import FUSED_FLAP_LIMIT
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(
        process, _definition(micro_batch=2,
                             class_name="BadKernelScale"))
    responses = queue.Queue()
    stream = pipeline.create_stream("s1", queue_response=responses)
    process.run(in_thread=True)
    for wave in range(FUSED_FLAP_LIMIT + 1):  # one wave per group
        for index in range(2):
            pipeline.create_frame(
                stream,
                {"x": np.full((1, 2), float(wave * 2 + index),
                              np.float32)})
        for _ in range(2):
            _, frame, outputs = responses.get(timeout=30)
            expected = frame.frame_id * 10.0
            assert float(np.asarray(outputs["y"])[0, 0]) == expected
    assert pipeline._fused_disabled == {"scale"}
    registry = pipeline.telemetry.registry
    # exactly the flap limit failed (the breaker then stopped fused
    # attempts entirely -- regardless of how frames grouped)
    assert registry.counter(
        "pipeline.fused_failures").value == FUSED_FLAP_LIMIT
    assert registry.counter("pipeline.fused_disabled").value == 1
    # every group ultimately ran chained (process_frame saw them all)
    assert len(stream.variables["calls"]) >= FUSED_FLAP_LIMIT
    process.terminate()


class StringErrorElement(PipelineElement):
    """Contract edge: _safe_call only validates the StreamEvent half,
    so a non-dict ERROR payload reaches the error handlers intact."""

    def process_frame(self, stream, x):
        return StreamEvent.ERROR, "plain text failure"


def test_non_dict_error_payload_is_handled_not_leaked():
    definition = _definition(class_name="StringErrorElement",
                             element_params={"on_error": "drop_frame"})
    got, pipeline, stream, process, dead = _run_collect(
        definition, _frames(1), expect=0)
    wait_for(lambda: dead, timeout=10)
    assert dead[0][1][0]["diagnostic"] == "plain text failure"
    wait_for(lambda: not stream.frames and stream.pending == 0)
    assert "s1" in pipeline.streams  # frame released, stream alive
    process.terminate()


def test_singleton_group_consumes_fault_rule():
    """A one-frame micro-batch group must CONSUME a times=1 fault (it
    goes straight to the error policy with no isolation pass): the next
    frame flows clean instead of the peeked rule poisoning forever."""
    definition = _definition(
        micro_batch=4,
        element_params={"on_error": "drop_frame"},
        pipeline_params={"faults": "element_raise:node=scale:times=1"})
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, definition)
    responses = queue.Queue()
    stream = pipeline.create_stream("s1", queue_response=responses)
    process.run(in_thread=True)
    # loop running: each frame parks alone and flushes as a singleton
    pipeline.create_frame(stream, {"x": np.ones((1, 2), np.float32)})
    wait_for(lambda: pipeline.telemetry.registry.counter(
        "pipeline.dead_letters").value == 1, timeout=10)
    pipeline.create_frame(stream, {"x": np.ones((1, 2), np.float32)})
    _, frame, outputs = responses.get(timeout=10)  # second frame flows
    assert float(np.asarray(outputs["y"])[0, 0]) == 10.0
    assert pipeline.faults.stats() == {"element_raise": 1}
    process.terminate()


class FlakyKernelScale(Scale):
    """Fused kernel failure steerable per group: `fail_next` is
    captured at group_kernel time (fresh closure per call, so every
    group rebuilds + re-traces)."""

    fail_next = False

    def group_kernel(self, stream):
        def kernel(context, x, _fail=self.fail_next):
            if _fail:
                raise RuntimeError("flaky kernel")
            return {"y": x * 10.0}

        return kernel, ()


def test_fused_breaker_resets_on_healthy_group():
    """Only CONSECUTIVE fused failures trip the breaker: a healthy
    fused group in between resets the flap count, so scattered poison
    frames over a long deployment never pin a healthy kernel."""
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(
        process, _definition(micro_batch=2,
                             class_name="FlakyKernelScale"))
    responses = queue.Queue()
    stream = pipeline.create_stream("s1", queue_response=responses)
    process.run(in_thread=True)
    element = pipeline.elements["scale"]
    frame_value = [0]

    def wave(fail):
        # ONE frame per wave: with the loop running it flushes as one
        # singleton group, so each wave is exactly one fused attempt
        element.fail_next = fail
        pipeline.create_frame(
            stream, {"x": np.full((1, 2), float(frame_value[0]),
                                  np.float32)})
        frame_value[0] += 1
        responses.get(timeout=30)

    for fail in (True, False, True, True):  # fail, reset, fail x2
        wave(fail)
    # 3 total failures but never 3 CONSECUTIVE: breaker must not trip
    assert "scale" not in pipeline._fused_disabled
    registry = pipeline.telemetry.registry
    assert registry.counter("pipeline.fused_failures").value == 3
    assert registry.counter("pipeline.fused_disabled").value == 0
    process.terminate()


# -- transfer plane ----------------------------------------------------------

def test_fetch_survives_one_injected_socket_drop(monkeypatch):
    """`transfer.fetch` retries an injected socket drop; the
    fetch_errors / fetch_retries counters reconcile (every failed
    attempt was retried and recovered)."""
    from aiko_services_tpu.observe.metrics import get_registry
    from aiko_services_tpu.pipeline.transfer import (
        TensorTransferServer, fetch)
    monkeypatch.setenv("AIKO_FAULTS", "fetch_drop:times=1")
    monkeypatch.setenv("AIKO_TRANSFER_RETRY_MS", "1")
    faults_module.reset_injector()
    registry = get_registry()
    errors0 = registry.counter("transfer.fetch_errors").value
    retries0 = registry.counter("transfer.fetch_retries").value
    fetches0 = registry.counter("transfer.fetches").value
    server = TensorTransferServer()
    try:
        array = np.arange(64, dtype=np.float32).reshape(8, 8)
        fetched = fetch(server.offer(array))
        np.testing.assert_array_equal(fetched, array)
    finally:
        server.close()
    assert registry.counter(
        "transfer.fetch_errors").value - errors0 == 1
    assert registry.counter(
        "transfer.fetch_retries").value - retries0 == 1
    assert registry.counter("transfer.fetches").value - fetches0 == 1


def test_fetch_exhausted_retries_still_raise(monkeypatch):
    from aiko_services_tpu.pipeline.transfer import (
        TensorTransferServer, TransferError, fetch)
    monkeypatch.setenv("AIKO_FAULTS", "fetch_drop:times=-1")
    monkeypatch.setenv("AIKO_TRANSFER_RETRY_MS", "1")
    faults_module.reset_injector()
    server = TensorTransferServer()
    try:
        descriptor = server.offer(np.ones(4))
        with pytest.raises(TransferError, match="attempts"):
            fetch(descriptor, retries=2)
    finally:
        server.close()


# -- dispatch delay (latency-shaped fault) -----------------------------------

def test_dispatch_delay_injects_latency_not_errors():
    definition = _definition(
        element_params=RETRY_PARAMS,
        pipeline_params={"faults":
                         "dispatch_delay:node=scale:ms=80:times=1"})
    start = time.monotonic()
    got, pipeline, stream, process, dead = _run_collect(
        definition, _frames(1), expect=1)
    elapsed = time.monotonic() - start
    assert float(np.asarray(got[0]["y"])[0, 0]) == 0.0
    assert elapsed >= 0.08  # the delay really ran
    assert not dead
    assert pipeline.faults.stats() == {"dispatch_delay": 1}
    process.terminate()


# -- generator-side policy ---------------------------------------------------

class FlakyNumberSource(PipelineElement):
    def process_frame(self, stream, **inputs):
        return StreamEvent.OKAY, {}

    def start_stream(self, stream, stream_id):
        # own emission counter: the engine-side frame_id cursor advances
        # on the event-loop thread, racing a fast generator
        def generator(stream, frame_id):
            emitted = stream.variables.get("emitted", 0)
            if emitted == 1 and not stream.variables.get("tripped"):
                stream.variables["tripped"] = True
                raise RuntimeError("transient ingest hiccup")
            if emitted >= 3:
                return StreamEvent.STOP, None
            stream.variables["emitted"] = emitted + 1
            return StreamEvent.OKAY, {
                "x": np.full((1, 1), float(emitted), np.float32)}

        self.create_frames(stream, generator, rate=200)
        return StreamEvent.OKAY, None


def test_generator_fault_with_drop_policy_keeps_stream_alive():
    """A transient frame-generator exception under `on_error:
    drop_frame` skips the tick instead of destroying the stream (the
    historical stop_stream default is unchanged elsewhere)."""
    definition = {
        "name": "gen_pipe",
        "graph": ["(source (scale))"],
        "elements": [
            {"name": "source", "output": [{"name": "x"}],
             "parameters": {"on_error": "drop_frame"},
             "deploy": {"local": {"module": "tests.test_faults",
                                  "class_name": "FlakyNumberSource"}}},
            {"name": "scale", "input": [{"name": "x"}],
             "output": [{"name": "y"}],
             "deploy": {"local": {"module": "tests.test_faults",
                                  "class_name": "Scale"}}},
        ],
    }
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, definition)
    process.run(in_thread=True)
    responses = queue.Queue()
    pipeline.create_stream("s1", queue_response=responses)
    got = sorted(float(np.asarray(responses.get(timeout=10)[2]["y"])[0, 0])
                 for _ in range(3))
    assert got == [0.0, 10.0, 20.0]  # frames 0..2 all delivered
    process.terminate()
