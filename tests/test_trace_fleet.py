# Fleet-scope distributed tracing (ISSUE 14): cross-process trace
# propagation (gateway = root-span owner, replicas continue the same
# trace), clock-aligned deterministic merging (observe/collector.py +
# `aiko trace merge|collect`), per-stream end-to-end decomposition +
# per-priority SLO accounting in the gateway summary, and the tune
# loader's admission-bound floor over merged multi-process artifacts.
#
# The acceptance invariants: one merged artifact from a gateway +
# >=2-replica (disagg) run shows a single stream's trace crossing >=3
# processes with correct parent/child nesting and monotonic
# clock-aligned timestamps; merging is byte-deterministic; `aiko tune`
# classifies the admission-bound floor on a synthetic known-floor
# fixture; and `telemetry: false` puts ZERO trace-context bytes on the
# wire (frame payloads byte-identical to the untraced build).

import json
import queue

import numpy as np
import pytest

from aiko_services_tpu.observe import (
    TRACE_CONTEXT_KEY, Tracer, attach_trace_context,
    chrome_trace_document, collect_traces, make_trace_context,
    merge_trace_documents, merge_trace_files, pop_trace_context,
    trace_context_of, trace_summary)
from aiko_services_tpu.observe.trace import trace_metadata
from aiko_services_tpu.pipeline import create_pipeline
from aiko_services_tpu.pipeline.tensors import encode_frame_data
from aiko_services_tpu.runtime import Process, Registrar
from aiko_services_tpu.serve import Gateway
from aiko_services_tpu.transport import reset_brokers

from helpers import wait_for
from test_serve import _frame, _replica_definition


@pytest.fixture(autouse=True)
def clean_brokers():
    reset_brokers()
    yield
    reset_brokers()


def frame_events(document):
    return [event for event in document["traceEvents"]
            if event.get("ph") == "X" and event.get("cat") == "frame"]


def gateway_events(document, prefix):
    return [event for event in document["traceEvents"]
            if event.get("cat") == "gateway"
            and str(event.get("name", "")).startswith(prefix)]


# -- trace context plumbing --------------------------------------------------


class TestTraceContext:
    def test_round_trip_and_adoption(self):
        tracer = Tracer(pid=11)
        root = tracer.begin("s", 3)
        context = make_trace_context(root)
        assert context == {"trace_id": root.trace_id,
                           "span_id": root.span_id}
        data = attach_trace_context({"x": 1}, context)
        assert trace_context_of(data) == context
        assert "x" in data
        # attach copies: the original dict stays pristine (failover
        # replay byte-equality depends on it)
        original = {"x": 1}
        attached = attach_trace_context(original, context)
        assert TRACE_CONTEXT_KEY not in original
        assert pop_trace_context(attached) == context
        assert attached == original

        downstream = Tracer(pid=22)
        child = downstream.begin("s", 3)
        child.adopt(context)
        assert child.trace_id == root.trace_id
        assert child.parent_span_id == root.span_id
        downstream.finish(child)
        [frame] = frame_events(
            chrome_trace_document(downstream.chrome_events()))
        assert frame["args"]["trace_id"] == root.trace_id
        assert frame["args"]["parent"] == root.span_id
        assert frame["args"]["span_id"] == child.span_id

    def test_pop_is_ingress_safe(self):
        assert pop_trace_context(None) is None
        assert pop_trace_context({"a": 1}) is None
        assert trace_context_of("not a dict") is None


# -- gateway root spans + propagation over the serving tier ------------------


class TestGatewayFleetTracing:
    def _run_fleet(self, telemetry=True, slo_ms=0):
        processes, replicas = [], []
        for index in range(2):
            process = Process(transport_kind="loopback")
            processes.append(process)
            replicas.append(create_pipeline(
                process, _replica_definition(f"replica{index}")))
        gateway_process = Process(transport_kind="loopback")
        processes.append(gateway_process)
        gateway = Gateway(gateway_process,
                          policy="max_inflight=4;queue=16",
                          telemetry=telemetry, metrics_interval=60.0)
        for replica in replicas:
            gateway.attach_replica(replica)
        for process in processes:
            process.run(in_thread=True)
        responses = queue.Queue()
        parameters = {"slo_ms": slo_ms} if slo_ms else {}
        for stream in range(2):
            gateway.submit_stream(f"s{stream}", parameters,
                                  queue_response=responses)
        done = 0
        for stream in range(2):
            for frame_id in range(3):
                gateway.submit_frame(f"s{stream}", _frame(frame_id),
                                     frame_id=frame_id)
        while done < 6:
            item = responses.get(timeout=30)
            assert item[3] == "ok", item
            done += 1
        return gateway, replicas, processes

    def test_root_spans_and_cross_process_continuation(self):
        gateway, replicas, processes = self._run_fleet()
        try:
            documents = [("gateway", chrome_trace_document(
                gateway.telemetry.chrome_events(),
                metadata=gateway.telemetry.trace_metadata()))]
            for index, replica in enumerate(replicas):
                documents.append((f"replica{index}",
                                  chrome_trace_document(
                                      replica.telemetry.chrome_events(),
                                      metadata=replica.telemetry
                                      .trace_metadata())))
            gateway_doc = documents[0][1]
            # the gateway emitted real admit-wait and route spans
            assert len(gateway_events(gateway_doc, "admit:")) == 6
            assert len(gateway_events(gateway_doc, "route:")) == 6
            merged = merge_trace_documents(documents)
            summary = trace_summary(merged)
            # every admitted frame's trace crosses gateway + replica,
            # parent-linked with no dangling references
            assert summary["traces"] == 6
            assert summary["multi_process_traces"] == 6
            assert summary["max_processes_per_trace"] == 2
            assert summary["linked_spans"] >= 6
            assert summary["dangling_parents"] == []
            # replica frame spans carry the GATEWAY's trace ids
            gateway_ids = {event["args"]["trace_id"]
                           for event in frame_events(gateway_doc)}
            for _name, document in documents[1:]:
                for event in frame_events(document):
                    assert event["args"]["trace_id"] in gateway_ids
                    assert "parent" in event["args"]
            # merged timestamps are monotonic (sorted) and clock
            # alignment keeps the gateway's root start at/before its
            # replica continuation
            timestamps = [event.get("ts", 0.0)
                          for event in merged["traceEvents"]
                          if event.get("ph") != "M"]
            assert timestamps == sorted(timestamps)
            spans = {event["args"]["span_id"]: event
                     for event in merged["traceEvents"]
                     if event.get("cat") == "frame"
                     and "span_id" in event.get("args", {})}
            linked = 0
            for event in merged["traceEvents"]:
                parent = event.get("args", {}).get("parent")
                if parent and parent in spans:
                    linked += 1
                    assert spans[parent]["ts"] <= event["ts"] + 1.0
            assert linked >= 6
        finally:
            for process in processes:
                process.terminate()

    def test_slo_counters_and_decomposition(self):
        gateway, _replicas, processes = self._run_fleet(slo_ms=30000)
        try:
            summary = gateway.telemetry.summary()
            slo = summary["slo"]
            assert slo["0"]["ok"] == 6
            assert slo["0"]["miss"] == 0
            assert slo["0"]["attainment"] == 1.0
            assert slo["0"]["burn"] == 0.0
            decomposition = summary["stream_decomposition"]
            for stream in ("s0", "s1"):
                stages = decomposition[stream]
                for stage in ("admit", "route", "queue", "decode",
                              "emit"):
                    assert stage in stages, (stream, stages)
            total = decomposition["_total"]
            assert total["decode"] > 0
            # destroyed streams fold into the persistent total
            gateway.destroy_stream("s0")
            wait_for(lambda: "s0" not in gateway.streams)
            after = gateway.telemetry.summary()[
                "stream_decomposition"]
            assert "s0" not in after
            assert after["_total"]["decode"] >= total["decode"] - 0.001
        finally:
            for process in processes:
                process.terminate()

    def test_telemetry_off_zero_trace_bytes_on_the_wire(self):
        """The zero-overhead contract: with gateway telemetry off the
        dispatched frame payload is the SAME object content as the
        submitted frame data -- byte-identical on the wire codec, no
        trace-context key, no frame traces anywhere."""
        processes = []
        replica_process = Process(transport_kind="loopback")
        processes.append(replica_process)
        replica = create_pipeline(replica_process, _replica_definition(
            "replica0", parameters={"telemetry": False}))
        gateway_process = Process(transport_kind="loopback")
        processes.append(gateway_process)
        gateway = Gateway(gateway_process,
                          policy="max_inflight=4;queue=16",
                          telemetry=False)
        gateway.attach_replica(replica)
        dispatched = []
        original_post = replica.post_message

        def recording_post(command, parameters, **kwargs):
            if command == "process_frame":
                dispatched.append(parameters[1])
            return original_post(command, parameters, **kwargs)

        replica.post_message = recording_post
        for process in processes:
            process.run(in_thread=True)
        try:
            responses = queue.Queue()
            gateway.submit_stream("s0", queue_response=responses)
            frame_data = _frame(7)
            reference_bytes = encode_frame_data(frame_data)
            gateway.submit_frame("s0", frame_data, frame_id=0)
            assert responses.get(timeout=30)[3] == "ok"
            assert len(dispatched) == 1
            payload = dispatched[0]
            assert TRACE_CONTEXT_KEY not in payload
            # byte-compare against the seed wire encoding: tracing off
            # means the frame payload is EXACTLY what was submitted
            assert encode_frame_data(payload) == reference_bytes
            assert payload is frame_data  # no copy either
            # and no spans were recorded anywhere
            assert not gateway.telemetry.tracer.completed
            assert not replica.telemetry.tracer.completed
        finally:
            for process in processes:
                process.terminate()

    def test_telemetry_on_context_rides_but_never_leaks(self):
        gateway, replicas, processes = self._run_fleet()
        try:
            # element inputs/outputs never see the reserved key: the
            # replica pops it at stream ingress
            for replica in replicas:
                for trace in replica.telemetry.tracer.completed:
                    assert trace.origin_trace_id is not None
        finally:
            for process in processes:
                process.terminate()


# -- merging: clock calibration + byte determinism ---------------------------


def _synthetic_document(pid, epoch_us, name="proc", span_ts=1000.0):
    # ids are pid-derived exactly like FrameTrace's ({pid:x}-{seq:x} /
    # {pid:x}.{seq:x}): the collision test proves the merger rewrites
    # them alongside the event pid
    events = [
        {"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
         "args": {"name": name}},
        {"ph": "X", "name": "frame 0", "cat": "frame", "ts": span_ts,
         "dur": 500.0, "pid": pid, "tid": 1,
         "args": {"trace_id": f"{pid:x}-1", "span_id": f"{pid:x}.1",
                  "status": "ok", "stream": "s"}},
    ]
    metadata = trace_metadata()
    metadata["clock_epoch_unix_us"] = epoch_us
    return chrome_trace_document(events, metadata=metadata)


class TestMerge:
    def test_clock_alignment_shifts_to_the_earliest_epoch(self):
        # process B booted 2 s after A: B's local ts 1000 is wall time
        # 2_001_000 on A's timeline
        doc_a = _synthetic_document(1, 1_000_000.0, "a")
        doc_b = _synthetic_document(2, 3_000_000.0, "b")
        merged = merge_trace_documents([("a", doc_a), ("b", doc_b)])
        spans = {event["pid"]: event
                 for event in frame_events(merged)}
        assert spans[1]["ts"] == 1000.0
        assert spans[2]["ts"] == 1000.0 + 2_000_000.0
        aiko = merged["metadata"]["aiko"]
        assert aiko["clock_epoch_unix_us"] == 1_000_000.0
        assert aiko["merged"]["b"]["offset_us"] == 2_000_000.0

    def test_pid_collisions_remap_deterministically(self):
        doc_a = _synthetic_document(7, 0.0, "a")
        doc_b = _synthetic_document(7, 0.0, "b")
        merged = merge_trace_documents([("a", doc_a), ("b", doc_b)])
        assert sorted(event["pid"]
                      for event in frame_events(merged)) == [7, 8]
        assert merged["metadata"]["aiko"]["merged"]["b"]["pids"] == [8]
        # pid-derived trace/span ids are rewritten WITH the pid:
        # two unrelated hosts must not read as one trace
        ids = {event["pid"]: event["args"]
               for event in frame_events(merged)}
        assert ids[7]["trace_id"] == "7-1"
        assert ids[8]["trace_id"] == "8-1"
        assert ids[8]["span_id"] == "8.1"
        assert trace_summary(merged)["traces"] == 2
        assert merged["metadata"]["aiko"]["pid_collisions"] == {
            "7": ["b"]}

    def test_collision_remap_preserves_propagated_links(self):
        # a colliding replica doc ADOPTED the gateway's trace: its own
        # span_id is rewritten with the fresh pid, but the propagated
        # trace_id and parent were minted by the GATEWAY (which keeps
        # pid 7) -- rewriting them would split the cross-process trace
        # the merger exists to preserve
        gateway_doc = _synthetic_document(7, 0.0, "gateway")
        replica_doc = _synthetic_document(7, 0.0, "replica")
        replica_doc["traceEvents"][1]["args"] = {
            "trace_id": "7-1", "span_id": "7.2", "parent": "7.1",
            "status": "ok", "stream": "s"}
        merged = merge_trace_documents([("gateway", gateway_doc),
                                        ("replica", replica_doc)])
        ids = {event["pid"]: event["args"]
               for event in frame_events(merged)}
        assert ids[8]["span_id"] == "8.2"      # locally minted
        assert ids[8]["trace_id"] == "7-1"     # gateway's, untouched
        assert ids[8]["parent"] == "7.1"       # gateway's, untouched
        summary = trace_summary(merged)
        assert summary["multi_process_traces"] == 1
        assert summary["dangling_parents"] == []

    def test_summary_counts_span_id_less_parent_links(self):
        # adopt spans carry a cross-process parent but no span_id of
        # their own: a broken link must still surface as dangling
        document = _synthetic_document(3, 0.0, "decode")
        document["traceEvents"].append(
            {"ph": "X", "name": "adopt:lm", "cat": "engine",
             "ts": 1100.0, "dur": 50.0, "pid": 3, "tid": 1,
             "args": {"trace_id": "3-1", "parent": "dead.1"}})
        summary = trace_summary(document)
        assert summary["linked_spans"] == 1
        assert summary["dangling_parents"] == ["adopt:lm@1100.0"]

    def test_unaligned_sources_are_flagged_not_dropped(self):
        doc = _synthetic_document(1, 0.0, "a")
        foreign = {"traceEvents": [
            {"ph": "X", "name": "x", "cat": "element", "ts": 5.0,
             "dur": 1.0, "pid": 9, "tid": 0, "args": {}}]}
        merged = merge_trace_documents([("a", doc),
                                        ("foreign", foreign)])
        assert merged["metadata"]["aiko"]["unaligned_sources"] == [
            "foreign"]
        assert len(merged["traceEvents"]) == 3

    def test_merge_files_is_byte_deterministic(self, tmp_path):
        doc_a = _synthetic_document(1, 1_000.0, "a")
        doc_b = _synthetic_document(2, 9_000.0, "b")
        for name, document in (("a", doc_a), ("b", doc_b)):
            (tmp_path / f"{name}.json").write_text(
                json.dumps(document))
        inputs = [str(tmp_path / "b.json"), str(tmp_path / "a.json")]
        out1, out2 = str(tmp_path / "m1.json"), str(tmp_path / "m2.json")
        merge_trace_files(inputs, output=out1)
        merge_trace_files(list(reversed(inputs)), output=out2)
        bytes1 = open(out1, "rb").read()
        bytes2 = open(out2, "rb").read()
        assert bytes1 == bytes2  # input ORDER is normalized away
        assert len(bytes1) > 0

    def test_rejects_non_trace_documents(self):
        with pytest.raises(ValueError):
            merge_trace_documents([("bad", {"nope": 1})])


# -- acceptance: three processes on one stream's trace (disagg) --------------


class TestThreeProcessTrace:
    def test_disagg_frame_crosses_gateway_prefill_decode(self):
        """One stream's frame: gateway root span -> prefill replica
        child span -> decode replica child span (adopt parented under
        the PREFILL hop via the handoff descriptor) -- >=3 processes on
        one merged, clock-aligned timeline."""
        from test_disagg import make_decode_pipeline, \
            make_prefill_pipeline
        processes = []
        prefill_process = Process(transport_kind="loopback")
        processes.append(prefill_process)
        prefill_pipe = make_prefill_pipeline(prefill_process, "pre0")
        decode_process = Process(transport_kind="loopback")
        processes.append(decode_process)
        decode_pipe = make_decode_pipeline(decode_process, "dec0")
        gateway_process = Process(transport_kind="loopback")
        processes.append(gateway_process)
        gateway = Gateway(gateway_process,
                          policy="max_inflight=8;queue=32",
                          disagg="adopt_timeout=5",
                          metrics_interval=60.0)
        gateway.attach_replica(prefill_pipe)
        gateway.attach_replica(decode_pipe)
        for process in processes:
            process.run(in_thread=True)
        try:
            rng = np.random.default_rng(5)
            responses = queue.Queue()
            gateway.submit_stream("g1", {}, queue_response=responses)
            for frame_id in range(2):
                gateway.submit_frame(
                    "g1",
                    {"tokens": rng.integers(
                        1, 300, size=(1, 6)).astype(np.int32)},
                    frame_id=frame_id)
            for _ in range(2):
                assert responses.get(timeout=120)[3] == "ok"
            documents = [
                ("gateway", chrome_trace_document(
                    gateway.telemetry.chrome_events(),
                    metadata=gateway.telemetry.trace_metadata())),
                ("pre0", chrome_trace_document(
                    prefill_pipe.telemetry.chrome_events(),
                    metadata=prefill_pipe.telemetry.trace_metadata())),
                ("dec0", chrome_trace_document(
                    decode_pipe.telemetry.chrome_events(),
                    metadata=decode_pipe.telemetry.trace_metadata())),
            ]
            merged = merge_trace_documents(documents)
            summary = trace_summary(merged)
            assert summary["max_processes_per_trace"] >= 3, summary
            assert summary["dangling_parents"] == []
            # nesting: both replica frame spans parent under the SAME
            # gateway root span for a given trace id
            gateway_spans = {event["args"]["span_id"]
                             for event in frame_events(documents[0][1])}
            crossing = {}
            for event in frame_events(merged):
                args = event["args"]
                if args.get("parent") in gateway_spans:
                    crossing.setdefault(args["trace_id"], []).append(
                        event["pid"])
            assert any(len(set(pids)) >= 2
                       for pids in crossing.values()), crossing
            # the decode replica's adopt span links to the prefill hop
            adopt_parents = [
                event["args"].get("parent")
                for event in merged["traceEvents"]
                if str(event.get("name", "")).startswith("adopt:")]
            prefill_spans = {event["args"]["span_id"]
                             for event in frame_events(documents[1][1])}
            assert any(parent in prefill_spans
                       for parent in adopt_parents), adopt_parents
            # decomposition saw the prefill hop
            decomposition = gateway.telemetry.summary()[
                "stream_decomposition"]
            assert decomposition["g1"]["prefill"] > 0
        finally:
            for process in processes:
                process.terminate()


# -- tune: the admission-bound floor over a merged fleet artifact ------------


def synthesize_admission_bound_document():
    """A deterministic known-floor fixture: gateway admit-waits of
    ~80 ms dominate a 1 ms replica element -- streams wait at the
    gate."""
    events = []
    for index in range(20):
        base = 1000.0 + index * 100_000.0
        trace_id = f"t-{index}"
        events.append({"ph": "X", "name": f"frame {index}",
                       "cat": "frame", "ts": base, "dur": 82_000.0,
                       "pid": 1, "tid": 1,
                       "args": {"trace_id": trace_id,
                                "span_id": f"1.{index}",
                                "status": "ok", "stream": "s"}})
        events.append({"ph": "X", "name": "admit:gateway",
                       "cat": "gateway", "ts": base,
                       "dur": 80_000.0, "pid": 1, "tid": 1,
                       "args": {"trace_id": trace_id}})
        events.append({"ph": "X", "name": "route:gateway",
                       "cat": "gateway", "ts": base + 80_000.0,
                       "dur": 50.0, "pid": 1, "tid": 1,
                       "args": {"trace_id": trace_id,
                                "replica": "replica0"}})
        events.append({"ph": "X", "name": f"frame {index}",
                       "cat": "frame", "ts": base + 80_100.0,
                       "dur": 1_200.0, "pid": 2, "tid": 1,
                       "args": {"trace_id": trace_id,
                                "span_id": f"2.{index}",
                                "parent": f"1.{index}",
                                "status": "ok", "stream": "s"}})
        events.append({"ph": "X", "name": "scale", "cat": "element",
                       "ts": base + 80_200.0, "dur": 1_000.0,
                       "pid": 2, "tid": 1,
                       "args": {"trace_id": trace_id,
                                "path": "inline"}})
    metadata = trace_metadata(definition_document=json.loads(
        json.dumps(_replica_definition("replica0"))))
    metadata["clock_epoch_unix_us"] = 0.0
    metadata["pids"] = [1, 2]
    return chrome_trace_document(events, metadata=metadata)


class TestAdmissionBoundFloor:
    def test_classifies_and_recommends_replicas(self, tmp_path):
        from aiko_services_tpu.tune import run_tune
        path = tmp_path / "admission_bound.json"
        path.write_text(json.dumps(
            synthesize_admission_bound_document()))
        report = run_tune(str(path))
        gateway_record = report["elements"]["gateway"]
        assert gateway_record["floor"] == "admission-bound"
        evidence = gateway_record["evidence"]["gateway"]
        assert evidence["admit_median_s"] == pytest.approx(0.080)
        assert gateway_record["evidence"]["fleet_busy_ms"] == \
            pytest.approx(1.0)
        # the replica element itself stays an ordinary floor -- the
        # gate, not the kernel, is the bottleneck
        assert report["elements"]["scale"]["floor"] != "unobserved"
        targets = {(record["target"], record["knob"]):
                   record for record in report["recommendations"]}
        replica_rec = targets[("gateway", "autoscale_policy")]
        assert "admission-bound" in replica_rec["reason"]
        assert "min_replicas=2" in str(replica_rec["proposed"])
        # no AIKO503 complaint about the gateway pseudo-node
        assert not any("gateway" in diagnostic["message"]
                       for diagnostic in report["diagnostics"])

    def test_report_is_deterministic(self, tmp_path):
        from aiko_services_tpu.tune import report_json, run_tune
        path = tmp_path / "admission_bound.json"
        path.write_text(json.dumps(
            synthesize_admission_bound_document()))
        first = report_json(run_tune(str(path)))
        second = report_json(run_tune(str(path)))
        assert first == second

    def test_healthy_gateway_classifies_dispatch_bound(self, tmp_path):
        """Admit-wait BELOW the busiest element: the gateway is not the
        bottleneck tier and gets no recommendation."""
        from aiko_services_tpu.tune import run_tune
        document = synthesize_admission_bound_document()
        for event in document["traceEvents"]:
            if event.get("name") == "admit:gateway":
                event["dur"] = 100.0    # 0.1 ms << the 1 ms element
        path = tmp_path / "healthy.json"
        path.write_text(json.dumps(document))
        report = run_tune(str(path))
        assert report["elements"]["gateway"]["floor"] == \
            "dispatch-bound"
        assert not any(record["floor"] == "admission-bound"
                       for record in report["recommendations"])


# -- live collection over the control plane ----------------------------------


class TestCollect:
    def test_collects_gateway_and_pipeline_documents(self):
        processes = []
        registrar_process = Process(transport_kind="loopback")
        processes.append(registrar_process)
        Registrar(registrar_process)
        replica_process = Process(transport_kind="loopback")
        processes.append(replica_process)
        replica = create_pipeline(replica_process,
                                  _replica_definition("replica0"))
        gateway_process = Process(transport_kind="loopback")
        processes.append(gateway_process)
        gateway = Gateway(gateway_process,
                          policy="max_inflight=4;queue=16",
                          metrics_interval=60.0)
        gateway.attach_replica(replica)
        client = Process(transport_kind="loopback")
        processes.append(client)
        for process in processes:
            process.run(in_thread=True)
        try:
            responses = queue.Queue()
            gateway.submit_stream("s0", queue_response=responses)
            gateway.submit_frame("s0", _frame(1), frame_id=0)
            assert responses.get(timeout=30)[3] == "ok"
            collected = collect_traces(client, wait=2.0)
            if (gateway.topic_path not in collected
                    or replica.topic_path not in collected):
                # registrar discovery syncs async; a loaded CI box can
                # outlast the short wait -- one longer retry absorbs it
                collected = collect_traces(client, wait=6.0)
            assert gateway.topic_path in collected
            assert replica.topic_path in collected
            merged = merge_trace_documents(sorted(collected.items()))
            summary = trace_summary(merged)
            assert summary["multi_process_traces"] >= 1
        finally:
            for process in processes:
                process.terminate()
