# Warm KV failover (ISSUE 13): incremental decode-state checkpointing
# (decode/checkpoint.py), DecodeEngine.restore_request, the AIKO409
# grammar, gateway restore hints + recovery-storm pacing, the per-peer
# transfer circuit breaker, and the seeded transfer_stall fault point.
#
# The acceptance invariant everywhere: a stream restored from a
# checkpoint is BIT-IDENTICAL to an uncrashed run (greedy determinism
# re-decodes the post-snapshot tail), streamed token offsets stay
# gapless, and EVERY degraded path -- dead keeper, stale snapshot,
# block-size mismatch, open circuit, stalled transfer -- falls back to
# the existing replay re-prefill, never losing a frame.

import json
import queue
import time

import numpy as np
import pytest

import jax

from aiko_services_tpu import faults as faults_module
from aiko_services_tpu.decode import (
    CheckpointKeeper, CheckpointPolicy, DecodeCheckpointer,
    DecodeEngine, PrefillEngine, register_keeper, reset_keepers)
from aiko_services_tpu.models import (
    TransformerConfig, generate, init_params)
from aiko_services_tpu.observe.metrics import get_registry
from aiko_services_tpu.pipeline import create_pipeline
from aiko_services_tpu.pipeline.transfer import (
    TransferError, fetch_many, get_transfer_server, reset_circuits,
    reset_transfer_server)
from aiko_services_tpu.runtime import Process
from aiko_services_tpu.serve import Gateway
from aiko_services_tpu.transport import reset_brokers
from aiko_services_tpu.utils import parse

from helpers import wait_for

ELEMENTS = "aiko_services_tpu.elements"

TINY = dict(vocab_size=64, n_layers=2, n_heads=2, n_kv_heads=2,
            d_model=32, d_ff=64, max_seq_len=64, dtype="float32")


@pytest.fixture(autouse=True)
def clean(monkeypatch):
    reset_brokers()
    reset_keepers()
    reset_circuits()
    faults_module.reset_injector()
    yield
    reset_brokers()
    reset_keepers()
    reset_circuits()
    faults_module.reset_injector()


@pytest.fixture(scope="module")
def tiny_model():
    config = TransformerConfig(**TINY)
    return init_params(config, jax.random.PRNGKey(0)), config


def reference(params, config, prompt, max_new):
    out, _ = generate(params, config, np.asarray(prompt)[None],
                      max_new_tokens=max_new)
    return np.asarray(out)[0]


def drain(engine, done=None, emitted=None):
    done = {} if done is None else done
    steps = 0
    while engine.has_work():
        report = engine.step()
        if emitted is not None:
            emitted.extend((offset, token) for _rid, offset, token
                           in report.emitted)
        for completion in report.completions:
            done[completion.request_id] = completion
        steps += 1
        assert steps < 4000
    return done


def run_with_checkpoints(params, config, prompt, max_new, *,
                         spec, steps, keeper=None):
    """Run one request on a checkpointed engine for `steps` engine
    ticks; returns (engine, checkpointer, keeper, emitted)."""
    keeper = keeper or CheckpointKeeper("k1")
    policy = CheckpointPolicy.parse(spec)
    engine = DecodeEngine(params, config, decode_slots=2,
                          kv_block_size=8)
    checkpointer = DecodeCheckpointer(engine, policy, keeper=keeper)
    engine.submit("r", prompt, max_new)
    emitted = []
    for _ in range(steps):
        report = engine.step()
        emitted.extend((offset, token) for _rid, offset, token
                       in report.emitted)
        checkpointer.tick()
    assert keeper.flush()
    return engine, checkpointer, keeper, emitted


# -- the checkpointer: incremental deltas, lag bound -------------------------


class TestCheckpointer:
    def test_ships_incremental_deltas(self, tiny_model):
        """KV is append-only: after the first full snapshot, later
        snapshots re-ship only the partial last block and anything
        after it -- never the whole prompt again."""
        params, config = tiny_model
        prompt = np.arange(1, 10, dtype=np.int32)  # 9 tokens, 2 blocks
        keeper = CheckpointKeeper("k1")
        shipped = []
        original = keeper.store

        def spy(snapshot):
            shipped.append((snapshot["delta_from"],
                            len(snapshot["kv_blocks"]),
                            snapshot["blocks_total"]))
            original(snapshot)

        keeper.store = spy
        engine, checkpointer, keeper, _ = run_with_checkpoints(
            params, config, prompt, 14,
            spec="checkpoint_every=2;max_checkpoint_lag=32;keeper=k1",
            steps=10, keeper=keeper)
        assert len(shipped) >= 3
        first_from, first_count, first_total = shipped[0]
        assert first_from == 0 and first_count == first_total
        for delta_from, count, total in shipped[1:]:
            assert delta_from > 0, "a later snapshot re-shipped block 0"
            assert count == total - delta_from
        assert checkpointer.counters["checkpoints"] == len(shipped)
        assert checkpointer.counters["checkpoint_bytes"] > 0
        assert keeper.kept_blocks("r") == shipped[-1][2]

    def test_max_checkpoint_lag_forces_snapshots(self, tiny_model):
        """With a glacial checkpoint_every, max_checkpoint_lag still
        bounds how many tokens any crash can force re-decoding."""
        params, config = tiny_model
        prompt = np.arange(1, 6, dtype=np.int32)
        keeper = CheckpointKeeper("k1")
        policy = CheckpointPolicy.parse(
            "checkpoint_every=10000;max_checkpoint_lag=3;keeper=k1")
        engine = DecodeEngine(params, config, decode_slots=1,
                              kv_block_size=8)
        checkpointer = DecodeCheckpointer(engine, policy, keeper=keeper)
        engine.submit("r", prompt, 12)
        while engine.has_work():
            engine.step()
            checkpointer.tick()
            request = (engine.slots[0].request
                       if engine.slots[0] is not None else None)
            if request is not None:
                entry = checkpointer._state.get("r")
                lag = len(request.generated) - (entry["gen"]
                                                if entry else 0)
                assert lag <= 3, f"crash lag {lag} exceeds the bound"
        assert checkpointer.counters["checkpoints"] >= 3

    def test_lost_delta_invalidates_instead_of_corrupting(
            self, tiny_model):
        """A delta that fails to ingest (dead producer, expired keys)
        leaves a SEQ GAP: the keeper must null the stale region so
        restore degrades to a re-prefill -- never silently serve the
        old partial block as if it were current (the bit-identity
        guarantee)."""
        params, config = tiny_model
        prompt = np.arange(1, 10, dtype=np.int32)
        keeper = CheckpointKeeper("k1")
        dropped = {"count": 0}
        original = keeper.store

        def lossy(snapshot):
            # swallow the SECOND delta, as a failed fetch would
            if snapshot["seq"] == 1:
                dropped["count"] += 1
                return
            original(snapshot)

        keeper.store = lossy
        # checkpoint_every=4 with block_size=8: the DROPPED delta is
        # the one that completes block 1 (positions 12->16), and the
        # next delta starts at block 2 -- so block 1 on the keeper is
        # a stale partial copy unless the seq gap invalidates it
        engine, checkpointer, keeper, _ = run_with_checkpoints(
            params, config, prompt, 16,
            spec="checkpoint_every=4;max_checkpoint_lag=32;keeper=k1",
            steps=13, keeper=keeper)
        assert dropped["count"] == 1
        assert checkpointer.counters["checkpoints"] >= 3
        with pytest.raises(KeyError, match="incomplete"):
            keeper.restore("r")
        # and the end-to-end ladder still completes via re-prefill
        survivor = DecodeEngine(params, config, decode_slots=1,
                                kv_block_size=8)
        record = None
        try:
            record = keeper.restore("r")
        except KeyError:
            pass
        report = survivor.restore_request(
            "r", record, prompt_tokens=prompt, max_new_tokens=16)
        done = {c.request_id: c for c in report.completions}
        drain(survivor, done)
        assert survivor.counters["restore_fallbacks"] == 1
        np.testing.assert_array_equal(
            done["r"].tokens, reference(params, config, prompt, 16))

    def test_forget_drops_keeper_state(self, tiny_model):
        params, config = tiny_model
        prompt = np.arange(1, 6, dtype=np.int32)
        engine, checkpointer, keeper, _ = run_with_checkpoints(
            params, config, prompt, 8,
            spec="checkpoint_every=1;keeper=k1", steps=4)
        assert keeper.kept_count() == 1
        checkpointer.forget("r")
        assert keeper.flush()
        assert keeper.kept_count() == 0
        assert keeper.counters["dropped"] == 1


# -- restore: bit-identity, gapless offsets, degraded paths ------------------


class TestRestore:
    @pytest.mark.parametrize("kv_dtype", ("", "int8"))
    def test_bit_identical_f32_and_int8(self, kv_dtype):
        """The tentpole invariant: a mid-decode crash restored from
        the keeper finishes BIT-IDENTICAL to an uncrashed run, for
        both the f32 and int8 (codes + scales) pool layouts."""
        config = TransformerConfig(**{**TINY, "kv_dtype": kv_dtype})
        params = init_params(config, jax.random.PRNGKey(0))
        rng = np.random.default_rng(5)
        prompt = rng.integers(1, 64, size=11).astype(np.int32)
        max_new = 14
        engine, _, keeper, emitted = run_with_checkpoints(
            params, config, prompt, max_new,
            spec="checkpoint_every=2;max_checkpoint_lag=4;keeper=k1",
            steps=7)
        assert 0 < len(emitted) < max_new, "crash must be mid-decode"
        # the crash: abandon the engine, restore on a fresh one
        survivor = DecodeEngine(params, config, decode_slots=1,
                                kv_block_size=8)
        record = keeper.restore("r")
        report = survivor.restore_request("r", record)
        emitted2 = [(offset, token) for _rid, offset, token
                    in report.emitted]
        done = {c.request_id: c for c in report.completions}
        drain(survivor, done, emitted2)
        np.testing.assert_array_equal(
            done["r"].tokens, reference(params, config, prompt,
                                        max_new))
        assert survivor.counters["restores"] == 1
        assert survivor.counters["restore_fallbacks"] == 0
        assert survivor.counters["kv_migrated_bytes"] > 0
        # restored emission covers every offset exactly once
        assert sorted(dict(emitted2)) == list(range(max_new))
        assert survivor.stats()["free_blocks"] == \
            survivor.blocks.capacity

    def test_resume_from_is_gapless_and_counts_replayed(
            self, tiny_model):
        """A client that already holds offsets [0, crash) passes
        resume_from: tokens between the snapshot and the crash
        re-decode SILENTLY (decode.restore_replayed_tokens counts
        them, bounded by max_checkpoint_lag) and emission resumes at
        exactly the crash offset -- no duplicate, no gap."""
        params, config = tiny_model
        prompt = np.arange(1, 8, dtype=np.int32)
        max_new = 12
        # one early snapshot, then decode on without another
        engine, checkpointer, keeper, emitted = run_with_checkpoints(
            params, config, prompt, max_new,
            spec="checkpoint_every=2;max_checkpoint_lag=32;keeper=k1",
            steps=3)
        for _ in range(4):          # post-snapshot progress, unshipped
            report = engine.step()
            emitted.extend((offset, token) for _rid, offset, token
                           in report.emitted)
        crash_count = len(emitted)
        record = keeper.restore("r")
        snapshot_count = len(record["generated"])
        assert snapshot_count < crash_count
        survivor = DecodeEngine(params, config, decode_slots=1,
                                kv_block_size=8)
        report = survivor.restore_request("r", record,
                                          resume_from=crash_count)
        emitted2 = [(offset, token) for _rid, offset, token
                    in report.emitted]
        done = {c.request_id: c for c in report.completions}
        drain(survivor, done, emitted2)
        assert (survivor.counters["restore_replayed_tokens"]
                == crash_count - snapshot_count)
        offsets = sorted(dict(emitted2))
        assert offsets and offsets[0] == crash_count
        combined = dict(emitted)
        combined.update(dict(emitted2))
        assert sorted(combined) == list(range(max_new))
        np.testing.assert_array_equal(
            np.asarray([combined[i] for i in range(max_new)]),
            reference(params, config, prompt, max_new))

    def test_degraded_paths_fall_back_to_reprefill(self, tiny_model):
        """Every failure -- no record, unknown request, stale
        snapshot, block-size mismatch -- degrades to the existing
        replay re-prefill: the request completes bit-identically and
        the granted blocks are returned first."""
        params, config = tiny_model
        prompt = np.arange(1, 10, dtype=np.int32)
        max_new = 6
        expected = reference(params, config, prompt, max_new)

        def restored(engine, record, **kwargs):
            report = engine.restore_request("r", record,
                                            prompt_tokens=prompt,
                                            max_new_tokens=max_new,
                                            **kwargs)
            done = {c.request_id: c for c in report.completions}
            drain(engine, done)
            np.testing.assert_array_equal(done["r"].tokens, expected)

        # 1) no record at all (dead keeper)
        engine = DecodeEngine(params, config, decode_slots=1,
                              kv_block_size=8)
        restored(engine, None)
        assert engine.counters["restore_fallbacks"] == 1

        # 2) stale snapshot: keeper max_age expired
        keeper = CheckpointKeeper("k_stale", max_age_s=0.01)
        _, _, keeper, _ = run_with_checkpoints(
            params, config, prompt, max_new,
            spec="checkpoint_every=1;keeper=k_stale", steps=3,
            keeper=keeper)
        time.sleep(0.05)
        with pytest.raises(KeyError):
            keeper.restore("r")
        assert keeper.counters["expired"] == 1

        # 3) unknown request key
        with pytest.raises(KeyError):
            CheckpointKeeper("k_empty").restore("missing")

        # 4) block-size mismatch (mixed fleet)
        keeper2 = CheckpointKeeper("k2")
        _, _, keeper2, _ = run_with_checkpoints(
            params, config, prompt, max_new,
            spec="checkpoint_every=1;keeper=k2", steps=3,
            keeper=keeper2)
        record = keeper2.restore("r")
        other = DecodeEngine(params, config, decode_slots=1,
                             kv_block_size=16)
        free_before = other.blocks.free_count
        restored(other, record)
        assert other.counters["restore_fallbacks"] == 1
        assert other.counters["restores"] == 0
        assert other.blocks.free_count == free_before

        # 5) expired transfer keys (the keeper's server restarted)
        keeper3 = CheckpointKeeper("k3")
        _, _, keeper3, _ = run_with_checkpoints(
            params, config, prompt, max_new,
            spec="checkpoint_every=1;keeper=k3", steps=3,
            keeper=keeper3)
        record = keeper3.restore("r")
        reset_transfer_server()
        engine3 = DecodeEngine(params, config, decode_slots=1,
                               kv_block_size=8)
        restored(engine3, record, timeout=1)
        assert engine3.counters["restore_fallbacks"] == 1


# -- the AIKO409 grammar ------------------------------------------------------


class TestCheckpointGrammar:
    def test_scopes_parse_and_reject(self):
        engine_side = CheckpointPolicy.parse(
            "checkpoint_every=4;max_checkpoint_lag=8;keeper=k")
        engine_side.validate_engine()
        assert engine_side.checkpoint_every == 4
        gateway_side = CheckpointPolicy.parse(
            "recovery_rate=2.5;keeper=k")
        gateway_side.validate_gateway()
        assert gateway_side.recovery_rate == 2.5
        with pytest.raises(ValueError, match="gateway-side"):
            CheckpointPolicy.parse("recovery_rate=1").validate_engine()
        with pytest.raises(ValueError, match="engine-side"):
            CheckpointPolicy.parse(
                "checkpoint_every=4").validate_gateway()

    def test_lint_parity(self):
        from aiko_services_tpu.analyze.policies import (
            check_checkpoint_policy, check_decode_parameters)
        assert check_checkpoint_policy("recovery_rate=2;keeper=k") == []
        problems = check_checkpoint_policy("recovery_rate=-1")
        assert any(code == "AIKO409" for code, _ in problems)
        problems = check_checkpoint_policy("warp=9")
        assert any(code == "AIKO404" for code, _ in problems)
        problems = check_checkpoint_policy("recovery_rate=1",
                                           element=True)
        assert any(code == "AIKO409" for code, _ in problems)
        # element cross-fields: checkpoint rides the slot engine
        problems = check_decode_parameters(
            {"checkpoint": "checkpoint_every=2"})
        assert any(code == "AIKO409" for code, _ in problems)
        problems = check_decode_parameters(
            {"checkpoint": "checkpoint_every=2", "continuous": True})
        assert problems == []
        problems = check_decode_parameters(
            {"checkpoint": "checkpoint_every=2", "role": "prefill"})
        assert any(code == "AIKO409" for code, _ in problems)

    def test_gateway_construction_matches_lint(self):
        process = Process(transport_kind="loopback")
        with pytest.raises(ValueError, match="AIKO409"):
            Gateway(process, name="bad", checkpoint="recovery_rate=-1")
        with pytest.raises(ValueError, match="AIKO404"):
            Gateway(process, name="bad2", checkpoint="warp=9")
        with pytest.raises(ValueError, match="AIKO409"):
            Gateway(process, name="bad3",
                    checkpoint="checkpoint_every=4")


# -- gateway warm failover ----------------------------------------------------


LM_PARAMS = {"vocab_size": 300, "d_model": 32, "n_layers": 1,
             "n_heads": 2, "n_kv_heads": 1, "d_ff": 64,
             "max_seq_len": 128, "dtype": "float32"}


def lm_definition(name, extra):
    return {
        "name": name,
        "graph": ["(lm)"],
        "elements": [
            {"name": "lm",
             "input": [{"name": "tokens"},
                       {"name": "restore", "optional": True}],
             "output": [{"name": "generated"}],
             "parameters": {**LM_PARAMS, **extra},
             "deploy": {"local": {"module": ELEMENTS,
                                  "class_name": "LMGenerate"}}},
        ],
    }


DECODE_EXTRA = {"continuous": True, "decode_slots": 4,
                "kv_block_size": 8, "max_new_tokens": 24,
                "stream_tokens": True, "stream_chunk": 1,
                "checkpoint": ("checkpoint_every=1;"
                               "max_checkpoint_lag=4;keeper=gwk")}


def closed_batch_reference(frames, max_new):
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, lm_definition(
        "ref", {"max_new_tokens": max_new}))
    process.run(in_thread=True)
    responses = queue.Queue()
    stream = pipeline.create_stream("s", queue_response=responses,
                                    grace_time=300)
    for frame in frames:
        pipeline.create_frame(stream, {"tokens": frame})
    expected = [np.asarray(responses.get(timeout=120)[2]["generated"])
                for _ in frames]
    process.terminate()
    reset_brokers()
    return expected


def _collect_chunks(chunks, payload):
    try:
        command, parameters = parse(payload)
    except ValueError:
        return
    if command != "token_chunk" or len(parameters) < 5:
        return
    stream_id = str(parameters[0])
    row = int(parameters[2])
    offset = int(parameters[3])
    tokens = [int(token) for token in parameters[4][0]]
    chunks.append((stream_id, row, offset, tokens))


class TestGatewayWarmFailover:
    def test_decode_replica_kill_restores_paced_and_bit_identical(
            self):
        """The tentpole end to end: a decode replica dies mid-storm;
        the gateway's paced failover replays every stream with a
        RESTORE hint; the survivor adopts checkpoints instead of
        re-prefilling; completions AND streamed chunk offsets are
        bit-identical/gapless vs an uncrashed run."""
        rng = np.random.default_rng(13)
        streams_n = 4
        max_new = 24
        frames = [rng.integers(1, 300, size=(1, 6)).astype(np.int32)
                  for _ in range(streams_n)]
        expected = closed_batch_reference(frames, max_new)

        keeper = CheckpointKeeper("gwk")
        processes = []

        def make_replica(name):
            process = Process(transport_kind="loopback")
            processes.append(process)
            return process, create_pipeline(
                process, lm_definition(name, DECODE_EXTRA))

        process0, replica0 = make_replica("wf0")
        process1, replica1 = make_replica("wf1")
        gateway_process = Process(transport_kind="loopback")
        processes.append(gateway_process)
        gateway = Gateway(
            gateway_process, policy="max_inflight=16;queue=64",
            checkpoint="recovery_rate=2;keeper=gwk")
        gateway.attach_replica(replica0)
        chunks = []
        for process, replica in ((process0, replica0),
                                 (process1, replica1)):
            process.add_message_handler(
                lambda topic, payload: _collect_chunks(chunks, payload),
                f"{replica.elements['lm'].topic_path}/out")
        for process in processes:
            process.run(in_thread=True)
        try:
            responses = queue.Queue()
            for index, frame in enumerate(frames):
                stream_id = f"s{index}"
                gateway.submit_stream(stream_id, {},
                                      queue_response=responses)
                gateway.submit_frame(stream_id, {"tokens": frame},
                                     frame_id=0)
            # mid-storm: wait until every stream has checkpoints but
            # none has finished, then kill the only serving replica
            wait_for(lambda: keeper.flush(timeout=0.1)
                     and keeper.kept_count() >= streams_n, timeout=60)
            gateway.attach_replica(replica1)
            gateway.post_message("_replica_lost", [
                replica0.topic_path, "decode_replica_kill"])
            got = {}
            deadline = time.monotonic() + 120
            while len(got) < streams_n:
                assert time.monotonic() < deadline
                stream_id, frame_id, outputs, status = responses.get(
                    timeout=120)
                assert status == "ok", (stream_id, outputs)
                got[stream_id] = np.asarray(outputs["generated"])
            for index in range(streams_n):
                np.testing.assert_array_equal(got[f"s{index}"],
                                              expected[index])
            survivor = replica1.elements["lm"].engine_stats()
            assert survivor is not None
            assert survivor["restores"] >= 1, survivor
            avoided = survivor["restores"] / max(
                survivor["restores"] + survivor["restore_fallbacks"], 1)
            assert avoided > 0
            # pacing: with recovery_rate=2 and 4 migrated streams, at
            # least one stream's replay wave was deferred
            assert gateway.telemetry.recovery_paced.value >= 1
            # streamed chunks: offsets assemble gaplessly into the
            # reference sequence; restore re-emissions are idempotent
            # duplicates (same offset, same token), never gaps
            def covered(stream_id):
                seen = set()
                for s, _row, offset, tokens in list(chunks):
                    if s == stream_id:
                        seen.update(range(offset,
                                          offset + len(tokens)))
                return len(seen)

            wait_for(lambda: all(covered(f"s{i}") >= max_new
                                 for i in range(streams_n)),
                     timeout=30)
            for index in range(streams_n):
                assembled = {}
                for stream_id, row, offset, tokens in chunks:
                    if stream_id != f"s{index}":
                        continue
                    for j, token in enumerate(tokens):
                        previous = assembled.get(offset + j)
                        assert previous in (None, token), (
                            f"offset {offset + j} re-emitted a "
                            f"DIFFERENT token")
                        assembled[offset + j] = token
                assert sorted(assembled) == list(range(max_new))
                np.testing.assert_array_equal(
                    np.asarray([assembled[i] for i in range(max_new)]),
                    expected[index][0])
            # the survivor's telemetry surfaces the restore ledger
            summary = replica1.telemetry.decode_summary()
            assert summary["restores"] == survivor["restores"]
        finally:
            for process in processes:
                process.terminate()

    def test_element_resume_from_publishes_floor_offsets(self):
        """A replaying client that already holds offsets [0, crash)
        passes resume_from through the restore hint: the restored
        element's `(token_chunk …)` offsets must START at the floor --
        publishing them from 0 would make an offset-keyed consumer
        overwrite its held prefix with later tokens."""
        rng = np.random.default_rng(21)
        frame = rng.integers(1, 300, size=(1, 6)).astype(np.int32)
        max_new = 24
        [expected] = closed_batch_reference([frame], max_new)
        keeper = CheckpointKeeper("ek")
        extra = {"continuous": True, "decode_slots": 2,
                 "kv_block_size": 8, "max_new_tokens": max_new,
                 "stream_tokens": True, "stream_chunk": 1,
                 "checkpoint": ("checkpoint_every=1;"
                                "max_checkpoint_lag=4;keeper=ek")}
        chunks_a, chunks_b = [], []
        process_a = Process(transport_kind="loopback")
        replica_a = create_pipeline(process_a, lm_definition(
            "ra", extra))
        process_a.add_message_handler(
            lambda t, p: _collect_chunks(chunks_a, p),
            f"{replica_a.elements['lm'].topic_path}/out")
        process_a.run(in_thread=True)
        replica_a.create_stream("s", grace_time=300,
                                queue_response=queue.Queue())
        stream_a = replica_a.streams["s"]
        replica_a.create_frame(stream_a, {"tokens": frame})
        wait_for(lambda: keeper.flush(timeout=0.1)
                 and keeper.kept_count() >= 1
                 and len(chunks_a) >= 4, timeout=60)
        process_a.terminate()   # the crash: mid-decode, chunks held
        held = {}
        for _sid, _row, offset, tokens in chunks_a:
            for j, token in enumerate(tokens):
                held[offset + j] = token
        crash = 0
        while crash in held:
            crash += 1
        assert 0 < crash < max_new, "crash must be mid-stream"
        reset_brokers()

        process_b = Process(transport_kind="loopback")
        replica_b = create_pipeline(process_b, lm_definition(
            "rb", extra))
        process_b.add_message_handler(
            lambda t, p: _collect_chunks(chunks_b, p),
            f"{replica_b.elements['lm'].topic_path}/out")
        process_b.run(in_thread=True)
        try:
            responses = queue.Queue()
            replica_b.create_stream("s", grace_time=300,
                                    queue_response=responses)
            replica_b.create_frame(replica_b.streams["s"], {
                "tokens": frame,
                "restore": {"keeper": "ek",
                            "resume_from": {0: crash}}})
            _, _frame, outputs = responses.get(timeout=120)
            np.testing.assert_array_equal(
                np.asarray(outputs["generated"]), expected)
            stats = replica_b.elements["lm"].engine_stats()
            assert stats["restores"] == 1, stats
            wait_for(lambda: sum(len(t) for _s, _r, _o, t in chunks_b)
                     >= max_new - crash, timeout=30)
            offsets = sorted({offset + j
                              for _s, _r, offset, tokens in chunks_b
                              for j in range(len(tokens))})
            assert offsets[0] == crash, (
                f"restored chunks start at {offsets[0]}, the client "
                f"already holds [0, {crash})")
            assert offsets == list(range(crash, max_new))
            resumed = dict(held)
            for _sid, _row, offset, tokens in chunks_b:
                for j, token in enumerate(tokens):
                    resumed[offset + j] = token
            np.testing.assert_array_equal(
                np.asarray([resumed[i] for i in range(max_new)]),
                expected[0])
        finally:
            process_b.terminate()

    def test_journal_replay_dedupe_of_streamed_frames(self, tmp_path):
        """Continuous-mode analogue of the round-13 exactly-once test:
        after a gateway restart adopts the journal, a client's replay
        of an already-delivered frame is absorbed against the journaled
        delivered_floor -- the engine never re-admits it, and no
        duplicate completion reaches the client."""
        db_path = tmp_path / "gw.db"
        rng = np.random.default_rng(3)
        frame = rng.integers(1, 300, size=(1, 6)).astype(np.int32)
        process_r = Process(transport_kind="loopback")
        replica = create_pipeline(process_r, lm_definition(
            "jr0", {"continuous": True, "decode_slots": 2,
                    "kv_block_size": 8, "max_new_tokens": 8,
                    "stream_tokens": True, "stream_chunk": 1}))
        process_a = Process(transport_kind="loopback")
        gateway_a = Gateway(process_a, name="gwa",
                            policy="max_inflight=8;queue=16",
                            journal=f"path={db_path};interval=0")
        gateway_a.attach_replica(replica)
        for process in (process_r, process_a):
            process.run(in_thread=True)
        try:
            responses = queue.Queue()
            gateway_a.submit_stream("s", {}, queue_response=responses,
                                    grace_time=300)
            gateway_a.submit_frame("s", {"tokens": frame}, frame_id=0)
            _, frame_id, outputs, status = responses.get(timeout=120)
            assert status == "ok" and frame_id == 0
            gateway_a.journal_flush()
            engine_before = replica.elements["lm"].engine_stats()
            # the crash: a NEW gateway adopts the same journal
            process_b = Process(transport_kind="loopback")
            gateway_b = Gateway(process_b, name="gwb",
                                policy="max_inflight=8;queue=16",
                                journal=f"path={db_path};interval=0")
            gateway_b.attach_replica(replica)
            process_b.run(in_thread=True)
            wait_for(lambda: gateway_b.recover_now() or
                     "s" in gateway_b.streams, timeout=30)
            stream = gateway_b.streams["s"]
            assert stream.delivered_floor == 0, (
                "the journaled floor must survive the restart")
            replays = queue.Queue()
            stream.queue_response = replays
            # client replays its un-acked frame 0: absorbed exactly-once
            duplicates_before = gateway_b.telemetry.duplicates.value
            gateway_b.submit_frame("s", {"tokens": frame}, frame_id=0)
            wait_for(lambda: gateway_b.telemetry.duplicates.value
                     > duplicates_before, timeout=30)
            assert (replica.elements["lm"].engine_stats()["admitted"]
                    == engine_before["admitted"]), (
                "the replayed frame must not re-admit into the engine")
            assert replays.empty()
            # and the stream keeps serving: the NEXT frame decodes
            gateway_b.submit_frame("s", {"tokens": frame}, frame_id=1)
            _, frame_id, outputs, status = replays.get(timeout=120)
            assert status == "ok" and frame_id == 1
            gateway_b.stop()
            process_b.terminate()
        finally:
            gateway_a.stop()
            for process in (process_r, process_a):
                process.terminate()


# -- satellite: transfer_stall bounds a slow keeper ---------------------------


class TestTransferStall:
    def test_adopt_timeout_bounds_a_stalled_producer(
            self, monkeypatch, tiny_model):
        """A keeper/producer that accepts but answers after a long
        stall must not wedge the engine pump: the adopt_timeout cuts
        each attempt, the retry budget expires quickly, and the
        request degrades to a local re-prefill."""
        params, config = tiny_model
        prompt = np.arange(1, 10, dtype=np.int32)
        prefill = PrefillEngine(params, config, kv_block_size=8)
        prefill.submit("r", prompt, 5)
        [handoff] = prefill.step()
        monkeypatch.setenv("AIKO_FAULTS",
                           "transfer_stall:ms=5000:times=-1")
        monkeypatch.setenv("AIKO_TRANSFER_RETRY_MS", "1")
        faults_module.reset_injector()
        engine = DecodeEngine(params, config, decode_slots=1,
                              kv_block_size=8)
        started = time.perf_counter()
        report = engine.adopt_request("r", handoff, timeout=0.3)
        elapsed = time.perf_counter() - started
        assert elapsed < 4.0, (
            f"a 5 s stall held the adopt for {elapsed:.1f} s")
        assert engine.counters["adopt_fallbacks"] == 1
        done = {c.request_id: c for c in report.completions}
        drain(engine, done)
        np.testing.assert_array_equal(
            done["r"].tokens, reference(params, config, prompt, 5))

    def test_transient_stall_survives_on_retry(self, monkeypatch,
                                               tiny_model):
        """times=1: only the first connection stalls; the retry lands
        and the adoption still goes through warm."""
        params, config = tiny_model
        prompt = np.arange(1, 8, dtype=np.int32)
        prefill = PrefillEngine(params, config, kv_block_size=8)
        prefill.submit("r", prompt, 4)
        [handoff] = prefill.step()
        monkeypatch.setenv("AIKO_FAULTS",
                           "transfer_stall:ms=5000:times=1")
        monkeypatch.setenv("AIKO_TRANSFER_RETRY_MS", "1")
        faults_module.reset_injector()
        engine = DecodeEngine(params, config, decode_slots=1,
                              kv_block_size=8)
        report = engine.adopt_request("r", handoff, timeout=0.3)
        assert engine.counters["adopted"] == 1
        assert engine.counters["adopt_fallbacks"] == 0
        done = {c.request_id: c for c in report.completions}
        drain(engine, done)
        np.testing.assert_array_equal(
            done["r"].tokens, reference(params, config, prompt, 4))


# -- satellite: per-peer transfer circuit breaker -----------------------------


class TestCircuitBreaker:
    DEAD = {"host": "127.0.0.1", "port": 1, "key": "a" * 32,
            "dtype": "float32", "shape": [2]}

    def test_trips_fast_fails_and_heals(self, monkeypatch):
        monkeypatch.setenv("AIKO_TRANSFER_CIRCUIT_MS", "400")
        monkeypatch.setenv("AIKO_TRANSFER_RETRY_MS", "5")
        registry = get_registry()
        opens_before = registry.counter(
            "transfer.peer_open_circuits").value
        with pytest.raises(TransferError):
            fetch_many([dict(self.DEAD)], timeout=0.2)
        assert (registry.counter("transfer.peer_open_circuits").value
                == opens_before + 1)
        # the circuit is open: the next call fails FAST -- no retry
        # budget burned on the event loop
        started = time.perf_counter()
        with pytest.raises(TransferError, match="circuit open"):
            fetch_many([dict(self.DEAD)], timeout=5)
        assert time.perf_counter() - started < 0.05
        started = time.perf_counter()
        with pytest.raises(TransferError, match="circuit open"):
            from aiko_services_tpu.pipeline.transfer import fetch
            fetch(dict(self.DEAD), timeout=5)
        assert time.perf_counter() - started < 0.05
        # after the window the peer gets real attempts again
        time.sleep(0.45)
        errors_before = registry.counter("transfer.fetch_errors").value
        with pytest.raises(TransferError):
            fetch_many([dict(self.DEAD)], timeout=0.2)
        assert (registry.counter("transfer.fetch_errors").value
                > errors_before)

    def test_success_closes_an_open_circuit(self, monkeypatch):
        from aiko_services_tpu.pipeline import transfer
        monkeypatch.setenv("AIKO_TRANSFER_CIRCUIT_MS", "200")
        server = get_transfer_server()
        array = np.ones((8, 8), np.float32)
        descriptor = server.offer(array)
        address = (descriptor["host"], int(descriptor["port"]))
        transfer._trip_circuit(address)
        with pytest.raises(TransferError, match="circuit open"):
            fetch_many([descriptor])
        time.sleep(0.25)
        [fetched] = fetch_many([descriptor])
        np.testing.assert_array_equal(fetched, array)
        assert not transfer._circuit_open(address)

    def test_disabled_by_env(self, monkeypatch):
        monkeypatch.setenv("AIKO_TRANSFER_CIRCUIT_MS", "0")
        monkeypatch.setenv("AIKO_TRANSFER_RETRY_MS", "1")
        with pytest.raises(TransferError):
            fetch_many([dict(self.DEAD)], timeout=0.2)
        # no circuit was opened: the second call retries for real
        registry = get_registry()
        errors_before = registry.counter("transfer.fetch_errors").value
        with pytest.raises(TransferError):
            fetch_many([dict(self.DEAD)], timeout=0.2)
        assert (registry.counter("transfer.fetch_errors").value
                > errors_before)


# -- tune: the checkpoint-bound floor -----------------------------------------


class TestCheckpointBoundFloor:
    def _cost(self, checkpoint_ms, compute_ms=2.0, queue_ms=0.5):
        from aiko_services_tpu.tune.model import (
            CostModel, ElementCost, classify_elements)
        cost = ElementCost(name="lm", calls=50)
        cost.compute_median_s = compute_ms / 1e3
        cost.per_call_median_s = compute_ms / 1e3
        cost.queue_median_s = queue_ms / 1e3
        cost.engine = {
            "queue_median_s": queue_ms / 1e3,
            "prefill_median_s": 0.001, "decode_median_s": 0.002,
            "adopt_median_s": 0.0, "adoptions": 0,
            "checkpoint_median_s": checkpoint_ms / 1e3,
            "checkpoints": 20, "preemptions": 0, "tokens": 400,
            "requests": 20,
        }
        model = CostModel(elements={"lm": cost})
        classify_elements(model)
        return cost

    def test_classifies_checkpoint_bound_with_evidence(self):
        cost = self._cost(checkpoint_ms=25.0)
        assert cost.floor == "checkpoint-bound"
        assert cost.evidence["engine"]["checkpoint_median_s"] > 0
        # a cheap cadence stays compute-bound
        assert self._cost(checkpoint_ms=0.1).floor == "compute-bound"

    def test_recommender_stretches_the_cadence(self):
        from aiko_services_tpu.tune.recommend import (
            _engine_recommendations)
        cost = self._cost(checkpoint_ms=25.0)
        parameters = {"checkpoint":
                      "checkpoint_every=4;max_checkpoint_lag=8",
                      "decode_slots": 4}
        [recommendation] = _engine_recommendations(
            "lm", cost, parameters, None)
        assert recommendation.knob == "checkpoint"
        assert "checkpoint_every=8" in str(recommendation.proposed)
        assert recommendation.floor == "checkpoint-bound"

    def test_span_global_renders_a_duration_event(self):
        from aiko_services_tpu.observe.trace import Tracer
        tracer = Tracer()
        tracer.span_global("checkpoint:lm", "engine", 0.02,
                           {"bytes": 4096})
        events = tracer.chrome_events()
        [span] = [event for event in events
                  if event.get("name") == "checkpoint:lm"]
        assert span["ph"] == "X" and span["cat"] == "engine"
        assert span["dur"] == pytest.approx(20000.0, rel=0.5)
