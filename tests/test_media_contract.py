# Fake-backend contract tests for the hard-gated media elements
# (gstreamer_io.py, webcam_io.py).  TPU pods ship neither PyGObject nor
# a camera, so these suites inject STUB backends into sys.modules and
# pin the element contracts that real deployments rely on: the frame
# schema ((3, H, W) float32 RGB in [0, 1]), the gating diagnostics when
# the backend is absent, error-policy behavior on bad ticks, and
# backend resource cleanup at stream stop.

import queue
import types

import numpy as np
import pytest

from aiko_services_tpu.pipeline import create_pipeline
from aiko_services_tpu.runtime import Process
from aiko_services_tpu.transport import reset_brokers

from helpers import wait_for

ELEMENTS = "aiko_services_tpu.elements"


@pytest.fixture(autouse=True)
def clean_brokers():
    reset_brokers()
    yield
    reset_brokers()


def local(class_name):
    return {"local": {"module": ELEMENTS, "class_name": class_name}}


def run_source(definition, count, timeout=60, destroy_after=None):
    """Drive a one-source pipeline; returns (responses list, pipeline,
    process) with the process still running (caller terminates)."""
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, definition)
    process.run(in_thread=True)
    responses = queue.Queue()
    pipeline.create_stream("s", queue_response=responses, grace_time=60)
    results = [responses.get(timeout=timeout) for _ in range(count)]
    if destroy_after:
        pipeline.destroy_stream("s")
    return results, pipeline, process


# -- fake GStreamer backend --------------------------------------------------

class FakeMapped:
    def __init__(self, data):
        self.data = data


class FakeGstBuffer:
    def __init__(self, data, map_ok=True):
        self._data = data
        self._map_ok = map_ok
        self.unmapped = False
        self.pts = None
        self.duration = None

    def map(self, _flags):
        if not self._map_ok:
            return False, None
        return True, FakeMapped(self._data)

    def unmap(self, _mapped):
        self.unmapped = True


class FakeCaps:
    def __init__(self, width, height):
        self._values = {"width": width, "height": height}

    def get_structure(self, _index):
        return self

    def get_value(self, key):
        return self._values[key]


class FakeSample:
    def __init__(self, array, map_ok=True):
        height, width = array.shape[:2]
        self.buffer = FakeGstBuffer(array.tobytes(), map_ok=map_ok)
        self.caps = FakeCaps(width, height)

    def get_buffer(self):
        return self.buffer

    def get_caps(self):
        return self.caps


class FakeGstElement:
    """appsink / appsrc stand-in: pull-sample pops the scripted sample
    list; push-buffer / end-of-stream record what the writer sent."""

    def __init__(self, samples=None):
        self.samples = list(samples or [])
        self.pushed = []
        self.eos = False

    def emit(self, signal, *arguments):
        if signal == "pull-sample":
            return self.samples.pop(0) if self.samples else None
        if signal == "push-buffer":
            self.pushed.append(arguments[0])
            return None
        if signal == "end-of-stream":
            self.eos = True
            return None
        raise AssertionError(f"unexpected Gst signal {signal!r}")


class FakeGstPipeline:
    def __init__(self, description, element):
        self.description = description
        self.element = element
        self.states = []

    def get_by_name(self, _name):
        return self.element

    def set_state(self, state):
        self.states.append(state)


def make_fake_gst(samples=None):
    """A stub `gi`/`gi.repository.Gst` pair implementing exactly the
    surface gstreamer_io.py touches."""
    gst = types.SimpleNamespace()
    gst.launched = []
    element = FakeGstElement(samples)

    class State:
        PLAYING = "PLAYING"
        NULL = "NULL"

    class MapFlags:
        READ = "READ"

    class Buffer:
        @staticmethod
        def new_wrapped(data):
            return FakeGstBuffer(data)

    def parse_launch(description):
        fake = FakeGstPipeline(description, element)
        gst.launched.append(fake)
        return fake

    gst.init = lambda _argv: None
    gst.parse_launch = parse_launch
    gst.State = State
    gst.MapFlags = MapFlags
    gst.Buffer = Buffer
    gst.SECOND = 10 ** 9
    gst.element = element

    gi = types.ModuleType("gi")
    gi.require_version = lambda _name, _version: None
    repository = types.ModuleType("gi.repository")
    repository.Gst = gst
    gi.repository = repository
    return gi, repository, gst


@pytest.fixture
def fake_gst(monkeypatch):
    def install(samples=None):
        gi, repository, gst = make_fake_gst(samples)
        monkeypatch.setitem(__import__("sys").modules, "gi", gi)
        monkeypatch.setitem(__import__("sys").modules, "gi.repository",
                            repository)
        return gst
    return install


class TestVideoStreamReaderContract:
    def _definition(self, parameters=None):
        return {
            "name": "gst_read", "graph": ["(reader)"],
            "elements": [
                {"name": "reader", "output": [{"name": "image"}],
                 "parameters": {"data_sources": ["rtsp://fake/stream"],
                                **(parameters or {})},
                 "deploy": local("VideoStreamReader")}]}

    def test_frame_schema_and_url_wiring(self, fake_gst):
        rgb = (np.arange(2 * 3 * 3) % 255).astype(np.uint8).reshape(
            2, 3, 3)
        gst = fake_gst(samples=[FakeSample(rgb), FakeSample(rgb)])
        results, _, process = run_source(self._definition(), count=2)
        for _, _, outputs in results:
            image = outputs["image"]
            # the contract: (3, H, W) float32 RGB in [0, 1]
            assert image.shape == (3, 2, 3)
            assert image.dtype == np.float32
            np.testing.assert_allclose(
                image, rgb.astype(np.float32).transpose(2, 0, 1) / 255.0)
        # the appsink url reached the launch description
        assert "uri=rtsp://fake/stream" in gst.launched[0].description
        assert gst.launched[0].states[0] == "PLAYING"
        # every mapped buffer was unmapped (no leaked Gst buffers)
        assert all(sample.buffer.unmapped
                   for sample in gst.element.samples or [])
        process.terminate()

    def test_stream_end_stops_and_nulls_pipeline(self, fake_gst):
        rgb = np.zeros((2, 2, 3), np.uint8)
        gst = fake_gst(samples=[FakeSample(rgb)])  # then None -> STOP
        results, pipeline, process = run_source(self._definition(),
                                                count=1)
        assert results[0][2]["image"].shape == (3, 2, 2)
        # pull-sample returning None ends the stream; stop_stream must
        # drop the Gst pipeline to State.NULL
        wait_for(lambda: "NULL" in gst.launched[0].states, timeout=30)
        wait_for(lambda: not pipeline.streams, timeout=30)
        process.terminate()

    def test_bad_tick_with_drop_frame_keeps_stream(self, fake_gst):
        """A buffer whose map() fails is ONE bad tick: under `on_error:
        drop_frame` the reader drops it and keeps serving (PR-3
        generator contract), instead of destroying the stream."""
        rgb = np.full((2, 2, 3), 7, np.uint8)
        fake_gst(samples=[FakeSample(rgb), FakeSample(rgb, map_ok=False),
                          FakeSample(rgb)])
        results, _, process = run_source(
            self._definition({"on_error": "drop_frame"}), count=2)
        assert len(results) == 2  # 3 ticks, 1 dropped, stream alive
        for _, _, outputs in results:
            assert outputs["image"].shape == (3, 2, 2)
        process.terminate()

    def test_missing_backend_is_a_clear_error(self, monkeypatch):
        import sys
        monkeypatch.setitem(sys.modules, "gi", None)  # import -> error
        from aiko_services_tpu.elements.gstreamer_io import gst_available
        assert not gst_available()
        process = Process(transport_kind="loopback")
        pipeline = create_pipeline(process, self._definition())
        process.run(in_thread=True)
        pipeline.create_stream("s", grace_time=30)
        # start_stream ERRORs with the gating diagnostic: no stream
        wait_for(lambda: not pipeline.streams, timeout=30)
        process.terminate()


class TestVideoStreamWriterContract:
    def _definition(self):
        return {
            "name": "gst_write", "graph": ["(camera (writer))"],
            "elements": [
                {"name": "camera", "output": [{"name": "image"}],
                 "parameters": {"data_sources": [[3, 4, 4], [3, 4, 4]]},
                 "deploy": local("ImageSource")},
                {"name": "writer", "input": [{"name": "image"}],
                 "output": [{"name": "image"}],
                 "parameters": {"stream_url": "rtmp://fake/out",
                                "frame_rate": 5},
                 "deploy": local("VideoStreamWriter")}]}

    def test_pushes_uint8_buffers_with_timestamps(self, fake_gst):
        gst = fake_gst()
        results, pipeline, process = run_source(self._definition(),
                                                count=2)
        assert len(gst.launched) == 1
        launch = gst.launched[0]
        assert "location=rtmp://fake/out" in launch.description
        assert "width=4,height=4" in launch.description
        assert len(gst.element.pushed) == 2
        for index, buffer in enumerate(gst.element.pushed):
            assert len(buffer._data) == 4 * 4 * 3  # HWC uint8 bytes
            assert buffer.pts == index * gst.SECOND // 5
            assert buffer.duration == gst.SECOND // 5
        # the writer passes the image through for downstream consumers
        for _, _, outputs in results:
            assert np.asarray(outputs["image"]).shape[-2:] == (4, 4)
        pipeline.destroy_stream("s")
        wait_for(lambda: gst.element.eos, timeout=30)
        assert "NULL" in launch.states
        process.terminate()

    def test_missing_backend_is_a_clear_error(self, monkeypatch):
        import sys
        monkeypatch.setitem(sys.modules, "gi", None)
        process = Process(transport_kind="loopback")
        pipeline = create_pipeline(process, self._definition())
        process.run(in_thread=True)
        pipeline.create_stream("s", grace_time=30)
        wait_for(lambda: not pipeline.streams, timeout=30)
        process.terminate()


# -- fake cv2 backend --------------------------------------------------------

class FakeCapture:
    def __init__(self, device, frames, opened=True):
        self.device = device
        self.frames = list(frames)
        self.opened = opened
        self.released = False

    def isOpened(self):
        return self.opened

    def read(self):
        if not self.frames:
            return False, None
        return True, self.frames.pop(0)

    def release(self):
        self.released = True


def make_fake_cv2(frames, opened=True):
    cv2 = types.ModuleType("cv2")
    cv2.captures = []

    def video_capture(device):
        capture = FakeCapture(device, frames, opened=opened)
        cv2.captures.append(capture)
        return capture

    cv2.VideoCapture = video_capture
    return cv2


class TestVideoReadWebcamContract:
    def _definition(self, parameters=None):
        return {
            "name": "webcam", "graph": ["(camera)"],
            "elements": [
                {"name": "camera", "output": [{"name": "image"}],
                 "parameters": {"data_sources": [0],
                                **(parameters or {})},
                 "deploy": local("VideoReadWebcam")}]}

    def test_frame_schema_bgr_to_rgb(self, monkeypatch):
        import sys
        # BGR frame with distinct channels proves the reversal
        bgr = np.zeros((2, 3, 3), np.uint8)
        bgr[:, :, 0] = 255  # blue plane (cv2 order)
        cv2 = make_fake_cv2([bgr.copy(), bgr.copy()])
        monkeypatch.setitem(sys.modules, "cv2", cv2)
        results, _, process = run_source(self._definition(), count=2)
        for _, _, outputs in results:
            image = outputs["image"]
            assert image.shape == (3, 2, 3)
            assert image.dtype == np.float32
            assert (image[2] == 1.0).all()  # blue landed in RGB slot 2
            assert (image[:2] == 0.0).all()
        process.terminate()

    def test_device_string_coerced_and_released_on_end(self, monkeypatch):
        import sys
        frame = np.ones((2, 2, 3), np.uint8)
        cv2 = make_fake_cv2([frame])
        monkeypatch.setitem(sys.modules, "cv2", cv2)
        results, pipeline, process = run_source(
            self._definition({"data_sources": ["7"]}), count=1)
        assert cv2.captures[0].device == 7  # "7" -> int index
        # read() exhaustion STOPs the stream and releases the device
        wait_for(lambda: cv2.captures[0].released, timeout=30)
        wait_for(lambda: not pipeline.streams, timeout=30)
        process.terminate()

    def test_unopenable_device_is_a_clear_error(self, monkeypatch):
        import sys
        cv2 = make_fake_cv2([], opened=False)
        monkeypatch.setitem(sys.modules, "cv2", cv2)
        process = Process(transport_kind="loopback")
        pipeline = create_pipeline(process, self._definition())
        process.run(in_thread=True)
        pipeline.create_stream("s", grace_time=30)
        wait_for(lambda: not pipeline.streams, timeout=30)
        process.terminate()

    def test_missing_cv2_is_a_clear_error(self, monkeypatch):
        import sys
        monkeypatch.setitem(sys.modules, "cv2", None)
        process = Process(transport_kind="loopback")
        pipeline = create_pipeline(process, self._definition())
        process.run(in_thread=True)
        pipeline.create_stream("s", grace_time=30)
        wait_for(lambda: not pipeline.streams, timeout=30)
        process.terminate()
