# tune/ subsystem tests (ISSUE 10): loader joins trace <-> static
# graph for every element, the floor classifier on synthetic traces
# with KNOWN floors, recommender monotonicity (a tighter SLO never
# raises micro_batch), --apply round-trip through lint, what-if replay
# determinism, and graceful failure on a metadata-absent trace.

import json
from pathlib import Path

import pytest

from aiko_services_tpu.analyze import analyze_definition
from aiko_services_tpu.observe.trace import (
    chrome_trace_document, definition_fingerprint, trace_metadata)
from aiko_services_tpu.tune import (
    CostModel, Recommendation, SloSpec, apply_recommendations,
    check_tune_spec, classify_elements, load_trace, predict,
    recommend, report_json, run_tune)
from aiko_services_tpu.analyze.grammar import GrammarError

ASSETS = Path(__file__).parent / "assets"
FIXTURE = ASSETS / "traces" / "config5_smoke.json"
CASE_STUDIES = (ASSETS / "traces" / "longcontext_16k.json",
                ASSETS / "traces" / "train_step.json")
REPORTS = Path(__file__).parent.parent / "reports"


# -- synthetic trace builder -------------------------------------------------

def _definition(element_names):
    elements = []
    previous = None
    for name in element_names:
        record = {"name": name,
                  "output": [{"name": f"out_{name}", "type": "any"}],
                  "deploy": {"local": {
                      "module": "aiko_services_tpu.elements",
                      "class_name": "TextSource"}}}
        if previous is not None:
            record["input"] = [{"name": f"out_{previous}",
                                "type": "any"}]
        elements.append(record)
        previous = name
    graph = ""
    for name in reversed(element_names):
        graph = f"({name} {graph})" if graph else f"({name})"
    return {"name": "synthetic", "graph": [graph],
            "elements": elements}


def _make_trace(tmp_path, specs, frames=10, definition=None,
                metadata=True, config=None):
    """specs: {element: {compute_ms, queue_ms, group, compiles,
    path}} -> a trace file with `frames` spans per element."""
    definition = definition or _definition(sorted(specs))
    events = []
    ts = 0.0
    for frame_id in range(frames):
        frame_start = ts
        trace_id = f"1-{frame_id + 1:x}"
        for name in sorted(specs):
            spec = specs[name]
            queue_ms = spec.get("queue_ms", 0.0)
            if queue_ms:
                events.append({
                    "ph": "X", "name": f"queue:{name}",
                    "cat": "queue", "ts": round(ts, 3),
                    "dur": round(queue_ms * 1000, 3),
                    "pid": 1, "tid": 1,
                    "args": {"trace_id": trace_id}})
                ts += queue_ms * 1000
            compiles = spec.get("compiles", 0)
            if frame_id < compiles:
                events.append({
                    "ph": "i", "name": f"compile:{name}",
                    "cat": "compile", "ts": round(ts, 3), "pid": 1,
                    "tid": 0, "s": "t", "args": {}})
            duration = spec["compute_ms"] * 1000
            events.append({
                "ph": "X", "name": name, "cat": "element",
                "ts": round(ts, 3), "dur": round(duration, 3),
                "pid": 1, "tid": 1,
                "args": {"trace_id": trace_id, "frame_id": frame_id,
                         "path": spec.get("path", "inline"),
                         "group": spec.get("group", 1)}})
            ts += duration
        events.append({
            "ph": "X", "name": f"frame {frame_id}", "cat": "frame",
            "ts": round(frame_start, 3),
            "dur": round(ts - frame_start, 3), "pid": 1, "tid": 1,
            "args": {"trace_id": trace_id, "status": "ok",
                     "stream": "s"}})
        ts += 50.0
    document = chrome_trace_document(
        events,
        metadata=(trace_metadata(definition_document=definition,
                                 config=config)
                  if metadata else None))
    path = tmp_path / "trace.json"
    path.write_text(json.dumps(document))
    return str(path)


# -- loader / join -----------------------------------------------------------

class TestLoader:
    def test_fixture_joins_every_config5_element(self):
        loaded = load_trace(str(FIXTURE))
        assert loaded.definition is not None
        declared = {element.name
                    for element in loaded.definition.elements}
        assert declared == {"sources", "asr", "text", "lm", "reply",
                            "detector"}
        # the join covers every element with real spans -- no
        # "produced no spans" or "not an element" diagnostics
        assert declared == set(loaded.elements)
        for profile in loaded.elements.values():
            assert profile.calls > 0
        assert not [d for d in loaded.diagnostics
                    if d.code == "AIKO503"]
        assert loaded.fingerprint == definition_fingerprint(
            loaded.definition_document)
        assert loaded.config_name == "pipeline_multimodal"
        assert loaded.frame_count > 0 and loaded.wall_s > 0
        # the embedded metrics snapshot rode along
        assert "histograms" in loaded.metrics

    def test_span_for_undeclared_node_is_diagnosed(self, tmp_path):
        path = _make_trace(
            tmp_path, {"known": {"compute_ms": 1.0},
                       "ghost": {"compute_ms": 1.0}},
            definition=_definition(["known"]))
        loaded = load_trace(path)
        messages = [d.message for d in loaded.diagnostics
                    if d.code == "AIKO503"]
        assert any("ghost" in message for message in messages)

    def test_declared_but_unobserved_element_kept(self, tmp_path):
        path = _make_trace(
            tmp_path, {"a": {"compute_ms": 1.0}},
            definition=_definition(["a", "silent"]))
        loaded = load_trace(path)
        assert loaded.elements["silent"].calls == 0
        model = CostModel.from_trace(loaded)
        classify_elements(model)
        assert model.elements["silent"].floor == "unobserved"

    def test_metadata_absent_trace_diagnosed_and_joinable_via_side_channel(
            self, tmp_path):
        path = _make_trace(tmp_path, {"a": {"compute_ms": 1.0}},
                           metadata=False)
        loaded = load_trace(path)
        assert loaded.definition is None
        assert any("no aiko metadata" in d.message
                   for d in loaded.diagnostics)
        # the side channel still joins it
        loaded = load_trace(path, definition=_definition(["a"]))
        assert loaded.definition is not None
        assert loaded.elements["a"].calls == 10

    def test_combined_trace_run_selection_filters_by_pid(self,
                                                         tmp_path):
        """A combined multi-run artifact must ingest ONLY the
        selected run's spans: another config's same-named node would
        otherwise corrupt the medians."""
        def span(pid, dur_ms, frame_id):
            return [
                {"ph": "X", "name": "lm", "cat": "element",
                 "ts": 0.0, "dur": dur_ms * 1000, "pid": pid,
                 "tid": 1, "args": {"path": "inline", "group": 1,
                                    "frame_id": frame_id}},
                {"ph": "X", "name": f"frame {frame_id}",
                 "cat": "frame", "ts": 0.0, "dur": dur_ms * 1000,
                 "pid": pid, "tid": 1,
                 "args": {"status": "ok", "stream": "s"}},
            ]
        definition = _definition(["lm"])
        events = (span(1, 10.0, 0) + span(1, 10.0, 1)
                  + span(2, 1000.0, 0))
        document = chrome_trace_document(events, metadata={
            "schema": 1,
            "runs": {
                "fast": dict(trace_metadata(
                    definition_document=definition), pids=[1]),
                "slow": dict(trace_metadata(
                    definition_document=definition), pids=[2]),
            }})
        path = tmp_path / "combined.json"
        path.write_text(json.dumps(document))
        fast = load_trace(str(path), run="fast")
        assert fast.elements["lm"].compute_s == [0.01, 0.01]
        assert len(fast.frame_durations_s) == 2
        slow = load_trace(str(path), run="slow")
        assert slow.elements["lm"].compute_s == [1.0]

    def test_not_a_trace_raises(self, tmp_path):
        from aiko_services_tpu.tune import TraceLoadError
        path = tmp_path / "nope.json"
        path.write_text("{\"hello\": 1}")
        with pytest.raises(TraceLoadError):
            load_trace(str(path))
        path.write_text("not json")
        with pytest.raises(TraceLoadError):
            load_trace(str(path))


# -- floor classifier on known floors ----------------------------------------

class TestClassifier:
    def _classify(self, tmp_path, specs, config=None):
        loaded = load_trace(_make_trace(tmp_path, specs,
                                        config=config))
        model = CostModel.from_trace(loaded)
        classify_elements(model)
        return model

    def test_dispatch_bound(self, tmp_path):
        model = self._classify(
            tmp_path, {"fast": {"compute_ms": 0.3, "group": 1}})
        assert model.elements["fast"].floor == "dispatch-bound"
        evidence = model.elements["fast"].evidence
        assert evidence["per_call_median_ms"] <= \
            evidence["dispatch_floor_ms"]

    def test_compute_bound(self, tmp_path):
        model = self._classify(
            tmp_path, {"heavy": {"compute_ms": 50.0,
                                 "queue_ms": 1.0}})
        assert model.elements["heavy"].floor == "compute-bound"

    def test_queue_bound(self, tmp_path):
        model = self._classify(
            tmp_path, {"starved": {"compute_ms": 2.0,
                                   "queue_ms": 30.0}})
        assert model.elements["starved"].floor == "queue-bound"
        assert model.elements["starved"].evidence[
            "queue_median_ms"] > 2.0

    def test_compile_bound(self, tmp_path):
        # a compile event on EVERY call: hopeless re-specialization
        model = self._classify(
            tmp_path, {"churn": {"compute_ms": 5.0, "compiles": 10}})
        assert model.elements["churn"].floor == "compile-bound"
        assert model.elements["churn"].evidence["compile_ratio"] >= 1.0

    def test_warmup_compiles_do_not_flip_the_floor(self, tmp_path):
        # 1 compile over 10 calls at 5 ms: steady state, compute rules
        model = self._classify(
            tmp_path, {"warm": {"compute_ms": 50.0, "compiles": 0}})
        assert model.elements["warm"].floor == "compute-bound"

    def test_low_utilization_reads_dispatch_bound(self, tmp_path):
        # 3 ms/call is past the 1.5 ms floor, but the static FLOP
        # estimate says the chip did ~nothing: dispatch-bound
        loaded = load_trace(_make_trace(
            tmp_path, {"idle": {"compute_ms": 3.0}},
            config={"peak_tflops_assumed": 100.0}))
        model = CostModel.from_trace(
            loaded, static_costs={"idle": {"rows": 1, "flops": 1e6,
                                           "bytes_in": 4,
                                           "bytes_out": 4}})
        classify_elements(model)
        assert model.elements["idle"].floor == "dispatch-bound"
        assert model.elements["idle"].achieved_utilization < 0.02


# -- recommender -------------------------------------------------------------

class TestRecommender:
    def test_monotonic_micro_batch_under_tightening_p99(self):
        """The contract: a TIGHTER SLO budget never RAISES a proposed
        micro_batch."""
        previous = None
        for budget_ms in (100000.0, 1000.0, 50.0, 5.0, 0.5):
            report = run_tune(
                str(FIXTURE),
                slo_spec=SloSpec.parse(
                    f"slo=throughput;p99_ms={budget_ms}"),
                static_costs={})
            proposed = {}
            for record in report["recommendations"]:
                if record["knob"] == "micro_batch":
                    proposed[record["target"]] = record["proposed"]
            if previous is not None:
                for target in set(previous) | set(proposed):
                    # absent proposal == stays at current (1)
                    assert proposed.get(target, 1) <= \
                        previous.get(target, 1), (budget_ms, target)
            previous = proposed

    def test_latency_slo_proposes_window_one_not_bigger_batches(self):
        report = run_tune(str(FIXTURE),
                          slo_spec=SloSpec.parse("latency"),
                          static_costs={})
        knobs = {(r["target"], r["knob"]): r["proposed"]
                 for r in report["recommendations"]}
        assert knobs.get(("pipeline", "frame_window")) == 1
        for record in report["recommendations"]:
            if record["knob"] == "micro_batch":
                assert record["proposed"] <= record["current"]

    def test_every_recommendation_carries_evidence(self):
        report = run_tune(str(FIXTURE), static_costs={})
        assert report["recommendations"]
        for record in report["recommendations"]:
            assert record["reason"]
            assert isinstance(record["evidence"], dict)
            assert record["evidence"]

    def test_queue_bound_starved_groups_shrink_micro_batch(
            self, tmp_path):
        definition = _definition(["starved"])
        definition["elements"][0]["parameters"] = {"micro_batch": 16}
        path = _make_trace(
            tmp_path,
            {"starved": {"compute_ms": 2.0, "queue_ms": 30.0,
                         "group": 2}},
            definition=definition)
        report = run_tune(path, static_costs={})
        records = {(r["target"], r["knob"]): r
                   for r in report["recommendations"]}
        record = records[("element:starved", "micro_batch")]
        assert record["current"] == 16
        assert record["proposed"] == 2

    def test_engine_slot_wait_raises_decode_slots(self, tmp_path):
        definition = _definition(["lm"])
        definition["elements"][0]["parameters"] = {
            "continuous": True, "decode_slots": 2,
            "kv_block_size": 8, "max_new_tokens": 4}
        events = []
        ts = 0.0
        for frame_id in range(6):
            trace_id = f"1-{frame_id + 1:x}"
            for row in range(2):
                events.append({
                    "ph": "X", "name": f"queue:lm[{row}]",
                    "cat": "queue", "ts": ts, "dur": 50000.0,
                    "pid": 1, "tid": 1, "args": {}})
                events.append({
                    "ph": "X", "name": f"prefill:lm[{row}]",
                    "cat": "engine", "ts": ts + 50000.0,
                    "dur": 2000.0, "pid": 1, "tid": 1, "args": {}})
                events.append({
                    "ph": "X", "name": f"decode_steps:lm[{row}]",
                    "cat": "engine", "ts": ts + 52000.0,
                    "dur": 8000.0, "pid": 1, "tid": 1,
                    "args": {"decode_steps": 4, "preemptions": 0,
                             "tokens": 3}})
            events.append({
                "ph": "X", "name": f"frame {frame_id}",
                "cat": "frame", "ts": ts, "dur": 60000.0,
                "pid": 1, "tid": 1,
                "args": {"trace_id": trace_id, "status": "ok",
                         "stream": "s"}})
            ts += 61000.0
        path = tmp_path / "engine.json"
        path.write_text(json.dumps(chrome_trace_document(
            events, metadata=trace_metadata(
                definition_document=definition))))
        report = run_tune(str(path), static_costs={})
        records = {(r["target"], r["knob"]): r
                   for r in report["recommendations"]}
        slots = records[("element:lm", "decode_slots")]
        assert slots["current"] == 2 and slots["proposed"] == 4
        # completions averaged 3 tokens in 8-token blocks: halve them
        blocks = records[("element:lm", "kv_block_size")]
        assert blocks["proposed"] == 4


# -- apply / lint round trip -------------------------------------------------

class TestApply:
    def test_apply_round_trips_through_lint(self):
        report = run_tune(str(FIXTURE), static_costs={})
        loaded = load_trace(str(FIXTURE))
        recommendations = [
            Recommendation(**{key: record[key] for key in
                              ("target", "knob", "current", "proposed",
                               "reason", "floor", "evidence")})
            for record in report["recommendations"]]
        assert recommendations
        document, diagnostics = apply_recommendations(
            loaded.definition_document, recommendations)
        assert diagnostics == []
        # the applied knobs landed
        applied = {element["name"]:
                   element.get("parameters", {}).get("micro_batch")
                   for element in document["elements"]}
        changed = [record for record in report["recommendations"]
                   if record["knob"] == "micro_batch"]
        for record in changed:
            name = record["target"].split(":", 1)[1]
            assert applied[name] == record["proposed"]
        # and the document passes the same passes `aiko lint` runs at
        # construction time
        lint = analyze_definition(document, passes=("graph", "policy"))
        assert lint.failures() == [], [d.render()
                                       for d in lint.failures()]

    def test_apply_missing_element_is_aiko502(self):
        loaded = load_trace(str(FIXTURE))
        document, diagnostics = apply_recommendations(
            loaded.definition_document,
            [Recommendation("element:nonexistent", "micro_batch",
                            1, 4, "test")])
        assert [d.code for d in diagnostics] == ["AIKO502"]

    def test_apply_never_overwrites_existing_policy(self):
        loaded = load_trace(str(FIXTURE))
        loaded.definition_document.setdefault("parameters", {})[
            "gateway_policy"] = "max_inflight=4"
        document, diagnostics = apply_recommendations(
            loaded.definition_document,
            [Recommendation("gateway", "gateway_policy", None,
                            "bucket:0=9/2", "test")])
        assert document["parameters"]["gateway_policy"] == \
            "max_inflight=4"
        assert [d.code for d in diagnostics] == ["AIKO502"]


# -- what-if replay determinism ----------------------------------------------

class TestReplay:
    def test_report_bit_deterministic(self):
        one = report_json(run_tune(str(FIXTURE), static_costs={}))
        two = report_json(run_tune(str(FIXTURE), static_costs={}))
        assert one == two

    def test_predict_scales_with_settings(self):
        loaded = load_trace(str(FIXTURE))
        model = CostModel.from_trace(loaded)
        classify_elements(model)
        from aiko_services_tpu.tune import element_settings_of
        settings = element_settings_of(loaded.definition_document)
        baseline = predict(model, settings)
        doubled = predict(model, settings, {"replicas": 2})
        assert doubled["frames_per_sec"] == pytest.approx(
            2 * baseline["frames_per_sec"])
        batched = predict(
            model, settings,
            {"elements": {baseline["bottleneck"]:
                          {"micro_batch": 8}}})
        assert batched["frames_per_sec"] >= baseline["frames_per_sec"]

    def test_predict_same_inputs_same_bytes(self):
        loaded = load_trace(str(FIXTURE))
        model = CostModel.from_trace(loaded)
        from aiko_services_tpu.tune import element_settings_of
        settings = element_settings_of(loaded.definition_document)
        overrides = {"elements": {"asr": {"micro_batch": 4}}}
        assert json.dumps(predict(model, settings, overrides)) == \
            json.dumps(predict(model, settings, overrides))


# -- grammar / AIKO501 -------------------------------------------------------

class TestGrammar:
    def test_valid_specs(self):
        assert check_tune_spec("throughput") == []
        assert check_tune_spec("slo=latency;p99_ms=250") == []
        assert check_tune_spec(
            "p99_ms=10;max_micro_batch=8;dispatch_floor_ms=0.05") == []

    def test_bad_value_is_501_unknown_is_404(self):
        assert [code for code, _ in
                check_tune_spec("slo=goodput")] == ["AIKO501"]
        assert [code for code, _ in
                check_tune_spec("p99_ms=-4")] == ["AIKO501"]
        assert [code for code, _ in
                check_tune_spec("slos=latency")] == ["AIKO404"]

    def test_parse_raises_on_bad_spec(self):
        with pytest.raises(GrammarError):
            SloSpec.parse("p99_ms=zero")

    def test_definition_tune_parameter_linted(self):
        definition = _definition(["a"])
        definition["parameters"] = {"tune": "slo=nope"}
        report = analyze_definition(definition, passes=("policy",))
        assert "AIKO501" in {d.code for d in report.findings}


# -- CLI ---------------------------------------------------------------------

class TestCli:
    def _invoke(self, *args):
        from click.testing import CliRunner
        from aiko_services_tpu.cli import main
        return CliRunner().invoke(main, list(args))

    def test_cli_json_deterministic_on_fixture(self):
        one = self._invoke("tune", str(FIXTURE), "--json",
                           "--no-flops")
        two = self._invoke("tune", str(FIXTURE), "--json",
                           "--no-flops")
        assert one.exit_code == 0, one.output
        assert one.output == two.output
        report = json.loads(one.output)
        assert len(report["elements"]) == 6
        assert all(record["floor"] != "unobserved"
                   for record in report["elements"].values())

    def test_cli_metadata_absent_fails_gracefully(self, tmp_path):
        path = _make_trace(tmp_path, {"a": {"compute_ms": 1.0}},
                           metadata=False)
        result = self._invoke("tune", path)
        assert result.exit_code == 2
        assert "no aiko metadata" in result.output \
            or "not joined" in result.output

    def test_cli_what_if(self):
        result = self._invoke(
            "tune", str(FIXTURE), "--json", "--no-flops",
            "--what-if", "lm.micro_batch=4;replicas=2")
        assert result.exit_code == 0, result.output
        report = json.loads(result.output)
        assert report["recommendations"] == []
        assert report["replay"]["proposed"]["replicas"] == 2

    def test_cli_what_if_rejects_typos_and_apply_combination(
            self, tmp_path):
        # unknown element
        result = self._invoke("tune", str(FIXTURE), "--no-flops",
                              "--what-if", "lmm.micro_batch=4")
        assert result.exit_code != 0
        assert "unknown element" in result.output
        # unknown knob
        result = self._invoke("tune", str(FIXTURE), "--no-flops",
                              "--what-if", "lm.micro_bacth=4")
        assert result.exit_code != 0 and "knob" in result.output
        # --what-if with --apply: loud usage error, no file written
        out = tmp_path / "never.json"
        result = self._invoke("tune", str(FIXTURE), "--no-flops",
                              "--what-if", "lm.micro_batch=4",
                              "--apply", str(out))
        assert result.exit_code == 2
        assert "mutually exclusive" in result.output
        assert not out.exists()

    def test_cli_apply_writes_lintable_definition(self, tmp_path):
        out = tmp_path / "tuned.json"
        result = self._invoke("tune", str(FIXTURE), "--no-flops",
                              "--apply", str(out))
        assert result.exit_code == 0, result.output
        document = json.loads(out.read_text())
        lint = analyze_definition(document, passes=("graph", "policy"))
        assert lint.failures() == []


# -- case studies ------------------------------------------------------------

class TestCaseStudies:
    def test_roofline_traces_classify_compute_bound(self):
        """The two VERDICT rooflines: tune's report must EXPLAIN the
        floor -- compute-bound with achieved utilization equal to the
        recorded MFU, ruling out dispatch/queue/compile."""
        for path in CASE_STUDIES:
            loaded = load_trace(str(path))
            assert loaded.definition is not None, path
        report = json.loads(
            (REPORTS / "tune_longcontext_16k.json").read_text())
        assert report["elements"]["prefill_4k"]["floor"] == \
            "compute-bound"
        assert report["elements"]["prefill_16k"]["floor"] == \
            "compute-bound"
        assert report["elements"]["prefill_4k"][
            "achieved_utilization"] == pytest.approx(0.1308)
        assert report["elements"]["prefill_16k"][
            "achieved_utilization"] == pytest.approx(0.0647)
        train = json.loads(
            (REPORTS / "tune_train_step.json").read_text())
        assert train["elements"]["train_step"]["floor"] == \
            "compute-bound"
        assert train["elements"]["train_step"][
            "achieved_utilization"] == pytest.approx(0.3845)
