# aiko_services_tpu: a TPU-native distributed ML pipeline framework.
#
# Brand-new implementation with the capabilities of the reference
# aiko_services (distributed actor-model services, registrar discovery,
# eventually-consistent state shares, streaming ML pipelines), redesigned
# around a JAX/XLA data plane: element compute runs as jit-compiled JAX
# functions on TPU, inter-element tensors stay HBM-resident as jax.Array,
# multi-stage graphs shard over a jax.sharding.Mesh, and the S-expression
# control plane rides a pluggable transport (in-process loopback broker by
# default; MQTT when available).
#
# Layering (see SURVEY.md section 1 for the reference layer map):
#   utils/     L0 kernel utilities (sexpr codec, graph, config, logging)
#   transport/ L1 message transports (loopback broker, MQTT, null)
#   runtime/   L2-L8 event engine, process, service, actor, share, registrar
#   observe/   telemetry: metrics registry, frame tracer, live export
#   analyze/   definition-time static analysis: typed tensor ports,
#              shape-flow verification, actor-safety lint (aiko lint)
#   pipeline/  L9 pipeline engine: streams, frames, elements, graphs
#   serve/     L10 serving tier: gateway (admission, routing, failover)
#   ops/       TPU ops: attention, mel spectrogram, image, pallas kernels
#   parallel/  mesh management, sharding specs, collectives, ring attention
#   models/    flagship model families: LLM (Llama-style), Whisper, YOLO
#   elements/  pipeline elements: media I/O + ML elements over models/

__version__ = "0.1.0"
