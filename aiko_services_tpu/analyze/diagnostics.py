# Diagnostics: the stable rule-code vocabulary of the static analyzer.
#
# Every finding carries a rule code (AIKO1xx graph/ports, AIKO2xx
# shape/dtype flow, AIKO3xx element/actor safety, AIKO4xx policy
# grammars, AIKO5xx profile-guided tuning, AIKO6xx static
# concurrency), a severity, and a location
# (definition / element / port),
# so CI can diff reports across commits and operators can suppress a
# rule by code (element or pipeline parameter `lint_ignore`).
#
# Severity ladder:
#   error    the definition is wrong: construction-time validation
#            raises DefinitionError for these
#   warning  legal but suspicious (dead output, blocking call): logged
#            at construction, fails `aiko lint --strict`
#   info     analysis limits (a trace the analyzer could not run):
#            reported, never fails the build

from __future__ import annotations

import json
from dataclasses import dataclass, field

__all__ = ["Diagnostic", "AnalysisReport", "RULES", "severity_of"]

# code -> (default severity, one-line summary).  This table IS the
# README rule-code table; tests assert the two stay in sync.
RULES = {
    # -- AIKO1xx: graph / ports -----------------------------------------
    "AIKO100": ("error", "definition does not parse (schema error)"),
    "AIKO101": ("error", "graph node has no element definition"),
    "AIKO102": ("error", "duplicate element name"),
    "AIKO103": ("error", "element input not produced by any ancestor"),
    "AIKO104": ("warning",
                "dead output: overwritten downstream before any read"),
    "AIKO105": ("error", "map_in names an input port the element "
                         "does not declare"),
    "AIKO106": ("error", "map_out names an output port the element "
                         "does not declare"),
    "AIKO107": ("error", "duplicate port name within an element"),
    # -- AIKO2xx: shape / dtype flow ------------------------------------
    "AIKO201": ("error", "port type is not in the tensor-spec grammar"),
    "AIKO202": ("error", "dtype clash between producer and consumer"),
    "AIKO203": ("error", "tensor rank mismatch between producer and "
                         "consumer"),
    "AIKO204": ("error", "fixed dimension mismatch between producer "
                         "and consumer"),
    "AIKO205": ("error", "symbolic dimension bound to conflicting "
                         "sizes"),
    "AIKO206": ("error", "sharding spec names an axis absent from the "
                         "element's mesh axes"),
    "AIKO207": ("error", "declared output spec disagrees with the "
                         "jax.eval_shape traced output"),
    "AIKO208": ("info", "shape trace unavailable for this element"),
    # -- AIKO3xx: element / actor safety --------------------------------
    "AIKO301": ("warning", "blocking host call inside a non-async "
                           "element's frame path"),
    "AIKO302": ("error", "group_kernel defined on an AsyncHostElement"),
    "AIKO303": ("warning", "cross-stream shared state mutated outside "
                           "the mailbox"),
    "AIKO304": ("error", "deployed element class not importable or not "
                         "a PipelineElement"),
    # -- AIKO4xx: policy grammars ---------------------------------------
    "AIKO401": ("error", "invalid fault-tolerance parameter"),
    "AIKO402": ("error", "invalid fault-injection spec"),
    "AIKO403": ("error", "invalid gateway admission-policy spec"),
    "AIKO404": ("error", "unknown directive in a policy grammar"),
    "AIKO405": ("error", "invalid continuous-batching decode parameter"),
    "AIKO406": ("error", "invalid autoscale policy spec"),
    "AIKO407": ("error", "invalid gateway HA/journal policy spec"),
    "AIKO408": ("error", "invalid prefill/decode disaggregation spec"),
    "AIKO409": ("error", "invalid decode checkpoint/recovery policy "
                         "spec"),
    "AIKO410": ("error", "invalid gateway federation spec"),
    "AIKO411": ("error", "invalid prefix-cache policy spec"),
    "AIKO412": ("error", "invalid autopilot policy spec"),
    # -- AIKO5xx: profile-guided tuning (tune/) --------------------------
    "AIKO501": ("error", "invalid tune SLO/directive spec"),
    "AIKO502": ("warning", "tune recommendation not applicable to the "
                           "definition"),
    "AIKO503": ("info", "trace metadata absent or not joinable against "
                        "the static graph"),
    # -- AIKO6xx: static concurrency (analyze/concurrency.py) ------------
    "AIKO600": ("info", "concurrency pass note (stale baseline entry "
                        "or unreadable source)"),
    "AIKO601": ("warning", "unsynchronized iteration of a container "
                           "attribute mutated from another thread "
                           "role"),
    "AIKO602": ("warning", "check-then-act on a shared attribute "
                           "across thread roles without a lock"),
    "AIKO603": ("warning", "blocking call while holding a lock"),
    "AIKO604": ("warning", "lock-order inversion: acquire-graph cycle "
                           "across methods"),
    "AIKO605": ("warning", "mutable class-level default mutated "
                           "through self"),
}


def severity_of(code: str) -> str:
    return RULES.get(code, ("error", ""))[0]


@dataclass
class Diagnostic:
    code: str
    message: str
    definition: str = ""      # pipeline definition name
    element: str = ""         # element name ("" = pipeline level)
    port: str = ""            # port name when the finding is port-scoped
    severity: str = ""        # defaulted from RULES when empty
    source: str = ""          # file path the definition came from

    def __post_init__(self):
        if not self.severity:
            self.severity = severity_of(self.code)

    @property
    def location(self) -> str:
        parts = [part for part in (self.definition, self.element,
                                   self.port) if part]
        return ".".join(parts) if parts else "<definition>"

    def render(self) -> str:
        return f"{self.code} [{self.severity}] {self.location}: " \
               f"{self.message}"

    def to_dict(self) -> dict:
        return {"code": self.code, "severity": self.severity,
                "definition": self.definition, "element": self.element,
                "port": self.port, "source": self.source,
                "message": self.message}


@dataclass
class AnalysisReport:
    """All findings from one analysis run (one or many definitions)."""

    findings: list = field(default_factory=list)
    passes_run: list = field(default_factory=list)

    def add(self, diagnostic: Diagnostic) -> None:
        self.findings.append(diagnostic)

    def extend(self, other: "AnalysisReport") -> None:
        self.findings.extend(other.findings)
        for name in other.passes_run:
            if name not in self.passes_run:
                self.passes_run.append(name)
        traced = getattr(other, "traced_elements", None)
        if traced:
            mine = getattr(self, "traced_elements", None) or []
            self.traced_elements = mine + list(traced)

    def errors(self) -> list:
        return [d for d in self.findings if d.severity == "error"]

    def warnings(self) -> list:
        return [d for d in self.findings if d.severity == "warning"]

    def failures(self, strict: bool = False) -> list:
        """Findings that should fail the run: errors always; warnings
        too under --strict.  Info diagnostics never fail."""
        if strict:
            return [d for d in self.findings
                    if d.severity in ("error", "warning")]
        return self.errors()

    def by_code(self) -> dict:
        counts: dict[str, int] = {}
        for diagnostic in self.findings:
            counts[diagnostic.code] = counts.get(diagnostic.code, 0) + 1
        return dict(sorted(counts.items()))

    def to_json(self) -> str:
        return json.dumps({
            "version": 1,
            "passes": list(self.passes_run),
            "summary": {
                "findings": len(self.findings),
                "errors": len(self.errors()),
                "warnings": len(self.warnings()),
                "by_code": self.by_code(),
            },
            "findings": [d.to_dict() for d in self.findings],
        }, indent=2, sort_keys=True)

    def render(self) -> str:
        lines = [d.render() for d in self.findings]
        by_code = self.by_code()
        summary = ", ".join(f"{code}x{count}"
                            for code, count in by_code.items())
        lines.append(
            f"{len(self.findings)} finding(s) "
            f"({len(self.errors())} error(s), "
            f"{len(self.warnings())} warning(s))"
            + (f": {summary}" if summary else ""))
        return "\n".join(lines)
