# Shared directive-grammar core: ONE spec-checking engine behind every
# operator-facing mini-grammar.
#
# PR 3 (fault injection) and PR 4 (gateway admission policy) each grew
# a hand-rolled `key=value;...` parser, and the definition layer
# validates `on_error`/`max_retries`/... with ad-hoc checks; each had
# its own error style and none was checkable OFFLINE (you had to
# construct the object to find the typo).  This module folds them
# behind one core:
#
#   Field            one typed value: coercion + range + choices with
#                    uniform error messages
#   DirectiveGrammar a `;`-separated directive string: bare key=value
#                    options, `head(:key=value)*` directives (the fault
#                    spec shape), and `prefix:tail=value` entries (the
#                    policy's `bucket:P=rate/burst`)
#   check()          the lint surface: the same validation as parse(),
#                    returning problems instead of raising, so
#                    `aiko lint` checks a spec without building the
#                    injector/policy it describes
#
# GrammarError subclasses ValueError, so existing callers that caught
# ValueError keep working unchanged.

from __future__ import annotations

__all__ = ["Field", "DirectiveGrammar", "GrammarError",
           "ParsedDirectives", "split_directives"]


class GrammarError(ValueError):
    """One grammar violation.  `kind` separates "unknown directive/key"
    (the AIKO404 shape) from a bad value (AIKO401/402/403)."""

    def __init__(self, message: str, kind: str = "value"):
        super().__init__(message)
        self.kind = kind


_TRUTHY = ("1", "true", "yes", "on")
_FALSY = ("0", "false", "no", "off", "")


class Field:
    """One typed value in a grammar: kind in int|float|str|flag, with
    optional bounds and choices.  coerce() accepts wire strings or
    already-typed values and raises GrammarError with a message naming
    the grammar, the key, and the exact problem."""

    __slots__ = ("kind", "minimum", "maximum", "choices", "help")

    def __init__(self, kind: str = "str", minimum=None, maximum=None,
                 choices=None, help: str = ""):
        self.kind = kind
        self.minimum = minimum
        self.maximum = maximum
        self.choices = tuple(choices) if choices else None
        self.help = help

    def coerce(self, grammar_name: str, key: str, value):
        try:
            if self.kind == "int":
                value = int(value)
            elif self.kind == "float":
                value = float(value)
            elif self.kind == "flag":
                if isinstance(value, str):
                    lowered = value.strip().lower()
                    if lowered in _TRUTHY:
                        value = True
                    elif lowered in _FALSY:
                        value = False
                    else:
                        raise ValueError(value)
                else:
                    value = bool(value)
            else:
                value = str(value)
        except (TypeError, ValueError):
            raise GrammarError(
                f"{grammar_name}: {key}={value!r} is not a valid "
                f"{self.kind}") from None
        if self.choices is not None:
            comparable = (value.lower() if isinstance(value, str)
                          else value)
            if comparable not in self.choices:
                raise GrammarError(
                    f"{grammar_name}: {key} must be one of "
                    f"{self.choices}, got {value!r}")
            return comparable
        if self.minimum is not None and value < self.minimum:
            raise GrammarError(
                f"{grammar_name}: {key}={value} is below the minimum "
                f"{self.minimum}")
        if self.maximum is not None and value > self.maximum:
            raise GrammarError(
                f"{grammar_name}: {key}={value} is above the maximum "
                f"{self.maximum}")
        return value


def split_directives(spec: str, separator: str = ";") -> list:
    return [part.strip() for part in str(spec).split(separator)
            if part.strip()]


class ParsedDirectives:
    """parse() result: coerced bare options, head directives with
    their coerced args, and prefixed entries."""

    __slots__ = ("options", "directives", "prefixed")

    def __init__(self):
        self.options: dict = {}
        self.directives: list = []   # (head, {key: value})
        self.prefixed: list = []     # (prefix, tail, value)


class DirectiveGrammar:
    """Declarative spec for one `;`-separated directive grammar.

    options    bare `key=value` entries (gateway policy keys; the fault
               spec's `seed`)
    heads      `head(:key=value)*` directives: head word -> arg Field
               table (the fault spec's injection points); unknown heads
               raise with `unknown_head_message` ("unknown fault
               point ..." keeps the historical wording)
    prefixes   `prefix:tail=value` entries, parsed by a callable
               (tail, value) -> parsed, raising GrammarError/ValueError
               on bad input (the policy's `bucket:P=rate/burst`)
    """

    def __init__(self, name: str, options: dict | None = None,
                 heads: dict | None = None, prefixes: dict | None = None,
                 unknown_head_message: str | None = None):
        self.name = name
        self.options = dict(options or {})
        self.heads = dict(heads or {})
        self.prefixes = dict(prefixes or {})
        self.unknown_head_message = unknown_head_message

    # -- parsing -------------------------------------------------------

    def parse(self, spec) -> ParsedDirectives:
        """Parse a directive string (or an options dict) with full
        validation; raises GrammarError on the first problem."""
        parsed = ParsedDirectives()
        if spec is None or spec == "":
            return parsed
        if isinstance(spec, dict):
            for key, value in spec.items():
                self._parse_option(parsed, str(key), value)
            return parsed
        for part in split_directives(spec):
            tokens = part.split(":")
            head = tokens[0].strip()
            if "=" in head:
                self._parse_option(parsed, *self._split_kv(part))
                continue
            if head in self.prefixes and len(tokens) > 1:
                tail, _, value = ":".join(tokens[1:]).partition("=")
                try:
                    parsed.prefixed.append(
                        (head, tail.strip(),
                         self.prefixes[head](tail.strip(),
                                             value.strip())))
                except GrammarError:
                    raise
                except (TypeError, ValueError) as error:
                    raise GrammarError(
                        f"{self.name}: bad {head} directive "
                        f"{part!r}: {error}") from None
                continue
            if head in self.heads:
                fields = self.heads[head]
                args = {}
                for token in tokens[1:]:
                    key, _, value = token.partition("=")
                    key = key.strip()
                    field = fields.get(key)
                    if field is None:
                        raise GrammarError(
                            f"{self.name}: directive {head!r} has "
                            f"unknown key {key!r} (valid: "
                            f"{sorted(fields)})", kind="unknown")
                    args[key] = field.coerce(self.name, key,
                                             value.strip())
                parsed.directives.append((head, args))
                continue
            if self.heads and self.unknown_head_message:
                raise GrammarError(
                    f"{self.unknown_head_message} {head!r} "
                    f"(valid: {tuple(self.heads)})", kind="unknown")
            raise GrammarError(
                f"{self.name}: directive {part!r} is not key=value",
                kind="unknown")
        return parsed

    def _split_kv(self, part: str) -> tuple:
        key, sep, value = part.partition("=")
        if not sep:
            raise GrammarError(
                f"{self.name}: directive {part!r} is not key=value",
                kind="unknown")
        return key.strip(), value.strip()

    def _parse_option(self, parsed: ParsedDirectives, key: str,
                      value) -> None:
        if key.startswith(tuple(f"{prefix}:" for prefix
                                in self.prefixes)):
            # dict-shaped prefixed entry ({"bucket:2": (10, 4)})
            prefix, _, tail = key.partition(":")
            try:
                parsed.prefixed.append(
                    (prefix, tail, self.prefixes[prefix](tail, value)))
            except GrammarError:
                raise
            except (TypeError, ValueError) as error:
                raise GrammarError(
                    f"{self.name}: bad {prefix} entry {key!r}: "
                    f"{error}") from None
            return
        field = self.options.get(key)
        if field is None:
            raise GrammarError(
                f"{self.name}: unknown directive {key!r} (valid: "
                f"{sorted(self.options)})", kind="unknown")
        parsed.options[key] = field.coerce(self.name, key, value)

    # -- the lint surface ----------------------------------------------

    def check(self, spec, value_code: str,
              unknown_code: str = "AIKO404") -> list:
        """Validate without constructing: every problem as a
        (code, message) pair -- unknown directives/keys map to
        `unknown_code`, bad values to `value_code`."""
        problems = []
        if spec is None or spec == "":
            return problems
        if isinstance(spec, dict):
            items = [{key: value} for key, value in spec.items()]
        else:
            items = split_directives(spec)
        for part in items:
            try:
                self.parse(part)
            except GrammarError as error:
                problems.append(
                    (unknown_code if error.kind == "unknown"
                     else value_code, str(error)))
            except ValueError as error:
                problems.append((value_code, str(error)))
        return problems
