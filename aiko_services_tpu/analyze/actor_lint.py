# Pass 3 -- element/actor safety lint (AIKO3xx).
#
# An AST pass over the modules a definition actually deploys.  The
# engine's concurrency model makes three classes of element code wrong
# in ways that only surface under load:
#
#   AIKO301  a blocking host call (time.sleep, socket dial, subprocess,
#            .block_until_ready) inside process_frame/compute of a
#            NON-AsyncHostElement: it stalls the pipeline event loop --
#            on a tunneled TPU one 100 ms readback serializes every
#            stream.  AsyncHostElement.process_async runs on a worker
#            thread, where blocking is the point.
#   AIKO302  group_kernel on an AsyncHostElement: host work cannot
#            trace into a fused device program (the engine rejects this
#            at build; the linter catches it offline).
#   AIKO303  mutation of cross-stream shared state outside the mailbox:
#            `global` writes or attribute stores on self.pipeline /
#            self.process from inside process_frame race other streams'
#            frames; route mutations through post_message instead.
#
# Only methods DEFINED by deployed element classes are scanned (the
# framework engine's own process_frame wrappers are trusted); a line
# carrying "# aiko: allow" suppresses its findings, and an element
# parameter `lint_ignore: ["AIKO301"]` suppresses by rule code.

from __future__ import annotations

import ast
import inspect
import textwrap

from .diagnostics import AnalysisReport, Diagnostic

__all__ = ["run_actor_pass", "statement_suppressed"]

# dotted-call patterns that block the calling thread.  Matched against
# the rendered dotted name of Call nodes ("time.sleep", "socket.create_
# connection", ...) -- a prefix match on the first token catches
# module-level families (subprocess.run / .call / .Popen).
_BLOCKING_CALLS = {
    "time.sleep": "time.sleep blocks the pipeline event loop",
    "sleep": "sleep() blocks the pipeline event loop",
    "input": "input() blocks the pipeline event loop",
    "open": "file I/O on the event loop stalls every stream",
}
_BLOCKING_MODULES = {
    "socket": "socket I/O on the event loop stalls every stream",
    "subprocess": "subprocess calls block the event loop",
    "requests": "network I/O on the event loop stalls every stream",
    "urllib": "network I/O on the event loop stalls every stream",
    "http": "network I/O on the event loop stalls every stream",
}
_BLOCKING_ATTRS = {
    "block_until_ready": ".block_until_ready() stalls the event loop "
                         "on device completion (use blocking_metrics "
                         "or an AsyncHostElement)",
}

# methods that run ON the event loop (or trace into a device program)
_FRAME_PATH_METHODS = ("process_frame", "compute", "group_kernel")

_FRAMEWORK_PREFIX = "aiko_services_tpu.pipeline"


def _dotted_name(node) -> str | None:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def statement_suppressed(source_lines, ast_node) -> bool:
    """True when ANY line a statement spans carries "# aiko: allow" --
    a multi-line call or comprehension is suppressible on whichever of
    its lines the comment reads best (shared with the AIKO6xx
    concurrency pass in concurrency.py)."""
    start = getattr(ast_node, "lineno", 0) - 1
    if start < 0 or start >= len(source_lines):
        return False
    end = getattr(ast_node, "end_lineno", None) or (start + 1)
    for index in range(start, min(end, len(source_lines))):
        if "# aiko: allow" in source_lines[index]:
            return True
    return False


_suppressed = statement_suppressed  # historical internal name


class _MethodScanner(ast.NodeVisitor):
    def __init__(self, report, definition_name, element_name,
                 method_name, source_lines, line_offset):
        self.report = report
        self.definition_name = definition_name
        self.element_name = element_name
        self.method_name = method_name
        self.source_lines = source_lines
        self.line_offset = line_offset

    def _add(self, code, message, node):
        if _suppressed(self.source_lines, node):
            return
        self.report.add(Diagnostic(
            code,
            f"{self.method_name}() line "
            f"{node.lineno + self.line_offset}: {message}",
            definition=self.definition_name,
            element=self.element_name))

    def visit_Call(self, node):
        dotted = _dotted_name(node.func)
        if dotted is not None:
            if dotted in _BLOCKING_CALLS:
                self._add("AIKO301", _BLOCKING_CALLS[dotted], node)
            else:
                root = dotted.split(".", 1)[0]
                if root in _BLOCKING_MODULES:
                    self._add("AIKO301", _BLOCKING_MODULES[root], node)
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in _BLOCKING_ATTRS):
            self._add("AIKO301", _BLOCKING_ATTRS[node.func.attr], node)
        self.generic_visit(node)

    def visit_Global(self, node):
        self._add(
            "AIKO303",
            f"`global {', '.join(node.names)}` mutates process-wide "
            f"state from the frame path; cross-stream state must go "
            f"through the mailbox (post_message)", node)
        self.generic_visit(node)

    def visit_Assign(self, node):
        for target in node.targets:
            self._check_store(target)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._check_store(node.target)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self._check_store(node.target)
        self.generic_visit(node)

    def _check_store(self, target):
        if isinstance(target, (ast.Tuple, ast.List)):
            for entry in target.elts:  # unpacking assignment targets
                self._check_store(entry)
            return
        if isinstance(target, ast.Starred):
            self._check_store(target.value)
            return
        dotted = _dotted_name(target) if isinstance(
            target, ast.Attribute) else None
        if dotted and (dotted.startswith("self.pipeline.")
                       or dotted.startswith("self.process.")):
            self._add(
                "AIKO303",
                f"assignment to {dotted} from the frame path mutates "
                f"state shared by every stream; post a mailbox message "
                f"instead", target)


def _scan_method(report, definition_name, element_name, cls,
                 method_name) -> None:
    """Scan the resolved method if a NON-framework class defines it."""
    for klass in cls.__mro__:
        function = klass.__dict__.get(method_name)
        if function is None:
            continue
        module_name = getattr(klass, "__module__", "")
        if module_name.startswith(_FRAMEWORK_PREFIX):
            return  # the engine's own implementation: trusted
        try:
            source = textwrap.dedent(inspect.getsource(function))
            _, line = inspect.getsourcelines(function)
        except (OSError, TypeError):
            return
        try:
            tree = ast.parse(source)
        except SyntaxError:
            return
        scanner = _MethodScanner(
            report, definition_name, element_name, method_name,
            source.splitlines(), line - 1)
        scanner.visit(tree)
        return


def run_actor_pass(definition) -> AnalysisReport:
    """AST-lint every locally-deployed element class of a parsed
    PipelineDefinition."""
    from ..pipeline.element import AsyncHostElement, PipelineElement
    from ..utils import load_module

    report = AnalysisReport(passes_run=["actor"])
    scanned: set = set()
    for element in definition.elements:
        if not element.is_local:
            continue
        module_name = element.deploy_local["module"]
        class_name = element.deploy_local["class_name"]
        try:
            module = load_module(module_name)
            cls = getattr(module, class_name)
        except Exception as error:
            report.add(Diagnostic(
                "AIKO304",
                f"cannot import {class_name} from {module_name}: "
                f"{error}", definition=definition.name,
                element=element.name))
            continue
        if not (isinstance(cls, type)
                and issubclass(cls, PipelineElement)):
            report.add(Diagnostic(
                "AIKO304",
                f"{module_name}.{class_name} is not a PipelineElement",
                definition=definition.name, element=element.name))
            continue
        if cls in scanned:
            continue  # one finding set per class, not per graph seat
        scanned.add(cls)
        if issubclass(cls, AsyncHostElement):
            if (cls.group_kernel
                    is not PipelineElement.group_kernel):
                report.add(Diagnostic(
                    "AIKO302",
                    f"{class_name} is an AsyncHostElement but defines "
                    f"group_kernel; host-thread work cannot trace into "
                    f"a fused device program",
                    definition=definition.name, element=element.name))
            continue  # blocking calls are legal in process_async
        for method_name in _FRAME_PATH_METHODS:
            _scan_method(report, definition.name, element.name, cls,
                         method_name)
    return report
