# Pass 4 -- policy-grammar verification (AIKO4xx).
#
# Every operator-facing mini-grammar the engine grew -- the
# fault-tolerance parameters (`on_error`, `max_retries`, ...), the
# fault-injection spec (faults.py), and the gateway admission policy
# (serve/policy.py) -- now parses through ONE shared core
# (analyze/grammar.py), so this pass can verify any of them OFFLINE
# with the same error quality construction would produce: a typo'd
# policy is a lint finding in CI, not a wedged stream at 2 a.m.

from __future__ import annotations

from .diagnostics import AnalysisReport, Diagnostic
from .grammar import Field, GrammarError, split_directives

__all__ = ["run_policy_pass", "check_gateway_policy",
           "check_autopilot_policy", "check_autoscale_policy",
           "check_checkpoint_policy", "check_disagg_policy",
           "check_faults_spec", "check_federation_policy",
           "check_journal_policy", "check_decode_parameters",
           "check_prefix_policy", "check_tune_spec",
           "parse_speculative_spec", "FAULT_TOLERANCE_FIELDS",
           "DECODE_FIELDS", "DISAGG_FIELDS", "SPECULATIVE_FIELDS"]

# The PR-3 fault-tolerance parameter vocabulary (pipeline / element /
# stream scoped).  `on_error` choices are filled in lazily from the
# engine's ERROR_POLICIES so the two can never drift.
FAULT_TOLERANCE_FIELDS = {
    "max_retries": Field("int", minimum=0),
    "retry_backoff_ms": Field("float", minimum=0.0),
    "error_budget": Field("int", minimum=0),
    "error_window": Field("float", minimum=0.0),
    "frame_deadline": Field("float", minimum=0.0),
    "park_timeout": Field("float", minimum=0.0),
}


# The continuous-batching engine parameters (decode/, LMGenerate
# `continuous: true`).  kv_blocks >= 2 because block 0 is the reserved
# trash block (decode/blocks.py) -- a 1-block pool has zero allocatable
# capacity.
DECODE_FIELDS = {
    "continuous": Field("flag"),
    "decode_slots": Field("int", minimum=1),
    "kv_block_size": Field("int", minimum=1),
    "kv_blocks": Field("int", minimum=2),
    "max_context": Field("int", minimum=1),
    "eos_id": Field("int", minimum=0),
    "prefill_chunk_size": Field("int", minimum=1),
    "speculative": Field("str"),
}

# Element-level disaggregation parameters (LMGenerate `role` /
# `adopt_timeout`): checked as AIKO408 -- the same rule family as the
# gateway's `disagg` policy spec, because both describe the SAME
# prefill/decode split and must fail the same way offline and at
# construction.
DISAGG_FIELDS = {
    "role": Field("str", choices=("prefill", "decode")),
    "adopt_timeout": Field("float", minimum=0.0),
}


# The `speculative` directive (LMGenerate parameter, `;`-separated
# key=value through the shared grammar core): greedy-exact speculative
# decoding on the continuous engine.  `draft` selects the proposal
# model -- an _LM_PRESETS name, or "self" for the target's own config
# family shrunk by the `layers`/`d_ff` overrides (random-init from
# `seed`; the production path loads a trained draft via a preset).
# `k` is the proposal run length per verify window.
SPECULATIVE_FIELDS = {
    "draft": Field("str"),
    "k": Field("int", minimum=1, maximum=16),
    "layers": Field("int", minimum=1),
    "d_ff": Field("int", minimum=1),
    "seed": Field("int", minimum=0),
}


def parse_speculative_spec(spec) -> dict:
    """`draft=<preset|self>;k=<n>[;layers=<n>][;d_ff=<n>][;seed=<n>]`
    -> coerced dict.  Raises GrammarError (a ValueError) with the same
    message offline lint reports as AIKO405."""
    parsed = {}
    for part in split_directives(spec):
        key, separator, value = part.partition("=")
        key = key.strip()
        if not separator or key not in SPECULATIVE_FIELDS:
            raise GrammarError(
                f"speculative: unknown entry {part!r}; expected "
                f"key=value with keys {sorted(SPECULATIVE_FIELDS)}",
                kind="unknown")
        parsed[key] = SPECULATIVE_FIELDS[key].coerce(
            "speculative", key, value.strip())
    for required in ("draft", "k"):
        if required not in parsed:
            raise GrammarError(
                f"speculative: missing required entry "
                f"{required}=<value>")
    if parsed["draft"] != "self" and (
            "layers" in parsed or "d_ff" in parsed):
        raise GrammarError(
            "speculative: layers=/d_ff= overrides only apply to "
            "draft=self (a preset draft has its own dims)")
    return parsed


def check_decode_parameters(parameters: dict,
                            disagg_scope: bool = True) -> list:
    """(code, message) problems in one element's continuous-batching
    parameter set: per-field type/bounds, plus the cross-field pool
    sanity check (a pool that cannot hold even one completion admits
    nothing -- every submit would raise, which should be a lint
    finding, not a serving-time surprise).  `disagg_scope=False` skips
    the AIKO408 role/adopt_timeout rules: `role` is a generic
    parameter name, and only elements that actually interpret it as a
    disagg pool (LMGenerate) may be judged by its vocabulary."""
    problems = []
    clean = {}
    for key, field in DECODE_FIELDS.items():
        if key not in parameters:
            continue
        try:
            clean[key] = field.coerce("decode", key, parameters[key])
        except ValueError as error:
            problems.append(("AIKO405", str(error)))
    if disagg_scope:
        for key, field in DISAGG_FIELDS.items():
            if key not in parameters:
                continue
            try:
                clean[key] = field.coerce("disagg", key,
                                          parameters[key])
            except ValueError as error:
                problems.append(("AIKO408", str(error)))
        if "checkpoint" in parameters:
            # the warm-KV-failover snapshot spec (decode/checkpoint.py)
            # is engine-scoped here: recovery_rate belongs on the
            # gateway's `checkpoint` / the `checkpoint_policy` parameter
            checkpoint_problems = check_checkpoint_policy(
                parameters["checkpoint"], element=True)
            problems.extend(checkpoint_problems)
            if not checkpoint_problems:
                clean["checkpoint"] = parameters["checkpoint"]
        if "prefix_policy" in parameters:
            # the cross-request prefix-reuse spec (decode/prefix.py) is
            # engine-scoped here: affinity_weight belongs on the
            # gateway's `prefix` / the definition-level `prefix_policy`
            prefix_problems = check_prefix_policy(
                parameters["prefix_policy"], element=True)
            problems.extend(prefix_problems)
            if not prefix_problems:
                clean["prefix_policy"] = parameters["prefix_policy"]
    if "speculative" in clean:
        try:
            parse_speculative_spec(clean["speculative"])
        except ValueError as error:
            problems.append(("AIKO405", str(error)))
    # both kernel-floor features ride the continuous engine: on the
    # closed-batch path they would be silently ignored, which is a
    # misconfiguration worth failing offline
    for feature in ("speculative", "prefill_chunk_size"):
        if feature in clean and not clean.get("continuous"):
            if clean.get("role") == "prefill" \
                    and feature == "prefill_chunk_size":
                continue  # the prefill engine chunks without decoding
            problems.append((
                "AIKO405",
                f"{feature} requires continuous=true (the closed-batch "
                f"path ignores it)"))
    # disagg cross-field rules: a decode-pool element IS the continuous
    # engine (adoption rewrites slot block tables); a prefill-pool
    # element never decodes, so the continuous/speculative knobs on it
    # are dead configuration worth failing offline
    role = clean.get("role")
    if role == "decode" and not clean.get("continuous"):
        problems.append((
            "AIKO408",
            "role=decode requires continuous=true (adoption needs the "
            "slot engine)"))
    if role == "prefill":
        for feature in ("continuous", "speculative"):
            if clean.get(feature):
                problems.append((
                    "AIKO408",
                    f"role=prefill does not decode; drop {feature}"))
    if "adopt_timeout" in clean and role != "decode":
        problems.append((
            "AIKO408",
            "adopt_timeout only applies to role=decode (the adopting "
            "side of the KV migration)"))
    if "checkpoint" in clean:
        if role == "prefill":
            problems.append((
                "AIKO409",
                "role=prefill holds no decode state to checkpoint; "
                "drop checkpoint"))
        elif not clean.get("continuous"):
            problems.append((
                "AIKO409",
                "checkpoint requires continuous=true (snapshots ride "
                "the slot engine)"))
    if "prefix_policy" in clean:
        if role == "prefill":
            problems.append((
                "AIKO411",
                "role=prefill exports its KV per handoff, not into a "
                "slot pool; drop prefix_policy"))
        elif not clean.get("continuous"):
            problems.append((
                "AIKO411",
                "prefix_policy requires continuous=true (the cache "
                "indexes the slot engine's paged pool)"))
    if problems or not clean.get("continuous"):
        return problems
    block_size = clean.get("kv_block_size", 16)
    kv_blocks = clean.get("kv_blocks")
    max_new = parameters.get("max_new_tokens")
    if kv_blocks is not None and max_new is not None:
        try:
            max_new = int(max_new)
        except (TypeError, ValueError):
            return problems  # max_new_tokens is not this pass's rule
        needed = -(-(max_new + 1) // block_size)
        if needed > kv_blocks - 1:
            problems.append((
                "AIKO405",
                f"kv_blocks={kv_blocks} gives {kv_blocks - 1} "
                f"allocatable blocks of {block_size}, but one "
                f"completion of max_new_tokens={max_new} needs "
                f"{needed}: no request could ever be admitted"))
    max_context = clean.get("max_context")
    if max_context is not None and max_new is not None:
        # mirror DecodeEngine.__init__: max_context is rounded UP to a
        # block multiple at runtime, so the lint must judge the rounded
        # capacity or it rejects configs the engine accepts
        effective = -(-max_context // block_size) * block_size
        try:
            if int(max_new) + 1 > effective:
                problems.append((
                    "AIKO405",
                    f"max_context={max_context} (rounded to "
                    f"{effective} = a kv_block_size={block_size} "
                    f"multiple) cannot hold a single completion of "
                    f"max_new_tokens={int(max_new)} plus a 1-token "
                    f"prompt"))
        except (TypeError, ValueError):
            pass
    return problems


def _on_error_field():
    from ..pipeline.element import ERROR_POLICIES
    return Field("str", choices=ERROR_POLICIES)


def check_faults_spec(spec) -> list:
    """(code, message) problems in a fault-injection spec."""
    from ..faults import FAULTS_GRAMMAR
    return FAULTS_GRAMMAR.check(spec, value_code="AIKO402")


def check_gateway_policy(spec) -> list:
    """(code, message) problems in a gateway admission-policy spec.

    After the per-directive grammar check, a grammar-clean spec goes
    through the REAL AdmissionPolicy.parse so cross-field constraints
    (throttle_low <= throttle_high, bucket rate/burst > 0) fail
    offline exactly as they would at Gateway construction."""
    from ..serve.policy import POLICY_GRAMMAR, AdmissionPolicy
    problems = POLICY_GRAMMAR.check(spec, value_code="AIKO403")
    if not problems:
        try:
            AdmissionPolicy.parse(spec)
        except ValueError as error:
            problems.append(("AIKO403", str(error)))
    return problems


def check_journal_policy(spec) -> list:
    """(code, message) problems in a gateway HA/journal spec.  Same
    shape as check_gateway_policy: the per-directive grammar check,
    then the REAL JournalPolicy.parse so the cross-field constraint
    (backend=sqlite requires path=) fails offline exactly as it would
    at Gateway construction."""
    from ..serve.journal import JOURNAL_GRAMMAR, JournalPolicy
    problems = JOURNAL_GRAMMAR.check(spec, value_code="AIKO407")
    if not problems:
        try:
            JournalPolicy.parse(spec)
        except ValueError as error:
            problems.append(("AIKO407", str(error)))
    return problems


def check_tune_spec(spec) -> list:
    """(code, message) problems in a `tune` SLO/directive spec (the
    operating point a definition pins for `aiko tune`): the shared
    grammar core validates it offline as AIKO501, exactly as
    SloSpec.parse would at tune time."""
    from ..tune.slo import check_tune_spec as check
    return check(spec)


def check_disagg_policy(spec) -> list:
    """(code, message) problems in a prefill/decode disaggregation
    spec (gateway `disagg` parameter, or a replica definition's
    `disagg: "role=..."`).  Same shape as check_gateway_policy: the
    per-directive grammar check as AIKO408, then the REAL
    DisaggPolicy.parse so cross-field constraints (role= is
    replica-side only) fail offline exactly as at construction."""
    from ..serve.disagg import DISAGG_GRAMMAR, DisaggPolicy
    problems = DISAGG_GRAMMAR.check(spec, value_code="AIKO408")
    if not problems:
        try:
            DisaggPolicy.parse(spec)
        except ValueError as error:
            problems.append(("AIKO408", str(error)))
    return problems


def check_checkpoint_policy(spec, element: bool = False) -> list:
    """(code, message) problems in a warm-KV-failover checkpoint spec
    (rule code AIKO409).  Same shape as check_disagg_policy: the
    per-directive grammar check, then the REAL CheckpointPolicy.parse
    plus its scope validation -- `recovery_rate` is gateway-side
    (failover pacing), `checkpoint_every`/`max_checkpoint_lag` are
    engine-side (snapshot cadence) -- so a spec on the wrong side
    fails offline exactly as at construction."""
    from ..decode.checkpoint import CHECKPOINT_GRAMMAR, CheckpointPolicy
    problems = CHECKPOINT_GRAMMAR.check(spec, value_code="AIKO409")
    if not problems:
        try:
            policy = CheckpointPolicy.parse(spec)
            if element:
                policy.validate_engine()
            else:
                policy.validate_gateway()
        except ValueError as error:
            problems.append(("AIKO409", str(error)))
    return problems


def check_autoscale_policy(spec) -> list:
    """(code, message) problems in an elastic-fleet autoscale spec.
    Same shape as check_gateway_policy: the per-directive grammar
    check, then the REAL ScalePolicy.parse so cross-field constraints
    (min <= max replicas, low_water < high_water) fail offline exactly
    as they would when the gateway enables the autoscaler."""
    from ..serve.autoscale import AUTOSCALE_GRAMMAR, ScalePolicy
    problems = AUTOSCALE_GRAMMAR.check(spec, value_code="AIKO406")
    if not problems:
        try:
            ScalePolicy.parse(spec)
        except ValueError as error:
            problems.append(("AIKO406", str(error)))
    return problems


def check_prefix_policy(spec, element: bool = False) -> list:
    """(code, message) problems in a cross-request prefix-reuse spec
    (rule code AIKO411).  Same shape as check_checkpoint_policy: the
    per-directive grammar check, then the REAL PrefixPolicy.parse plus
    its scope validation -- `affinity_weight` is gateway-side (routing
    score), `min_prefix_blocks`/`cache_blocks` are engine-side (cache
    shape) -- so a spec on the wrong side fails offline exactly as at
    construction."""
    from ..decode.prefix import PREFIX_GRAMMAR, PrefixPolicy
    problems = PREFIX_GRAMMAR.check(spec, value_code="AIKO411")
    if not problems:
        try:
            policy = PrefixPolicy.parse(spec)
            if element:
                policy.validate_engine()
            else:
                policy.validate_gateway()
        except ValueError as error:
            problems.append(("AIKO411", str(error)))
    return problems


def check_autopilot_policy(spec) -> list:
    """(code, message) problems in an online SLO autopilot spec (rule
    code AIKO412).  Same shape as check_gateway_policy: the
    per-directive grammar check, then the REAL AutopilotPolicy.parse
    so cross-field constraints (burn_window > 0, max_delta_frac > 0)
    fail offline exactly as Gateway construction would."""
    from ..serve.autopilot import AUTOPILOT_GRAMMAR, AutopilotPolicy
    problems = AUTOPILOT_GRAMMAR.check(spec, value_code="AIKO412")
    if not problems:
        try:
            AutopilotPolicy.parse(spec)
        except ValueError as error:
            problems.append(("AIKO412", str(error)))
    return problems


def check_federation_policy(spec) -> list:
    """(code, message) problems in a federated-gateway spec.  Same
    shape as check_gateway_policy: the per-directive grammar check as
    AIKO410, then the REAL FederationPolicy.parse so cross-field
    constraints (non-empty unique groups, own group in the set) fail
    offline exactly as Gateway construction would."""
    from ..serve.federation import FEDERATION_GRAMMAR, FederationPolicy
    problems = FEDERATION_GRAMMAR.check(spec, value_code="AIKO410")
    if not problems:
        try:
            FederationPolicy.parse(spec)
        except ValueError as error:
            problems.append(("AIKO410", str(error)))
    return problems


def run_policy_pass(definition) -> AnalysisReport:
    report = AnalysisReport(passes_run=["policy"])
    name = definition.name
    on_error = _on_error_field()
    scopes = ([("", definition.parameters, None)]
              + [(element.name, element.parameters, element)
                 for element in definition.elements])
    for element_name, parameters, element in scopes:
        parameters = parameters or {}
        fields = dict(FAULT_TOLERANCE_FIELDS)
        fields["on_error"] = on_error
        for key, field in fields.items():
            if key not in parameters:
                continue
            try:
                field.coerce("fault-tolerance", key, parameters[key])
            except ValueError as error:
                report.add(Diagnostic(
                    "AIKO401", str(error), definition=name,
                    element=element_name))
        # `role`/`adopt_timeout` are only disagg vocabulary on elements
        # that interpret them (LMGenerate); a Detector with
        # role="primary" must not trip AIKO408
        disagg_scope = (
            element is not None
            and (element.deploy_local or {}).get("class_name")
            == "LMGenerate")
        triggers = (tuple(DECODE_FIELDS)
                    + ((tuple(DISAGG_FIELDS)
                        + ("checkpoint", "prefix_policy"))
                       if disagg_scope else ()))
        if any(key in parameters for key in triggers):
            for code, message in check_decode_parameters(
                    parameters, disagg_scope=disagg_scope):
                report.add(Diagnostic(code, message, definition=name,
                                      element=element_name))
            if (disagg_scope and parameters.get("checkpoint")
                    and element is not None
                    and not any(
                        str(port.get("name")) == "restore"
                        for port in (element.input or []))):
                # without the optional `restore` input port the
                # gateway's failover hint is dropped by map_in: the
                # element pays the snapshot tax every tick but every
                # failover silently re-prefills cold
                report.add(Diagnostic(
                    "AIKO409",
                    "checkpoint is set but the element declares no "
                    "`restore` input port (add {\"name\": \"restore\", "
                    "\"optional\": true}): failover hints would be "
                    "dropped and every recovery re-prefills cold",
                    definition=name, element=element_name))
    faults_spec = (definition.parameters or {}).get("faults")
    if faults_spec:
        for code, message in check_faults_spec(faults_spec):
            report.add(Diagnostic(code, message, definition=name))
    # gateways are services, not graph nodes, but operators embed their
    # policy next to the definition often enough to be worth checking
    policy_spec = (definition.parameters or {}).get("gateway_policy")
    if policy_spec:
        for code, message in check_gateway_policy(policy_spec):
            report.add(Diagnostic(code, message, definition=name))
    autoscale_spec = (definition.parameters or {}).get("autoscale_policy")
    if autoscale_spec:
        for code, message in check_autoscale_policy(autoscale_spec):
            report.add(Diagnostic(code, message, definition=name))
    # `disagg` pins a REPLICA's pool role; `disagg_policy` is a
    # gateway-side spec embedded next to the definition (both AIKO408)
    for parameter in ("disagg", "disagg_policy"):
        disagg_spec = (definition.parameters or {}).get(parameter)
        if disagg_spec:
            for code, message in check_disagg_policy(disagg_spec):
                report.add(Diagnostic(code, message, definition=name))
    # `checkpoint_policy` is the gateway-side warm-failover spec
    # embedded next to the definition (element-level `checkpoint` specs
    # are checked engine-scoped through check_decode_parameters above)
    checkpoint_spec = (definition.parameters or {}).get(
        "checkpoint_policy")
    if checkpoint_spec:
        for code, message in check_checkpoint_policy(checkpoint_spec):
            report.add(Diagnostic(code, message, definition=name))
    journal_spec = (definition.parameters or {}).get("journal_policy")
    if journal_spec:
        for code, message in check_journal_policy(journal_spec):
            report.add(Diagnostic(code, message, definition=name))
    # `autopilot_policy` is the gateway-side online-tuning loop spec
    # embedded next to the definition (serve/autopilot.py)
    autopilot_spec = (definition.parameters or {}).get(
        "autopilot_policy")
    if autopilot_spec:
        for code, message in check_autopilot_policy(autopilot_spec):
            report.add(Diagnostic(code, message, definition=name))
    # `federation_policy` is the gateway-side federated-tier spec
    # embedded next to the definition (stream -> group consistent hash)
    federation_spec = (definition.parameters or {}).get(
        "federation_policy")
    if federation_spec:
        for code, message in check_federation_policy(federation_spec):
            report.add(Diagnostic(code, message, definition=name))
    # DEFINITION-level `prefix_policy` is the gateway-side affinity
    # spec embedded next to the definition; element-level
    # `prefix_policy` specs were checked engine-scoped through
    # check_decode_parameters above (same split as checkpoint)
    prefix_spec = (definition.parameters or {}).get("prefix_policy")
    if prefix_spec:
        for code, message in check_prefix_policy(prefix_spec):
            report.add(Diagnostic(code, message, definition=name))
    tune_spec = (definition.parameters or {}).get("tune")
    if tune_spec:
        for code, message in check_tune_spec(tune_spec):
            report.add(Diagnostic(code, message, definition=name))
    return report
