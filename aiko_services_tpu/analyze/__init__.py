# Definition-time static analysis: prove a pipeline definition
# well-typed before any frame moves.
#
# Four passes, each with a stable rule-code band (diagnostics.py):
#
#   graph   AIKO1xx  graph/port dataflow: unbound inputs, dead outputs,
#                    map renames, duplicate names/ports
#           AIKO2xx  tensor-spec flow: dtype/rank/dim clashes and
#                    symbolic-dim conflicts propagated
#                    producer->consumer, sharding axes vs the mesh
#   eval    AIKO207+ abstract interpretation: element device programs
#                    dry-run under jax.eval_shape against declared
#                    specs (no allocation, no compile, no device)
#   actor   AIKO3xx  AST safety lint over deployed element modules:
#                    blocking calls on the event loop, shared-state
#                    mutation, group_kernel on async elements
#   policy  AIKO4xx  operator grammars (fault-tolerance parameters,
#                    fault-injection specs, gateway admission policy)
#                    verified through the shared directive-grammar core
#   code    AIKO6xx  whole-package static concurrency lint over Python
#                    SOURCE (not definitions): thread-role inference
#                    over the actor fleet, unsynchronized container
#                    iteration, cross-role check-then-act, blocking
#                    under lock, lock-order inversion, mutable
#                    class-level defaults (aiko lint --code)
#
# `Pipeline.__init__` runs the cheap passes (graph + policy) at
# construction unless the pipeline parameter `validate` is false;
# `aiko lint` runs all four over definition files and CI artifacts;
# `aiko lint --code` runs the AIKO6xx pass over source trees against
# a committed baseline file.

from __future__ import annotations

import contextlib
import os
import sys

from .concurrency import (                                     # noqa: F401
    apply_baseline, finding_fingerprint, load_baseline, role_map,
    run_code_pass, write_baseline)
from .diagnostics import (                                     # noqa: F401
    AnalysisReport, Diagnostic, RULES, severity_of)
from .grammar import (                                         # noqa: F401
    DirectiveGrammar, Field, GrammarError)
from .specs import (                                           # noqa: F401
    PortSpec, SpecError, parse_port_type)

__all__ = [
    "AnalysisReport", "Diagnostic", "RULES", "severity_of",
    "DirectiveGrammar", "Field", "GrammarError",
    "PortSpec", "SpecError", "parse_port_type",
    "CHEAP_PASSES", "ALL_PASSES", "analyze_definition",
    "run_code_pass", "role_map", "finding_fingerprint",
    "load_baseline", "apply_baseline", "write_baseline",
]

CHEAP_PASSES = ("graph", "policy")
ALL_PASSES = ("graph", "policy", "actor", "eval")


def _lint_ignores(definition) -> dict:
    """Suppression sets: "" -> pipeline-wide codes, element name ->
    element-scoped codes (the `lint_ignore` parameter)."""
    ignores = {}

    def codes_of(parameters):
        value = (parameters or {}).get("lint_ignore")
        if not value:
            return frozenset()
        if isinstance(value, str):
            value = [value]
        return frozenset(str(code).upper() for code in value)

    ignores[""] = codes_of(definition.parameters)
    for element in definition.elements:
        ignores[element.name] = codes_of(element.parameters)
    return ignores


@contextlib.contextmanager
def _definition_dir_importable(source):
    """Make a definition file's own directory importable while its
    passes run, so `deploy` modules that live next to the definition
    (fixture elements, project-local elements) resolve under offline
    lint exactly as they do for a process launched from that
    directory."""
    directory = None
    with contextlib.suppress(TypeError, ValueError, OSError):
        path = os.fspath(source)
        if isinstance(path, str) and os.path.isfile(path):
            directory = os.path.dirname(os.path.abspath(path))
    if directory is None or directory in sys.path:
        yield
        return
    sys.path.insert(0, directory)
    already_loaded = frozenset(sys.modules)
    try:
        yield
    finally:
        with contextlib.suppress(ValueError):
            sys.path.remove(directory)
        # evict modules this analysis imported FROM the directory, so a
        # later definition in another directory whose deploy module
        # shares the name is not linted against this directory's file
        from ..utils.importer import unload_module
        for name, module in list(sys.modules.items()):
            if name in already_loaded:
                continue
            origin = getattr(module, "__file__", None)
            if (origin
                    and os.path.dirname(os.path.abspath(origin))
                    == directory):
                unload_module(name)


def analyze_definition(source, passes=ALL_PASSES,
                       source_path: str = "") -> AnalysisReport:
    """Run the selected passes over one definition (dict, JSON text,
    path, or an already-parsed PipelineDefinition).

    Never raises on a broken definition: schema errors surface as
    AIKO100 findings so a corpus of deliberately-defective definitions
    (tests/assets/lint_golden) can be linted in one sweep."""
    from ..pipeline.definition import (
        DefinitionError, PipelineDefinition, parse_pipeline_definition)

    report = AnalysisReport()
    if isinstance(source, PipelineDefinition):
        definition = source
    else:
        try:
            definition = parse_pipeline_definition(source,
                                                   validate=False)
        except DefinitionError as error:
            report.add(Diagnostic("AIKO100", str(error),
                                  source=source_path))
            return report
        except Exception as error:  # unreadable file, bad JSON type
            report.add(Diagnostic(
                "AIKO100", f"{type(error).__name__}: {error}",
                source=source_path))
            return report

    with _definition_dir_importable(source):
        graph_report = None
        if "graph" in passes:
            from .graph_flow import run_graph_pass
            graph_report = run_graph_pass(definition)
            report.extend(graph_report)
        if "policy" in passes:
            from .policies import run_policy_pass
            report.extend(run_policy_pass(definition))
        if "actor" in passes:
            from .actor_lint import run_actor_pass
            report.extend(run_actor_pass(definition))
        if "eval" in passes:
            if graph_report is None:
                from .graph_flow import run_graph_pass
                graph_report = run_graph_pass(definition)
            from .shape_eval import run_eval_pass
            report.extend(run_eval_pass(
                definition, graph_report.input_specs,
                graph_report.output_specs,
                graph_report.symbol_bindings))

    ignores = _lint_ignores(definition)
    pipeline_wide = ignores.get("", frozenset())
    kept = []
    for diagnostic in report.findings:
        suppress = (pipeline_wide
                    | ignores.get(diagnostic.element, frozenset()))
        if diagnostic.code in suppress:
            continue
        if source_path and not diagnostic.source:
            diagnostic.source = source_path
        kept.append(diagnostic)
    report.findings = kept
    return report
