# Pass 2 -- abstract interpretation of element compute under
# jax.eval_shape (AIKO207/AIKO208).
#
# For local elements exposing a pure device program (the
# PipelineElement.eval_kernel contract), the pass synthesizes
# jax.ShapeDtypeStructs from the DECLARED input specs and dry-runs
# state-build + kernel under jax.eval_shape: the declared output specs
# are PROVEN against the traced outputs without allocating a parameter,
# compiling a program, or touching a device -- the same trick
# jax.eval_shape plays for cost estimation, pointed at the pipeline
# definition layer.
#
# Elements with no pure program (sources, async host elements, custom
# host-side process_frame without an eval_kernel override) are skipped;
# elements whose trace fails report AIKO208 (info -- an analysis limit,
# not a defect).

from __future__ import annotations

from .diagnostics import AnalysisReport, Diagnostic
from .specs import resolve_dims

__all__ = ["run_eval_pass", "element_cost_estimates"]


def _synthesize(spec, bindings: dict, default_size: int):
    """Concrete shape for one input spec, or None when it cannot be
    synthesized faithfully.  The LEADING axis may default (it is the
    batch contract -- any size traces the same program); an UNBOUND
    symbol or wildcard on an inner axis means the definition does not
    pin the sizes the kernel's architecture depends on, so the element
    is skipped rather than traced at a made-up size."""
    if not spec.is_tensor:
        return None
    shape = []
    for axis, dim in enumerate(spec.dims):
        if isinstance(dim, int):
            shape.append(dim)
            continue
        bound = bindings.get(dim) if dim != "*" else None
        if bound is not None:
            shape.append(bound[0])
            continue
        if axis > 0:
            return None
        if dim != "*":
            bindings[dim] = (default_size, "synthesized")
        shape.append(default_size)
    return tuple(shape)


def _compare(report, definition_name, element_name, port_name,
             declared, traced, bindings) -> None:
    """AIKO207 when a traced leaf disagrees with its declared spec."""
    expected_shape = resolve_dims(declared, bindings)
    traced_shape = tuple(getattr(traced, "shape", ()))
    traced_dtype = str(getattr(traced, "dtype", ""))
    problems = []
    if declared.dtype is not None and traced_dtype != declared.dtype:
        problems.append(f"dtype {traced_dtype} != declared "
                        f"{declared.dtype}")
    if expected_shape is not None:
        if len(traced_shape) != len(declared.dims):
            problems.append(
                f"rank {len(traced_shape)} != declared rank "
                f"{len(declared.dims)}")
        else:
            for axis, (dim, traced_size) in enumerate(
                    zip(declared.dims, traced_shape)):
                if dim == "*":
                    continue
                if isinstance(dim, int):
                    if traced_size != dim:
                        problems.append(
                            f"axis {axis}: traced {traced_size} != "
                            f"declared {dim}")
                else:
                    bound = bindings.get(dim)
                    if bound is None:
                        bindings[dim] = (traced_size, "traced output")
                    elif bound[0] != traced_size:
                        problems.append(
                            f"axis {axis}: traced {traced_size} != "
                            f"symbol {dim!r} bound to {bound[0]}")
    if problems:
        report.add(Diagnostic(
            "AIKO207",
            f"declared {declared.raw!r} but jax.eval_shape traced "
            f"{traced_dtype}{list(traced_shape)}: "
            + "; ".join(problems),
            definition=definition_name, element=element_name,
            port=str(port_name)))


def _instantiate_element(element_def, process):
    """Instantiate a LOCAL element for shape tracing; None when the
    deploy target is not a PipelineElement (AIKO304 is the actor
    pass's finding).  Shared by the eval pass and the tune cost
    estimates so the two can never drift."""
    from ..pipeline.element import PipelineElement
    from ..utils import load_module

    module = load_module(element_def.deploy_local["module"])
    cls = getattr(module, element_def.deploy_local["class_name"])
    if not (isinstance(cls, type)
            and issubclass(cls, PipelineElement)):
        return None
    return cls(process, None, element_def)


def _kernel_structs(element, input_specs, bindings, default_size):
    """(kernel, state_struct, input structs) from the element's
    eval_kernel contract and its declared input specs -- or None when
    the element has no pure program, or an input is opaque (str
    prompts, "any") / un-pinned on an inner axis and cannot be
    synthesized faithfully (skipped, not a finding: declare concrete
    tensor specs to opt the element in)."""
    import jax

    kernel_spec = element.eval_kernel()
    if kernel_spec is None:
        return None
    kernel, state_fn = kernel_spec
    structs = {}
    for port_name, spec in input_specs.items():
        shape = _synthesize(spec, bindings, default_size)
        if shape is None:
            return None
        structs[port_name] = jax.ShapeDtypeStruct(
            shape, jax.numpy.dtype(spec.dtype))
    state_struct = (jax.eval_shape(state_fn)
                    if state_fn is not None else None)
    return kernel, state_struct, structs


def _trace_element(report, definition, element_def, element, input_specs,
                   output_specs, bindings, default_size) -> None:
    import jax

    resolved = _kernel_structs(element, input_specs, bindings,
                               default_size)
    if resolved is None:
        return
    kernel, state_struct, structs = resolved
    traced = jax.eval_shape(kernel, state_struct, **structs)
    if not isinstance(traced, dict):
        report.add(Diagnostic(
            "AIKO208",
            f"eval kernel returned {type(traced).__name__}, not a "
            f"dict of outputs", definition=definition.name,
            element=element_def.name))
        return
    for port_name, declared in output_specs.items():
        if not declared.is_tensor:
            continue  # opaque declared types prove nothing
        leaf = traced.get(port_name)
        if leaf is None or not hasattr(leaf, "shape"):
            # host-produced output (text decode, overlay dicts): the
            # kernel covers the device subset only
            continue
        _compare(report, definition.name, element_def.name, port_name,
                 declared, leaf, bindings)
    report.traced_elements.append(element_def.name)


def _struct_bytes(tree) -> int:
    """Total bytes of every array leaf in an eval_shape result."""
    import jax
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        size = 1
        for dim in shape:
            size *= int(dim)
        total += size * jax.numpy.dtype(dtype).itemsize
    return total


def element_cost_estimates(definition, include_flops: bool = True,
                           default_symbol_size: int = 2) -> dict:
    """Static FLOP/byte estimates per element, the analyze/ half of the
    tune/ cost model: for every local element with a pure device
    program (the eval_kernel contract), synthesize ShapeDtypeStructs
    from the declared port specs and measure -- WITHOUT running the
    kernel -- bytes in/out/parameters (jax.eval_shape) and, when
    `include_flops`, the XLA flop estimate from lowering the kernel
    (Lowered.cost_analysis; skipped silently where the backend does
    not report it).

    Returns {element_name: {"rows", "bytes_in", "bytes_out",
    "param_bytes", "flops"}}; elements that cannot be traced are
    absent (the tune report marks them estimate-free rather than
    guessing)."""
    import jax

    from ..runtime import Process
    from .graph_flow import run_graph_pass

    graph_report = run_graph_pass(definition)
    input_specs = getattr(graph_report, "input_specs", {}) or {}
    bindings = dict(getattr(graph_report, "symbol_bindings", {}) or {})
    estimates: dict = {}
    process = Process(transport_kind="null")
    try:
        for element_def in definition.elements:
            if not element_def.is_local:
                continue
            try:
                element = _instantiate_element(element_def, process)
                if element is None:
                    continue
                resolved = _kernel_structs(
                    element, input_specs.get(element_def.name, {}),
                    bindings, default_symbol_size)
                if resolved is None or not resolved[2]:
                    continue
                kernel, state_struct, structs = resolved
                rows = None
                for struct in structs.values():
                    if struct.shape:
                        rows = int(struct.shape[0])
                        break
                traced = jax.eval_shape(kernel, state_struct, **structs)
                record = {
                    "rows": rows or 1,
                    "bytes_in": _struct_bytes(structs),
                    "bytes_out": _struct_bytes(traced),
                    "param_bytes": _struct_bytes(state_struct),
                    "flops": None,
                }
                if include_flops:
                    try:
                        lowered = jax.jit(kernel).lower(
                            state_struct, **structs)
                        analysis = lowered.cost_analysis()
                        if isinstance(analysis, (list, tuple)):
                            analysis = analysis[0] if analysis else {}
                        flops = (analysis or {}).get("flops")
                        if flops is not None:
                            record["flops"] = float(flops)
                    except Exception:
                        pass  # backend without cost analysis
                estimates[element_def.name] = record
            except Exception:
                continue  # uninstantiable element: no estimate
    finally:
        try:
            process.terminate()
        except Exception:
            pass
    return estimates


def run_eval_pass(definition, input_specs, output_specs,
                  symbol_bindings=None,
                  default_symbol_size: int = 2) -> AnalysisReport:
    """Dry-run every local element's pure device program under
    jax.eval_shape against the declared port specs.

    `input_specs`/`output_specs` are the per-element {port: PortSpec}
    maps the graph pass resolved; `symbol_bindings` its symbol table
    (shared so the whole graph traces under ONE binding)."""
    from ..runtime import Process

    report = AnalysisReport(passes_run=["eval"])
    report.traced_elements = []
    bindings = dict(symbol_bindings or {})
    process = Process(transport_kind="null")
    try:
        for element_def in definition.elements:
            if not element_def.is_local:
                continue
            try:
                element = _instantiate_element(element_def, process)
                if element is None:
                    continue  # AIKO304 is the actor pass's finding
            except Exception as error:
                report.add(Diagnostic(
                    "AIKO208",
                    f"cannot instantiate for shape tracing: {error}",
                    definition=definition.name,
                    element=element_def.name))
                continue
            try:
                _trace_element(
                    report, definition, element_def, element,
                    input_specs.get(element_def.name, {}),
                    output_specs.get(element_def.name, {}),
                    bindings, default_symbol_size)
            except Exception as error:
                report.add(Diagnostic(
                    "AIKO208",
                    f"shape trace failed: {type(error).__name__}: "
                    f"{error}", definition=definition.name,
                    element=element_def.name))
    finally:
        try:
            process.terminate()
        except Exception:
            pass
    report.symbol_bindings = bindings
    return report
