# Tensor-spec grammar: the typed language of pipeline ports.
#
# The definition layer's port "type" field was a vestigial string
# ("any") the engine never read.  The static analyzer gives it a real
# grammar so shape/dtype flow can be PROVEN at definition time, the way
# an MLIR verifier proves an IR module well-typed before any pass runs:
#
#   type     := opaque | tensor
#   opaque   := "any" | "str" | "bytes" | "int" | "float" | "bool"
#             | "dict" | "list"
#   tensor   := dtype "[" dims? "]"
#   dtype    := "f32" | "f16" | "bf16" | "f64" | "i8" | "i16" | "i32"
#             | "i64" | "u8" | "u16" | "u32" | "u64" | "bool"
#             (long forms "float32", "int32", ... are accepted too)
#   dims     := dim ("," dim)*
#   dim      := INT          a fixed size, checked exactly
#             | SYMBOL       a symbolic size ("b", "t", "seq"): bound to
#                            one size per graph -- two ports binding the
#                            same symbol must agree
#             | "*" | "?"    wildcard: any size, no binding
#
# Examples: "f32[b,3,224,224]"  "i32[b,t]"  "f32[]" (a scalar)
#           "bf16[b,*,d]"       "str"       "any"
#
# Symbols are scoped to ONE pipeline definition: "b" in the source's
# output and "b" in the detector's input are the same batch.  The
# analyzer binds a symbol the first time it meets a fixed size and
# reports AIKO205 when a later port disagrees.

from __future__ import annotations

import re
from dataclasses import dataclass

__all__ = [
    "PortSpec", "SpecError", "parse_port_type", "check_flow",
    "resolve_dims", "OPAQUE_KINDS", "DTYPE_ALIASES",
]


class SpecError(ValueError):
    """A port "type" string that is not in the tensor-spec grammar."""


# Short dtype mnemonics -> canonical jax/numpy dtype names.  Long forms
# map to themselves so either spelling round-trips.
DTYPE_ALIASES = {
    "f16": "float16", "f32": "float32", "f64": "float64",
    "bf16": "bfloat16",
    "i8": "int8", "i16": "int16", "i32": "int32", "i64": "int64",
    "u8": "uint8", "u16": "uint16", "u32": "uint32", "u64": "uint64",
    "bool": "bool",
}
DTYPE_ALIASES.update({name: name for name in list(DTYPE_ALIASES.values())})

# Non-tensor port kinds: the analyzer treats them as opaque values that
# flow by name only (host strings, overlay dicts, detection pytrees).
# "any" is the universal wildcard -- compatible with everything, which
# is also why it proves nothing.
OPAQUE_KINDS = ("any", "str", "bytes", "int", "float", "bool", "dict",
                "list")

_SYMBOL_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")
_TENSOR_RE = re.compile(r"^(?P<dtype>[A-Za-z0-9_]+)\[(?P<dims>[^\]]*)\]$")


@dataclass(frozen=True)
class PortSpec:
    """One parsed port type: either an opaque kind or a tensor spec."""

    kind: str                 # "tensor" or one of OPAQUE_KINDS
    dtype: str | None = None  # canonical dtype name (tensor only)
    dims: tuple | None = None  # int | str symbol | "*" per axis
    raw: str = "any"

    @property
    def is_tensor(self) -> bool:
        return self.kind == "tensor"

    @property
    def is_any(self) -> bool:
        return self.kind == "any"

    def __str__(self):
        return self.raw


ANY = PortSpec(kind="any", raw="any")


def parse_port_type(text) -> PortSpec:
    """Parse one port "type" string; raises SpecError with the exact
    grammar problem (the message becomes the AIKO201 diagnostic)."""
    if text is None:
        return ANY
    raw = str(text).strip()
    if not raw:
        return ANY
    lowered = raw.lower()
    if lowered in OPAQUE_KINDS:
        return PortSpec(kind=lowered, raw=lowered)
    match = _TENSOR_RE.match(raw)
    if not match:
        if "[" in raw or "]" in raw:
            raise SpecError(
                f"type {raw!r} is not a tensor spec: expected "
                f"dtype[dim,...] like f32[b,3,224,224]")
        raise SpecError(
            f"type {raw!r} is not a known port type: expected one of "
            f"{OPAQUE_KINDS} or a tensor spec like f32[b,3,224,224]")
    dtype_token = match.group("dtype").lower()
    dtype = DTYPE_ALIASES.get(dtype_token)
    if dtype is None:
        raise SpecError(
            f"type {raw!r} names unknown dtype {dtype_token!r}; known: "
            f"{sorted(set(DTYPE_ALIASES))}")
    dims_text = match.group("dims").strip()
    dims = []
    if dims_text:
        for token in dims_text.split(","):
            token = token.strip()
            if not token:
                raise SpecError(f"type {raw!r} has an empty dimension")
            if token in ("*", "?"):
                dims.append("*")
            elif token.lstrip("-").isdigit():
                size = int(token)
                if size <= 0:
                    raise SpecError(
                        f"type {raw!r}: dimension {token} must be a "
                        f"positive size")
                dims.append(size)
            elif _SYMBOL_RE.match(token):
                dims.append(token)
            else:
                raise SpecError(
                    f"type {raw!r}: dimension {token!r} is not an int, "
                    f"a symbol, or '*'")
    return PortSpec(kind="tensor", dtype=dtype, dims=tuple(dims), raw=raw)


def check_flow(producer: PortSpec, consumer: PortSpec,
               bindings: dict) -> list:
    """Check one producer->consumer edge; returns (code, message)
    problems.  `bindings` is the graph-wide symbol table
    symbol -> (size, where) -- symbols bind on first concrete contact
    and every later contact must agree (AIKO205)."""
    if not producer.is_tensor or not consumer.is_tensor:
        # "any" matches everything; a tensor flowing into a non-any
        # opaque port (or vice versa) clashes; two opaque kinds are
        # compatible -- host elements legitimately hand a str where a
        # list[str] arrives (per-row batching), so Python duck-typing
        # is the ground truth between opaque ports
        if producer.is_any or consumer.is_any:
            return []
        if producer.is_tensor != consumer.is_tensor:
            return [("AIKO202",
                     f"producer type {producer.raw!r} is not consumable "
                     f"as {consumer.raw!r}")]
        return []
    problems = []
    if producer.dtype != consumer.dtype:
        problems.append((
            "AIKO202",
            f"dtype clash: producer {producer.raw!r} vs consumer "
            f"{consumer.raw!r}"))
    if len(producer.dims) != len(consumer.dims):
        problems.append((
            "AIKO203",
            f"rank mismatch: producer {producer.raw!r} is rank "
            f"{len(producer.dims)}, consumer {consumer.raw!r} is rank "
            f"{len(consumer.dims)}"))
        return problems
    for axis, (left, right) in enumerate(
            zip(producer.dims, consumer.dims)):
        problems.extend(_check_dim(axis, left, right, bindings))
    return problems


def _check_dim(axis: int, left, right, bindings: dict) -> list:
    """Unify one dimension pair under the graph symbol table."""
    if left == "*" or right == "*":
        return []
    if isinstance(left, int) and isinstance(right, int):
        if left != right:
            return [("AIKO204",
                     f"axis {axis}: producer size {left} != consumer "
                     f"size {right}")]
        return []
    problems = []
    for symbol, size in ((left, right), (right, left)):
        if isinstance(symbol, str) and isinstance(size, int):
            bound = bindings.get(symbol)
            if bound is None:
                bindings[symbol] = (size, f"axis {axis}")
            elif bound[0] != size:
                problems.append((
                    "AIKO205",
                    f"axis {axis}: symbol {symbol!r} already bound to "
                    f"{bound[0]} ({bound[1]}) but meets size {size} "
                    f"here"))
            break
    # symbol-vs-symbol: compatible; distinct names stay independent
    return problems


def resolve_dims(spec: PortSpec, bindings: dict,
                 default_symbol_size: int = 2) -> tuple | None:
    """Concrete shape for a tensor spec: symbols resolve through
    `bindings` (falling back to `default_symbol_size`), wildcards to the
    default.  None for opaque specs."""
    if not spec.is_tensor:
        return None
    shape = []
    for dim in spec.dims:
        if isinstance(dim, int):
            shape.append(dim)
        elif dim == "*":
            shape.append(default_symbol_size)
        else:
            bound = bindings.get(dim)
            shape.append(bound[0] if bound else default_symbol_size)
    return tuple(shape)
