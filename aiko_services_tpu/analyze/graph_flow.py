# Pass 1 -- graph dataflow verification (AIKO1xx) and static
# shape/dtype flow (AIKO2xx).
#
# The MLIR-verifier move: prove the WHOLE graph well-typed from the
# definition alone, before any element is constructed or any frame
# moves.  Port specs (specs.py grammar) propagate producer->consumer
# through the graph S-expression, the map_in/map_out renames, and a
# graph-wide symbolic-dimension table; the sharding block is checked
# against its own mesh axes.  Runs in microseconds, so Pipeline
# construction runs it by default (opt-out `validate: false`).

from __future__ import annotations

from .diagnostics import AnalysisReport, Diagnostic
from .specs import SpecError, check_flow, parse_port_type

__all__ = ["run_graph_pass", "collect_sharding_axes"]


def _parse_ports(report, definition_name, element, direction):
    """Parse every port type of one direction; AIKO201/AIKO107 on the
    way.  Returns {port_name: PortSpec} (unparseable types become
    "any" so later checks still run)."""
    specs = {}
    ports = element.input if direction == "input" else element.output
    for port in ports:
        name = port.get("name")
        if name in specs:
            report.add(Diagnostic(
                "AIKO107",
                f"{direction} port {name!r} declared more than once",
                definition=definition_name, element=element.name,
                port=str(name)))
            continue
        try:
            specs[name] = parse_port_type(port.get("type"))
        except SpecError as error:
            report.add(Diagnostic(
                "AIKO201", str(error), definition=definition_name,
                element=element.name, port=str(name)))
            specs[name] = parse_port_type(None)
    return specs


def collect_sharding_axes(sharding: dict) -> set:
    """Every mesh-axis name a sharding block's input/state specs
    reference (nested pytrees of axis lists, reference
    parallel/mesh.py partition_spec shapes)."""
    names: set = set()

    def walk(node):
        if node is None:
            return
        if isinstance(node, str):
            names.add(node)
        elif isinstance(node, dict):
            for value in node.values():
                walk(value)
        elif isinstance(node, (list, tuple)):
            for entry in node:
                walk(entry)

    walk(sharding.get("inputs"))
    walk(sharding.get("state"))
    return names


def _check_sharding(report, definition_name, element) -> None:
    sharding = element.sharding or {}
    if not sharding:
        return
    axes = sharding.get("axes")
    # with no axes block the engine builds the default {"data": -1}
    # mesh (tpu_element.py get_mesh contract)
    mesh_axes = set(axes) if isinstance(axes, dict) else {"data"}
    for name in sorted(collect_sharding_axes(sharding)):
        if name not in mesh_axes:
            report.add(Diagnostic(
                "AIKO206",
                f"sharding spec names axis {name!r} but the element's "
                f"mesh axes are {sorted(mesh_axes)}",
                definition=definition_name, element=element.name))


def run_graph_pass(definition, graph=None) -> AnalysisReport:
    """Verify one parsed PipelineDefinition's graph and port flow.

    Returns the report; also attaches the resolved per-element input
    specs and the graph symbol table on the report
    (`report.input_specs[element]`, `report.symbol_bindings`) for the
    eval-shape pass to synthesize ShapeDtypeStructs from."""
    report = AnalysisReport(passes_run=["graph"])
    name = definition.name

    # element-level structural checks
    seen: set = set()
    input_specs: dict = {}
    output_specs: dict = {}
    for element in definition.elements:
        if element.name in seen:
            report.add(Diagnostic(
                "AIKO102", f"element {element.name!r} defined more "
                f"than once", definition=name, element=element.name))
        seen.add(element.name)
        input_specs[element.name] = _parse_ports(
            report, name, element, "input")
        output_specs[element.name] = _parse_ports(
            report, name, element, "output")
        for port_name in element.map_in:
            if port_name not in input_specs[element.name]:
                report.add(Diagnostic(
                    "AIKO105",
                    f"map_in names input port {port_name!r} but the "
                    f"element declares inputs "
                    f"{sorted(input_specs[element.name])}",
                    definition=name, element=element.name,
                    port=str(port_name)))
        for port_name in element.map_out:
            if port_name not in output_specs[element.name]:
                report.add(Diagnostic(
                    "AIKO106",
                    f"map_out names output port {port_name!r} but the "
                    f"element declares outputs "
                    f"{sorted(output_specs[element.name])}",
                    definition=name, element=element.name,
                    port=str(port_name)))
        _check_sharding(report, name, element)

    if graph is None:
        from ..utils import Graph
        try:
            graph = Graph.traverse(definition.graph)
        except Exception as error:
            report.add(Diagnostic(
                "AIKO100", f"graph does not traverse: {error}",
                definition=name))
            return report

    for node_name in graph.node_names():
        if definition.element(node_name) is None:
            report.add(Diagnostic(
                "AIKO101", f"graph node {node_name!r} has no element "
                f"definition", definition=name, element=node_name))

    # dataflow: walk the execution path, tracking for each swag key its
    # producing (element, port, spec) and whether it has been read
    # since (AIKO104 dead-store detection), while unifying specs over
    # the graph symbol table
    bindings: dict = {}
    produced: dict = {}   # swag key -> {"element", "port", "spec", "read"}
    heads = set(graph.head_nodes())
    descendants_cache: dict = {}

    def descendants(node):
        if node not in descendants_cache:
            try:
                descendants_cache[node] = graph.descendants(node)
            except Exception:
                descendants_cache[node] = frozenset()
        return descendants_cache[node]

    def ancestor_keys(node):
        """Swag keys produced by strict ancestors (the engine's
        validate contract: inputs must come from an ancestor, not
        merely an earlier sibling in path order)."""
        keys = set()
        frontier = list(graph.predecessors(node))
        visited = set()
        while frontier:
            ancestor = frontier.pop()
            if ancestor in visited:
                continue
            visited.add(ancestor)
            ancestor_def = definition.element(ancestor)
            if ancestor_def is not None:
                for output_name in output_specs.get(ancestor, {}):
                    keys.add(ancestor_def.map_out.get(
                        output_name, output_name))
            frontier.extend(graph.predecessors(ancestor))
        return keys

    for node_name in graph.get_path():
        element = definition.element(node_name)
        if element is None:
            continue  # AIKO101 already reported
        element_inputs = input_specs.get(node_name, {})
        element_outputs = output_specs.get(node_name, {})
        available = (None if node_name in heads
                     else ancestor_keys(node_name))
        # -- consume inputs
        for port_name, consumer_spec in element_inputs.items():
            swag_key = element.map_in.get(port_name, port_name)
            if available is not None and swag_key not in available:
                report.add(Diagnostic(
                    "AIKO103",
                    f"input {port_name!r} (swag key {swag_key!r}) "
                    f"is not produced by any ancestor; available: "
                    f"{sorted(available)}",
                    definition=name, element=node_name,
                    port=str(port_name)))
                continue
            # heads included: the engine's swag is ONE dict per frame
            # across all graph roots (create_frame data first, then
            # every map_out in path order), so a head whose input key
            # an earlier root already wrote receives THAT value at
            # runtime -- the flow check against the path-order producer
            # mirrors execution exactly
            record = produced.get(swag_key)
            if record is None:
                continue
            record["read"] = True
            for code, message in check_flow(
                    record["spec"], consumer_spec, bindings):
                report.add(Diagnostic(
                    code,
                    f"{message} (produced by "
                    f"{record['element']}.{record['port']})",
                    definition=name, element=node_name,
                    port=str(port_name)))
        # -- produce outputs
        for port_name, producer_spec in element_outputs.items():
            swag_key = element.map_out.get(port_name, port_name)
            previous = produced.get(swag_key)
            if (previous is not None and not previous["read"]
                    and node_name in descendants(previous["element"])):
                # write-before-read by a true descendant: the earlier
                # value can never be observed -- a dead output
                report.add(Diagnostic(
                    "AIKO104",
                    f"output {previous['port']!r} (swag key "
                    f"{swag_key!r}) is overwritten by descendant "
                    f"{node_name!r} before any element reads it",
                    definition=name, element=previous["element"],
                    port=str(previous["port"])))
            produced[swag_key] = {"element": node_name,
                                  "port": port_name,
                                  "spec": producer_spec, "read": False}

    report.input_specs = input_specs
    report.output_specs = output_specs
    report.symbol_bindings = bindings
    return report
